"""Multi-tenant serving fleet: shared device residency, cross-tenant
micro-batch multiplexing, per-tenant SLO isolation.

`ml_ops serve` hosted exactly one model and one stream; a production
deployment scores many tenants/days concurrently on the same devices.
The scarce resources are the device-resident weights and the padded
AOT-warmed compiled-program family (plans/warmup.warmup_serving) — so
the fleet shares THOSE while isolating everything per-tenant:

`FleetRegistry`
    N hot models with per-tenant atomic hot-swap: one
    serving/registry.py `ModelRegistry` per tenant (validation +
    double-buffered publish + monotonic versions, unchanged), plus a
    *stacked snapshot* per topic-count K — every member tenant's
    [D_t+1, K] theta and [V_t+1, K] p concatenated row-wise with
    per-tenant base offsets.  The stack is itself double-buffered: a
    publish rebuilds it OUTSIDE the registry lock and swaps one
    reference, so tenant A's `RefreshLoop` publish never stalls tenant
    B's scoring path, and because every tenant's row count is stable
    across swaps the stacked shape — and therefore the compiled program
    — survives every hot-swap (keyed by shape, not tenant: zero
    retraces).

`FleetScorer`
    Cross-tenant micro-batch multiplexing into ONE compiled dispatch:
    events from every tenant's admission queue drain globally
    oldest-first into a shared micro-batch; each tenant segment
    featurizes with its own day's quantile cuts, maps onto its own
    model slice via `tenant base offset + local row` — the tenant-id
    column driving the on-device gather — and all segments of a
    K-group score as one `batched_scores` call at a shared padded
    shape.  Tenants whose K diverges form their own pack group
    (per-tenant segment dispatch), so heterogeneous fleets degrade to
    more dispatches, never to wrong scores.  Results demux back to
    per-tenant `ScoreFuture`s (journaled as `{"kind": "demux"}`),
    with per-tenant `serve.<tenant>.*` histograms/counters on the
    shared metrics plane and bounded per-tenant admission
    (serving/tenants.py) for ingress isolation.

Correctness invariant, pinned by tests/test_fleet.py: a packed
cross-tenant flush produces bit-identical scores to scoring each
tenant's events alone through `score_features` — packing changes WHICH
dispatch a row rides, never its arithmetic.
"""

from __future__ import annotations

import heapq
import threading
import time
from dataclasses import dataclass

import numpy as np

from ..config import ServingConfig
from ..scoring import ScoringModel
from ..scoring.score import batched_scores, use_device_path
from ..sources.device import DeviceBatch, device_batch, resolve_engine
from .metrics import MetricsEmitter
from .registry import ModelRegistry, ModelSnapshot
from .tenants import (
    AdmissionRejected,
    TenantLane,
    TenantSpec,
    _PendingEvent,
)


@dataclass(frozen=True)
class StackedSnapshot:
    """One pack group's shared-residency view: every member tenant's
    theta/p concatenated row-wise (each slice INCLUDES its own fallback
    row, so per-tenant fallback semantics survive packing).  Readers
    treat every field as immutable; a publish installs a fresh instance
    (so the device cache `scoring.score._device_model` hangs off re-
    uploads the new weights exactly once, while in-flight flushes
    finish on the instance — and device buffers — they started with)."""

    k: int
    tenants: tuple[str, ...]
    model: ScoringModel            # stacked [sum(D_t+1), K] / [sum(V_t+1), K]
    members: dict                  # tenant -> ModelSnapshot the stack was built from
    ip_base: dict                  # tenant -> row offset into stacked theta
    word_base: dict                # tenant -> row offset into stacked p
    stack_version: int             # monotonic per K-group build counter
    capacity: int = 0              # tenant-slot capacity tier (0 = exact census)
    precision: str = "f32"         # device storage dtype of the stacked model

    def version_of(self, tenant: str) -> int:
        return self.members[tenant].version


def _pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


def _build_stack(k: int, tenants: "list[str]", snaps: dict,
                 stack_version: int, *, tier: "dict | None" = None,
                 precision: str = "f32") -> StackedSnapshot:
    """Concatenate member models into one stacked ScoringModel.  Pure
    function of the member snapshots — called OUTSIDE any lock.

    `tier` (capacity-tier mode, the tiered-residency path) pads the
    stacked matrices with zero rows up to ``capacity * slot_rows``:
    `capacity` is the power-of-two tenant-slot count and the slot row
    budgets cover the largest tenant the K-group has ever seen, so the
    stacked SHAPE — and with it the compiled program family — is a
    function of the capacity tier alone, not of which tenants happen to
    be resident.  Promotion/eviction churn within a tier then retraces
    nothing; only crossing a power-of-two census boundary mints one new
    program family.  The pad rows are never indexed (tenant base
    offsets only cover real members), so padding cannot change a
    score.

    `precision="bf16"` marks the stacked model for half-width DEVICE
    storage (scoring.score._device_model honors the marker): double the
    HBM-hot residency per byte, f32 accumulation in the gather-dot
    kernel, ~2^-8 relative score drift vs the f32 stack (documented
    tolerance).  Host matrices stay float64 either way."""
    thetas, ps = [], []
    ip_base: dict = {}
    word_base: dict = {}
    ip_off = word_off = 0
    for t in tenants:
        m = snaps[t].model
        ip_base[t] = ip_off
        word_base[t] = word_off
        thetas.append(np.asarray(m.theta, np.float64))
        ps.append(np.asarray(m.p, np.float64))
        ip_off += m.theta.shape[0]
        word_off += m.p.shape[0]
    capacity = 0
    if tier is not None:
        capacity = int(tier["capacity"])
        pad_ip = capacity * int(tier["ip_slot"]) - ip_off
        pad_word = capacity * int(tier["word_slot"]) - word_off
        if pad_ip < 0 or pad_word < 0:
            raise RuntimeError(
                f"capacity tier {tier} cannot hold {len(tenants)} "
                f"members ({ip_off}/{word_off} rows)"
            )
        if pad_ip:
            thetas.append(np.zeros((pad_ip, k)))
        if pad_word:
            ps.append(np.zeros((pad_word, k)))
    stacked = ScoringModel(
        ip_index={}, theta=np.concatenate(thetas),
        word_index={}, p=np.concatenate(ps),
    )
    if precision == "bf16":
        stacked._device_dtype = "bfloat16"
    return StackedSnapshot(
        k=k, tenants=tuple(tenants), model=stacked, members=dict(snaps),
        ip_base=ip_base, word_base=word_base, stack_version=stack_version,
        capacity=capacity, precision=precision,
    )


class _TenantRegistryView:
    """ModelRegistry facade for ONE tenant of a FleetRegistry — what a
    per-tenant RefreshLoop binds to, so the refresh machinery works
    unchanged while its publishes route through the fleet's stack
    rebuild."""

    def __init__(self, fleet: "FleetRegistry", tenant: str) -> None:
        self._fleet = fleet
        self._tenant = tenant

    def publish(self, model: ScoringModel, source: str) -> ModelSnapshot:
        return self._fleet.publish(self._tenant, model, source)

    def active(self) -> ModelSnapshot:
        return self._fleet.active(self._tenant)

    def previous(self) -> "ModelSnapshot | None":
        return self._fleet.previous(self._tenant)

    @property
    def version(self) -> int:
        return self._fleet.version(self._tenant)


class FleetRegistry:
    """N per-tenant ModelRegistries + per-K stacked snapshots with
    double-buffered installs.  `journal`/`recorder` are optional
    telemetry hooks: every publish journals a `{"kind":
    "fleet_publish"}` record and bumps `serve.<tenant>.publishes`."""

    def __init__(self, journal=None, recorder=None, *,
                 capacity_tiers: bool = False,
                 stack_precision: str = "f32") -> None:
        if stack_precision not in ("f32", "bf16"):
            raise ValueError(
                f"stack_precision must be f32|bf16, got {stack_precision!r}"
            )
        self._lock = threading.Lock()
        self._registries: dict[str, ModelRegistry] = {}
        self._specs: dict[str, TenantSpec] = {}
        self._order: list[str] = []
        self._tenant_k: dict[str, int] = {}
        self._stacks: dict[int, StackedSnapshot] = {}
        self._stack_builds: dict[int, int] = {}
        # -- tiered residency state (serving/residency.py drives it) --
        # _hot: stack membership per tenant (True = HBM-hot).  Legacy
        # fleets never flip it, so every published tenant stays
        # stack-resident.  _tenant_rows remembers each tenant's
        # (theta, p) row counts across cold unloads so the capacity
        # tier's slot budgets survive paging; _tiers holds the per-K
        # high-water {capacity, ip_slot, word_slot} — monotone, so
        # shrinking census never shrinks the compiled shape.
        self._hot: dict[str, bool] = {}
        self._tenant_rows: dict[str, tuple] = {}
        self._tiers: dict[int, dict] = {}
        self._capacity_tiers = capacity_tiers
        self._stack_precision = stack_precision
        self._journal = getattr(journal, "journal", journal)
        self._recorder = recorder

    @property
    def capacity_tiers(self) -> bool:
        return self._capacity_tiers

    @property
    def stack_precision(self) -> str:
        return self._stack_precision

    # -- tenant membership --------------------------------------------------

    def add_tenant(self, spec: TenantSpec, *, hot: bool = True) -> None:
        """Register one tenant.  `hot=False` (the tiered-residency
        startup path) keeps the tenant OUT of the stacked snapshot until
        a promotion admits it — a thousand-tenant fleet then pays one
        stack build per hot slot, not one per tenant."""
        with self._lock:
            if spec.tenant in self._registries:
                raise ValueError(f"tenant {spec.tenant!r} already added")
            self._registries[spec.tenant] = ModelRegistry()
            self._specs[spec.tenant] = spec
            self._order.append(spec.tenant)
            self._hot[spec.tenant] = hot

    def tenants(self) -> "list[str]":
        with self._lock:
            return list(self._order)

    def spec(self, tenant: str) -> TenantSpec:
        with self._lock:
            return self._specs[tenant]

    def view(self, tenant: str) -> _TenantRegistryView:
        self._registry(tenant)          # raise early on unknown tenant
        return _TenantRegistryView(self, tenant)

    def _registry(self, tenant: str) -> ModelRegistry:
        with self._lock:
            reg = self._registries.get(tenant)
        if reg is None:
            raise KeyError(
                f"unknown tenant {tenant!r} (known: {self.tenants()})"
            )
        return reg

    # -- publish / read -----------------------------------------------------

    def publish(self, tenant: str, model: ScoringModel,
                source: str) -> ModelSnapshot:
        """Validate and atomically promote `model` for ONE tenant, then
        install a rebuilt stacked snapshot for its K-group.  The
        per-tenant swap has registry.py semantics (validation failure
        leaves the active snapshot untouched); the stack rebuild runs
        outside the lock and never blocks another tenant's scoring."""
        reg = self._registry(tenant)
        snap = reg.publish(model, source)     # validates; per-tenant swap
        k = model.theta.shape[1]
        with self._lock:
            old_k = self._tenant_k.get(tenant)
            self._tenant_k[tenant] = k
            self._tenant_rows[tenant] = (
                model.theta.shape[0], model.p.shape[0],
            )
            stale = old_k if old_k is not None and old_k != k else None
            hot = self._hot.get(tenant, True)
        if stale is not None:
            self._refresh_stack(stale)
        if hot:
            self._refresh_stack(k)
        if self._journal is not None:
            self._journal.append({
                "kind": "fleet_publish", "tenant": tenant,
                "version": snap.version, "source": source, "k": k,
                "ip_rows": model.theta.shape[0],
                "word_rows": model.p.shape[0],
            })
        if self._recorder is not None:
            self._recorder.counter(f"serve.{tenant}.publishes").add(1)
        return snap

    def load_day(self, tenant: str, day_dir: str,
                 fallback: float) -> ModelSnapshot:
        """registry.load_day for one tenant — read the artifacts
        through the per-tenant registry's loader, publish through the
        fleet so the stack rebuilds."""
        doc = ModelRegistry()
        snap = doc.load_day(day_dir, fallback)
        return self.publish(tenant, snap.model, source=day_dir)

    def active(self, tenant: str) -> ModelSnapshot:
        return self._registry(tenant).active()

    def previous(self, tenant: str) -> "ModelSnapshot | None":
        return self._registry(tenant).previous()

    def version(self, tenant: str) -> int:
        return self._registry(tenant).version

    # -- stacked snapshots --------------------------------------------------

    def tenant_k(self, tenant: str) -> int:
        with self._lock:
            k = self._tenant_k.get(tenant)
        if k is None:
            raise RuntimeError(
                f"tenant {tenant!r} has no published model yet"
            )
        return k

    def stack(self, k: int) -> StackedSnapshot:
        with self._lock:
            snap = self._stacks.get(k)
        if snap is None:
            raise RuntimeError(f"no stacked snapshot for K={k}")
        return snap

    def stack_for(self, tenant: str) -> StackedSnapshot:
        return self.stack(self.tenant_k(tenant))

    def _tier_locked(self, k: int, census: int) -> "dict | None":
        """Caller holds self._lock.  The K-group's capacity tier:
        power-of-two tenant-slot count covering the hot-census
        high-water, slot row budgets covering the largest tenant the
        group KNOWS (hot, warm, or cold — a warm tenant must fit its
        slot the day it promotes without changing the compiled shape).
        Monotone: census shrink never shrinks a tier, so the program
        family only changes when the census first crosses a
        power-of-two boundary (or a strictly larger tenant joins the
        group)."""
        if not self._capacity_tiers:
            return None
        ip_slot = word_slot = 1
        for t in self._order:
            if self._tenant_k.get(t) != k:
                continue
            rows = self._tenant_rows.get(t)
            if rows is not None:
                ip_slot = max(ip_slot, _pow2(rows[0]))
                word_slot = max(word_slot, _pow2(rows[1]))
        prev = self._tiers.get(k, {})
        tier = {
            "capacity": max(_pow2(census), prev.get("capacity", 1)),
            "ip_slot": max(ip_slot, prev.get("ip_slot", 1)),
            "word_slot": max(word_slot, prev.get("word_slot", 1)),
        }
        self._tiers[k] = tier
        return tier

    def tier(self, k: int) -> "dict | None":
        """The K-group's current capacity tier (None when capacity
        tiers are off) — what the shape-stability tests assert on."""
        with self._lock:
            t = self._tiers.get(k)
            return dict(t) if t is not None else None

    def _refresh_stack(self, k: int) -> None:
        """Rebuild the K-group's stacked snapshot from the HOT members'
        CURRENT actives and install it — concatenation runs outside the
        lock; the install re-checks that no member published (or paged)
        meanwhile (loop until the built stack matches the live member
        versions, so concurrent publishes converge on a stack
        containing both)."""
        while True:
            with self._lock:
                members = [
                    t for t in self._order
                    if self._tenant_k.get(t) == k
                    and self._hot.get(t, True)
                ]
                regs = {t: self._registries[t] for t in members}
                tier = self._tier_locked(k, len(members))
            try:
                snaps = {t: regs[t].active() for t in members}
            except RuntimeError:
                # A member snapshotted as hot was paged out (and its
                # registry unloaded) while we held no lock — its
                # membership flip already re-queued a rebuild; retry
                # against the fresh census.
                continue
            if not snaps:
                with self._lock:
                    self._stacks.pop(k, None)
                return
            with self._lock:
                self._stack_builds[k] = self._stack_builds.get(k, 0) + 1
                build = self._stack_builds[k]
            built = _build_stack(k, members, snaps, build, tier=tier,
                                 precision=self._stack_precision)
            with self._lock:
                live = {
                    t: self._registries[t].version
                    for t in members
                    if self._tenant_k.get(t) == k
                    and self._hot.get(t, True)
                }
                if live == {t: s.version for t, s in snaps.items()}:
                    cur = self._stacks.get(k)
                    if cur is None or cur.stack_version < build:
                        self._stacks[k] = built
                    return
            # a member published (or paged) while we concatenated —
            # rebuild.

    # -- tiered residency hooks (serving/residency.py) ---------------------

    def is_hot(self, tenant: str) -> bool:
        with self._lock:
            return self._hot.get(tenant, True)

    def hot_census(self, k: int) -> "list[str]":
        """HOT members of the K-group, in registration order."""
        with self._lock:
            return [
                t for t in self._order
                if self._tenant_k.get(t) == k and self._hot.get(t, True)
            ]

    def set_hot(self, tenant: str, hot: bool) -> None:
        """Flip one tenant's stack membership and rebuild its K-group's
        stacked snapshot — the promotion/eviction primitive.  The
        rebuild runs OUTSIDE the lock exactly like a hot-swap publish,
        so resident tenants' scoring never stalls on another tenant's
        paging; under capacity tiers the stacked shape is unchanged,
        so the compiled program family survives too."""
        self.set_hot_many({tenant: hot})

    def set_hot_many(self, changes: "dict[str, bool]") -> None:
        """Flip several memberships with ONE stack rebuild per affected
        K-group — a paired promotion+eviction costs one concatenation,
        not two."""
        for tenant in changes:
            self._registry(tenant)      # raise early on unknown tenant
        ks: set = set()
        with self._lock:
            for tenant, hot in changes.items():
                if self._hot.get(tenant, True) == hot:
                    continue
                self._hot[tenant] = hot
                k = self._tenant_k.get(tenant)
                if k is not None:
                    ks.add(k)
        for k in sorted(ks):
            self._refresh_stack(k)

    def unload_tenant(self, tenant: str) -> "ModelSnapshot | None":
        """Drop one NON-hot tenant's host-resident snapshot (keeping
        its version counter) — the warm→cold demotion.  Returns the
        snapshot that was active so the caller can checkpoint it."""
        if self.is_hot(tenant):
            raise RuntimeError(
                f"tenant {tenant!r} is stack-resident — evict to warm "
                "before unloading to cold"
            )
        return self._registry(tenant).unload()

    def restore_tenant(self, tenant: str, model: ScoringModel,
                       source: str, version: int) -> ModelSnapshot:
        """Reinstall a cold tenant's checkpointed model at its original
        version — the cold→warm promotion.  Does NOT touch the stack;
        a subsequent set_hot(tenant, True) completes warm→hot."""
        return self._registry(tenant).restore(model, source, version)

    def loaded(self, tenant: str) -> bool:
        return self._registry(tenant).loaded


def tenant_pairs(feats, dsource: str, model: ScoringModel,
                 ip_base: int, word_base: int):
    """One tenant segment's (ip_rows, word_rows) in STACKED coordinates
    plus its pairs-per-event multiplicity: flow events contribute two
    (endpoint, word) pairs each — src block then dst block, min-combined
    at demux (flow_post_lda.scala:227-239) — DNS and other client-keyed
    sources one.  The per-source pair layout comes from the source
    spec's `event_pairs` hook, so a new registered source serves through
    this path with zero edits here.  Row lookups go through the tenant's
    OWN index maps (misses land on the tenant's fallback row), then
    shift by the tenant's base offset into the stacked matrices: the
    tenant-id column realized as an index offset, which is what lets one
    compiled gather serve every tenant."""
    from ..sources import get as get_source

    pairs = get_source(dsource).event_pairs(feats)
    ip = np.concatenate(
        [model.ip_rows(keys) for keys, _ in pairs]
    ) + np.int32(ip_base)
    w = np.concatenate(
        [model.word_rows(words) for _, words in pairs]
    ) + np.int32(word_base)
    return ip.astype(np.int32), w.astype(np.int32), len(pairs)


def demux_scores(scores_seg: np.ndarray, mult: int) -> np.ndarray:
    """Per-event scores from a tenant's pair-score segment: multi-pair
    sources (flow's mult=2 src/dst blocks) min-combine block-wise,
    single-pair sources pass through."""
    if mult == 2:
        n = scores_seg.shape[0] // 2
        return np.minimum(scores_seg[:n], scores_seg[n:])
    if mult > 2:
        n = scores_seg.shape[0] // mult
        return scores_seg.reshape(mult, n).min(axis=0)
    return scores_seg


class FleetScorer:
    """Cross-tenant micro-batching front end over a FleetRegistry.

    `featurizers` maps tenant -> serving featurizer (serving/events.py
    semantics: validate one event, featurize a list, name its dsource).
    `on_batch(tenant, snapshot, feats, scores)` runs per tenant segment
    after each flush — per-tenant refresh loops and flagged-event sinks
    hang off it.  Flush triggers (`fleet_max_batch` /
    `fleet_max_wait_ms`) resolve through the plan layer exactly like
    the single-model scorer's serve_max_batch/serve_max_wait_ms."""

    def __init__(
        self,
        fleet: FleetRegistry,
        featurizers: dict,
        config: "ServingConfig | None" = None,
        metrics: "MetricsEmitter | None" = None,
        on_batch=None,
        journal=None,
        residency=None,
        dynamic: bool = False,
    ) -> None:
        self.fleet = fleet
        self.config = config or ServingConfig()
        # Tiered residency (serving/residency.py): when attached, the
        # worker drains only HBM-hot tenants' lanes; a non-hot tenant's
        # admission requests an async promotion and its events wait in
        # their own bounded lane — the promotion miss shows up as THAT
        # tenant's latency, never as a stall on a resident tenant.
        self._residency = residency
        from ..plans import resolve

        mb, mb_src = resolve("fleet_max_batch", self.config.fleet_max_batch)
        mw, mw_src = resolve("fleet_max_wait_ms",
                             self.config.fleet_max_wait_ms)
        self.metrics = metrics
        self.on_batch = on_batch
        self._journal = getattr(journal, "journal", journal) \
            if journal is not None \
            else (metrics._journal if metrics is not None else None)
        # `dynamic=True` (the replicated-serving replica path,
        # serving/replica.py): the scorer starts with however many
        # tenants the registry knows — possibly zero — and grows lanes
        # at runtime via add_tenant() as the router places tenants on
        # this replica.  The worker simply parks on "no drainable
        # lane" until the first lane appears.
        self._dynamic = dynamic
        self._lanes: dict[str, TenantLane] = {}
        for tenant in fleet.tenants():
            spec = fleet.spec(tenant)
            fz = featurizers.get(tenant)
            if fz is None:
                raise ValueError(f"no featurizer for tenant {tenant!r}")
            self._lanes[tenant] = self._make_lane(spec, fz)
        if not self._lanes and not dynamic:
            raise ValueError("FleetScorer needs at least one tenant")
        # Remember the plan resolution so the dynamic add_tenant path
        # can re-apply the degradation guard as capacity grows.
        self._plan_max_batch = int(mb)
        self._plan_max_batch_src = mb_src
        total_capacity = sum(l.queue_max for l in self._lanes.values())
        if self._lanes and mb_src == "plan" and int(mb) > total_capacity:
            # Same degradation guard as BatchScorer: a plan flush size
            # above the fleet's total admission capacity would make the
            # max_batch trigger unreachable (every flush silently
            # becomes the latency timer) — fall back to the default.
            mb, mb_src = self.config.fleet_max_batch, "default"
        self.max_batch = int(mb)
        self.max_wait_ms = float(mw)
        # Featurize plane (sources/device.py): which engine builds word
        # rows on the flush path, and the pow2 pad floor for the fused
        # dispatch.  Resolved once at construction — engine swaps are a
        # restart, like every other serving engine knob.
        eng, eng_src = resolve_engine(self.config.featurize_engine)
        self._featurize_engine = eng
        fb, fb_src = resolve("featurize_block", self.config.featurize_block)
        self._featurize_block = int(fb)
        # Size-aware engine gate: below the measured break-even a
        # device featurize dispatch LOSES to the vectorized host parse
        # on pure glue (the 0.91x paged A/B), so small segments stay
        # host-side even under a device/fused engine.  Resolved once,
        # like the engine itself.
        if eng == "host":
            be, be_src = 1, "engine"
        else:
            from ..sources.device import resolve_break_even

            be, be_src = resolve_break_even(
                self.config.featurize_break_even)
        self._featurize_break_even = int(be)
        self.plan = {
            "max_batch": {"value": self.max_batch, "source": mb_src},
            "max_wait_ms": {"value": self.max_wait_ms, "source": mw_src},
            "featurize_engine": {"value": eng, "source": eng_src},
            "featurize_block": {"value": self._featurize_block,
                                "source": fb_src},
            "featurize_break_even": {
                "value": self._featurize_break_even, "source": be_src},
        }
        if self.max_batch < 1:
            raise ValueError(f"fleet_max_batch ({self.max_batch}) must "
                             "be >= 1")
        if self.max_wait_ms <= 0:
            raise ValueError(
                f"fleet_max_wait_ms must be > 0, got {self.max_wait_ms}"
            )
        if self.config.device_score_min in (0, "auto"):
            # Pay the one-time host-vs-device calibration at
            # construction, never inside a latency-bounded flush
            # (BatchScorer's contract).
            from ..scoring import dispatch_calibration

            dispatch_calibration()
        self._cond = threading.Condition()
        self._closed = False
        self._force_flush = False
        self._batch_seq = 0
        self._events_scored = 0
        import contextvars

        if self._residency is not None:
            # Promotion completions must wake a worker parked on "no
            # drainable lane"; the waker only touches the condvar, so
            # the pager thread never nests the manager lock inside it.
            self._residency.add_waker(self._wake)
        ctx = contextvars.copy_context()
        self._worker = threading.Thread(
            target=lambda: ctx.run(self._run),
            name="oni-fleet-scorer", daemon=True,
        )
        self._worker.start()

    def _make_lane(self, spec: TenantSpec, fz) -> TenantLane:
        """Validated lane construction — shared by __init__ and the
        dynamic add_tenant path so both enforce the same
        dsource/queue/admission resolution."""
        if getattr(fz, "dsource", None) != spec.dsource:
            raise ValueError(
                f"tenant {spec.tenant!r} declares dsource "
                f"{spec.dsource!r} but its featurizer is "
                f"{getattr(fz, 'dsource', None)!r}"
            )
        lane = TenantLane(
            spec=spec,
            featurizer=fz,
            queue_max=spec.queue_max or self.config.tenant_queue_max,
            admission=spec.admission or self.config.admission,
            threshold=(spec.threshold
                       if spec.threshold is not None
                       else self.config.threshold),
        )
        if lane.queue_max < 1:
            raise ValueError(
                f"tenant {lane.spec.tenant!r} queue_max must be >= 1"
            )
        return lane

    def add_tenant(self, spec: TenantSpec, featurizer) -> None:
        """Grow one admission lane at runtime (dynamic fleets only —
        the replicated-serving router places tenants on a running
        replica).  The tenant must already be registered (and
        published) in the FleetRegistry; the new lane becomes
        drainable on the next take."""
        if not self._dynamic:
            raise RuntimeError(
                "add_tenant on a static FleetScorer — construct with "
                "dynamic=True"
            )
        self.fleet.spec(spec.tenant)    # raise early on unknown tenant
        lane = self._make_lane(spec, featurizer)
        with self._cond:
            if self._closed:
                raise RuntimeError("FleetScorer is closed")
            if spec.tenant in self._lanes:
                raise ValueError(
                    f"tenant {spec.tenant!r} already has a lane"
                )
            self._lanes[spec.tenant] = lane
            # Re-apply the plan-flush degradation guard at the grown
            # capacity: a plan-sourced max_batch above the fleet's
            # total admission capacity is unreachable (silent
            # latency-timer flushes); once capacity covers it, the
            # measured plan value takes effect.
            if self._plan_max_batch_src == "plan":
                total = sum(l.queue_max for l in self._lanes.values())
                if self._plan_max_batch > total:
                    self.max_batch = self.config.fleet_max_batch
                    src = "default"
                else:
                    self.max_batch = self._plan_max_batch
                    src = "plan"
                self.plan["max_batch"] = {
                    "value": self.max_batch, "source": src,
                }
            self._cond.notify_all()

    def _wake(self) -> None:
        with self._cond:
            self._cond.notify_all()

    # -- producer side ------------------------------------------------------

    def submit(self, tenant: str, raw):
        """Enqueue one raw event for `tenant`.  Raises ValueError on a
        malformed event (never enqueued), KeyError on an unknown
        tenant, RuntimeError after close().  A full tenant queue either
        BLOCKS (admission="block" — backpressure, the stall priced into
        `serve.<tenant>.admission_stall_s` and journaled like a
        dataplane edge) or raises AdmissionRejected
        (admission="reject" — load shedding, journaled as
        `{"kind": "admission_reject"}`)."""
        lane = self._lanes.get(tenant)
        if lane is None:
            raise KeyError(
                f"unknown tenant {tenant!r} "
                f"(known: {sorted(self._lanes)})"
            )
        admit = getattr(lane.featurizer, "admit", None)
        if admit is not None:
            # Edge columnar parse: the line splits ONCE here; the flush
            # path reuses the row (device featurize consumes it
            # directly, the host oracle still gets `raw`).
            validated, row = admit(raw)
        else:
            validated = lane.featurizer.validate(raw)
            row = None
        reject_info = None
        with self._cond:
            if self._closed:
                raise RuntimeError("FleetScorer is closed")
            if lane.full_locked() and lane.admission == "reject":
                lane.rejected += 1
                reject_info = (len(lane.pending), lane.queue_max)
            else:
                wait_ns = 0
                t0 = None
                while not self._closed and lane.full_locked():
                    if t0 is None:
                        t0 = time.perf_counter_ns()
                    self._cond.wait()
                if t0 is not None:
                    wait_ns = time.perf_counter_ns() - t0
                    lane.admission_stall_ns += wait_ns
                if self._closed:
                    raise RuntimeError("FleetScorer is closed")
                p = _PendingEvent(validated, time.perf_counter(), row)
                lane.pending.append(p)
                lane.submitted += 1
                depth = len(lane.pending)
                self._cond.notify_all()
        if reject_info is not None:
            depth, capacity = reject_info
            self._journal_safe({
                "kind": "admission_reject", "tenant": tenant,
                "depth": depth, "capacity": capacity,
            })
            if self.metrics is not None:
                self.metrics.recorder.counter(
                    f"serve.{tenant}.admission_rejects"
                ).add(1)
            raise AdmissionRejected(tenant, depth, capacity)
        if wait_ns and self.metrics is not None:
            self.metrics.recorder.histogram(
                f"serve.{tenant}.admission_stall_s"
            ).observe(wait_ns / 1e9)
        if wait_ns:
            # The dataplane's stall-pricing record shape (channel.py
            # _note), on the admission edge: the fleet's ingress
            # backpressure shows up in trace_view next to every other
            # priced stall.
            self._journal_safe({
                "kind": "dataplane", "event": "depth",
                "edge": f"admit.{tenant}", "side": "put",
                "depth": depth, "wait_s": round(wait_ns / 1e9, 6),
            })
        if self._residency is not None:
            # Outside _cond: the residency manager has its own lock and
            # pager thread, and nesting it under the scorer's condvar
            # would deadlock against the promotion waker.  The touch is
            # the LRU/LFU admission signal; a non-hot tenant's touch
            # enqueues an async promotion (idempotent).
            self._residency.note_admission(tenant)
        return p.future

    def flush(self) -> None:
        """Flush whatever is queued without waiting for either trigger
        (no-op on an empty fleet queue — BatchScorer semantics)."""
        with self._cond:
            if any(lane.pending for lane in self._lanes.values()):
                self._force_flush = True
                self._cond.notify_all()

    def close(self, timeout: "float | None" = None) -> bool:
        """Drain every tenant queue, then stop the worker.  With a
        finite timeout, an overlong drain FAILS the still-queued
        futures and returns False instead of abandoning them."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout)
        if not self._worker.is_alive():
            return True
        undrained: list = []
        with self._cond:
            for lane in self._lanes.values():
                undrained.extend(lane.pending)
                lane.pending.clear()
        err = RuntimeError(
            f"FleetScorer.close timed out after {timeout}s with "
            f"{len(undrained)} events undrained"
        )
        for p in undrained:
            p.future._fail(err)
        return False

    @property
    def events_scored(self) -> int:
        with self._cond:
            return self._events_scored

    @property
    def batches_flushed(self) -> int:
        with self._cond:
            return self._batch_seq

    def tenant_stats(self) -> "list[dict]":
        with self._cond:
            return [self._lanes[t].stats_locked()
                    for t in sorted(self._lanes)]

    def tenant_threshold(self, tenant: str) -> float:
        """The resolved suspicion threshold for one tenant (spec
        override, else the fleet config) — the ONE resolution, so
        flagged-event consumers can't drift from the lane's own
        flagged accounting."""
        return self._lanes[tenant].threshold

    # -- worker side --------------------------------------------------------

    def _request_stranded_locked(self) -> None:
        """Caller holds self._cond.  An event admitted while its tenant
        was hot strands if the tenant is evicted before the drain (no
        later admission re-triggers paging): re-request promotion for
        every pending, non-drainable lane.  Lock ordering is safe one
        way — the manager never acquires the scorer's condvar while
        holding its own lock (wakers fire lock-free)."""
        if self._residency is None:
            return
        ready = self._residency.drainable
        stranded = [
            l.spec.tenant for l in self._lanes.values()
            if l.pending and l.spec.tenant not in ready
        ]
        if stranded:
            self._residency.request_promotions(stranded)

    def _drainable_locked(self) -> "list[TenantLane]":
        """Caller holds self._cond.  Lanes the worker may drain NOW:
        pending events whose tenant is HBM-hot (or residency off).
        After close() every lane drains — a still-paging tenant's
        events resolve through the solo fallback instead of wedging
        shutdown.  A paging tenant's lane is simply invisible to the
        flush triggers: its events wait out the promotion in their own
        bounded queue while resident tenants keep flushing."""
        lanes = self._lanes.values()
        if self._residency is None or self._closed:
            return [l for l in lanes if l.pending]
        ready = self._residency.drainable
        # Unmanaged tenants (in the fleet but never registered with
        # the residency manager) keep legacy always-drainable behavior
        # — they can never be promoted, so gating them on the hot set
        # would park their events until shutdown.
        return [l for l in lanes
                if l.pending and (l.spec.tenant in ready
                                  or not self._residency.is_managed(
                                      l.spec.tenant))]

    def _take_batch(self):
        """Block until a flush trigger fires; returns (batch, trigger,
        total_depth_after) where batch is [(tenant, _PendingEvent)]
        drained GLOBALLY OLDEST-FIRST across the drainable tenant
        queues — the no-head-of-line-blocking drain: a bursty tenant
        fills its own bounded queue, but cannot delay an older event of
        another tenant.  Empty batch means shutdown."""
        max_wait_s = self.max_wait_ms / 1e3
        lanes = self._lanes
        with self._cond:
            while not self._closed and not self._drainable_locked():
                self._request_stranded_locked()
                self._cond.wait()
            if not self._drainable_locked():
                return [], "shutdown", 0
            trigger = "close" if self._closed else None
            while trigger is None:
                ready = self._drainable_locked()
                if not ready:
                    # Every drainable lane was taken by a promotion
                    # reversal mid-wait; park again.
                    self._request_stranded_locked()
                    self._cond.wait()
                    if self._closed:
                        trigger = "close"
                    continue
                if self._force_flush:
                    trigger = "flush"
                    break
                total = sum(len(l.pending) for l in ready)
                if total >= self.max_batch:
                    trigger = "max_batch"
                    break
                oldest = min(l.pending[0].t_enqueue for l in ready)
                waited = time.perf_counter() - oldest
                if waited >= max_wait_s:
                    trigger = "max_wait"
                    break
                self._cond.wait(max_wait_s - waited)
                if self._closed:
                    trigger = "close"
            self._force_flush = False
            # K-way merge on enqueue time via a heap of lane heads:
            # O(batch log tenants) while holding the lock every
            # submitter shares — a linear scan per taken event would
            # make admission stalls scale with tenant count.
            heads = [
                (lane.pending[0].t_enqueue, lane.spec.tenant)
                for lane in self._drainable_locked()
            ]
            heapq.heapify(heads)
            batch: list = []
            while heads and len(batch) < self.max_batch:
                _, t = heapq.heappop(heads)
                lane = lanes[t]
                batch.append((t, lane.pending.popleft()))
                if lane.pending:
                    heapq.heappush(
                        heads, (lane.pending[0].t_enqueue, t)
                    )
            depth = sum(len(l.pending) for l in lanes.values())
            self._cond.notify_all()   # release blocked submitters
            return batch, trigger, depth

    def _run(self) -> None:
        while True:
            batch, trigger, depth = self._take_batch()
            if not batch:
                return
            try:
                self._score_batch(batch, trigger, depth)
            except Exception as e:
                # The worker survives anything a batch throws; futures
                # already resolved keep their scores, the rest fail
                # with the cause (BatchScorer contract).
                for _, p in batch:
                    p.future._fail(e)

    def _lane_features(self, lane, items, model):
        """Featurize one tenant segment: device-compiled tables when the
        engine allows it, the model snapshot is known, AND every pending
        event carried an admission-parsed row — otherwise the host
        featurizer (the golden oracle; also the fallback for unlowerable
        vocabularies, which `device_batch` reports as None after
        journaling one `featurize_compile` record)."""
        if (model is not None and self._featurize_engine != "host"
                and len(items) >= self._featurize_break_even):
            rows = [p.row for p in items]
            if all(r is not None for r in rows):
                batch, info = device_batch(
                    lane.featurizer, rows, [p.raw for p in items], model,
                )
                if info is not None:
                    self._journal_safe(info)
                if batch is not None:
                    return batch
        return lane.featurizer([p.raw for p in items])

    @staticmethod
    def _pair_rows(feats, dsource: str, model: ScoringModel,
                   ip_base: int, word_base: int):
        """tenant_pairs through the device featurizer's LUT gather when
        the segment was device-featurized against THIS model (identity
        check: a republish between featurize and score falls back to the
        host oracle rather than gathering stale rows)."""
        if isinstance(feats, DeviceBatch) and feats.model is model:
            return feats.pair_rows(ip_base, word_base)
        return tenant_pairs(feats, dsource, model, ip_base, word_base)

    def _fused_group(self, tenant, stack, feats_by_tenant, tenant_scores,
                     tenant_snaps, tenant_device, failures) -> bool:
        """The fused single-dispatch flush path (featurize+gather+dot in
        one jit program, ops/featurize_kernel.py) for a single-tenant
        K-group whose segment was device-featurized against the stack
        member's model.  Returns False — caller runs the generic packed
        path — whenever the preconditions don't hold; returns True with
        scores demuxed on success (and on failure, which is recorded
        like any other group failure)."""
        feats = feats_by_tenant[tenant]
        member = stack.members[tenant]
        if not (isinstance(feats, DeviceBatch)
                and feats.model is member.model):
            return False
        try:
            from ..scoring.pipeline import fused_featurize_scores

            dev, codes, ip = feats.fused_operands(stack.ip_base[tenant])
            t_g0 = time.perf_counter()
            pair_scores = fused_featurize_scores(
                stack.model, dev, codes, ip,
                word_base=stack.word_base[tenant],
                block=self._featurize_block,
            )
            if self.metrics is not None:
                rec = self.metrics.recorder
                rec.histogram("serve.device_score_ms").observe(
                    (time.perf_counter() - t_g0) * 1e3
                )
                rec.counter("serve.device_events").add(
                    feats.num_raw_events
                )
            tenant_scores[tenant] = demux_scores(
                pair_scores, dev.pairs_per_event
            )
            tenant_snaps[tenant] = member
            tenant_device[tenant] = True
        except Exception as e:
            failures.setdefault(tenant, e)
        return True

    def _score_batch(self, batch, trigger: str, depth: int) -> None:
        cfg = self.config
        t0 = time.perf_counter()
        # Segment the drained batch per tenant (submit order preserved
        # inside each segment), then group tenants by topic count K:
        # one stacked snapshot — one compiled dispatch — per group.
        segments: dict[str, list] = {}
        for tenant, p in batch:
            segments.setdefault(tenant, []).append(p)
        stacks: dict[int, "StackedSnapshot | None"] = {}
        tenant_scores: dict[str, np.ndarray] = {}
        tenant_snaps: dict = {}
        failures: dict[str, Exception] = {}
        groups: dict[int, list] = {}
        solo: list = []
        feats_by_tenant: dict = {}
        # Each tenant's K is read ONCE here and reused at demux/emit:
        # a concurrent publish may change a tenant's K mid-flush, and a
        # re-read after scoring would look up a stack this flush never
        # grabbed (KeyError failing OTHER tenants' futures too).
        tenant_ks: dict[str, int] = {}
        for tenant, items in segments.items():
            lane = self._lanes[tenant]
            try:
                k = self.fleet.tenant_k(tenant)
                tenant_ks[tenant] = k
                if k not in stacks:
                    try:
                        stacks[k] = self.fleet.stack(k)
                    except RuntimeError:
                        # No hot member in the K-group at all (every
                        # tenant paged out) — the group scores solo.
                        stacks[k] = None
                stack = stacks[k]
                member = (stack.members.get(tenant)
                          if stack is not None else None)
                feats = self._lane_features(
                    lane, items,
                    member.model if member is not None else None,
                )
                if feats.num_raw_events != len(items):
                    raise RuntimeError(
                        f"tenant {tenant!r} featurizer returned "
                        f"{feats.num_raw_events} rows for "
                        f"{len(items)} events"
                    )
                feats_by_tenant[tenant] = feats
                if member is not None:
                    groups.setdefault(k, []).append(tenant)
                else:
                    # Residency miss at scoring time (tenant evicted
                    # between take and score, or a close-time drain of
                    # a still-paging lane): score against the tenant's
                    # OWN registry snapshot.  The gather-dot is per-row
                    # arithmetic, so on the default f32 stack solo
                    # scores are bit-identical to packed ones.  Under
                    # stack_precision="bf16" the solo path scores at
                    # FULL precision (the registry model carries no
                    # storage marker), so it agrees with the packed
                    # path within bf16's documented tolerance, not
                    # bitwise — strictly more accurate, never wrong.
                    solo.append(tenant)
            except Exception as e:
                # Tenant-scoped failure isolation: a tenant whose
                # featurization (or stack lookup) fails takes down ITS
                # futures only — the rest of the flush still scores.
                failures[tenant] = e
        dispatches = 0
        device_dispatches = 0
        tenant_device: dict[str, bool] = {}
        for k, group in sorted(groups.items()):
            stack = stacks[k]
            if (self._featurize_engine == "fused" and len(group) == 1
                    and self._fused_group(group[0], stack,
                                          feats_by_tenant, tenant_scores,
                                          tenant_snaps, tenant_device,
                                          failures)):
                dispatches += 1
                device_dispatches += 1
                continue
            try:
                parts = []
                mults = {}
                for tenant in group:
                    ip, w, mult = self._pair_rows(
                        feats_by_tenant[tenant],
                        self._lanes[tenant].spec.dsource,
                        stack.members[tenant].model,
                        stack.ip_base[tenant],
                        stack.word_base[tenant],
                    )
                    parts.append((tenant, ip, w))
                    mults[tenant] = mult
                ip_all = np.concatenate([ip for _, ip, _ in parts])
                w_all = np.concatenate([w for _, _, w in parts])
                # ONE dispatch for the whole K-group: every tenant's
                # pairs ride the same padded compiled program.  The
                # device-path decision is made on the packed PAIR
                # count, not the flush's event count (flow events pack
                # two pairs each, and each K group decides
                # independently); device dispatches feed the serve
                # roofline histograms per GROUP — exact wall, exact
                # events — so a flush mixing device and host groups
                # can never price host scoring as device dispatches.
                is_device = use_device_path(
                    len(ip_all), cfg.device_score_min
                )
                t_g0 = time.perf_counter()
                pair_scores = batched_scores(
                    stack.model, ip_all, w_all, cfg.device_score_min
                )
                dispatches += 1
                if is_device:
                    device_dispatches += 1
                    if self.metrics is not None:
                        rec = self.metrics.recorder
                        rec.histogram("serve.device_score_ms").observe(
                            (time.perf_counter() - t_g0) * 1e3
                        )
                        rec.counter("serve.device_events").add(sum(
                            feats_by_tenant[t].num_raw_events
                            for t in group
                        ))
                off = 0
                for tenant, ip, _ in parts:
                    seg = pair_scores[off:off + len(ip)]
                    off += len(ip)
                    tenant_scores[tenant] = demux_scores(
                        seg, mults[tenant]
                    )
                    tenant_snaps[tenant] = stack.members[tenant]
                    tenant_device[tenant] = is_device
            except Exception as e:
                for tenant in group:
                    failures.setdefault(tenant, e)
        # Solo fallback dispatches — one per missed tenant, each on the
        # tenant's own (unstacked) model.
        for tenant in solo:
            try:
                try:
                    snap = self.fleet.active(tenant)
                except RuntimeError:
                    if self._residency is None:
                        raise
                    # Checkpoint-cold tenant drained NOW (close-time
                    # drain, or a demotion racing this flush): read
                    # the checkpoint through without a tier change —
                    # the events score against the exact unloaded
                    # model at its preserved version instead of
                    # failing.
                    snap = self._residency.read_through(tenant)
                ip, w, mult = tenant_pairs(
                    feats_by_tenant[tenant],
                    self._lanes[tenant].spec.dsource,
                    snap.model, 0, 0,
                )
                is_device = use_device_path(
                    len(ip), cfg.device_score_min
                )
                t_g0 = time.perf_counter()
                pair_scores = batched_scores(
                    snap.model, ip, w, cfg.device_score_min
                )
                dispatches += 1
                if is_device:
                    device_dispatches += 1
                    if self.metrics is not None:
                        rec = self.metrics.recorder
                        rec.histogram("serve.device_score_ms").observe(
                            (time.perf_counter() - t_g0) * 1e3
                        )
                        rec.counter("serve.device_events").add(
                            feats_by_tenant[tenant].num_raw_events
                        )
                tenant_scores[tenant] = demux_scores(pair_scores, mult)
                tenant_snaps[tenant] = snap
                tenant_device[tenant] = is_device
            except Exception as e:
                failures.setdefault(tenant, e)
        t1 = time.perf_counter()
        # Demux: resolve per-tenant futures against the snapshot the
        # segment actually scored on (version isolation: tenant B's
        # futures carry B's version even while A hot-swaps or pages).
        flagged: dict[str, int] = {}
        for tenant, items in segments.items():
            if tenant in failures:
                for p in items:
                    p.future._fail(failures[tenant])
                continue
            scores = tenant_scores[tenant]
            version = tenant_snaps[tenant].version
            for p, s in zip(items, scores):
                p.future._resolve(float(s), version)
            flagged[tenant] = int(
                np.sum(scores < self._lanes[tenant].threshold)
            )
        t2 = time.perf_counter()
        scored_n = sum(
            len(items) for t, items in segments.items()
            if t not in failures
        )
        with self._cond:
            seq = self._batch_seq
            self._batch_seq += 1
            self._events_scored += scored_n
            for tenant, items in segments.items():
                if tenant in failures:
                    continue
                self._lanes[tenant].scored += len(items)
                self._lanes[tenant].flagged += flagged[tenant]
        self._journal_safe({
            "kind": "demux", "batch": seq, "events": len(batch),
            "tenants": len(segments), "segments": dispatches,
            "residency_misses": len(solo),
            "featurize": self._featurize_engine,
            "featurize_device_tenants": sum(
                isinstance(f, DeviceBatch)
                for f in feats_by_tenant.values()
            ),
            "score_ms": round((t1 - t0) * 1e3, 3),
            "demux_ms": round((t2 - t1) * 1e3, 3),
        })
        # Per-tenant consumers + metrics, then the aggregate record.
        # "device" only when at least one K-group's packed dispatch
        # actually took the device path (metrics._count feeds the
        # device roofline histogram off this label, flush-level records
        # only).
        score_s = t1 - t0
        n = len(batch)
        scorer_label = "device" if device_dispatches else "host"
        for tenant, items in sorted(segments.items()):
            if tenant in failures:
                self._emit_safe({
                    "stage": "serve", "tenant": tenant, "batch": seq,
                    "events": len(items),
                    "error": repr(failures[tenant]), "trigger": trigger,
                })
                continue
            k = tenant_ks[tenant]
            snap = tenant_snaps[tenant]
            if self.on_batch is not None:
                try:
                    self.on_batch(tenant, snap, feats_by_tenant[tenant],
                                  tenant_scores[tenant])
                except Exception as e:
                    # Consumer failures never take down scoring.
                    self._emit_safe({
                        "stage": "serve", "tenant": tenant,
                        "batch": seq, "on_batch_error": repr(e),
                    })
            oldest = items[0].t_enqueue
            stack = stacks.get(k)
            self._emit_safe({
                "stage": "serve", "tenant": tenant, "batch": seq,
                "events": len(items), "trigger": trigger,
                "model_version": snap.version,
                # None = a solo (residency-miss) dispatch: the tenant's
                # segment never rode a stacked program this flush.
                "stack_version": (
                    stack.stack_version
                    if stack is not None and tenant in stack.members
                    else None
                ),
                # The tenant's OWN segment's dispatch decision — in a
                # mixed-K flush a host-scored tenant must not be
                # labeled by another group's device dispatch.
                "scorer": ("device" if tenant_device.get(tenant)
                           else "host"),
                "latency_ms": round((t1 - oldest) * 1e3, 3),
                "queue_wait_ms": round((t0 - oldest) * 1e3, 3),
                "score_ms": round(score_s * 1e3, 3),
                "demux_ms": round((t2 - t1) * 1e3, 3),
                "flagged": flagged[tenant],
            })
        oldest_all = batch[0][1].t_enqueue
        self._emit_safe({
            "stage": "serve", "batch": seq, "events": n,
            "tenants": len(segments), "segments": dispatches,
            "segments_device": device_dispatches,
            "trigger": trigger, "scorer": scorer_label,
            "latency_ms": round((t1 - oldest_all) * 1e3, 3),
            "queue_wait_ms": round((t0 - oldest_all) * 1e3, 3),
            "score_ms": round(score_s * 1e3, 3),
            "demux_ms": round((t2 - t1) * 1e3, 3),
            "events_per_sec": round(n / score_s, 1) if score_s else None,
            "queue_depth": depth,
            "flagged": sum(flagged.values()),
        })

    # -- telemetry sinks ----------------------------------------------------

    def _emit_safe(self, record: dict) -> None:
        if self.metrics is None:
            return
        try:
            self.metrics.emit(record)
        except Exception as e:
            import sys

            print(f"fleet metrics emit failed: {e!r}", file=sys.stderr)

    def _journal_safe(self, record: dict) -> None:
        if self._journal is None:
            return
        try:
            self._journal.append(record)
        except Exception as e:
            import sys

            print(f"fleet journal append failed: {e!r}", file=sys.stderr)
