"""Multi-tenant serving fleet: shared device residency, cross-tenant
micro-batch multiplexing, per-tenant SLO isolation.

`ml_ops serve` hosted exactly one model and one stream; a production
deployment scores many tenants/days concurrently on the same devices.
The scarce resources are the device-resident weights and the padded
AOT-warmed compiled-program family (plans/warmup.warmup_serving) — so
the fleet shares THOSE while isolating everything per-tenant:

`FleetRegistry`
    N hot models with per-tenant atomic hot-swap: one
    serving/registry.py `ModelRegistry` per tenant (validation +
    double-buffered publish + monotonic versions, unchanged), plus a
    *stacked snapshot* per topic-count K — every member tenant's
    [D_t+1, K] theta and [V_t+1, K] p concatenated row-wise with
    per-tenant base offsets.  The stack is itself double-buffered: a
    publish rebuilds it OUTSIDE the registry lock and swaps one
    reference, so tenant A's `RefreshLoop` publish never stalls tenant
    B's scoring path, and because every tenant's row count is stable
    across swaps the stacked shape — and therefore the compiled program
    — survives every hot-swap (keyed by shape, not tenant: zero
    retraces).

`FleetScorer`
    Cross-tenant micro-batch multiplexing into ONE compiled dispatch:
    events from every tenant's admission queue drain globally
    oldest-first into a shared micro-batch; each tenant segment
    featurizes with its own day's quantile cuts, maps onto its own
    model slice via `tenant base offset + local row` — the tenant-id
    column driving the on-device gather — and all segments of a
    K-group score as one `batched_scores` call at a shared padded
    shape.  Tenants whose K diverges form their own pack group
    (per-tenant segment dispatch), so heterogeneous fleets degrade to
    more dispatches, never to wrong scores.  Results demux back to
    per-tenant `ScoreFuture`s (journaled as `{"kind": "demux"}`),
    with per-tenant `serve.<tenant>.*` histograms/counters on the
    shared metrics plane and bounded per-tenant admission
    (serving/tenants.py) for ingress isolation.

Correctness invariant, pinned by tests/test_fleet.py: a packed
cross-tenant flush produces bit-identical scores to scoring each
tenant's events alone through `score_features` — packing changes WHICH
dispatch a row rides, never its arithmetic.
"""

from __future__ import annotations

import heapq
import threading
import time
from dataclasses import dataclass

import numpy as np

from ..config import ServingConfig
from ..scoring import ScoringModel
from ..scoring.score import (
    _dns_client_strings,
    _flow_endpoint_strings,
    batched_scores,
    use_device_path,
)
from .metrics import MetricsEmitter
from .registry import ModelRegistry, ModelSnapshot
from .tenants import (
    AdmissionRejected,
    TenantLane,
    TenantSpec,
    _PendingEvent,
)


@dataclass(frozen=True)
class StackedSnapshot:
    """One pack group's shared-residency view: every member tenant's
    theta/p concatenated row-wise (each slice INCLUDES its own fallback
    row, so per-tenant fallback semantics survive packing).  Readers
    treat every field as immutable; a publish installs a fresh instance
    (so the device cache `scoring.score._device_model` hangs off re-
    uploads the new weights exactly once, while in-flight flushes
    finish on the instance — and device buffers — they started with)."""

    k: int
    tenants: tuple[str, ...]
    model: ScoringModel            # stacked [sum(D_t+1), K] / [sum(V_t+1), K]
    members: dict                  # tenant -> ModelSnapshot the stack was built from
    ip_base: dict                  # tenant -> row offset into stacked theta
    word_base: dict                # tenant -> row offset into stacked p
    stack_version: int             # monotonic per K-group build counter

    def version_of(self, tenant: str) -> int:
        return self.members[tenant].version


def _build_stack(k: int, tenants: "list[str]", snaps: dict,
                 stack_version: int) -> StackedSnapshot:
    """Concatenate member models into one stacked ScoringModel.  Pure
    function of the member snapshots — called OUTSIDE any lock."""
    thetas, ps = [], []
    ip_base: dict = {}
    word_base: dict = {}
    ip_off = word_off = 0
    for t in tenants:
        m = snaps[t].model
        ip_base[t] = ip_off
        word_base[t] = word_off
        thetas.append(np.asarray(m.theta, np.float64))
        ps.append(np.asarray(m.p, np.float64))
        ip_off += m.theta.shape[0]
        word_off += m.p.shape[0]
    stacked = ScoringModel(
        ip_index={}, theta=np.concatenate(thetas),
        word_index={}, p=np.concatenate(ps),
    )
    return StackedSnapshot(
        k=k, tenants=tuple(tenants), model=stacked, members=dict(snaps),
        ip_base=ip_base, word_base=word_base, stack_version=stack_version,
    )


class _TenantRegistryView:
    """ModelRegistry facade for ONE tenant of a FleetRegistry — what a
    per-tenant RefreshLoop binds to, so the refresh machinery works
    unchanged while its publishes route through the fleet's stack
    rebuild."""

    def __init__(self, fleet: "FleetRegistry", tenant: str) -> None:
        self._fleet = fleet
        self._tenant = tenant

    def publish(self, model: ScoringModel, source: str) -> ModelSnapshot:
        return self._fleet.publish(self._tenant, model, source)

    def active(self) -> ModelSnapshot:
        return self._fleet.active(self._tenant)

    def previous(self) -> "ModelSnapshot | None":
        return self._fleet.previous(self._tenant)

    @property
    def version(self) -> int:
        return self._fleet.version(self._tenant)


class FleetRegistry:
    """N per-tenant ModelRegistries + per-K stacked snapshots with
    double-buffered installs.  `journal`/`recorder` are optional
    telemetry hooks: every publish journals a `{"kind":
    "fleet_publish"}` record and bumps `serve.<tenant>.publishes`."""

    def __init__(self, journal=None, recorder=None) -> None:
        self._lock = threading.Lock()
        self._registries: dict[str, ModelRegistry] = {}
        self._specs: dict[str, TenantSpec] = {}
        self._order: list[str] = []
        self._tenant_k: dict[str, int] = {}
        self._stacks: dict[int, StackedSnapshot] = {}
        self._stack_builds: dict[int, int] = {}
        self._journal = getattr(journal, "journal", journal)
        self._recorder = recorder

    # -- tenant membership --------------------------------------------------

    def add_tenant(self, spec: TenantSpec) -> None:
        with self._lock:
            if spec.tenant in self._registries:
                raise ValueError(f"tenant {spec.tenant!r} already added")
            self._registries[spec.tenant] = ModelRegistry()
            self._specs[spec.tenant] = spec
            self._order.append(spec.tenant)

    def tenants(self) -> "list[str]":
        with self._lock:
            return list(self._order)

    def spec(self, tenant: str) -> TenantSpec:
        with self._lock:
            return self._specs[tenant]

    def view(self, tenant: str) -> _TenantRegistryView:
        self._registry(tenant)          # raise early on unknown tenant
        return _TenantRegistryView(self, tenant)

    def _registry(self, tenant: str) -> ModelRegistry:
        with self._lock:
            reg = self._registries.get(tenant)
        if reg is None:
            raise KeyError(
                f"unknown tenant {tenant!r} (known: {self.tenants()})"
            )
        return reg

    # -- publish / read -----------------------------------------------------

    def publish(self, tenant: str, model: ScoringModel,
                source: str) -> ModelSnapshot:
        """Validate and atomically promote `model` for ONE tenant, then
        install a rebuilt stacked snapshot for its K-group.  The
        per-tenant swap has registry.py semantics (validation failure
        leaves the active snapshot untouched); the stack rebuild runs
        outside the lock and never blocks another tenant's scoring."""
        reg = self._registry(tenant)
        snap = reg.publish(model, source)     # validates; per-tenant swap
        k = model.theta.shape[1]
        with self._lock:
            old_k = self._tenant_k.get(tenant)
            self._tenant_k[tenant] = k
            stale = old_k if old_k is not None and old_k != k else None
        if stale is not None:
            self._refresh_stack(stale)
        self._refresh_stack(k)
        if self._journal is not None:
            self._journal.append({
                "kind": "fleet_publish", "tenant": tenant,
                "version": snap.version, "source": source, "k": k,
                "ip_rows": model.theta.shape[0],
                "word_rows": model.p.shape[0],
            })
        if self._recorder is not None:
            self._recorder.counter(f"serve.{tenant}.publishes").add(1)
        return snap

    def load_day(self, tenant: str, day_dir: str,
                 fallback: float) -> ModelSnapshot:
        """registry.load_day for one tenant — read the artifacts
        through the per-tenant registry's loader, publish through the
        fleet so the stack rebuilds."""
        doc = ModelRegistry()
        snap = doc.load_day(day_dir, fallback)
        return self.publish(tenant, snap.model, source=day_dir)

    def active(self, tenant: str) -> ModelSnapshot:
        return self._registry(tenant).active()

    def previous(self, tenant: str) -> "ModelSnapshot | None":
        return self._registry(tenant).previous()

    def version(self, tenant: str) -> int:
        return self._registry(tenant).version

    # -- stacked snapshots --------------------------------------------------

    def tenant_k(self, tenant: str) -> int:
        with self._lock:
            k = self._tenant_k.get(tenant)
        if k is None:
            raise RuntimeError(
                f"tenant {tenant!r} has no published model yet"
            )
        return k

    def stack(self, k: int) -> StackedSnapshot:
        with self._lock:
            snap = self._stacks.get(k)
        if snap is None:
            raise RuntimeError(f"no stacked snapshot for K={k}")
        return snap

    def stack_for(self, tenant: str) -> StackedSnapshot:
        return self.stack(self.tenant_k(tenant))

    def _refresh_stack(self, k: int) -> None:
        """Rebuild the K-group's stacked snapshot from the members'
        CURRENT actives and install it — concatenation runs outside the
        lock; the install re-checks that no member published meanwhile
        (loop until the built stack matches the live member versions,
        so concurrent publishes converge on a stack containing both)."""
        while True:
            with self._lock:
                members = [
                    t for t in self._order if self._tenant_k.get(t) == k
                ]
                regs = {t: self._registries[t] for t in members}
            snaps = {t: regs[t].active() for t in members}
            if not snaps:
                with self._lock:
                    self._stacks.pop(k, None)
                return
            with self._lock:
                self._stack_builds[k] = self._stack_builds.get(k, 0) + 1
                build = self._stack_builds[k]
            built = _build_stack(k, members, snaps, build)
            with self._lock:
                live = {
                    t: self._registries[t].version
                    for t in members
                    if self._tenant_k.get(t) == k
                }
                if live == {t: s.version for t, s in snaps.items()}:
                    cur = self._stacks.get(k)
                    if cur is None or cur.stack_version < build:
                        self._stacks[k] = built
                    return
            # a member published while we concatenated — rebuild.


def tenant_pairs(feats, dsource: str, model: ScoringModel,
                 ip_base: int, word_base: int):
    """One tenant segment's (ip_rows, word_rows) in STACKED coordinates
    plus its pairs-per-event multiplicity: flow events contribute two
    (endpoint, word) pairs each — src block then dst block, min-combined
    at demux (flow_post_lda.scala:227-239) — DNS events one.  Row
    lookups go through the tenant's OWN index maps (misses land on the
    tenant's fallback row), then shift by the tenant's base offset into
    the stacked matrices: the tenant-id column realized as an index
    offset, which is what lets one compiled gather serve every tenant."""
    n = feats.num_raw_events
    if dsource == "flow":
        sips, dips = _flow_endpoint_strings(feats, n)
        ip = np.concatenate(
            [model.ip_rows(sips), model.ip_rows(dips)]
        ) + np.int32(ip_base)
        w = np.concatenate(
            [model.word_rows(feats.src_word[:n]),
             model.word_rows(feats.dest_word[:n])]
        ) + np.int32(word_base)
        return ip.astype(np.int32), w.astype(np.int32), 2
    ip = model.ip_rows(_dns_client_strings(feats, n)) + np.int32(ip_base)
    w = model.word_rows(list(feats.word[:n])) + np.int32(word_base)
    return ip.astype(np.int32), w.astype(np.int32), 1


def demux_scores(scores_seg: np.ndarray, mult: int) -> np.ndarray:
    """Per-event scores from a tenant's pair-score segment: flow
    (mult=2) min-combines the src/dst halves, DNS passes through."""
    if mult == 2:
        n = scores_seg.shape[0] // 2
        return np.minimum(scores_seg[:n], scores_seg[n:])
    return scores_seg


class FleetScorer:
    """Cross-tenant micro-batching front end over a FleetRegistry.

    `featurizers` maps tenant -> serving featurizer (serving/events.py
    semantics: validate one event, featurize a list, name its dsource).
    `on_batch(tenant, snapshot, feats, scores)` runs per tenant segment
    after each flush — per-tenant refresh loops and flagged-event sinks
    hang off it.  Flush triggers (`fleet_max_batch` /
    `fleet_max_wait_ms`) resolve through the plan layer exactly like
    the single-model scorer's serve_max_batch/serve_max_wait_ms."""

    def __init__(
        self,
        fleet: FleetRegistry,
        featurizers: dict,
        config: "ServingConfig | None" = None,
        metrics: "MetricsEmitter | None" = None,
        on_batch=None,
        journal=None,
    ) -> None:
        self.fleet = fleet
        self.config = config or ServingConfig()
        from ..plans import resolve

        mb, mb_src = resolve("fleet_max_batch", self.config.fleet_max_batch)
        mw, mw_src = resolve("fleet_max_wait_ms",
                             self.config.fleet_max_wait_ms)
        self.metrics = metrics
        self.on_batch = on_batch
        self._journal = getattr(journal, "journal", journal) \
            if journal is not None \
            else (metrics._journal if metrics is not None else None)
        self._lanes: dict[str, TenantLane] = {}
        for tenant in fleet.tenants():
            spec = fleet.spec(tenant)
            fz = featurizers.get(tenant)
            if fz is None:
                raise ValueError(f"no featurizer for tenant {tenant!r}")
            if getattr(fz, "dsource", None) != spec.dsource:
                raise ValueError(
                    f"tenant {tenant!r} declares dsource "
                    f"{spec.dsource!r} but its featurizer is "
                    f"{getattr(fz, 'dsource', None)!r}"
                )
            self._lanes[tenant] = TenantLane(
                spec=spec,
                featurizer=fz,
                queue_max=spec.queue_max or self.config.tenant_queue_max,
                admission=spec.admission or self.config.admission,
                threshold=(spec.threshold
                           if spec.threshold is not None
                           else self.config.threshold),
            )
        if not self._lanes:
            raise ValueError("FleetScorer needs at least one tenant")
        total_capacity = sum(l.queue_max for l in self._lanes.values())
        if mb_src == "plan" and int(mb) > total_capacity:
            # Same degradation guard as BatchScorer: a plan flush size
            # above the fleet's total admission capacity would make the
            # max_batch trigger unreachable (every flush silently
            # becomes the latency timer) — fall back to the default.
            mb, mb_src = self.config.fleet_max_batch, "default"
        self.max_batch = int(mb)
        self.max_wait_ms = float(mw)
        self.plan = {
            "max_batch": {"value": self.max_batch, "source": mb_src},
            "max_wait_ms": {"value": self.max_wait_ms, "source": mw_src},
        }
        if self.max_batch < 1:
            raise ValueError(f"fleet_max_batch ({self.max_batch}) must "
                             "be >= 1")
        if self.max_wait_ms <= 0:
            raise ValueError(
                f"fleet_max_wait_ms must be > 0, got {self.max_wait_ms}"
            )
        for lane in self._lanes.values():
            if lane.queue_max < 1:
                raise ValueError(
                    f"tenant {lane.spec.tenant!r} queue_max must be "
                    ">= 1"
                )
        if self.config.device_score_min in (0, "auto"):
            # Pay the one-time host-vs-device calibration at
            # construction, never inside a latency-bounded flush
            # (BatchScorer's contract).
            from ..scoring import dispatch_calibration

            dispatch_calibration()
        self._cond = threading.Condition()
        self._closed = False
        self._force_flush = False
        self._batch_seq = 0
        self._events_scored = 0
        import contextvars

        ctx = contextvars.copy_context()
        self._worker = threading.Thread(
            target=lambda: ctx.run(self._run),
            name="oni-fleet-scorer", daemon=True,
        )
        self._worker.start()

    # -- producer side ------------------------------------------------------

    def submit(self, tenant: str, raw):
        """Enqueue one raw event for `tenant`.  Raises ValueError on a
        malformed event (never enqueued), KeyError on an unknown
        tenant, RuntimeError after close().  A full tenant queue either
        BLOCKS (admission="block" — backpressure, the stall priced into
        `serve.<tenant>.admission_stall_s` and journaled like a
        dataplane edge) or raises AdmissionRejected
        (admission="reject" — load shedding, journaled as
        `{"kind": "admission_reject"}`)."""
        lane = self._lanes.get(tenant)
        if lane is None:
            raise KeyError(
                f"unknown tenant {tenant!r} "
                f"(known: {sorted(self._lanes)})"
            )
        validated = lane.featurizer.validate(raw)
        reject_info = None
        with self._cond:
            if self._closed:
                raise RuntimeError("FleetScorer is closed")
            if lane.full_locked() and lane.admission == "reject":
                lane.rejected += 1
                reject_info = (len(lane.pending), lane.queue_max)
            else:
                wait_ns = 0
                t0 = None
                while not self._closed and lane.full_locked():
                    if t0 is None:
                        t0 = time.perf_counter_ns()
                    self._cond.wait()
                if t0 is not None:
                    wait_ns = time.perf_counter_ns() - t0
                    lane.admission_stall_ns += wait_ns
                if self._closed:
                    raise RuntimeError("FleetScorer is closed")
                p = _PendingEvent(validated, time.perf_counter())
                lane.pending.append(p)
                lane.submitted += 1
                depth = len(lane.pending)
                self._cond.notify_all()
        if reject_info is not None:
            depth, capacity = reject_info
            self._journal_safe({
                "kind": "admission_reject", "tenant": tenant,
                "depth": depth, "capacity": capacity,
            })
            if self.metrics is not None:
                self.metrics.recorder.counter(
                    f"serve.{tenant}.admission_rejects"
                ).add(1)
            raise AdmissionRejected(tenant, depth, capacity)
        if wait_ns and self.metrics is not None:
            self.metrics.recorder.histogram(
                f"serve.{tenant}.admission_stall_s"
            ).observe(wait_ns / 1e9)
        if wait_ns:
            # The dataplane's stall-pricing record shape (channel.py
            # _note), on the admission edge: the fleet's ingress
            # backpressure shows up in trace_view next to every other
            # priced stall.
            self._journal_safe({
                "kind": "dataplane", "event": "depth",
                "edge": f"admit.{tenant}", "side": "put",
                "depth": depth, "wait_s": round(wait_ns / 1e9, 6),
            })
        return p.future

    def flush(self) -> None:
        """Flush whatever is queued without waiting for either trigger
        (no-op on an empty fleet queue — BatchScorer semantics)."""
        with self._cond:
            if any(lane.pending for lane in self._lanes.values()):
                self._force_flush = True
                self._cond.notify_all()

    def close(self, timeout: "float | None" = None) -> bool:
        """Drain every tenant queue, then stop the worker.  With a
        finite timeout, an overlong drain FAILS the still-queued
        futures and returns False instead of abandoning them."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout)
        if not self._worker.is_alive():
            return True
        undrained: list = []
        with self._cond:
            for lane in self._lanes.values():
                undrained.extend(lane.pending)
                lane.pending.clear()
        err = RuntimeError(
            f"FleetScorer.close timed out after {timeout}s with "
            f"{len(undrained)} events undrained"
        )
        for p in undrained:
            p.future._fail(err)
        return False

    @property
    def events_scored(self) -> int:
        with self._cond:
            return self._events_scored

    @property
    def batches_flushed(self) -> int:
        with self._cond:
            return self._batch_seq

    def tenant_stats(self) -> "list[dict]":
        with self._cond:
            return [self._lanes[t].stats_locked()
                    for t in sorted(self._lanes)]

    def tenant_threshold(self, tenant: str) -> float:
        """The resolved suspicion threshold for one tenant (spec
        override, else the fleet config) — the ONE resolution, so
        flagged-event consumers can't drift from the lane's own
        flagged accounting."""
        return self._lanes[tenant].threshold

    # -- worker side --------------------------------------------------------

    def _take_batch(self):
        """Block until a flush trigger fires; returns (batch, trigger,
        total_depth_after) where batch is [(tenant, _PendingEvent)]
        drained GLOBALLY OLDEST-FIRST across tenant queues — the
        no-head-of-line-blocking drain: a bursty tenant fills its own
        bounded queue, but cannot delay an older event of another
        tenant.  Empty batch means shutdown."""
        max_wait_s = self.max_wait_ms / 1e3
        lanes = self._lanes
        with self._cond:
            while not self._closed and not any(
                    l.pending for l in lanes.values()):
                self._cond.wait()
            if not any(l.pending for l in lanes.values()):
                return [], "shutdown", 0
            trigger = "close" if self._closed else None
            while trigger is None:
                if self._force_flush:
                    trigger = "flush"
                    break
                total = sum(len(l.pending) for l in lanes.values())
                if total >= self.max_batch:
                    trigger = "max_batch"
                    break
                oldest = min(
                    l.pending[0].t_enqueue
                    for l in lanes.values() if l.pending
                )
                waited = time.perf_counter() - oldest
                if waited >= max_wait_s:
                    trigger = "max_wait"
                    break
                self._cond.wait(max_wait_s - waited)
                if self._closed:
                    trigger = "close"
            self._force_flush = False
            # K-way merge on enqueue time via a heap of lane heads:
            # O(batch log tenants) while holding the lock every
            # submitter shares — a linear scan per taken event would
            # make admission stalls scale with tenant count.
            heads = [
                (lane.pending[0].t_enqueue, t)
                for t, lane in lanes.items() if lane.pending
            ]
            heapq.heapify(heads)
            batch: list = []
            while heads and len(batch) < self.max_batch:
                _, t = heapq.heappop(heads)
                lane = lanes[t]
                batch.append((t, lane.pending.popleft()))
                if lane.pending:
                    heapq.heappush(
                        heads, (lane.pending[0].t_enqueue, t)
                    )
            depth = sum(len(l.pending) for l in lanes.values())
            self._cond.notify_all()   # release blocked submitters
            return batch, trigger, depth

    def _run(self) -> None:
        while True:
            batch, trigger, depth = self._take_batch()
            if not batch:
                return
            try:
                self._score_batch(batch, trigger, depth)
            except Exception as e:
                # The worker survives anything a batch throws; futures
                # already resolved keep their scores, the rest fail
                # with the cause (BatchScorer contract).
                for _, p in batch:
                    p.future._fail(e)

    def _score_batch(self, batch, trigger: str, depth: int) -> None:
        cfg = self.config
        t0 = time.perf_counter()
        # Segment the drained batch per tenant (submit order preserved
        # inside each segment), then group tenants by topic count K:
        # one stacked snapshot — one compiled dispatch — per group.
        segments: dict[str, list] = {}
        for tenant, p in batch:
            segments.setdefault(tenant, []).append(p)
        stacks: dict[int, StackedSnapshot] = {}
        tenant_scores: dict[str, np.ndarray] = {}
        failures: dict[str, Exception] = {}
        groups: dict[int, list] = {}
        feats_by_tenant: dict = {}
        # Each tenant's K is read ONCE here and reused at demux/emit:
        # a concurrent publish may change a tenant's K mid-flush, and a
        # re-read after scoring would look up a stack this flush never
        # grabbed (KeyError failing OTHER tenants' futures too).
        tenant_ks: dict[str, int] = {}
        for tenant, items in segments.items():
            lane = self._lanes[tenant]
            try:
                k = self.fleet.tenant_k(tenant)
                tenant_ks[tenant] = k
                if k not in stacks:
                    stacks[k] = self.fleet.stack(k)
                feats = lane.featurizer([p.raw for p in items])
                if feats.num_raw_events != len(items):
                    raise RuntimeError(
                        f"tenant {tenant!r} featurizer returned "
                        f"{feats.num_raw_events} rows for "
                        f"{len(items)} events"
                    )
                feats_by_tenant[tenant] = feats
                groups.setdefault(k, []).append(tenant)
            except Exception as e:
                # Tenant-scoped failure isolation: a tenant whose
                # featurization (or stack lookup) fails takes down ITS
                # futures only — the rest of the flush still scores.
                failures[tenant] = e
        dispatches = 0
        device_dispatches = 0
        group_device: dict[int, bool] = {}
        for k, group in sorted(groups.items()):
            stack = stacks[k]
            try:
                parts = []
                mults = {}
                for tenant in group:
                    ip, w, mult = tenant_pairs(
                        feats_by_tenant[tenant],
                        self._lanes[tenant].spec.dsource,
                        stack.members[tenant].model,
                        stack.ip_base[tenant],
                        stack.word_base[tenant],
                    )
                    parts.append((tenant, ip, w))
                    mults[tenant] = mult
                ip_all = np.concatenate([ip for _, ip, _ in parts])
                w_all = np.concatenate([w for _, _, w in parts])
                # ONE dispatch for the whole K-group: every tenant's
                # pairs ride the same padded compiled program.  The
                # device-path decision is made on the packed PAIR
                # count, not the flush's event count (flow events pack
                # two pairs each, and each K group decides
                # independently); device dispatches feed the serve
                # roofline histograms per GROUP — exact wall, exact
                # events — so a flush mixing device and host groups
                # can never price host scoring as device dispatches.
                is_device = use_device_path(
                    len(ip_all), cfg.device_score_min
                )
                group_device[k] = is_device
                t_g0 = time.perf_counter()
                pair_scores = batched_scores(
                    stack.model, ip_all, w_all, cfg.device_score_min
                )
                dispatches += 1
                if is_device:
                    device_dispatches += 1
                    if self.metrics is not None:
                        rec = self.metrics.recorder
                        rec.histogram("serve.device_score_ms").observe(
                            (time.perf_counter() - t_g0) * 1e3
                        )
                        rec.counter("serve.device_events").add(sum(
                            feats_by_tenant[t].num_raw_events
                            for t in group
                        ))
                off = 0
                for tenant, ip, _ in parts:
                    seg = pair_scores[off:off + len(ip)]
                    off += len(ip)
                    tenant_scores[tenant] = demux_scores(
                        seg, mults[tenant]
                    )
            except Exception as e:
                for tenant in group:
                    failures.setdefault(tenant, e)
        t1 = time.perf_counter()
        # Demux: resolve per-tenant futures against the stack the
        # segment actually scored on (version isolation: tenant B's
        # futures carry B's version even while A hot-swaps).
        flagged: dict[str, int] = {}
        for tenant, items in segments.items():
            if tenant in failures:
                for p in items:
                    p.future._fail(failures[tenant])
                continue
            scores = tenant_scores[tenant]
            version = stacks[tenant_ks[tenant]].version_of(tenant)
            for p, s in zip(items, scores):
                p.future._resolve(float(s), version)
            flagged[tenant] = int(
                np.sum(scores < self._lanes[tenant].threshold)
            )
        t2 = time.perf_counter()
        scored_n = sum(
            len(items) for t, items in segments.items()
            if t not in failures
        )
        with self._cond:
            seq = self._batch_seq
            self._batch_seq += 1
            self._events_scored += scored_n
            for tenant, items in segments.items():
                if tenant in failures:
                    continue
                self._lanes[tenant].scored += len(items)
                self._lanes[tenant].flagged += flagged[tenant]
        self._journal_safe({
            "kind": "demux", "batch": seq, "events": len(batch),
            "tenants": len(segments), "segments": dispatches,
            "score_ms": round((t1 - t0) * 1e3, 3),
            "demux_ms": round((t2 - t1) * 1e3, 3),
        })
        # Per-tenant consumers + metrics, then the aggregate record.
        # "device" only when at least one K-group's packed dispatch
        # actually took the device path (metrics._count feeds the
        # device roofline histogram off this label, flush-level records
        # only).
        score_s = t1 - t0
        n = len(batch)
        scorer_label = "device" if device_dispatches else "host"
        for tenant, items in sorted(segments.items()):
            if tenant in failures:
                self._emit_safe({
                    "stage": "serve", "tenant": tenant, "batch": seq,
                    "events": len(items),
                    "error": repr(failures[tenant]), "trigger": trigger,
                })
                continue
            k = tenant_ks[tenant]
            snap = stacks[k].members[tenant]
            if self.on_batch is not None:
                try:
                    self.on_batch(tenant, snap, feats_by_tenant[tenant],
                                  tenant_scores[tenant])
                except Exception as e:
                    # Consumer failures never take down scoring.
                    self._emit_safe({
                        "stage": "serve", "tenant": tenant,
                        "batch": seq, "on_batch_error": repr(e),
                    })
            oldest = items[0].t_enqueue
            self._emit_safe({
                "stage": "serve", "tenant": tenant, "batch": seq,
                "events": len(items), "trigger": trigger,
                "model_version": snap.version,
                "stack_version": stacks[k].stack_version,
                # The tenant's OWN K-group's dispatch decision — in a
                # mixed-K flush a host-scored tenant must not be
                # labeled by another group's device dispatch.
                "scorer": ("device" if group_device.get(k)
                           else "host"),
                "latency_ms": round((t1 - oldest) * 1e3, 3),
                "queue_wait_ms": round((t0 - oldest) * 1e3, 3),
                "score_ms": round(score_s * 1e3, 3),
                "demux_ms": round((t2 - t1) * 1e3, 3),
                "flagged": flagged[tenant],
            })
        oldest_all = batch[0][1].t_enqueue
        self._emit_safe({
            "stage": "serve", "batch": seq, "events": n,
            "tenants": len(segments), "segments": dispatches,
            "segments_device": device_dispatches,
            "trigger": trigger, "scorer": scorer_label,
            "latency_ms": round((t1 - oldest_all) * 1e3, 3),
            "queue_wait_ms": round((t0 - oldest_all) * 1e3, 3),
            "score_ms": round(score_s * 1e3, 3),
            "demux_ms": round((t2 - t1) * 1e3, 3),
            "events_per_sec": round(n / score_s, 1) if score_s else None,
            "queue_depth": depth,
            "flagged": sum(flagged.values()),
        })

    # -- telemetry sinks ----------------------------------------------------

    def _emit_safe(self, record: dict) -> None:
        if self.metrics is None:
            return
        try:
            self.metrics.emit(record)
        except Exception as e:
            import sys

            print(f"fleet metrics emit failed: {e!r}", file=sys.stderr)

    def _journal_safe(self, record: dict) -> None:
        if self._journal is None:
            return
        try:
            self._journal.append(record)
        except Exception as e:
            import sys

            print(f"fleet journal append failed: {e!r}", file=sys.stderr)
