"""Streaming scoring service: the batch pipeline's once-a-day artifacts
served as a long-running, continuously-refreshed scorer.

    day artifacts (doc_results.csv / word_results.csv)
        -> ModelRegistry      validated snapshots, atomic hot-swap
        -> BatchScorer        micro-batch queue (max_batch / max_wait_ms),
                              featurize via features/, host-or-device
                              scoring by batch size, JSON-line metrics
        -> RefreshLoop        scored batches fold into online-LDA
                              natural-gradient steps; updated theta/p
                              republish through the registry

`python -m oni_ml_tpu.runner.ml_ops serve` is the CLI front end
(runner/serve.py); ServingConfig (config.py) holds the knobs.

Multi-tenant fleet (serving/fleet.py + serving/tenants.py): the same
stack scaled to N tenants sharing device residency and one compiled
batch family —

        -> FleetRegistry      per-tenant hot-swap registries + stacked
                              per-K snapshots (shared residency)
        -> FleetScorer        cross-tenant micro-batch multiplexing with
                              bounded per-tenant admission, async demux
                              to per-tenant ScoreFutures, and
                              serve.<tenant>.* metrics

`ml_ops serve --fleet manifest.json` is the fleet front end.

Tiered residency (serving/residency.py): HBM as a managed cache over
host RAM and checkpoints —

        -> ResidencyManager   HBM-hot / host-warm / checkpoint-cold
                              paging with admission-driven LRU/LFU
                              eviction; promotions rebuild the stack
                              outside the lock at a capacity-tier
                              shape, so paging never stalls resident
                              tenants and never retraces within a tier

`ServingConfig.fleet_hot_tenants` turns it on; the fleet scales from
"as many tenants as fit in HBM" to "as many tenants as fit on disk".

Replicated elastic serving (placement.py + replica.py + router.py):
the whole stack above replicated across N processes —

        -> place()            deterministic balanced consistent-hash
                              ring: primary + warm shadow per tenant,
                              minimal movement on ring change
        -> ReplicaServer      one full serving stack behind a framed
                              socket protocol, KV heartbeats
        -> FleetRouter        async scatter/gather front: bounded
                              per-replica admission windows, an
                              admission journal that replays in-flight
                              events on failover, shadow promotion on
                              BackendLost, rolling drain/join redeploy

`ml_ops route --replicas N` / `ml_ops replica` are the CLI front ends;
aggregate events/s scales with the replica count and a dead replica
costs a promotion window, not the fleet.

Cross-host, self-scaling serving (wire.py + autoscale.py): the fleet's
default frame is a versioned COLUMNAR wire (typed per-column
descriptors, zero-copy numpy decode, pickle only as a negotiated
one-release fallback); same-host router<->replica pairs upgrade to a
shared-memory double-buffered ring so local hops skip TCP entirely;
membership rides any KV client — the file store same-host, the TCP
``KVServer``/``TcpKVClient`` pair cross-host — so N routers run with
zero coordination (placement is a pure function of the roster,
failover backfill is settled by a first-writer-wins promotion claim);
and ``AutoScaler`` sizes the fleet by Little's law from the measured
admission-window occupancy, journaling every decision.
"""

from .autoscale import AutoScaler
from .batcher import BatchScorer, ScoreFuture
from .coscheduler import CoScheduler
from .fleet import (
    FleetRegistry,
    FleetScorer,
    StackedSnapshot,
    demux_scores,
    tenant_pairs,
)
from .tenants import (
    AdmissionRejected,
    TenantSpec,
    load_manifest,
    parse_manifest,
)
from .events import (
    DnsEventFeaturizer,
    FlowEventFeaturizer,
    event_documents,
    featurizer_from_features,
    score_features,
)
from .metrics import MetricsEmitter
from .placement import (
    Placement,
    load_by_replica,
    moved_primaries,
    place,
    shadow_for,
)
from .refresh import RefreshLoop, topic_probs_from_log_beta
from .replica import ReplicaServer, featurizer_for
from .router import FleetRouter, ReplicaLink
from .wire import ShmRing, decode_payload, encode_payload
from .registry import ModelRegistry, ModelSnapshot, validate_model
from .residency import (
    TIER_COLD,
    TIER_HOT,
    TIER_WARM,
    ResidencyManager,
    load_spill,
    resolve_hot_capacity,
    spill_model,
)

__all__ = [
    "BatchScorer",
    "CoScheduler",
    "ScoreFuture",
    "FleetRegistry",
    "FleetScorer",
    "StackedSnapshot",
    "demux_scores",
    "tenant_pairs",
    "AdmissionRejected",
    "TenantSpec",
    "load_manifest",
    "parse_manifest",
    "DnsEventFeaturizer",
    "FlowEventFeaturizer",
    "event_documents",
    "featurizer_from_features",
    "score_features",
    "MetricsEmitter",
    "Placement",
    "place",
    "shadow_for",
    "moved_primaries",
    "load_by_replica",
    "ReplicaServer",
    "featurizer_for",
    "FleetRouter",
    "ReplicaLink",
    "AutoScaler",
    "ShmRing",
    "encode_payload",
    "decode_payload",
    "RefreshLoop",
    "topic_probs_from_log_beta",
    "ModelRegistry",
    "ModelSnapshot",
    "validate_model",
    "ResidencyManager",
    "TIER_HOT",
    "TIER_WARM",
    "TIER_COLD",
    "resolve_hot_capacity",
    "spill_model",
    "load_spill",
]
