"""Streaming scoring service: the batch pipeline's once-a-day artifacts
served as a long-running, continuously-refreshed scorer.

    day artifacts (doc_results.csv / word_results.csv)
        -> ModelRegistry      validated snapshots, atomic hot-swap
        -> BatchScorer        micro-batch queue (max_batch / max_wait_ms),
                              featurize via features/, host-or-device
                              scoring by batch size, JSON-line metrics
        -> RefreshLoop        scored batches fold into online-LDA
                              natural-gradient steps; updated theta/p
                              republish through the registry

`python -m oni_ml_tpu.runner.ml_ops serve` is the CLI front end
(runner/serve.py); ServingConfig (config.py) holds the knobs.
"""

from .batcher import BatchScorer, ScoreFuture
from .events import (
    DnsEventFeaturizer,
    FlowEventFeaturizer,
    event_documents,
    featurizer_from_features,
    score_features,
)
from .metrics import MetricsEmitter
from .refresh import RefreshLoop, topic_probs_from_log_beta
from .registry import ModelRegistry, ModelSnapshot, validate_model

__all__ = [
    "BatchScorer",
    "ScoreFuture",
    "DnsEventFeaturizer",
    "FlowEventFeaturizer",
    "event_documents",
    "featurizer_from_features",
    "score_features",
    "MetricsEmitter",
    "RefreshLoop",
    "topic_probs_from_log_beta",
    "ModelRegistry",
    "ModelSnapshot",
    "validate_model",
]
