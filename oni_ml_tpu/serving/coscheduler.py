"""Cooperative two-priority device scheduler: refresh fits vs scoring.

The composed standing service (`ml_ops continuous --fleet
--replicated`) runs the window trainer on the SAME process (and, on an
accelerator, the same devices) the serving plane dispatches from.
Without arbitration a refresh fit head-of-line-blocks scoring for its
whole wall: one `fused_em_chunk=128` dispatch is seconds of device
time, and every flush that arrives behind it waits the full remainder.

This module is the MPMD pipeline-scheduling model (PAPERS.md,
arXiv:2412.14374) applied to that contention: the refresh fit is the
low-priority pipeline stage, micro-batch scoring the high-priority one,
and the stage boundary — the EM chunk boundary the fused driver
already syncs at — is the explicit yield point.  Rules:

* a refresh fit dispatches one CHUNK at a time under `train_chunk()`;
* a scoring flush runs under `serve_slot()` and always wins the NEXT
  dispatch slot: the trainer's chunk entry waits while any serve slot
  is pending or running;
* serve slots never wait on each other — only on an in-flight chunk,
  so their worst-case preemption wait is ONE chunk's wall (which
  `ContinuousConfig.fused_em_chunk` bounds); and they only wait AT
  ALL when scoring shares the trainer's dispatch stream (in-process
  scorer) — remote scoring through the replicated router registers
  the same pressure without blocking (`serve_slot(wait=False)`).

Both waits are priced exactly like dataplane channel stalls: a
recorder histogram (`cosched.yield_wait_s` for the trainer giving way,
`cosched.preempt_wait_s` for a flush waiting out a chunk) plus a
`{"kind": "cosched"}` journal record per CONTENDED wait — an
uncontended entry costs two lock acquisitions and writes nothing.
`tools/trace_view.py` renders these as train-vs-serve priority lanes
with YIELD/PREEMPT instants.

The scheduler is cooperative and host-side: it orders dispatch
ENQUEUE, which on a single-stream backend orders device execution.
`CoScheduler(enabled=False)` (or a `None` coscheduler everywhere) is
the uncoscheduled control leg the `continuous_replicated` bench
compares against: same counters and refresh-active tagging, no waits.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


class CoScheduler:
    """Two-priority cooperative dispatch token (train yields to serve).

    `starvation_s` bounds trainer livelock under a saturated serve
    plane: a chunk entry that has waited longer than this proceeds
    anyway (journaled with `capped: true`).  Scoring still wins every
    slot the trainer is not actively holding.

    `enabled=False` is the observe-only mode the uncoscheduled control
    leg of the `continuous_replicated` bench runs under: every bracket
    still counts chunks/slots and `refresh_active` still flips (so the
    serve-latency split is measured identically), but nothing ever
    waits — train and serve dispatch head-to-head, unarbitrated.
    """

    def __init__(self, *, recorder=None, journal=None,
                 starvation_s: float = 5.0, enabled: bool = True) -> None:
        self._cond = threading.Condition()
        self._train_active = False   # a chunk holds the dispatch slot
        self._serve_waiting = 0      # flushes blocked on the slot
        self._serve_busy = 0         # flushes currently dispatching
        self._fit_active = 0         # refresh fits in flight (0 or 1)
        self._journal = getattr(journal, "journal", journal)
        self._recorder = recorder
        self.enabled = bool(enabled)
        self.starvation_s = float(starvation_s)
        self.train_chunks = 0
        self.serve_slots = 0
        self.yields = 0              # contended chunk entries
        self.preempts = 0            # contended serve entries
        self.yield_wait_s = 0.0
        self.preempt_wait_s = 0.0
        self._fit_yields = 0         # per-fit running tallies
        self._fit_yield_wait_s = 0.0
        self._fit_chunks = 0
        self._fit_capped = 0

    # -- introspection ----------------------------------------------------

    @property
    def refresh_active(self) -> bool:
        """True while any refresh fit is between train_fit() entry and
        exit — the tag the serve-latency split (p99 during refresh vs
        idle) keys on.  Read without the lock: a boolean flip, and the
        consumers only bucket latency samples."""
        return self._fit_active > 0

    def _journal_safe(self, record: dict) -> None:
        if self._journal is None:
            return
        try:
            self._journal.append(record)
        except Exception:
            pass     # telemetry must never take down the service

    # -- train side -------------------------------------------------------

    @contextmanager
    def train_fit(self, tenant: str = ""):
        """Brackets one whole refresh fit.  Flips `refresh_active` and
        aggregates the fit's chunk/yield tallies into one journal
        record at exit (the per-wait records stay individually
        journaled; this is the fit-level rollup trace_view draws as
        the train lane's span)."""
        t0 = time.perf_counter()
        with self._cond:
            self._fit_active += 1
            self._fit_yields = 0
            self._fit_yield_wait_s = 0.0
            self._fit_chunks = 0
            self._fit_capped = 0
        try:
            yield self
        finally:
            wall = time.perf_counter() - t0
            with self._cond:
                self._fit_active -= 1
                chunks = self._fit_chunks
                yields = self._fit_yields
                ywait = self._fit_yield_wait_s
                capped = self._fit_capped
                self._cond.notify_all()
            self._journal_safe({
                "kind": "cosched", "event": "fit", "tenant": tenant,
                "wall_s": round(wall, 6), "chunks": chunks,
                "yields": yields, "yield_wait_s": round(ywait, 6),
                "capped": capped,
            })

    @contextmanager
    def train_chunk(self):
        """One preemptible chunk dispatch.  Entry is the yield point:
        wait while any scoring flush is pending or running (bounded by
        `starvation_s`), then hold the slot for the dispatch."""
        t0 = time.perf_counter()
        deadline = t0 + self.starvation_s
        capped = False
        with self._cond:
            contended = self.enabled and (
                self._serve_waiting > 0 or self._serve_busy > 0)
            while contended and (
                    self._serve_waiting > 0 or self._serve_busy > 0):
                remain = deadline - time.perf_counter()
                if remain <= 0:
                    capped = True
                    break
                self._cond.wait(timeout=remain)
            self._train_active = self.enabled
            self.train_chunks += 1
            self._fit_chunks += 1
            wait = time.perf_counter() - t0
            if contended:
                self.yields += 1
                self.yield_wait_s += wait
                self._fit_yields += 1
                self._fit_yield_wait_s += wait
                self._fit_capped += capped
        if contended:
            if self._recorder is not None:
                self._recorder.histogram(
                    "cosched.yield_wait_s").observe(wait)
            self._journal_safe({
                "kind": "cosched", "event": "yield",
                "wait_ms": round(wait * 1e3, 3), "capped": capped,
            })
        try:
            yield
        finally:
            with self._cond:
                self._train_active = False
                self._cond.notify_all()

    # -- serve side -------------------------------------------------------

    @contextmanager
    def serve_slot(self, *, wait: bool = True):
        """One scoring dispatch (submit burst + flush).  With
        `wait=True` (the in-process scorer: train and serve genuinely
        share ONE dispatch stream) it waits out at most the chunk
        currently in flight — registering as waiting FIRST, so the
        trainer's next chunk entry sees the pressure and gives way.
        With `wait=False` (remote scoring — the replicated router: no
        shared stream, so waiting would only inherit the chunk's wall)
        it registers the same pressure WITHOUT blocking: the flush
        dispatches immediately and the trainer still defers its next
        chunk until the slot drains."""
        t0 = time.perf_counter()
        with self._cond:
            self._serve_waiting += 1
            contended = self.enabled and wait and self._train_active
            while contended and self._train_active:
                self._cond.wait()
            self._serve_waiting -= 1
            self._serve_busy += 1
            self.serve_slots += 1
            wait = time.perf_counter() - t0
            if contended:
                self.preempts += 1
                self.preempt_wait_s += wait
        if contended:
            if self._recorder is not None:
                self._recorder.histogram(
                    "cosched.preempt_wait_s").observe(wait)
            self._journal_safe({
                "kind": "cosched", "event": "preempt",
                "wait_ms": round(wait * 1e3, 3),
            })
        try:
            yield
        finally:
            with self._cond:
                self._serve_busy -= 1
                if not self._serve_busy and not self._serve_waiting:
                    self._cond.notify_all()

    # -- the trainer-facing hook ------------------------------------------

    @property
    def yield_hook(self):
        """The context-manager factory `LDATrainer`/`WindowTrainer`
        accept as `yield_hook=`: each EM chunk (fused driver), EM
        iteration (stepwise driver), or reduce round (distributed
        driver) dispatches inside one `train_chunk()` slot."""
        return self.train_chunk

    def summary(self) -> dict:
        with self._cond:
            def _q(name, q):
                if self._recorder is None:
                    return None
                v = self._recorder.histogram(name).quantile(q)
                return round(v, 6) if v is not None else None

            return {
                "enabled": self.enabled,
                "train_chunks": self.train_chunks,
                "serve_slots": self.serve_slots,
                "yields": self.yields,
                "preempts": self.preempts,
                "yield_wait_s": round(self.yield_wait_s, 6),
                "preempt_wait_s": round(self.preempt_wait_s, 6),
                "yield_wait_p99_s": _q("cosched.yield_wait_s", 0.99),
                "preempt_wait_p99_s": _q("cosched.preempt_wait_s", 0.99),
            }
