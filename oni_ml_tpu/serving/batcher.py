"""BatchScorer — micro-batch accumulation and exactly-once scoring.

Arriving events enqueue with a per-event future; a single worker thread
flushes the queue into featurize+score calls whenever EITHER trigger
fires:

    max_batch    the queue holds a full batch (throughput trigger), or
    max_wait_ms  the oldest queued event has waited long enough
                 (latency trigger).

Each flush takes ONE registry snapshot, so a hot-swap that lands
mid-batch is invisible to that batch (it finishes on the model it
started with) and the very next batch scores on the new model — the
double-buffered contract from serving/registry.py, observed end to end.

Exactly-once: events are validated at submit (malformed events raise to
the CALLER and never enter the queue — the featurizers drop malformed
rows silently, which would desync scores from futures), each dequeued
event's future is resolved exactly once, and close() drains the queue
before stopping the worker, so no event is dropped or double-scored
across any interleaving of submits, flushes, swaps, and shutdown.
Backpressure: submit() blocks once queue_max events are pending, so an
ingest stream that outruns scoring throttles at the source instead of
accumulating futures until OOM.

Per-batch latency/throughput/queue-depth counters emit as JSON lines
(serving/metrics.py), one record per flush.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from ..config import ServingConfig
from ..scoring import use_device_path
from .events import event_documents, score_features
from .metrics import MetricsEmitter
from .registry import ModelRegistry


class ScoreFuture:
    """Single-event result handle: result() blocks until the event's
    micro-batch flushed (or the scorer failed it)."""

    __slots__ = ("_event", "_score", "_version", "_error")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._score = None
        self._version = None
        self._error = None

    def _resolve(self, score: float, version: int) -> None:
        if self._event.is_set():
            return  # exactly-once: first resolution wins
        self._score = score
        self._version = version
        self._event.set()

    def _fail(self, error: Exception) -> None:
        if self._event.is_set():
            return  # never turn an already-delivered score into an error
        self._error = error
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> tuple[float, int]:
        """(score, model_version); raises the scorer's error if the
        batch failed, TimeoutError if not resolved in time."""
        if not self._event.wait(timeout):
            raise TimeoutError("event not scored within timeout")
        if self._error is not None:
            raise self._error
        return self._score, self._version


class _Pending:
    __slots__ = ("raw", "t_enqueue", "future")

    def __init__(self, raw, t_enqueue: float) -> None:
        self.raw = raw
        self.t_enqueue = t_enqueue
        self.future = ScoreFuture()


class BatchScorer:
    """Micro-batching scoring front end over a ModelRegistry.

    `featurizer` is a serving featurizer (serving/events.py): it
    validates single events, turns a list of them into a feature
    container, and names its dsource.  `on_batch(snapshot, feats,
    scores)` runs on the worker thread after each flush — the refresh
    loop and output sinks hang off it.
    """

    def __init__(
        self,
        registry: ModelRegistry,
        featurizer,
        config: ServingConfig | None = None,
        metrics: MetricsEmitter | None = None,
        on_batch=None,
    ) -> None:
        self.registry = registry
        self.featurizer = featurizer
        self.config = config or ServingConfig()
        # Flush triggers resolve through the plan layer
        # (oni_ml_tpu/plans): an explicitly-set config value always
        # wins, else a measured plan entry for this backend, else the
        # shipped default.  `self.plan` names the source per knob for
        # the serve records.
        from ..plans import resolve

        mb, mb_src = resolve("serve_max_batch", self.config.max_batch)
        mw, mw_src = resolve("serve_max_wait_ms", self.config.max_wait_ms)
        if mb_src == "plan" and int(mb) > self.config.queue_max:
            # A plan flush size above the backpressure bound would make
            # the max_batch trigger unreachable (submit() blocks at
            # queue_max first) — every flush silently degrades to the
            # latency timer.  An operator-editable entry must not do
            # that; fall back to the shipped default.
            mb, mb_src = self.config.max_batch, "default"
        self.max_batch = int(mb)
        self.max_wait_ms = float(mw)
        self.plan = {
            "max_batch": {"value": self.max_batch, "source": mb_src},
            "max_wait_ms": {"value": self.max_wait_ms, "source": mw_src},
        }
        if self.max_batch < 1 or self.config.queue_max < 1:
            # max_batch=0 would make the first flush return an empty
            # batch — which the worker loop reads as shutdown — and
            # queue_max=0 deadlocks the first submit; fail construction
            # instead of hanging every future.
            raise ValueError(
                f"max_batch ({self.max_batch}) and queue_max "
                f"({self.config.queue_max}) must both be >= 1"
            )
        if self.max_wait_ms <= 0:
            raise ValueError(
                f"max_wait_ms must be > 0, got {self.max_wait_ms}"
            )
        self.metrics = metrics
        self.on_batch = on_batch
        if self.config.device_score_min in (0, "auto"):
            # Auto host-vs-device dispatch: pay the one-time calibration
            # (jit compiles + a few timed reps, ~a second) HERE at
            # construction, not inside the first flush — the worker's
            # scoring path is latency-bounded by max_wait_ms and must
            # never stall on it.  Cached per process, so only the first
            # scorer constructed pays.
            from ..scoring import dispatch_calibration

            dispatch_calibration()
        self._pending: deque[_Pending] = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._force_flush = False
        self._batch_seq = 0
        self._events_scored = 0
        # The worker runs inside a COPY of the constructing thread's
        # context: contextvar scopes (the plan store pinned by
        # plans.use_store — a --no-plans NullStore must bind the worker
        # too — and telemetry's current_recorder) do not cross thread
        # starts on their own, and a worker that fell back to the
        # process defaults would silently bypass the caller's opt-outs.
        import contextvars

        ctx = contextvars.copy_context()
        self._worker = threading.Thread(
            target=lambda: ctx.run(self._run),
            name="oni-batch-scorer", daemon=True,
        )
        self._worker.start()

    # -- producer side ------------------------------------------------------

    def submit(self, raw) -> ScoreFuture:
        """Enqueue one raw event; raises ValueError immediately on a
        malformed event (never enqueued), RuntimeError after close().
        BLOCKS for backpressure once queue_max events are pending, so a
        producer that outruns scoring throttles instead of growing the
        queue without bound."""
        validated = self.featurizer.validate(raw)
        with self._cond:
            while not self._closed and \
                    len(self._pending) >= self.config.queue_max:
                self._cond.wait()
            if self._closed:
                raise RuntimeError("BatchScorer is closed")
            p = _Pending(validated, time.perf_counter())
            self._pending.append(p)
            self._cond.notify_all()
            return p.future

    def submit_many(self, raws) -> list[ScoreFuture]:
        # lint: ok(hot-path-event-loop, the admission API itself — per-event queueing semantics; flush scoring is vectorized downstream)
        return [self.submit(r) for r in raws]

    def flush(self) -> None:
        """Flush whatever is queued without waiting for either trigger.
        No-op on an empty queue (an armed flag would otherwise flush the
        NEXT event, minutes later, as a batch of one)."""
        with self._cond:
            if self._pending:
                self._force_flush = True
                self._cond.notify_all()

    def close(self, timeout: float | None = None) -> bool:
        """Drain the queue, then stop the worker.  With the default
        timeout=None this blocks until every event submitted before
        close() has been scored (zero dropped).  With a finite timeout,
        a drain that outlives it FAILS the still-queued futures (so no
        caller blocks forever on a score that will never come) and
        returns False instead of silently abandoning them."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout)
        if not self._worker.is_alive():
            return True
        with self._cond:
            undrained = list(self._pending)
            self._pending.clear()
        err = RuntimeError(
            f"BatchScorer.close timed out after {timeout}s with "
            f"{len(undrained)} events undrained"
        )
        for p in undrained:
            p.future._fail(err)
        return False

    @property
    def events_scored(self) -> int:
        with self._cond:
            return self._events_scored

    @property
    def batches_flushed(self) -> int:
        with self._cond:
            return self._batch_seq

    # -- worker side --------------------------------------------------------

    def _take_batch(self) -> tuple[list[_Pending], str, int]:
        """Block until a flush trigger fires; returns (batch, trigger,
        queue_depth_after).  Empty batch means shutdown."""
        max_wait_s = self.max_wait_ms / 1e3
        with self._cond:
            while not self._pending and not self._closed:
                self._cond.wait()
            if not self._pending:
                return [], "shutdown", 0
            trigger = "close" if self._closed else None
            while trigger is None:
                if self._force_flush:
                    trigger = "flush"
                    break
                if len(self._pending) >= self.max_batch:
                    trigger = "max_batch"
                    break
                waited = time.perf_counter() - self._pending[0].t_enqueue
                if waited >= max_wait_s:
                    trigger = "max_wait"
                    break
                self._cond.wait(max_wait_s - waited)
                if self._closed:
                    trigger = "close"
            self._force_flush = False
            batch = [
                self._pending.popleft()
                for _ in range(min(len(self._pending), self.max_batch))
            ]
            self._cond.notify_all()  # release submitters blocked on queue_max
            return batch, trigger, len(self._pending)

    def _run(self) -> None:
        while True:
            batch, trigger, depth = self._take_batch()
            if not batch:
                return
            try:
                self._score_batch(batch, trigger, depth)
            except Exception as e:
                # The worker must survive ANYTHING a batch throws
                # (metrics IO, a consumer bug): a dead worker would hang
                # every future submit.  Futures already resolved keep
                # their scores; unresolved ones fail with the cause.
                for p in batch:
                    p.future._fail(e)

    def _score_batch(self, batch: list[_Pending], trigger: str,
                     depth: int) -> None:
        cfg = self.config
        t0 = time.perf_counter()
        try:
            snap = self.registry.active()
            feats = self.featurizer([p.raw for p in batch])
            if feats.num_raw_events != len(batch):
                # submit() validation should make this unreachable; if a
                # featurizer ever drops a validated row the misalignment
                # must fail the batch loudly, not score wrong rows.
                raise RuntimeError(
                    f"featurizer returned {feats.num_raw_events} rows "
                    f"for {len(batch)} events"
                )
            scores = score_features(
                snap.model, feats, self.featurizer.dsource,
                device_min=cfg.device_score_min,
            )
        except Exception as e:
            for p in batch:
                p.future._fail(e)
            # Counter writes take the queue lock: the worker increments
            # here while other threads read through the events_scored /
            # batches_flushed properties, and an unguarded += is a
            # read-modify-write race (lock-discipline lint).
            with self._cond:
                seq = self._batch_seq
                self._batch_seq += 1
            self._emit_safe({
                "stage": "serve", "batch": seq,
                "events": len(batch), "error": repr(e),
                "trigger": trigger,
            })
            return
        t1 = time.perf_counter()
        for p, s in zip(batch, scores):
            # lint: ok(hidden-host-sync, scores is a host np.ndarray — score_features returns numpy, the device sync already happened inside the scoring engine)
            p.future._resolve(float(s), snap.version)
        t2 = time.perf_counter()   # demux: every future delivered
        with self._cond:
            self._events_scored += len(batch)
            seq = self._batch_seq
            self._batch_seq += 1
        # Consumers run BEFORE the metrics emit: a metrics IO failure (a
        # full disk under --metrics) must not cost the batch its flagged
        # output / refresh evidence — observability is secondary to
        # delivery.  Both sides are isolated so neither can kill the
        # worker or skip the other.
        if self.on_batch is not None:
            try:
                self.on_batch(snap, feats, scores)
            except Exception as e:
                # A consumer failure (refresh-loop publish rejected, a
                # broken output pipe) must never take down scoring: the
                # batch's scores are already delivered — record the
                # error and keep serving.
                self._emit_safe({
                    "stage": "serve", "batch": seq,
                    "on_batch_error": repr(e),
                })
        n = len(batch)
        score_s = t1 - t0
        self._emit_safe({
            "stage": "serve",
            "batch": seq,
            "events": n,
            "trigger": trigger,
            "model_version": snap.version,
            # The SAME predicate batched_scores dispatched on (shared
            # helper, so the label cannot drift from the actual path;
            # device_score_min=0 prices the choice from the measured
            # dispatch calibration).
            "scorer": (
                "device" if use_device_path(n, cfg.device_score_min)
                else "host"
            ),
            # Latency of the oldest event, enqueue -> scored (the
            # number max_wait_ms bounds the left edge of), decomposed
            # along the path the event walked: queue wait (enqueue ->
            # flush start), score (featurize + device/host dispatch),
            # demux (scores -> every future delivered).  The fields
            # feed the shared serve.* histograms (serving/metrics.py),
            # whose bucket quantiles the SLO bench and the OpenMetrics
            # endpoint report.
            "latency_ms": round((t1 - batch[0].t_enqueue) * 1e3, 3),
            "queue_wait_ms": round((t0 - batch[0].t_enqueue) * 1e3, 3),
            "score_ms": round(score_s * 1e3, 3),
            "demux_ms": round((t2 - t1) * 1e3, 3),
            "events_per_sec": round(n / score_s, 1) if score_s else None,
            "queue_depth": depth,
            "flagged": int(np.sum(scores < cfg.threshold)),
        })

    def _emit_safe(self, record: dict) -> None:
        """Metrics emit that cannot take anything else down with it."""
        if self.metrics is None:
            return
        try:
            self.metrics.emit(record)
        except Exception as e:
            import sys

            print(f"serving metrics emit failed: {e!r}", file=sys.stderr)

    def observe_documents(self, feats):
        """Convenience passthrough so on_batch consumers need not import
        events.py: (ips, words) for this batch's refresh contribution."""
        return event_documents(feats, self.featurizer.dsource)
