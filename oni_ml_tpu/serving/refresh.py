"""RefreshLoop — fold scored micro-batches into online-LDA updates and
republish theta/p to the registry on a cadence.

This closes the loop the batch pipeline leaves open: the day's model
goes stale the moment it is published, and the reference's only answer
is tomorrow's retrain (ml_ops.sh runs once a day).  Here every scored
micro-batch also contributes its (ip, word) pairs as training evidence;
every `refresh_every` batches the accumulated evidence becomes one
stochastic-variational natural-gradient step (models/online_lda.py —
the SVI update is built for exactly this micro-batch regime), and the
updated topics republish through the registry's atomic hot-swap, so
in-flight scoring never sees a half-updated model.

Scope pinned at load time: the model's vocabulary and IP population are
frozen (events with unseen words/IPs score via the fallback rows and are
skipped as refresh evidence — extending the populations online would
change word/doc identity out from under the registry's validation).
Growing them is a corpus-versioning feature, not a refresh feature.
"""

from __future__ import annotations

import numpy as np

from ..config import OnlineLDAConfig
from ..io import Batch
from ..models.online_lda import OnlineLDATrainer
from ..scoring import ScoringModel
from .registry import ModelRegistry, ModelSnapshot


def topic_probs_from_log_beta(log_beta: np.ndarray) -> np.ndarray:
    """[K, V] log p(w|z) -> the [V, K] per-topic-normalized matrix the
    scorer consumes — the same exp-normalize io/formats.py
    write_word_results performs, so a refresh publishes exactly what a
    re-run of the batch post stage would."""
    log_beta = np.asarray(log_beta, np.float64)
    shifted = np.exp(log_beta - log_beta.max(axis=1, keepdims=True))
    return (shifted / shifted.sum(axis=1, keepdims=True)).T


class RefreshLoop:
    """Accumulates (ip, word) evidence per scored batch; every
    `every` batches performs one SVI step and publishes the result."""

    def __init__(
        self,
        registry: ModelRegistry,
        config: OnlineLDAConfig | None = None,
        every: int = 8,
        total_docs: int = 0,
        pseudo_tokens: float = 1e4,
    ) -> None:
        snap = registry.active()
        model = snap.model
        k = model.num_topics
        self.registry = registry
        self.every = every
        self.config = config or OnlineLDAConfig(num_topics=k)
        if self.config.num_topics != k:
            raise ValueError(
                f"refresh config has K={self.config.num_topics} but the "
                f"registry model has K={k}"
            )
        num_ips = len(model.ip_index)
        # p without its fallback row is the [V, K] matrix SVI refines.
        self.trainer = OnlineLDATrainer.from_topic_probs(
            self.config,
            np.asarray(model.p[:-1], np.float64),
            total_docs=total_docs or max(num_ips, 1),
            pseudo_tokens=pseudo_tokens,
        )
        self._counts: dict[str, dict[int, float]] = {}
        self._batches_seen = 0
        self.refreshes = 0

    def observe(self, snapshot: ModelSnapshot, ips: list[str],
                words: list[str]) -> "ModelSnapshot | None":
        """Fold one scored batch's (ip, word) pairs in; returns the new
        snapshot when this batch crossed the refresh cadence, else
        None.  Pairs with out-of-vocabulary words or unknown IPs are
        skipped (fallback rows are config constants, not trainable)."""
        model = snapshot.model
        v = len(model.word_index)
        word_rows = model.word_rows(words)
        ip_index = model.ip_index
        for ip, wr in zip(ips, word_rows):
            if wr == v or ip not in ip_index:
                continue
            doc = self._counts.setdefault(ip, {})
            doc[int(wr)] = doc.get(int(wr), 0.0) + 1.0
        self._batches_seen += 1
        if self.every and self._batches_seen % self.every == 0 \
                and self._counts:
            return self.refresh()
        return None

    def _build_batch(self) -> tuple[Batch, list[str]]:
        """Accumulated per-IP counts -> one padded micro-batch (the
        Batch contract of io/corpus.py: ids padded with 0, counts/mask
        0).  L pads to a multiple of 8 and B to a multiple of 8 so a
        steady refresh cadence reuses a handful of compiled shapes."""
        docs = sorted(self._counts.items())
        ips = [ip for ip, _ in docs]
        b = len(docs)
        l = max(len(d) for _, d in docs)
        l_pad = max(8, -(-l // 8) * 8)
        b_pad = max(8, -(-b // 8) * 8)
        word_idx = np.zeros((b_pad, l_pad), np.int32)
        counts = np.zeros((b_pad, l_pad), np.float32)
        mask = np.zeros((b_pad,), np.float32)
        for i, (_, doc) in enumerate(docs):
            for j, (wid, c) in enumerate(sorted(doc.items())):
                word_idx[i, j] = wid
                counts[i, j] = c
            mask[i] = 1.0
        return Batch(
            word_idx=word_idx,
            counts=counts,
            doc_index=np.arange(b_pad, dtype=np.int32),
            doc_mask=mask,
        ), ips

    def refresh(self) -> ModelSnapshot:
        """One natural-gradient step over the accumulated evidence, then
        publish: new p for every word, new theta rows for the IPs that
        appeared (everyone else keeps their batch-day posterior — SVI's
        doc-topic gamma is per-document local state, so absent documents
        have no update)."""
        batch, ips = self._build_batch()
        active = self.registry.active().model
        self.trainer.step(batch)
        gamma = self.trainer.infer_gamma([batch],
                                         num_docs=batch.word_idx.shape[0])
        p_vk = topic_probs_from_log_beta(self.trainer.log_beta())
        new_p = np.concatenate([p_vk, active.p[-1:]])  # keep fallback row
        new_theta = np.array(active.theta, np.float64, copy=True)
        for i, ip in enumerate(ips):
            row = gamma[i]
            total = row.sum()
            if total > 0:
                new_theta[active.ip_index[ip]] = row / total
        model = ScoringModel(
            ip_index=active.ip_index,
            theta=new_theta,
            word_index=active.word_index,
            p=new_p,
        )
        self._counts.clear()
        self.refreshes += 1
        return self.registry.publish(
            model, source=f"refresh-step{self.trainer.step_count}"
        )
