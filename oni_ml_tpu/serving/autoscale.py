"""Little's-law autoscaler: a controller thread that sizes the
replicated fleet to the offered load.

The router already enforces a bounded admission window per edge
(``route_max_inflight`` — the Little's-law cap: at most that many
events outstanding per replica).  That makes fleet sizing a one-line
application of Little's law: the concurrency actually present in the
system is L = lambda * W (arrival rate times per-event sojourn), and
the router MEASURES L directly — it is the sum of per-edge admission
windows' occupancy in ``stats()``.  The controller therefore never
estimates service times; it steers the measured occupancy fraction

    util = L / (n_replicas * route_max_inflight)

into a hysteresis band: above ``autoscale_high`` the fleet is one
replica short of keeping util at the band's midpoint — join one;
below ``autoscale_low`` (and above ``autoscale_min_replicas``) the
youngest controller-spawned replica drains out.  Utilization is
EWMA-smoothed with half-life ``autoscale_halflife_s`` so a single
bursty chunk cannot flap the fleet, and every action starts a
``autoscale_cooldown_s`` cooldown during which the controller only
observes — join/drain themselves shift util, and reacting to your own
transient is the classic controller oscillation.

Every tick journals a ``{"kind": "autoscale"}`` record carrying ALL
controller inputs (occupancy, util, EWMA, arrival rate, stall rate)
next to the decision, so a bench payload or trace_view lane can replay
exactly why the fleet grew when it did.  Scale-ups additionally carry
``reaction_s`` — the time from the band first being breached to the
replica joining — the headline the cross-host bench gates on.

Replica lifecycle is delegated: the constructor takes ``spawn()``
(returns ``(replica_id, host, port)`` of a STARTED replica) and
``stop(replica_id)`` callables, so the same controller drives
subprocess replicas (runner/route.py), in-process test replicas, and
whatever a real deployment uses.  The controller only ever drains
replicas it spawned itself — operator-connected replicas are the
floor it scales on top of.
"""

from __future__ import annotations

import threading
import time

from ..config import ServingConfig


class AutoScaler:
    """Controller-thread fleet sizing over a FleetRouter.  Lifecycle:
    construct -> start() -> (ticks happen) -> close().  ``tick()`` is
    public and takes an injectable timestamp so tests drive the
    control law without threads or sleeps."""

    def __init__(self, router, *, spawn, stop,
                 config: "ServingConfig | None" = None,
                 journal=None) -> None:
        self._router = router
        self._spawn = spawn
        self._stop_replica = stop
        self.config = config or getattr(router, "config", None) \
            or ServingConfig()
        self._journal = getattr(journal, "journal", journal)
        self._lock = threading.Lock()
        self._owned: "list[str]" = []      # spawn order; drain LIFO
        self._util_ewma: "float | None" = None
        self._last_t: "float | None" = None
        self._last_events: "int | None" = None
        self._last_stall_s: "float | None" = None
        self._cooldown_until = 0.0
        self._breach_t: "float | None" = None   # first over-band tick
        self.decisions: "list[dict]" = []
        self._stop_evt = threading.Event()
        self._thread: "threading.Thread | None" = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                raise RuntimeError("autoscaler already started")
            self._thread = threading.Thread(
                target=self._run, name="oni-autoscale", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.config.autoscale_interval_s):
            try:
                self.tick()
            except Exception as e:
                # A failed spawn/drain must not kill the controller —
                # journal it and keep observing.
                self._journal_safe({
                    "kind": "autoscale", "action": "error",
                    "error": repr(e)[:300],
                })

    def close(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    # -- the control law ---------------------------------------------------

    def tick(self, now: "float | None" = None) -> dict:
        """One controller step: sample, smooth, decide, act, journal.
        Returns the decision record (also journaled)."""
        now = time.monotonic() if now is None else now
        stats = self._router.stats()
        replicas = stats.get("replicas", [])
        n = len(replicas)
        cap = int(stats.get("max_inflight") or 0) or 1
        edges = stats.get("edges", {})
        occupancy = sum(int(e.get("inflight", 0))
                        for e in edges.values())
        events = sum(int(e.get("events", 0)) for e in edges.values())
        stall_s = sum(float(e.get("admission_stall_s", 0.0))
                      for e in edges.values())
        util = occupancy / float(max(1, n) * cap)

        with self._lock:
            dt = (now - self._last_t) if self._last_t is not None \
                else self.config.autoscale_interval_s
            dt = max(dt, 1e-9)
            # EWMA with a true half-life: alpha adapts to the actual
            # tick spacing, so a stalled controller thread does not
            # over-weight stale samples when it resumes.
            alpha = 1.0 - 0.5 ** (dt / self.config.autoscale_halflife_s)
            if self._util_ewma is None:
                self._util_ewma = util
            else:
                self._util_ewma += alpha * (util - self._util_ewma)
            util_ewma = self._util_ewma
            lambda_eps = (
                (events - self._last_events) / dt
                if self._last_events is not None else 0.0)
            stall_rate = (
                (stall_s - self._last_stall_s) / dt
                if self._last_stall_s is not None else 0.0)
            self._last_t = now
            self._last_events = events
            self._last_stall_s = stall_s
            in_cooldown = now < self._cooldown_until
            over = util_ewma > self.config.autoscale_high
            under = util_ewma < self.config.autoscale_low
            # The breach clock starts on the RAW signal (the instant
            # the band is first exceeded), while the decision waits
            # for the EWMA — so reaction_s measures what the operator
            # feels: smoothing delay + cooldown + spawn, not zero.
            raw_over = util > self.config.autoscale_high
            if raw_over and self._breach_t is None:
                self._breach_t = now
            elif not raw_over and not over:
                self._breach_t = None
            breach_t = self._breach_t

        action, reason, reaction_s = "hold", "in band", None
        if in_cooldown:
            action, reason = "hold", "cooldown"
        elif over and n >= self.config.autoscale_max_replicas:
            action, reason = "hold", "at max_replicas"
        elif over:
            action = "up"
            reason = (f"util_ewma {util_ewma:.3f} > "
                      f"high {self.config.autoscale_high:.3f}")
        elif under and n > max(self.config.autoscale_min_replicas, 1):
            with self._lock:
                candidates = [r for r in reversed(self._owned)
                              if r in replicas]
            if candidates:
                action = "down"
                reason = (f"util_ewma {util_ewma:.3f} < "
                          f"low {self.config.autoscale_low:.3f}")
            else:
                action, reason = "hold", "nothing owned to drain"

        record = {
            "kind": "autoscale", "action": action, "reason": reason,
            "replicas": n, "occupancy": occupancy,
            "util": round(util, 6), "util_ewma": round(util_ewma, 6),
            "lambda_eps": round(lambda_eps, 3),
            "stall_rate": round(stall_rate, 6),
            "cooldown": in_cooldown,
        }

        if action == "up":
            rid, host, port = self._spawn()
            self._router.join_replica(rid, host, port)
            with self._lock:
                self._owned.append(rid)
                self._cooldown_until = (
                    now + self.config.autoscale_cooldown_s)
                # The join absorbed the backlog the EWMA accumulated;
                # restart smoothing from the live sample so the next
                # decision reflects the GROWN fleet, not its history.
                self._util_ewma = None
                self._breach_t = None
            if breach_t is not None:
                reaction_s = now - breach_t
            record.update(replica=rid, reaction_s=round(
                reaction_s if reaction_s is not None else 0.0, 6))
        elif action == "down":
            victim = candidates[0]
            self._router.drain_replica(victim)
            try:
                self._stop_replica(victim)
            except Exception:
                pass
            with self._lock:
                self._owned.remove(victim)
                self._cooldown_until = (
                    now + self.config.autoscale_cooldown_s)
                self._util_ewma = None
            record.update(replica=victim)

        with self._lock:
            self.decisions.append(record)
        self._journal_safe(record)
        return record

    def _journal_safe(self, record: dict) -> None:
        if self._journal is None:
            return
        try:
            self._journal.append(record)
        except Exception as e:
            import sys

            print(f"autoscale journal append failed: {e!r}",
                  file=sys.stderr)
