"""Tiered model residency: HBM-hot / host-warm / checkpoint-cold
paging for the serving fleet.

PR 10's FleetRegistry stacks EVERY tenant's [D+1,K]/[V+1,K] theta/p
on-device, so residency is O(tenants × D × K) and a thousand-tenant
census dies at the HBM wall long before the cross-tenant batching path
saturates.  This module turns HBM into a managed cache over host RAM
and checkpoints — the LightLDA capacity-vs-model-scale move applied to
a fleet of models instead of one big one:

HBM-hot
    Members of the K-group's StackedSnapshot (serving/fleet.py): the
    shared compiled batch family scores them in packed cross-tenant
    dispatches, exactly as before.  Capacity per K-group is bounded
    (``ServingConfig.fleet_hot_tenants``, plan knob
    ``fleet_hot_tenants``).
host-warm
    The tenant's validated ModelSnapshot stays pinned in its per-tenant
    registry (host numpy), but the tenant is NOT in the stack: zero
    device bytes.  Promotion to hot is one stack rebuild — the same
    outside-the-lock hot-swap path a publish takes, so resident
    tenants never stall while another tenant pages, and under capacity
    tiers (fleet.py `_build_stack`) the stacked SHAPE never changes, so
    the compiled program family survives arbitrary promote/evict churn.
checkpoint-cold
    The model leaves host memory too.  Tenants loaded from a day
    directory reload from it (the PR 8 checkpoint contract:
    doc_results.csv / word_results.csv); programmatic tenants spill to
    an atomic npz (dataplane/sinks.py tmp+rename publication) under the
    spill dir.  float64 round-trips bit-exactly either way, and the
    registry's version counter survives the unload — a tenant paged
    cold and back serves the identical (model, version) pair.

The policy is ADMISSION-driven: every `FleetScorer.submit` touches the
tenant (`note_admission`), a touch of a non-hot tenant enqueues an
async promotion on the pager thread, and eviction victims are picked
LRU (least recently admitted) or LFU (fewest admissions), never a
tenant with events currently queued while a quiescent candidate
exists.  Every transition is journaled (``residency_promote`` /
``residency_evict``) with its priced stall, exactly like dataplane
channel stalls, and tier occupancy rides the metrics plane as
``residency.hot|warm|cold`` gauges.

Nothing here imports jax: paging is host bookkeeping + numpy IO; the
device side is entirely the stack rebuild it delegates to fleet.py.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from collections import deque
from dataclasses import dataclass

import numpy as np

from ..scoring import ScoringModel

TIER_HOT = "hot"
TIER_WARM = "warm"
TIER_COLD = "cold"
POLICIES = ("lru", "lfu")

# Pager-queue sentinel: run a warm-capacity enforcement sweep instead
# of a promotion.
_ENFORCE = "\x00enforce"


def resolve_hot_capacity(config) -> "tuple[int, str]":
    """The one resolution of the HBM-hot capacity: an explicit
    ``ServingConfig.fleet_hot_tenants`` > 0 wins (source "config"),
    else a measured plan entry for this device backend (source
    "plan"), else 0 = unbounded legacy residency (source "default").
    The config default of 0 maps to the knob's None default so the
    plan layer's override detection works unchanged."""
    from ..plans import resolve

    cfg_value = config.fleet_hot_tenants if config.fleet_hot_tenants > 0 \
        else None
    value, source = resolve("fleet_hot_tenants", cfg_value)
    return (int(value) if value else 0, source)


def spill_model(path: str, model: ScoringModel) -> int:
    """Checkpoint one model to an atomic npz (theta/p float64 plus the
    index key arrays in row order) — bit-exact round trip through
    `load_spill`.  Returns the byte size of the published file."""
    from ..dataplane.sinks import atomic_write

    ips = sorted(model.ip_index, key=model.ip_index.get)
    words = sorted(model.word_index, key=model.word_index.get)

    def _write(tmp: str) -> None:
        with open(tmp, "wb") as f:
            np.savez(
                f,
                theta=np.asarray(model.theta, np.float64),
                p=np.asarray(model.p, np.float64),
                ips=np.asarray(ips, dtype=object),
                words=np.asarray(words, dtype=object),
            )

    atomic_write(path, _write)
    return os.path.getsize(path)


def load_spill(path: str) -> ScoringModel:
    with np.load(path, allow_pickle=True) as z:  # lint: ok(no-pickle-wire, host-spill snapshot this process wrote itself — object-dtype string arrays, never wire input)
        ips = [str(s) for s in z["ips"]]
        words = [str(s) for s in z["words"]]
        return ScoringModel(
            ip_index={s: i for i, s in enumerate(ips)},
            theta=z["theta"],
            word_index={s: i for i, s in enumerate(words)},
            p=z["p"],
        )


@dataclass
class _TenantState:
    """Per-tenant residency bookkeeping.  NOT self-locking: every
    access runs under the owning ResidencyManager's lock."""

    tenant: str
    tier: str
    touch_ns: int = 0            # last admission (monotonic)
    touches: int = 0             # lifetime admissions (the LFU signal)
    promotions: int = 0
    evictions: int = 0
    day_source: "tuple | None" = None   # (day_dir, fallback) cold reload
    day_version: int = 0         # registry version the day artifacts ARE
    spill_path: "str | None" = None
    cold_spilled: bool = False   # this cold period reloads from the spill
    cold_version: int = 0
    cold_source: str = ""
    error: "str | None" = None
    # Promotion-in-flight accounting for the priced stall.
    requested_ns: "int | None" = None
    waiters: int = 0


@dataclass
class _Stats:
    promotions: int = 0
    evictions: int = 0
    cold_loads: int = 0
    spills: int = 0
    promotion_stall_ns: int = 0
    failures: int = 0
    rebuild_ns: int = 0
    read_throughs: int = 0


class ResidencyManager:
    """The three-tier pager.  Owns a daemon pager thread that performs
    promotions (and the evictions they force) OFF the scoring worker:
    the scorer only reads the lock-free `drainable` set and calls
    `note_admission` — a resident tenant's flush path never blocks on
    another tenant's disk read or stack rebuild.

    `hot_capacity` bounds stack membership per K-group (0 = unbounded:
    the manager degrades to pure bookkeeping and every registered
    tenant is immediately promoted); `warm_capacity` bounds how many
    non-hot tenants keep host-resident models (0 = unbounded, cold
    tier unused)."""

    def __init__(self, fleet, *, hot_capacity: int = 0,
                 warm_capacity: int = 0, policy: str = "lru",
                 spill_dir: str = "", journal=None, recorder=None,
                 capacity_source: str = "config") -> None:
        if policy not in POLICIES:
            raise ValueError(
                f"residency policy must be one of {POLICIES}, "
                f"got {policy!r}"
            )
        if hot_capacity < 0 or warm_capacity < 0:
            raise ValueError("residency capacities must be >= 0")
        self.fleet = fleet
        self.hot_capacity = int(hot_capacity)
        self.warm_capacity = int(warm_capacity)
        self.policy = policy
        self.plan = {"hot_tenants": {"value": self.hot_capacity,
                                     "source": capacity_source}}
        self._spill_dir = spill_dir
        self._journal = getattr(journal, "journal", journal)
        self._recorder = recorder
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._state: dict[str, _TenantState] = {}
        self._queue: deque = deque()
        self._queued: set = set()
        self._drainable: frozenset = frozenset()
        self._wakers: list = []
        self._pending_probe = None
        self._stop = False
        self.stats = _Stats()
        self._pager = threading.Thread(
            target=self._pager_loop, name="oni-residency-pager",
            daemon=True,
        )
        self._pager.start()

    # -- wiring -------------------------------------------------------------

    def add_waker(self, fn) -> None:
        """Register a callback fired (with NO residency lock held) after
        every promotion/eviction — the FleetScorer parks its worker on
        "no drainable lane" and needs the nudge."""
        with self._lock:
            self._wakers.append(fn)

    def set_pending_probe(self, fn) -> None:
        """`fn(tenant) -> bool` — does the tenant have events queued
        right now?  Admission-aware eviction consults it so a tenant
        with an in-flight burst is not evicted while a quiescent
        candidate exists.  Heuristic read (no scorer lock taken)."""
        with self._lock:
            self._pending_probe = fn

    def register(self, tenant: str, *,
                 day_source: "tuple | None" = None) -> None:
        """Admit one published tenant to residency management.  The
        tenant starts in whatever tier the fleet has it (hot if it is
        stack-resident, else warm); with a hot capacity of 0 a warm
        registrant is promoted immediately (legacy all-hot residency).
        `day_source=(day_dir, fallback)` marks the tenant cold-eligible
        via day-directory reload; without it, cold demotion spills an
        npz checkpoint.  A warm census past capacity is demoted by the
        pager in the background — a thousand-tenant startup never
        blocks registration on spill IO."""
        hot = self.fleet.is_hot(tenant)
        # The day artifacts represent the version published FROM them:
        # a later refresh publish makes them stale, and cold demotion
        # must then spill the live model instead of trusting the dir.
        day_version = 0
        if day_source is not None:
            try:
                day_version = self.fleet.version(tenant)
            except Exception:
                day_version = 0
        over_warm = False
        with self._lock:
            if tenant in self._state:
                raise ValueError(f"tenant {tenant!r} already registered")
            self._state[tenant] = _TenantState(
                tenant=tenant,
                tier=TIER_HOT if hot else TIER_WARM,
                day_source=day_source,
                day_version=day_version,
            )
            self._refresh_drainable_locked()
            if self.warm_capacity > 0 and not hot:
                warm = sum(1 for st in self._state.values()
                           if st.tier == TIER_WARM)
                over_warm = warm > self.warm_capacity
        if not hot and self.hot_capacity == 0:
            # Unbounded hot tier: residency degrades to bookkeeping.
            self._request_locked_free(tenant)
        elif over_warm:
            self._post_enforce()
        self._emit_gauges()

    def _post_enforce(self) -> None:
        """Queue a warm-capacity sweep on the pager (None sentinel)."""
        with self._lock:
            if _ENFORCE not in self._queued:
                self._queued.add(_ENFORCE)
                self._queue.append(_ENFORCE)
                self._work.notify_all()

    # -- the admission signal ----------------------------------------------

    def note_admission(self, tenant: str) -> bool:
        """Touch the tenant (the LRU/LFU signal) and, when it is not
        HBM-hot, enqueue an async promotion (idempotent).  Returns
        whether the tenant is drainable right now."""
        now = time.monotonic_ns()
        with self._lock:
            st = self._state.get(tenant)
            if st is None:
                return True          # unmanaged tenant: legacy behavior
            st.touch_ns = now
            st.touches += 1
            if st.tier == TIER_HOT:
                return True
            st.waiters += 1
            if st.requested_ns is None:
                st.requested_ns = now
            if tenant not in self._queued:
                self._queued.add(tenant)
                self._queue.append(tenant)
                self._work.notify_all()
            return tenant in self._drainable

    def _request_locked_free(self, tenant: str) -> None:
        with self._lock:
            st = self._state[tenant]
            if st.requested_ns is None:
                st.requested_ns = time.monotonic_ns()
            if tenant not in self._queued:
                self._queued.add(tenant)
                self._queue.append(tenant)
                self._work.notify_all()

    def read_through(self, tenant: str):
        """A checkpoint-cold tenant's model WITHOUT a tier change: load
        the checkpoint and hand back a snapshot at the tenant's
        preserved version.  The scorer's solo fallback uses this when
        it must drain a cold tenant's lane NOW (close-time drain, or a
        demotion racing a flush) — the events score correctly against
        the exact unloaded model instead of failing, at the price of
        one checkpoint read."""
        from .registry import ModelSnapshot

        model, version, source, origin, load_ns = \
            self._read_checkpoint(tenant)
        with self._lock:
            self.stats.read_throughs += 1
        self._journal_safe({
            "kind": "residency_promote", "tenant": tenant, "ok": True,
            "tier_from": TIER_COLD, "tier_to": "read_through",
            "load_s": round(load_ns / 1e9, 6),
            "source": origin,
        })
        # Not a publish and not registered anywhere: published_at 0.0
        # marks it as a transient read-through snapshot.
        return ModelSnapshot(model=model, version=version,
                             source=source, published_at=0.0)

    def request_promotions(self, tenants) -> None:
        """Re-request promotion for tenants with STRANDED events: an
        event admitted while its tenant was hot orphans if the tenant
        is evicted before the drain — no later admission exists to
        re-trigger paging.  The scorer calls this for any pending,
        non-drainable lane before parking its worker.  Idempotent; does
        not count as an admission touch (a stranded retry must not
        make the victim look recently used)."""
        now = time.monotonic_ns()
        with self._lock:
            for tenant in tenants:
                st = self._state.get(tenant)
                if st is None or st.tier == TIER_HOT:
                    continue
                if st.requested_ns is None:
                    st.requested_ns = now
                if tenant not in self._queued:
                    self._queued.add(tenant)
                    self._queue.append(tenant)
                    self._work.notify_all()

    def ensure_hot(self, tenant: str, timeout: float = 30.0) -> None:
        """Synchronous promotion: request and wait until the tenant is
        HBM-hot (tests, warmup).  Raises on promotion failure or
        timeout."""
        deadline = time.monotonic() + timeout
        self._request_locked_free(tenant)
        with self._lock:
            while True:
                st = self._state[tenant]
                if st.tier == TIER_HOT:
                    return
                if st.error is not None:
                    raise RuntimeError(
                        f"promotion of {tenant!r} failed: {st.error}"
                    )
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"promotion of {tenant!r} did not complete in "
                        f"{timeout}s"
                    )
                self._work.wait(min(left, 0.25))

    @property
    def drainable(self) -> frozenset:
        """Tenants the scorer may flush right now: the HBM-hot set plus
        any tenant whose promotion FAILED (its lane drains through the
        solo fallback, failing tenant-scoped instead of wedging the
        queue).  Lock-free read of an immutable snapshot."""
        return self._drainable

    def is_managed(self, tenant: str) -> bool:
        """Whether this tenant is under residency management.  An
        unmanaged tenant keeps full legacy behavior — the scorer
        drains it unconditionally (dict-membership read, no lock: the
        GIL makes it atomic and registration is monotonic)."""
        return tenant in self._state

    def tier_of(self, tenant: str) -> str:
        with self._lock:
            return self._state[tenant].tier

    def tiers(self) -> dict:
        with self._lock:
            out = {TIER_HOT: 0, TIER_WARM: 0, TIER_COLD: 0}
            for st in self._state.values():
                out[st.tier] += 1
            return out

    def stats_snapshot(self) -> dict:
        with self._lock:
            s = self.stats
            tiers = {TIER_HOT: 0, TIER_WARM: 0, TIER_COLD: 0}
            for st in self._state.values():
                tiers[st.tier] += 1
            return {
                "policy": self.policy,
                "hot_capacity": self.hot_capacity,
                "warm_capacity": self.warm_capacity,
                "tiers": tiers,
                "promotions": s.promotions,
                "evictions": s.evictions,
                "cold_loads": s.cold_loads,
                "spills": s.spills,
                "read_throughs": s.read_throughs,
                "failures": s.failures,
                "promotion_stall_s": round(
                    s.promotion_stall_ns / 1e9, 6),
                "rebuild_s": round(s.rebuild_ns / 1e9, 6),
                "plan": dict(self.plan),
            }

    def close(self, timeout: float = 10.0) -> None:
        with self._lock:
            self._stop = True
            self._work.notify_all()
        self._pager.join(timeout)

    # -- the pager ----------------------------------------------------------

    def _pager_loop(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._stop:
                    self._work.wait()
                if not self._queue:
                    return           # stop requested, queue drained
                tenant = self._queue.popleft()
            if tenant == _ENFORCE:
                with self._lock:
                    self._queued.discard(_ENFORCE)
                try:
                    self._enforce_warm_capacity()
                    self._emit_gauges()
                except Exception as e:
                    self._journal_safe({
                        "kind": "residency_evict", "tenant": None,
                        "ok": False, "error": repr(e)[:300],
                    })
                continue
            try:
                self._promote(tenant)
            except Exception as e:
                with self._lock:
                    st = self._state.get(tenant)
                    if st is not None:
                        st.error = repr(e)[:300]
                        st.requested_ns = None
                        st.waiters = 0
                    self._queued.discard(tenant)
                    self.stats.failures += 1
                    self._refresh_drainable_locked()
                    self._work.notify_all()
                self._journal_safe({
                    "kind": "residency_promote", "tenant": tenant,
                    "ok": False, "error": repr(e)[:300],
                })
            self._fire_wakers()

    def _promote(self, tenant: str) -> None:
        """One promotion, pager-thread only.  Cold tenants reload their
        checkpoint first (cold→warm), then the hot admission evicts a
        policy victim if the K-group is at capacity and flips both
        memberships in ONE stack rebuild — outside every lock the
        scoring path takes."""
        with self._lock:
            st = self._state[tenant]
            tier_from = st.tier
            self._queued.discard(tenant)
            if st.tier == TIER_HOT:
                st.requested_ns = None
                st.waiters = 0
                return
        if tier_from == TIER_COLD:
            self._load_cold(tenant)
        k = self.fleet.tenant_k(tenant)
        changes = {tenant: True}
        victims = []
        if self.hot_capacity > 0:
            census = [t for t in self.fleet.hot_census(k) if t != tenant]
            while len(census) + 1 > self.hot_capacity:
                victim = self._pick_victim(census)
                census.remove(victim)
                victims.append(victim)
                changes[victim] = False
        t0 = time.monotonic_ns()
        self.fleet.set_hot_many(changes)
        rebuild_ns = time.monotonic_ns() - t0
        now = time.monotonic_ns()
        with self._lock:
            st = self._state[tenant]
            stall_ns = (now - st.requested_ns) \
                if st.requested_ns is not None else 0
            waiters = st.waiters
            st.tier = TIER_HOT
            st.promotions += 1
            st.requested_ns = None
            st.waiters = 0
            st.error = None
            for v in victims:
                vs = self._state.get(v)
                if vs is not None:
                    vs.tier = TIER_WARM
                    vs.evictions += 1
            self.stats.promotions += 1
            self.stats.evictions += len(victims)
            self.stats.promotion_stall_ns += stall_ns
            self.stats.rebuild_ns += rebuild_ns
            self._refresh_drainable_locked()
            self._work.notify_all()
        tier = self.fleet.tier(k) or {}
        self._journal_safe({
            "kind": "residency_promote", "tenant": tenant, "ok": True,
            "tier_from": tier_from, "k": k,
            "stall_s": round(stall_ns / 1e9, 6),
            "rebuild_s": round(rebuild_ns / 1e9, 6),
            "waiters": waiters,
            "census": len(self.fleet.hot_census(k)),
            "capacity": tier.get("capacity"),
            "evicted": victims,
        })
        if self._recorder is not None:
            rec = self._recorder
            rec.counter("residency.promotions").add(1)
            rec.histogram("residency.promotion_stall_s").observe(
                stall_ns / 1e9)
            rec.histogram("residency.rebuild_s").observe(rebuild_ns / 1e9)
        for v in victims:
            self._journal_safe({
                "kind": "residency_evict", "tenant": v,
                "tier_to": TIER_WARM, "k": k, "policy": self.policy,
                "for_tenant": tenant,
            })
            if self._recorder is not None:
                self._recorder.counter("residency.evictions").add(1)
        self._enforce_warm_capacity()
        self._emit_gauges()

    def _pick_victim(self, census: "list[str]") -> str:
        """Admission-aware LRU/LFU: among the K-group's hot members,
        prefer tenants with NO events currently queued; order the
        preferred pool least-recently-admitted (lru) or
        least-admitted-overall with recency tiebreak (lfu).  Unmanaged
        tenants (registered with the fleet but not with residency) are
        never evicted."""
        with self._lock:
            probe = self._pending_probe
            managed = [t for t in census if t in self._state]
            if not managed:
                raise RuntimeError(
                    "hot K-group is at capacity but holds no "
                    "residency-managed tenant to evict"
                )
            quiescent = managed
            if probe is not None:
                idle = [t for t in managed if not probe(t)]
                if idle:
                    quiescent = idle

            def key(t):
                st = self._state[t]
                if self.policy == "lfu":
                    return (st.touches, st.touch_ns)
                return (st.touch_ns,)

            return min(quiescent, key=key)

    # -- cold tier ----------------------------------------------------------

    def _read_checkpoint(self, tenant: str):
        """THE cold-tier read, shared by the pager's cold→warm leg and
        the scorer's read-through: returns (model, version, source,
        origin, load_ns).  Reloads from the day dir only when this cold
        period did NOT spill (a refresh publish makes the day artifacts
        stale — `_demote_cold` then spills the live model and marks
        `cold_spilled`, and the reload must honor that)."""
        with self._lock:
            st = self._state[tenant]
            day_source = st.day_source
            spill_path = st.spill_path
            use_spill = st.cold_spilled or day_source is None
            version, source = st.cold_version, st.cold_source
        t0 = time.monotonic_ns()
        if not use_spill and day_source is not None:
            day_dir, fallback = day_source
            model = ScoringModel.from_files(
                os.path.join(day_dir, "doc_results.csv"),
                os.path.join(day_dir, "word_results.csv"),
                fallback,
            )
            origin = "day_dir"
        elif spill_path is not None:
            model = load_spill(spill_path)
            origin = "spill"
        else:
            raise RuntimeError(
                f"tenant {tenant!r} is cold with no checkpoint source"
            )
        return model, version, source, origin, time.monotonic_ns() - t0

    def _load_cold(self, tenant: str) -> None:
        """cold→warm: reload the checkpoint and reinstall it at the
        ORIGINAL version (registry restore, not publish).  If a publish
        raced the cold period (a RefreshLoop firing off a read-through
        drain), the registry already holds a NEWER model — adopt it
        instead of restoring over it."""
        if self.fleet.loaded(tenant):
            with self._lock:
                st = self._state[tenant]
                st.tier = TIER_WARM
            self._journal_safe({
                "kind": "residency_promote", "tenant": tenant,
                "ok": True, "tier_from": TIER_COLD,
                "tier_to": TIER_WARM, "source": "published",
            })
            return
        model, version, source, origin, load_ns = \
            self._read_checkpoint(tenant)
        self.fleet.restore_tenant(tenant, model, source, version)
        with self._lock:
            st = self._state[tenant]
            st.tier = TIER_WARM
            self.stats.cold_loads += 1
        self._journal_safe({
            "kind": "residency_promote", "tenant": tenant, "ok": True,
            "tier_from": TIER_COLD, "tier_to": TIER_WARM,
            "load_s": round(load_ns / 1e9, 6),
            "source": origin,
        })
        if self._recorder is not None:
            self._recorder.histogram("residency.cold_load_s").observe(
                load_ns / 1e9)

    def _enforce_warm_capacity(self) -> None:
        """Demote the policy-coldest warm tenants to checkpoint-cold
        until the warm census fits.  Pager-thread only."""
        if self.warm_capacity <= 0:
            return
        while True:
            with self._lock:
                warm_names = [st.tenant for st in self._state.values()
                              if st.tier == TIER_WARM]
            # Eligibility check OUTSIDE the manager lock (fleet.loaded
            # takes registry locks): a registered-but-never-published
            # tenant has nothing to unload and must not be re-picked
            # forever.
            eligible = [t for t in warm_names if self.fleet.loaded(t)]
            with self._lock:
                warm = [self._state[t] for t in eligible
                        if self._state[t].tier == TIER_WARM]
                over = len([st for st in self._state.values()
                            if st.tier == TIER_WARM]) \
                    - self.warm_capacity
                if over <= 0 or not warm:
                    return

                def key(st):
                    if self.policy == "lfu":
                        return (st.touches, st.touch_ns)
                    return (st.touch_ns,)

                victim = min(warm, key=key).tenant
            self._demote_cold(victim)

    def _demote_cold(self, tenant: str) -> None:
        snap = self.fleet.unload_tenant(tenant)
        if snap is None:
            return
        with self._lock:
            st = self._state[tenant]
            st.cold_version = snap.version
            st.cold_source = snap.source
            # The day artifacts ARE the model only at the version they
            # published; after a refresh the live snapshot must spill,
            # or a cold reload would silently resurrect the
            # pre-refresh model under the post-refresh version.
            spill = st.day_source is None \
                or snap.version != st.day_version
            st.cold_spilled = spill
        spill_bytes = None
        if spill:
            path = os.path.join(self._spill_root(), f"{tenant}.npz")
            spill_bytes = spill_model(path, snap.model)
            with self._lock:
                self._state[tenant].spill_path = path
                self.stats.spills += 1
        with self._lock:
            self._state[tenant].tier = TIER_COLD
            self.stats.evictions += 1
        self._journal_safe({
            "kind": "residency_evict", "tenant": tenant,
            "tier_to": TIER_COLD, "policy": self.policy,
            "version": snap.version,
            "spill_bytes": spill_bytes,
        })
        if self._recorder is not None:
            self._recorder.counter("residency.evictions").add(1)
        self._emit_gauges()

    def _spill_root(self) -> str:
        with self._lock:
            if not self._spill_dir:
                self._spill_dir = tempfile.mkdtemp(
                    prefix="oni_residency_")
            os.makedirs(self._spill_dir, exist_ok=True)
            return self._spill_dir

    # -- internals ----------------------------------------------------------

    def _refresh_drainable_locked(self) -> None:
        """Caller holds self._lock."""
        self._drainable = frozenset(
            t for t, st in self._state.items()
            if st.tier == TIER_HOT or st.error is not None
        )

    def _fire_wakers(self) -> None:
        with self._lock:
            wakers = list(self._wakers)
        for fn in wakers:
            try:
                fn()
            except Exception:
                pass

    def _emit_gauges(self) -> None:
        if self._recorder is None:
            return
        with self._lock:
            tiers = {TIER_HOT: 0, TIER_WARM: 0, TIER_COLD: 0}
            for st in self._state.values():
                tiers[st.tier] += 1
        for tier, n in tiers.items():
            self._recorder.gauge(f"residency.{tier}", n)

    def _journal_safe(self, record: dict) -> None:
        if self._journal is None:
            return
        try:
            self._journal.append(record)
        except Exception as e:
            import sys

            print(f"residency journal append failed: {e!r}",
                  file=sys.stderr)
