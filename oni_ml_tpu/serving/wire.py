"""Columnar zero-copy serving wire + same-host shared-memory ring.

The replicated fleet's original wire was length-prefixed **pickle**
frames (serving/replica.py, PR 15) — fine inside one trust domain, but
every submit re-serialized typed arrays as Python object graphs, and
unpickling is the one place a frame's bytes execute code, which a
cross-host fleet cannot accept.  This module replaces it with a
versioned columnar frame in the dataplane's own vocabulary
(dataplane/columns.py): typed arrays travel as raw buffers with
dtype/shape descriptors and decode as **zero-copy numpy views** over
the received frame; everything scalar rides a compact JSON meta blob.

Frame layout (`encode_payload`):

    header      !4sBBHI — magic b"OCWF", version, kind, ncols, meta_len
    descriptors per column: name (!H + utf8), dtype str (!B + utf8,
                numpy dtype.str, byte order explicit), ndim (!B),
                dims (!q each)
    meta        meta_len bytes of JSON (op name, scalar fields, the
                per-key encoding tags)
    buffers     each column's raw bytes, 8-byte aligned

`decode_payload` decodes columnar frames (``OCWF`` magic) always; a
frame that does not open with the magic is unpickled ONLY when the
``codec`` argument says this link actually negotiated the **pickle
fallback** (serving/wire_pickle.py — the one-release compatibility
path, behind ``ServingConfig.wire_accept_pickle`` and an allowlisted
unpickler).  On a columnar link a non-magic frame is rejected
outright: a peer can never force the pickle codec onto a receiver by
sending non-magic bytes.  Version mismatches, truncated buffers,
hostile descriptors, and length drift all fail loudly as
ConnectionError — the wire's single failure mode — before any
allocation-by-attacker.

Typed encodings (tagged per top-level message key):

    ``nd``     numpy array -> one column, zero-copy both ways
    ``i8l``    list[int] (submit_many ids) -> int64 column
    ``s1``     list[str] -> utf8 blob + int64 offsets
    ``s2``     list[list[str]] (submit_many raws) -> flattened utf8
               blob + offsets + per-row field counts
    ``cuts``   tuple of numeric sequences -> one float64 column each
    ``model``  ScoringModel -> theta/p columns + key/value columns
    ``colset`` dataplane ColumnSet -> one column per schema field
    ``opq``    no columnar encoding (the featurizer push) ->
               wire_pickle opaque bytes (decoded through the
               allowlisted unpickler), tagged so the lint budget for
               pickle stays exactly one module

Score batches (the replica resolver's coalesced responses) get a
dedicated frame kind: ids/scores/versions as three columns — the bulk
response path never materializes per-event dicts on the wire, and
float64 scores round-trip bit-identical by construction.

``ShmRing``: same-host router<->replica pairs negotiated via ``hello``
upgrade the DATA path to a pair of these — two fixed shared-memory
slabs (``multiprocessing.shared_memory``) double-buffered under a
futex-free seqlock header.  The producer fills slab ``wseq % 2`` while
the consumer drains the other; publication is a seqlock'd counter
bump (writer makes the guard odd, writes, makes it even; the reader
rereads until stable), so neither side ever takes a lock the other
can die holding, and a SIGKILL'd peer leaves nothing to clean but the
segment itself.  Local hops never touch the TCP stack; the TCP
connection stays open purely as the liveness/EOF signal and the
oversize-frame escape.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time

import numpy as np

from . import wire_pickle

MAGIC = b"OCWF"
WIRE_VERSION = 1
KIND_MSG = 1
KIND_SCORES = 2
_ALIGN = 8
_HDR = struct.Struct("!4sBBHI")
_LEN = struct.Struct("!I")
# One frame holds one op (the bulkiest is add_tenant carrying a
# tenant's model) — bound it so a corrupted length prefix fails loudly
# instead of allocating gigabytes.
MAX_FRAME_BYTES = 256 << 20


# ---------------------------------------------------------------------------
# scalar-field classification
# ---------------------------------------------------------------------------


def _jsonable(v) -> bool:
    """True when `v` survives the JSON meta blob faithfully (tuples
    coerce to lists — accepted and documented; non-str dict keys do
    NOT, so they fall through to a typed encoding or the opaque tag)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return True
    if isinstance(v, (list, tuple)):
        return all(_jsonable(x) for x in v)
    if isinstance(v, dict):
        return all(isinstance(k, str) and _jsonable(x)
                   for k, x in v.items())
    return False


def _is_model(v) -> bool:
    return (hasattr(v, "theta") and hasattr(v, "p")
            and hasattr(v, "ip_index") and hasattr(v, "word_index"))


def _is_colset(v) -> bool:
    return (hasattr(v, "schema") and hasattr(v, "columns")
            and hasattr(v, "names"))


def _is_cuts(v) -> bool:
    if not isinstance(v, (tuple, list)) or not v:
        return False
    for part in v:
        if isinstance(part, np.ndarray):
            if part.ndim != 1:
                return False
        elif isinstance(part, (list, tuple)):
            if not all(isinstance(x, (int, float)) for x in part):
                return False
        else:
            return False
    return True


def _pack_strs(strs) -> "tuple[np.ndarray, np.ndarray]":
    bs = [s.encode("utf-8") for s in strs]
    off = np.zeros(len(bs) + 1, np.int64)
    if bs:
        np.cumsum([len(b) for b in bs], out=off[1:])
    blob = np.frombuffer(b"".join(bs), np.uint8)
    return blob, off


def _unpack_strs(blob: np.ndarray, off: np.ndarray) -> "list[str]":
    raw = blob.tobytes()
    bounds = off.tolist()
    return [raw[bounds[i]:bounds[i + 1]].decode("utf-8")
            for i in range(len(bounds) - 1)]


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------


def encode_payload(obj) -> bytes:
    """One message -> one columnar frame payload.  Messages are the
    op dicts replica.py/router.py already exchange, or the resolver's
    list-of-score-responses batches."""
    if isinstance(obj, list):
        return _encode_scores(obj)
    if not isinstance(obj, dict):
        raise TypeError(
            f"wire payload must be an op dict or a score batch, "
            f"got {type(obj).__name__}")
    fields: dict = {}
    enc: dict = {}
    cuts_n: dict = {}
    cols: "list[tuple[str, np.ndarray]]" = []

    def add(name: str, arr: np.ndarray) -> None:
        cols.append((name, np.ascontiguousarray(arr)))

    for k, v in obj.items():
        if isinstance(v, np.ndarray):
            enc[k] = "nd"
            add(k, v)
        elif _is_model(v):
            enc[k] = "model"
            add(f"{k}.theta", np.asarray(v.theta))
            add(f"{k}.p", np.asarray(v.p))
            ikb, iko = _pack_strs(v.ip_index.keys())
            add(f"{k}.ikb", ikb)
            add(f"{k}.iko", iko)
            add(f"{k}.ikv", np.fromiter(
                v.ip_index.values(), np.int64, len(v.ip_index)))
            wkb, wko = _pack_strs(v.word_index.keys())
            add(f"{k}.wkb", wkb)
            add(f"{k}.wko", wko)
            add(f"{k}.wkv", np.fromiter(
                v.word_index.values(), np.int64, len(v.word_index)))
        elif _is_colset(v):
            enc[k] = "colset"
            for name in v.names():
                add(f"{k}.{name}", v.columns[name].values)
        elif (k == "raws" and isinstance(v, list)
                and all(isinstance(r, (list, tuple)) for r in v)):
            enc[k] = "s2"
            flat = [f for row in v for f in row]
            blob, off = _pack_strs(flat)
            add(f"{k}.b", blob)
            add(f"{k}.o", off)
            add(f"{k}.n", np.fromiter(
                (len(row) for row in v), np.int32, len(v)))
        elif (k == "ids" and isinstance(v, list)
                and all(isinstance(x, int) for x in v)):
            enc[k] = "i8l"
            add(k, np.asarray(v, np.int64))
        elif _is_cuts(v):
            enc[k] = "cuts"
            cuts_n[k] = len(v)
            for i, part in enumerate(v):
                add(f"{k}.{i}", np.asarray(part, np.float64))
        elif _jsonable(v):
            fields[k] = v
        else:
            enc[k] = "opq"
            add(k, np.frombuffer(wire_pickle.encode_opaque(v),
                                 np.uint8))
    meta = {"f": fields}
    if enc:
        meta["e"] = enc
    if cuts_n:
        meta["cn"] = cuts_n
    return _frame(KIND_MSG, meta, cols)


def _encode_scores(batch: list) -> bytes:
    n = len(batch)
    ids = np.empty(n, np.int64)
    scores = np.zeros(n, np.float64)
    versions = np.zeros(n, np.int64)
    errors = []
    for i, rsp in enumerate(batch):
        extra = set(rsp) - {"id", "score", "version", "error"}
        if extra:
            raise TypeError(
                f"score batch entry has non-score keys {sorted(extra)}")
        ids[i] = rsp["id"]
        if "error" in rsp:
            errors.append([i, str(rsp["error"])])
        else:
            scores[i] = rsp["score"]
            versions[i] = rsp.get("version", 0)
    meta = {"err": errors} if errors else {}
    return _frame(KIND_SCORES, meta,
                  [("id", ids), ("score", scores), ("ver", versions)])


def _frame(kind: int, meta: dict, cols) -> bytes:
    desc = bytearray()
    for name, arr in cols:
        nb = name.encode("utf-8")
        db = arr.dtype.str.encode("ascii")
        desc += struct.pack("!H", len(nb)) + nb
        desc += struct.pack("!B", len(db)) + db
        desc += struct.pack("!B", arr.ndim)
        for d in arr.shape:
            desc += struct.pack("!q", d)
    mb = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    head = _HDR.pack(MAGIC, WIRE_VERSION, kind, len(cols), len(mb))
    parts = [head, bytes(desc), mb]
    off = len(head) + len(desc) + len(mb)
    for _, arr in cols:
        pad = (-off) % _ALIGN
        if pad:
            parts.append(b"\0" * pad)
            off += pad
        parts.append(memoryview(arr).cast("B"))
        off += arr.nbytes
    return b"".join(parts)


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------


def decode_payload(buf, codec: str = "columnar"):
    """Frame payload -> message.  Columnar frames (magic match) decode
    as zero-copy views over `buf`.  A non-magic frame decodes through
    the pickle fallback ONLY when `codec` says this link negotiated
    it; on a columnar link it is rejected as a ConnectionError, so
    the unpickler is unreachable for peers that never negotiated the
    fallback."""
    mv = memoryview(buf)
    if len(mv) >= 4 and bytes(mv[:4]) == MAGIC:
        return _decode_columnar(mv)
    if codec == "pickle":
        return wire_pickle.decode_payload(mv)
    raise ConnectionError(
        f"non-columnar frame ({len(mv)} bytes) on a link that did "
        "not negotiate the pickle fallback")


def _short(mv, need: int, pos: int, what: str) -> None:
    if pos + need > len(mv):
        raise ConnectionError(
            f"truncated wire frame: {what} needs {need} bytes at "
            f"offset {pos}, frame is {len(mv)}")


def _decode_columnar(mv: memoryview):
    """Every decode failure — truncation, hostile descriptors (bad
    dtype strings, negative dims), missing columns, bad UTF-8/JSON —
    surfaces as the wire's uniform ConnectionError, never a
    codec-specific TypeError/ValueError/KeyError that would escape a
    reader's ``except (ConnectionError, OSError)``."""
    try:
        return _decode_columnar_body(mv)
    except ConnectionError:
        raise
    except Exception as e:
        raise ConnectionError(
            f"undecodable columnar frame ({len(mv)} bytes): {e!r}")


def _decode_columnar_body(mv: memoryview):
    _short(mv, _HDR.size, 0, "header")
    magic, ver, kind, ncols, meta_len = _HDR.unpack_from(mv, 0)
    if ver != WIRE_VERSION:
        raise ConnectionError(
            f"wire version mismatch: frame v{ver}, this end speaks "
            f"v{WIRE_VERSION}")
    pos = _HDR.size
    descs = []
    for _ in range(ncols):
        _short(mv, 2, pos, "descriptor")
        (nlen,) = struct.unpack_from("!H", mv, pos)
        pos += 2
        _short(mv, nlen + 2, pos, "descriptor")
        name = bytes(mv[pos:pos + nlen]).decode("utf-8")
        pos += nlen
        (dlen,) = struct.unpack_from("!B", mv, pos)
        pos += 1
        _short(mv, dlen + 1, pos, "descriptor")
        dt = bytes(mv[pos:pos + dlen]).decode("ascii")
        pos += dlen
        (ndim,) = struct.unpack_from("!B", mv, pos)
        pos += 1
        _short(mv, 8 * ndim, pos, "descriptor dims")
        shape = struct.unpack_from(f"!{ndim}q", mv, pos)
        pos += 8 * ndim
        descs.append((name, dt, shape))
    _short(mv, meta_len, pos, "meta")
    meta = json.loads(bytes(mv[pos:pos + meta_len]))
    pos += meta_len
    cols: "dict[str, np.ndarray]" = {}
    for name, dt, shape in descs:
        pos += (-pos) % _ALIGN
        dtype = np.dtype(dt)
        count = 1
        for d in shape:
            if d < 0:
                raise ConnectionError(
                    f"negative dim {d} in column {name!r} descriptor")
            count *= d
        nbytes = count * dtype.itemsize
        _short(mv, nbytes, pos, f"column {name!r}")
        arr = np.frombuffer(mv[pos:pos + nbytes], dtype=dtype)
        if len(shape) != 1:
            arr = arr.reshape(shape)
        cols[name] = arr
        pos += nbytes
    if pos != len(mv):
        raise ConnectionError(
            f"wire frame length drift: decoded {pos} of {len(mv)} "
            "bytes")
    if kind == KIND_SCORES:
        return _decode_scores(meta, cols)
    if kind == KIND_MSG:
        return _decode_msg(meta, cols)
    raise ConnectionError(f"unknown wire frame kind {kind}")


def _decode_scores(meta: dict, cols: dict) -> list:
    ids = cols["id"].tolist()
    scores = cols["score"]
    versions = cols["ver"].tolist()
    errs = {i: msg for i, msg in meta.get("err", [])}
    out = []
    for i, rid in enumerate(ids):
        if i in errs:
            out.append({"id": rid, "error": errs[i]})
        else:
            out.append({"id": rid, "score": float(scores[i]),
                        "version": versions[i]})
    return out


def _decode_msg(meta: dict, cols: dict) -> dict:
    obj = dict(meta.get("f", {}))
    for k, tag in meta.get("e", {}).items():
        if tag == "nd":
            obj[k] = cols[k]
        elif tag == "i8l":
            obj[k] = cols[k].tolist()
        elif tag == "s1":
            obj[k] = _unpack_strs(cols[f"{k}.b"], cols[f"{k}.o"])
        elif tag == "s2":
            flat = _unpack_strs(cols[f"{k}.b"], cols[f"{k}.o"])
            rows = []
            i = 0
            for n in cols[f"{k}.n"].tolist():
                rows.append(flat[i:i + n])
                i += n
            obj[k] = rows
        elif tag == "cuts":
            obj[k] = tuple(
                cols[f"{k}.{i}"].tolist()
                for i in range(meta["cn"][k]))
        elif tag == "model":
            from ..scoring.score import ScoringModel

            ik = _unpack_strs(cols[f"{k}.ikb"], cols[f"{k}.iko"])
            wk = _unpack_strs(cols[f"{k}.wkb"], cols[f"{k}.wko"])
            obj[k] = ScoringModel(
                ip_index=dict(zip(ik, cols[f"{k}.ikv"].tolist())),
                theta=cols[f"{k}.theta"],
                word_index=dict(zip(wk, cols[f"{k}.wkv"].tolist())),
                p=cols[f"{k}.p"],
            )
        elif tag == "colset":
            from ..dataplane.columns import Column, ColumnSet

            prefix = f"{k}."
            obj[k] = ColumnSet({
                name[len(prefix):]: Column(name[len(prefix):],
                                           cols[name])
                for name in cols if name.startswith(prefix)
            })
        elif tag == "opq":
            obj[k] = wire_pickle.decode_opaque(cols[k])
        else:
            raise ConnectionError(
                f"unknown wire field encoding {tag!r} for key {k!r}")
    return obj


# ---------------------------------------------------------------------------
# socket framing
# ---------------------------------------------------------------------------


def send_frame(sock: socket.socket, obj,
               lock: "threading.Lock | None" = None, *,
               codec: str = "columnar") -> int:
    """Encode `obj` with the link's negotiated codec and write one
    length-prefixed frame.  `lock` serializes concurrent writers on a
    shared socket (sendall is not atomic across threads).  Returns the
    payload byte count — the edges' wire_bytes accounting."""
    if codec == "pickle":
        data = wire_pickle.encode_payload(obj)
    else:
        data = encode_payload(obj)
    if len(data) > MAX_FRAME_BYTES:
        raise ValueError(f"frame too large: {len(data)} bytes")
    buf = _LEN.pack(len(data)) + data
    if lock is not None:
        with lock:
            sock.sendall(buf)
    else:
        sock.sendall(buf)
    return len(data)


def recv_frame(sock: socket.socket, codec: str = "columnar"):
    """Read one frame; raises ConnectionError on EOF / short read /
    oversized announcement / malformed payload.  `codec` is this
    link's NEGOTIATED frame codec: a non-columnar frame only decodes
    when the link settled on the pickle fallback."""
    return recv_frame_tagged(sock, codec)[0]


def recv_frame_tagged(sock: socket.socket,
                      codec: str = "columnar") -> "tuple[object, str]":
    """recv_frame plus the codec the peer used on THIS frame — the
    replica mirrors it on responses, so a negotiated-fallback peer is
    answered in the codec it can actually read.  Decoding is gated by
    `codec` (what the link negotiated), not by the tag: a pickle
    frame on a columnar link raises instead of unpickling."""
    head = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME_BYTES:
        raise ConnectionError(f"oversized frame announced: {n} bytes")
    payload = _recv_exact(sock, n)
    tag = ("columnar" if payload[:4] == MAGIC else "pickle")
    return decode_payload(payload, codec=codec), tag


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    parts = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        parts.append(chunk)
        n -= len(chunk)
    return b"".join(parts)


# ---------------------------------------------------------------------------
# same-host shared-memory ring
# ---------------------------------------------------------------------------

_RING_MAGIC = b"OCWR"


class _RingStuck(ConnectionError):
    """Seqlock guard never stabilized: the peer died between its odd
    and even guard writes (SIGKILL mid-_locked_write).  push/pop
    translate this into their closed-ring return values so callers
    fall back to the TCP path."""



# Header: magic+ver (8) | pseq (8) | wseq (8) | len0 (8) | len1 (8)
#         | cseq (8) | rseq (8) | closed (8)
_RING_HDR = 64
_Q = struct.Struct("<Q")
_OFF_PSEQ, _OFF_WSEQ, _OFF_LEN0, _OFF_LEN1 = 8, 16, 24, 32
_OFF_CSEQ, _OFF_RSEQ, _OFF_CLOSED = 40, 48, 56


class ShmRing:
    """Single-producer single-consumer frame ring over one shared-memory
    segment: two fixed slabs, double-buffered, published through a
    futex-free seqlock header.  The producer fills slab ``wseq % 2``
    while the consumer drains slab ``rseq % 2``; a slab is reused only
    after the consumer's seqlock'd ``rseq`` bump acknowledges it, so
    frame bytes are never overwritten while the peer may still read
    them.  No locks, no fds, no syscalls on the hot path — a SIGKILL'd
    peer leaves the ring in a consistent state and the survivor's
    poll loop simply times out.  The one inconsistent death — killed
    BETWEEN the odd and even guard writes of a seqlock publish — is
    bounded by ``_SEQLOCK_STUCK_S``: a guard that never stabilizes
    marks the ring closed and the survivor degrades to TCP instead of
    spinning forever."""

    # How long a reader rereads an odd/unstable seqlock guard before
    # declaring the writer dead mid-publish.  A live writer holds the
    # guard odd for a handful of header stores (microseconds); seconds
    # of instability means the peer died inside _locked_write.
    _SEQLOCK_STUCK_S = 2.0

    def __init__(self, shm, slab_bytes: int, *, owner: bool) -> None:
        self._shm = shm
        self._buf = shm.buf
        self._slab = slab_bytes
        self._owner = owner
        self._unlinked = False
        self.name = shm.name

    # -- construction ------------------------------------------------------

    @classmethod
    def create(cls, slab_bytes: int) -> "ShmRing":
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(
            create=True, size=_RING_HDR + 2 * slab_bytes)
        shm.buf[:_RING_HDR] = bytes(_RING_HDR)
        shm.buf[:4] = _RING_MAGIC
        shm.buf[4] = WIRE_VERSION
        return cls(shm, slab_bytes, owner=True)

    @classmethod
    def attach(cls, name: str, slab_bytes: int) -> "ShmRing":
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(name=name)
        # On < 3.13 the attach side's resource_tracker would UNLINK the
        # segment when this process exits, yanking it from the owner —
        # deregister it; the creating side owns cleanup.
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:
            pass
        if bytes(shm.buf[:4]) != _RING_MAGIC:
            shm.close()
            raise ConnectionError(f"shm segment {name!r} is not a ring")
        if shm.buf[4] != WIRE_VERSION:
            ver = shm.buf[4]
            shm.close()
            raise ConnectionError(
                f"ring version mismatch: segment v{ver}, this end "
                f"speaks v{WIRE_VERSION}")
        return cls(shm, slab_bytes, owner=False)

    # -- seqlock'd header fields ------------------------------------------

    def _read_u64(self, off: int) -> int:
        return _Q.unpack_from(self._buf, off)[0]

    def _write_u64(self, off: int, value: int) -> None:
        _Q.pack_into(self._buf, off, value)

    def _locked_write(self, seq_off: int, field_writes) -> None:
        """Writer side of the seqlock: guard odd -> fields -> guard
        even.  Each guard has exactly one writer (pseq: producer,
        cseq: consumer), so no CAS is needed."""
        seq = self._read_u64(seq_off)
        self._write_u64(seq_off, seq + 1)
        for off, value in field_writes:
            self._write_u64(off, value)
        self._write_u64(seq_off, seq + 2)

    def _stable_read(self, seq_off: int, field_offs) -> "list[int]":
        """Reader side: retry until the guard is even and unchanged
        across the field reads (a torn 8-byte read is theoretical on
        CPython but the seqlock makes it impossible, not unlikely).
        Bounded: a guard that stays odd/unstable past
        ``_SEQLOCK_STUCK_S`` means the writer was SIGKILL'd between
        its guard writes — mark the ring closed (for both ends) and
        raise _RingStuck so push/pop report the ring dead instead of
        busy-looping at 100% CPU forever."""
        deadline = None
        spin = 0
        while True:
            s0 = self._read_u64(seq_off)
            if not (s0 & 1):
                vals = [self._read_u64(off) for off in field_offs]
                if self._read_u64(seq_off) == s0:
                    return vals
            spin += 1
            if spin <= 64:
                continue    # genuine contention resolves in a few reads
            if deadline is None:
                deadline = time.monotonic() + self._SEQLOCK_STUCK_S
            elif time.monotonic() > deadline:
                try:
                    self._buf[_OFF_CLOSED] = 1
                except (TypeError, ValueError):
                    pass    # this side's mapping already released
                raise _RingStuck(
                    f"shm ring seqlock stuck for "
                    f"{self._SEQLOCK_STUCK_S}s — peer died mid-write; "
                    "ring closed")
            time.sleep(1e-5)

    # -- data path ---------------------------------------------------------

    @property
    def closed(self) -> bool:
        try:
            return bool(self._buf[_OFF_CLOSED])
        except (TypeError, ValueError):
            return True    # this side's mapping already released

    def capacity(self) -> int:
        return self._slab

    def push(self, payload, timeout_s: float = 5.0) -> bool:
        """Producer: claim the free slab, copy `payload` in, publish.
        False when the peer closed the ring or no slab freed within
        the timeout (caller falls back to the TCP path)."""
        try:
            return self._push(payload, timeout_s)
        except _RingStuck:
            return False        # peer died mid-publish — ring is dead
        except (TypeError, ValueError) as e:
            if "released" in str(e):
                return False    # close() raced this push — ring is gone
            raise

    def _push(self, payload, timeout_s: float) -> bool:
        n = len(payload)
        if n > self._slab:
            raise ValueError(
                f"frame of {n} bytes exceeds ring slab "
                f"({self._slab} bytes)")
        deadline = time.monotonic() + timeout_s
        spin = 0
        while True:
            if self.closed:
                return False
            wseq = self._stable_read(_OFF_PSEQ, (_OFF_WSEQ,))[0]
            rseq = self._stable_read(_OFF_CSEQ, (_OFF_RSEQ,))[0]
            if wseq - rseq < 2:
                break
            spin += 1
            if spin > 64:
                if time.monotonic() > deadline:
                    return False
                time.sleep(min(1e-3, 1e-5 * spin))
        slab = wseq % 2
        start = _RING_HDR + slab * self._slab
        self._buf[start:start + n] = payload
        self._locked_write(_OFF_PSEQ, (
            (_OFF_LEN0 if slab == 0 else _OFF_LEN1, n),
            (_OFF_WSEQ, wseq + 1),
        ))
        return True

    def pop(self, timeout_s: float = 0.25) -> "bytes | None":
        """Consumer: copy the oldest published slab out and ack it.
        None on timeout; check `closed` to tell quiescence from
        shutdown (pending slabs still drain after close)."""
        try:
            return self._pop(timeout_s)
        except _RingStuck:
            return None         # peer died mid-publish — ring is dead
        except (TypeError, ValueError) as e:
            if "released" in str(e):
                return None     # close() raced this pop — ring is gone
            raise

    def _pop(self, timeout_s: float) -> "bytes | None":
        deadline = time.monotonic() + timeout_s
        spin = 0
        while True:
            rseq = self._stable_read(_OFF_CSEQ, (_OFF_RSEQ,))[0]
            wseq, len0, len1 = self._stable_read(
                _OFF_PSEQ, (_OFF_WSEQ, _OFF_LEN0, _OFF_LEN1))
            if wseq > rseq:
                break
            if self.closed or time.monotonic() > deadline:
                return None
            spin += 1
            time.sleep(0 if spin < 64 else min(1e-3, 1e-5 * spin))
        slab = rseq % 2
        n = len0 if slab == 0 else len1
        start = _RING_HDR + slab * self._slab
        payload = bytes(self._buf[start:start + n])
        self._locked_write(_OFF_CSEQ, ((_OFF_RSEQ, rseq + 1),))
        return payload

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Signal the peer and drop this side's mapping.  The owner
        also unlinks the segment (idempotent)."""
        try:
            self._buf[_OFF_CLOSED] = 1
        except (TypeError, ValueError):
            pass    # mapping already released
        try:
            self._shm.close()
        except Exception:
            pass
        if self._owner and not self._unlinked:
            self._unlinked = True
            # When both ends live in ONE process (in-process replicas)
            # the attach side's tracker deregistration removed the
            # shared cache entry; unlink() deregisters again and the
            # tracker daemon logs a KeyError.  Re-registering first
            # makes the owner's unlink clean in both topologies, and
            # the once-flag keeps a double close from re-registering a
            # segment that no longer exists.
            try:
                from multiprocessing import resource_tracker

                resource_tracker.register(
                    self._shm._name, "shared_memory")
            except Exception:
                pass
            try:
                self._shm.unlink()
            except Exception:
                pass
