"""Consistent-hash tenant placement for the replicated serving fleet.

One serve process is one blast radius: ROADMAP item 5 replaces it with
N replicas and a router, which needs a placement function answering
"which replica owns tenant t?" with four properties the router (and the
chaos tests) lean on:

1. **Deterministic across processes.**  Placement is a pure function of
   (tenant set, replica set) built on ``hashlib.blake2b`` — never
   Python's per-process-salted ``hash()`` — so the router, every
   replica, and a postmortem debugger all compute the identical ring
   from the membership snapshot, with no coordination round.

2. **Balanced by construction.**  Highest-random-weight (rendezvous)
   preference alone leaves multinomial fluctuation (a 256-tenant /
   4-replica census routinely puts ~72 tenants on the worst replica
   against a 64 mean).  Placement therefore walks each tenant's HRW
   preference order under a hard capacity ``ceil(T / N)`` — no replica
   ever owns more than its fair ceiling, which is also what turns the
   failover bound ("a dead replica's tenants all move") into the
   minimal-movement bound below.

3. **Minimal movement on ring change.**  A tenant considers replicas
   in a preference order keyed by ``hash(tenant, replica)`` — adding or
   removing a replica perturbs only the positions where that replica
   appears, so a membership change moves about ``T/N`` tenants instead
   of rehashing the world.  Assignment is two-phase to keep the
   balancing pass from amplifying that: every tenant first lands on
   its HRW argmax, then only the *overflow* beyond each replica's
   ``ceil(T/N)`` ceiling rebalances (weakest-preference members bump
   first, in canonical order) — a join perturbs one argmax set plus
   the shrunken overflow, not the whole capacity tiling.  The property
   tests pin ``<= ceil(T/N)`` moved primaries across join/leave in the
   fleet regime (tenants-per-replica >= ~16, the 256-tenant censuses
   the benches run), and zero movement on a no-op recompute.

4. **Primary != shadow.**  Every tenant gets a shadow replica — the
   warm standby that promotes on BackendLost — chosen further down the
   same preference order, never equal to the primary (requires >= 2
   replicas; with one replica the shadow is None and failover is
   impossible, which the router surfaces rather than hides).

The router treats this module as the *initial* and *join-time*
assignment; on failover it deliberately does NOT recompute from
scratch — the shadow promotes in place (zero model movement at the
worst possible moment) and only the vacated shadow slots are refilled
through ``shadow_for``.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass


def stable_hash(*parts: str) -> int:
    """64-bit digest of the joined parts — deterministic across
    processes and Python versions (unlike builtin ``hash``, which is
    salted per process and would scatter every replica's view of the
    ring)."""
    h = hashlib.blake2b(digest_size=8)
    for p in parts:
        h.update(p.encode("utf-8"))
        h.update(b"\x1f")
    return int.from_bytes(h.digest(), "big")


@dataclass(frozen=True)
class Placement:
    """One tenant's assignment: the replica that scores its traffic and
    the warm standby that promotes when the primary is lost."""

    primary: str
    shadow: "str | None"


def preference(tenant: str, replicas: "list[str]") -> "list[str]":
    """The tenant's full HRW preference order over ``replicas``:
    descending ``stable_hash(tenant, replica)``, ties broken by replica
    id.  A replica joining or leaving inserts/deletes one element and
    leaves the relative order of all others unchanged — the property
    minimal movement rides on."""
    return sorted(
        replicas,
        key=lambda r: (-stable_hash("place", tenant, r), r),
    )


def _cap(n_tenants: int, n_replicas: int) -> int:
    return -(-n_tenants // n_replicas) if n_replicas else 0


def place(tenants, replicas, *, shadows: bool = True
          ) -> "dict[str, Placement]":
    """Assign every tenant a primary (and shadow) replica.

    Pure function of the two sets.  Phase 1 puts every tenant on its
    HRW argmax replica.  Phase 2 enforces the ``ceil(T/N)`` ceiling:
    each over-full replica keeps the ``cap`` tenants that score it
    highest and releases the rest, and the released tenants — in a
    canonical hash-derived order (NOT sorted-id order: adjacent ids
    must not get adjacent capacity decisions) — walk their preference
    to the first replica with room.  Shadows then walk the same
    preference past the primary under their own ``ceil(T/N)`` bound
    (falling back to the least-loaded non-primary when every preferred
    one is full, so a shadow always exists when N >= 2)."""
    tenants = list(tenants)
    replicas = sorted(set(replicas))
    if not replicas:
        raise ValueError("placement needs at least one replica")
    if len(set(tenants)) != len(tenants):
        raise ValueError("duplicate tenant ids in placement census")
    cap = _cap(len(tenants), len(replicas))
    prefs = {t: preference(t, replicas) for t in tenants}
    groups: "dict[str, list]" = {r: [] for r in replicas}
    for t in tenants:
        groups[prefs[t][0]].append(t)
    primary: "dict[str, str]" = {}
    primary_load = {r: 0 for r in replicas}
    overflow: "list[str]" = []
    for r in replicas:
        g = sorted(groups[r],
                   key=lambda t: (-stable_hash("place", t, r), t))
        for t in g[:cap]:
            primary[t] = r
        overflow.extend(g[cap:])
        primary_load[r] = min(len(g), cap)
    overflow.sort(key=lambda t: (stable_hash("order", t), t))
    for t in overflow:
        r = next(r for r in prefs[t] if primary_load[r] < cap)
        primary[t] = r
        primary_load[r] += 1
    shadow_load = {r: 0 for r in replicas}
    out: "dict[str, Placement]" = {}
    order = sorted(tenants, key=lambda t: (stable_hash("order", t), t))
    for t in order:
        shadow = None
        if shadows and len(replicas) > 1:
            shadow = next(
                (r for r in prefs[t]
                 if r != primary[t] and shadow_load[r] < cap),
                None,
            )
            if shadow is None:
                shadow = min(
                    (r for r in replicas if r != primary[t]),
                    key=lambda r: (shadow_load[r], r),
                )
            shadow_load[shadow] += 1
        out[t] = Placement(primary=primary[t], shadow=shadow)
    return {t: out[t] for t in tenants}


def shadow_for(tenant: str, replicas, *, exclude=()) -> "str | None":
    """The replacement-shadow pick after a failover or drain vacated a
    tenant's standby slot: the tenant's most-preferred surviving
    replica outside ``exclude`` (its promoted primary, the dead
    replica).  Stateless and deterministic, so the router and any
    observer agree on the refill without a placement-wide recompute —
    failover must not shuffle tenants that never touched the dead
    replica."""
    pref = preference(tenant, sorted(set(replicas)))
    for r in pref:
        if r not in exclude:
            return r
    return None


def moved_primaries(old: "dict[str, Placement]",
                    new: "dict[str, Placement]") -> "list[str]":
    """Tenants whose primary changed between two placements — the
    movement metric the minimal-movement property tests bound."""
    return sorted(
        t for t in old
        if t in new and old[t].primary != new[t].primary
    )


def load_by_replica(placement: "dict[str, Placement]"
                    ) -> "dict[str, int]":
    """Primary tenant count per replica (balance assertions)."""
    out: "dict[str, int]" = {}
    for p in placement.values():
        out[p.primary] = out.get(p.primary, 0) + 1
    return out
