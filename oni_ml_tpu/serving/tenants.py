"""Tenant demux/admission types for the multi-tenant serving fleet.

A *tenant* is one independently-owned scoring stream: its own trained
day (model + quantile cuts), its own admission queue, its own metrics
namespace (``serve.<tenant>.*``), its own hot-swap cadence.  What
tenants SHARE is the scarce part of serving — device residency of the
model weights and the padded AOT-warmed compiled-program family — so
the types here deliberately carry no model state: `FleetRegistry`
(serving/fleet.py) owns models, this module owns identity, admission,
and the per-event bookkeeping that demuxes a packed cross-tenant
micro-batch back into per-tenant futures.

Admission is the fleet's isolation primitive on the ingress side: each
tenant gets a BOUNDED queue, so one tenant's runaway producer saturates
its own queue (blocking or rejecting, per policy) instead of starving
every other tenant's latency budget.  Stalls are priced exactly like
the dataplane's channel stalls (``{"kind": "dataplane"}`` journal
records + ``serve.<tenant>.admission_stall_s`` histograms); rejects are
first-class journal records (``{"kind": "admission_reject"}``) and
``serve.<tenant>.admission_rejects`` counters.

Nothing here imports jax — tenant bookkeeping must work on a box that
only serves host-path scoring, like serving/registry.py.
"""

from __future__ import annotations

import json
import re
from collections import deque
from dataclasses import dataclass, field

from .batcher import ScoreFuture

# Tenant ids become metric-name components (`serve.<tenant>.latency_ms`
# -> OpenMetrics `serve_<tenant>_latency_ms`): restrict to characters
# the exporter's non-alphanumeric -> `_` rewrite maps INJECTIVELY, so
# two tenants can never collide onto one exposition series.  `-` is
# deliberately excluded: it rewrites to `_`, so "acme-eu" and "acme_eu"
# would silently merge their histograms.
_TENANT_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_]*$")

ADMISSION_POLICIES = ("block", "reject")


class AdmissionRejected(RuntimeError):
    """submit() on a full tenant queue under admission="reject": the
    event was NOT enqueued (no future exists for it) — the caller sheds
    load instead of waiting.  Carries the tenant and the queue bound so
    an ingest shim can surface a per-tenant 429."""

    def __init__(self, tenant: str, depth: int, capacity: int) -> None:
        super().__init__(
            f"tenant {tenant!r} admission queue full "
            f"({depth}/{capacity} pending)"
        )
        self.tenant = tenant
        self.depth = depth
        self.capacity = capacity


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's declaration — the fleet-manifest unit.

    `day_dir` names the completed day directory the tenant's model and
    featurizer load from ("" for programmatic tenants published through
    `FleetRegistry.publish` directly).  `queue_max` / `admission` /
    `threshold` of 0/""/None inherit the fleet-wide ServingConfig
    values, so a manifest only states what differs per tenant.
    `weight` is the tenant's declared load share — the load generator's
    mixing weight and an operator hint, not a scheduler input (the
    scorer drains globally oldest-first, which is what keeps one
    tenant's burst from inverting another's latency)."""

    tenant: str
    day_dir: str = ""
    dsource: str = "flow"
    queue_max: int = 0
    admission: str = ""
    threshold: "float | None" = None
    weight: float = 1.0
    refresh_every: int = 0

    def __post_init__(self) -> None:
        if not _TENANT_ID_RE.match(self.tenant):
            raise ValueError(
                f"tenant id {self.tenant!r} must match "
                f"{_TENANT_ID_RE.pattern} — ids become OpenMetrics "
                "name components"
            )
        from ..sources import names as source_names

        if self.dsource not in source_names():
            raise ValueError(
                f"tenant {self.tenant!r}: dsource must be one of "
                f"{'|'.join(source_names())}, got {self.dsource!r}"
            )
        if self.admission and self.admission not in ADMISSION_POLICIES:
            raise ValueError(
                f"tenant {self.tenant!r}: admission must be one of "
                f"{ADMISSION_POLICIES}, got {self.admission!r}"
            )
        if self.queue_max < 0:
            raise ValueError(
                f"tenant {self.tenant!r}: queue_max must be >= 0 "
                "(0 = fleet default)"
            )
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.tenant!r}: weight must be > 0"
            )


def load_manifest(path: str) -> list[TenantSpec]:
    """Parse a fleet manifest file: ``{"tenants": [{"tenant": "a",
    "day_dir": "...", "dsource": "flow", ...}, ...]}``.  Unknown keys
    fail loudly (a typo'd knob must not silently become the default),
    and duplicate tenant ids fail (two queues demuxing onto one metric
    namespace would corrupt both)."""
    with open(path) as f:
        data = json.load(f)
    return parse_manifest(data, origin=path)


def parse_manifest(data, origin: str = "<manifest>") -> list[TenantSpec]:
    if not isinstance(data, dict) or not isinstance(
            data.get("tenants"), list):
        raise ValueError(
            f"{origin}: manifest must be an object with a 'tenants' list"
        )
    allowed = set(TenantSpec.__dataclass_fields__)
    specs: list[TenantSpec] = []
    seen: set[str] = set()
    for i, entry in enumerate(data["tenants"]):
        if not isinstance(entry, dict):
            raise ValueError(f"{origin}: tenants[{i}] is not an object")
        unknown = set(entry) - allowed
        if unknown:
            raise ValueError(
                f"{origin}: tenants[{i}] has unknown keys "
                f"{sorted(unknown)} (allowed: {sorted(allowed)})"
            )
        spec = TenantSpec(**entry)
        if spec.tenant in seen:
            raise ValueError(
                f"{origin}: duplicate tenant id {spec.tenant!r}"
            )
        seen.add(spec.tenant)
        specs.append(spec)
    if not specs:
        raise ValueError(f"{origin}: manifest declares zero tenants")
    return specs


class _PendingEvent:
    """One admitted event awaiting its packed flush: the demux unit.
    `future` resolves with (score, tenant model version) exactly once.
    `row` is the edge columnar parse — the split column list produced
    at admission by featurizers exposing `admit()` — so the flush path
    never re-splits the raw line (None for validate-only featurizers,
    which shed the device path and featurize from `raw`)."""

    __slots__ = ("raw", "t_enqueue", "future", "row")

    def __init__(self, raw, t_enqueue: float, row=None) -> None:
        self.raw = raw
        self.t_enqueue = t_enqueue
        self.future = ScoreFuture()
        self.row = row


@dataclass
class TenantLane:
    """Per-tenant admission queue + counters.

    NOT self-locking: every method and every field access runs under
    the owning FleetScorer's condition variable (caller holds the
    scorer's _cond) — one lock orders admissions, flush takes, and
    counter reads across all lanes, which is what makes the global
    oldest-first drain and the per-tenant backpressure bounds
    mutually consistent."""

    spec: TenantSpec
    featurizer: object
    queue_max: int
    admission: str
    threshold: float
    pending: deque = field(default_factory=deque)
    submitted: int = 0
    scored: int = 0
    rejected: int = 0
    flagged: int = 0
    admission_stall_ns: int = 0

    def full_locked(self) -> bool:
        return len(self.pending) >= self.queue_max

    def stats_locked(self) -> dict:
        return {
            "tenant": self.spec.tenant,
            "dsource": self.spec.dsource,
            "queue_max": self.queue_max,
            "admission": self.admission,
            "pending": len(self.pending),
            "submitted": self.submitted,
            "scored": self.scored,
            "rejected": self.rejected,
            "flagged": self.flagged,
            "admission_stall_s": round(self.admission_stall_ns / 1e9, 6),
        }
