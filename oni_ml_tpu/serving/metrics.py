"""Per-batch serving metrics as JSON lines.

Same convention as runner/ml_ops.py's stage metrics (one json.dumps'd
dict per line to stdout, records retained for a file dump) so the
observability surface is uniform across batch and serving: a consumer
tailing metrics sees {"stage": "serve", ...} lines exactly where it
already sees {"stage": "lda", ...} ones.
"""

from __future__ import annotations

import json
import threading
from collections import deque


class MetricsEmitter:
    """Thread-safe JSON-lines emitter.  `path` appends each line to a
    file as it is emitted (crash-safe: flushed per line, nothing held
    for an exit-time dump); stdout printing can be disabled for
    library/test embedding.  `records` keeps only the most recent
    `keep_records` entries — a serve process flushing every 50 ms emits
    ~1.7M records/day, so unbounded retention (the batch runner's
    exit-time-dump convention) would be a slow OOM here; the durable
    history is the file/stdout stream."""

    def __init__(self, path: str = "", to_stdout: bool = True,
                 keep_records: int = 4096) -> None:
        self._lock = threading.Lock()
        self._to_stdout = to_stdout
        self._file = open(path, "a") if path else None
        self.records: deque[dict] = deque(maxlen=keep_records)

    def emit(self, record: dict) -> None:
        line = json.dumps(record)
        with self._lock:
            self.records.append(record)
            if self._to_stdout:
                print(line, flush=True)
            if self._file is not None:
                self._file.write(line + "\n")
                self._file.flush()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
