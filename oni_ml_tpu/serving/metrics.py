"""Per-batch serving metrics as JSON lines, on the shared telemetry
registry.

Same convention as runner/ml_ops.py's stage metrics (one json.dumps'd
dict per line to stdout, records retained for a file dump) so the
observability surface is uniform across batch and serving: a consumer
tailing metrics sees {"stage": "serve", ...} lines exactly where it
already sees {"stage": "lda", ...} ones.

Since the telemetry flight recorder landed (oni_ml_tpu/telemetry/),
the emitter is a THIN SINK over the shared registry rather than its
own accounting layer: every emit feeds the bound `Recorder`'s counters
and histograms (serve.emits / serve.events / serve.flagged /
serve.errors, latency/score-time distributions), and — when a journal
is attached — appends a crash-safe {"kind": "serve", ...} line, so a
killed serve process leaves its batch history on disk and
tools/trace_view.py can summarize it next to stage spans.  The JSON
line stream itself is unchanged; test_serving.py's record assertions
pin that.
"""

from __future__ import annotations

import json
import threading
from collections import deque

from ..telemetry.spans import Recorder, current_recorder

# Numeric record fields accumulated as counters (field -> counter name).
_COUNT_FIELDS = (
    ("events", "serve.events"),
    ("flagged", "serve.flagged"),
)
# Numeric record fields observed as histograms (field -> histogram name).
# The histograms are the shared fixed-boundary log-bucket kind
# (telemetry/spans.py), so snapshot() reports TRUE p50/p99/p999
# estimates from the bucket boundaries — the numbers the OpenMetrics
# exporter serves live and `bench.py serving_slo` reports.  The
# per-stage latency fields (queue_wait/score/demux) decompose the
# end-to-end latency along the enqueue -> flush -> device -> demux
# path the batcher walks.
_HIST_FIELDS = (
    ("latency_ms", "serve.latency_ms"),
    ("queue_wait_ms", "serve.queue_wait_ms"),
    ("score_ms", "serve.score_ms"),
    ("demux_ms", "serve.demux_ms"),
    ("queue_depth", "serve.queue_depth"),
)


def _scoped(name: str, tenant: "str | None") -> str:
    """serve.X -> serve.<tenant>.X for tenant-scoped records."""
    if not tenant:
        return name
    return f"serve.{tenant}.{name[len('serve.'):]}"


class MetricsEmitter:
    """Thread-safe JSON-lines emitter over the shared telemetry
    registry.  `path` appends each line to a file as it is emitted
    (crash-safe: flushed per line, nothing held for an exit-time dump);
    stdout printing can be disabled for library/test embedding.
    `records` keeps only the most recent `keep_records` entries — a
    serve process flushing every 50 ms emits ~1.7M records/day, so
    unbounded retention (the batch runner's exit-time-dump convention)
    would be a slow OOM here; the durable history is the file/stdout
    stream (and the journal, when one is attached).

    `recorder` is the telemetry Recorder fed by every emit; it defaults
    to the recorder active at CONSTRUCTION time (contextvars do not
    propagate into the scorer's worker thread, so binding happens here)
    or a private one.  `journal` (telemetry.Journal or RunJournal)
    additionally makes every record a crash-safe journal line."""

    def __init__(self, path: str = "", to_stdout: bool = True,
                 keep_records: int = 4096, recorder=None,
                 journal=None) -> None:
        self._lock = threading.Lock()
        self._to_stdout = to_stdout
        self._file = open(path, "a") if path else None
        self.records: deque[dict] = deque(maxlen=keep_records)
        self.recorder: Recorder = (
            recorder or current_recorder() or Recorder()
        )
        # Accept either a raw Journal or a RunJournal wrapper.
        self._journal = getattr(journal, "journal", journal)

    def emit(self, record: dict) -> None:
        line = json.dumps(record)
        with self._lock:
            self.records.append(record)
            if self._to_stdout:
                print(line, flush=True)
            if self._file is not None:
                self._file.write(line + "\n")
                self._file.flush()
        self._count(record)
        if self._journal is not None:
            self._journal.append({"kind": "serve", **record})

    def _count(self, record: dict) -> None:
        """Fold one record into the shared registry's aggregates.

        Tenant-scoped records (the fleet scorer emits one per tenant
        segment per flush, carrying a `tenant` field) feed a per-tenant
        namespace — `serve.<tenant>.latency_ms`, `serve.<tenant>.events`,
        ... — while tenant-less records (single-model serving, and the
        fleet's per-flush aggregate) keep feeding the fleet-wide
        `serve.*` names; routing by the field means per-tenant and
        aggregate numbers can never double-count each other.  The
        OpenMetrics exporter picks both namespaces up with no further
        wiring."""
        rec = self.recorder
        tenant = record.get("tenant")
        prefix = f"serve.{tenant}" if tenant else "serve"
        rec.counter(f"{prefix}.emits").add(1)
        if "error" in record or "on_batch_error" in record:
            rec.counter(f"{prefix}.errors").add(1)
        for field, name in _COUNT_FIELDS:
            v = record.get(field)
            if isinstance(v, (int, float)):
                rec.counter(_scoped(name, tenant)).add(int(v))
        for field, name in _HIST_FIELDS:
            v = record.get(field)
            if isinstance(v, (int, float)):
                rec.histogram(_scoped(name, tenant)).observe(float(v))
        if record.get("scorer") == "device" and not tenant \
                and "segments" not in record:
            # Flush-level single-model records only: the fleet's
            # per-tenant records repeat the flush's score_ms per tenant
            # segment, and its aggregate records (field `segments`)
            # span host AND device pack groups — either would price
            # host scoring as device dispatches.  The fleet scorer
            # feeds serve.device_score_ms / serve.device_events
            # directly, per device dispatch, with the exact group wall.
            # Device-dispatch flushes only: the serve roofline joins the
            # warmed device program's cost with THIS histogram's
            # count/sum — host-path flushes observing into it would
            # price host scoring as device dispatches and inflate the
            # utilization gauge arbitrarily.
            v = record.get("score_ms")
            if isinstance(v, (int, float)):
                rec.histogram("serve.device_score_ms").observe(float(v))
            ev = record.get("events")
            if isinstance(ev, (int, float)):
                rec.counter("serve.device_events").add(int(ev))

    def snapshot(self) -> dict:
        """The shared registry's aggregate view — what `ml_ops serve`
        prints at shutdown.  Histogram summaries carry true
        p50/p99/p999 quantile estimates read off the fixed log-bucket
        boundaries (spans.Histogram.quantile), not naive interpolation
        over min/max."""
        return self.recorder.snapshot()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
