"""Empirical-CDF quantile cuts with the reference's exact semantics.

The reference computes, per quantile q, the MAXIMUM value whose empirical
CDF (P[X <= v], over the full multiset) is strictly below q, with an
accumulator initialised to 0 so cuts never go negative and a missing match
yields 0 (flow_pre_lda.scala:102-137, duplicated at
dns_pre_lda.scala:234-269).  Binning counts how many cuts the value
strictly exceeds (bin_column, flow_pre_lda.scala:139-143 /
dns_pre_lda.scala:271-275).

Word identity across the whole pipeline depends on reproducing this rule
exactly (SURVEY.md §7 hard part (b)), so this module is the only place it
is implemented.

The reference needs three full-data Spark shuffles per variable to get
these cuts (and runs them twice, pre + post).  Here it is one
sort+cumsum over a host array, vectorized over all quantiles at once.
"""

from __future__ import annotations

import numpy as np

# Decile/quintile probe points used everywhere in the reference
# (flow_pre_lda.scala:90-91, dns_pre_lda.scala:52-53).
DECILES = np.array([0.0, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9])
QUINTILES = np.array([0.0, 0.2, 0.4, 0.6, 0.8])


def ecdf_cuts(values: np.ndarray, quantiles: np.ndarray) -> np.ndarray:
    """cuts[i] = max({v : cdf(v) < quantiles[i]} ∪ {0}).

    cdf(v) = (# samples <= v) / N over the full multiset; ties collapse to
    one (value, cdf) pair exactly as the reference's reduceByKey does.
    """
    quantiles = np.asarray(quantiles, dtype=np.float64)
    values = np.asarray(values, dtype=np.float64)
    if values.size == 0:
        return np.zeros(len(quantiles), dtype=np.float64)
    uniq, counts = np.unique(values, return_counts=True)
    cdf = np.cumsum(counts) / values.size
    cuts = np.zeros(len(quantiles), dtype=np.float64)
    for i, q in enumerate(quantiles):
        mask = cdf < q
        if mask.any():
            # uniq ascending => the last match is the max; floor at 0 like
            # the reference's zero-initialised aggregate.
            cuts[i] = max(0.0, uniq[mask][-1])
    return cuts


def bin_values(values: np.ndarray, cuts: np.ndarray) -> np.ndarray:
    """bin(v) = #{cuts c : v > c}, vectorized over values."""
    values = np.asarray(values, dtype=np.float64)
    cuts = np.asarray(cuts, dtype=np.float64)
    return (values[:, None] > cuts[None, :]).sum(axis=1).astype(np.int64)
