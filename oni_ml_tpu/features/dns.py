"""DNS featurization — replaces dns_pre_lda.scala (and the duplicate copy
inside dns_post_lda.scala:153-297).

Per DNS event (8 selected columns, dns_pre_lda.scala:149): the query name
is split into domain/subdomain with reverse-DNS and country-code-TLD
handling (extract_subdomain, dns_pre_lda.scala:185-220), the subdomain's
Shannon entropy is the DGA/tunneling signal (dns_pre_lda.scala:278-287),
decile cuts bin unix_tstamp and frame_len and quintile cuts (over the
positive subset) bin subdomain length / entropy / period count
(dns_pre_lda.scala:289-306), a whitelist flag marks known-good domains,
and the word concatenates flag + five bins + query type + rcode
(dns_pre_lda.scala:320-326).  The querying client `ip_dst` is the
document.

Reference quirks reproduced deliberately (word identity must match):
- A missing subdomain is the literal string "None", whose entropy (2.0 —
  four distinct characters) is what gets binned and even feeds the
  entropy-cut ECDF, since "None" passes the > 0 filter
  (dns_pre_lda.scala:286,301).
- `num.periods` is the total dot-separated part count of the full query
  name, not the subdomain's period count (dns_pre_lda.scala:219).
- The country-code set contains the empty string
  (dns_pre_lda.scala:180).
- The hardcoded customer whitelist `domain == "intel" -> "2"`
  (dns_pre_lda.scala:315).

Not reproduced: the reference's file-union loop skips its second input
file (`if (index > 1)`, dns_pre_lda.scala:144-148 — an off-by-one that
silently drops data); we read every input.
"""

from __future__ import annotations

import math
from collections import Counter
from dataclasses import dataclass, field
from typing import Iterable, Sequence

import numpy as np
from ..io.formats import contract_open as _open

from .quantiles import DECILES, QUINTILES, bin_values, ecdf_cuts

# The 8 columns selected from the raw source (dns_pre_lda.scala:149).
DNS_COLUMNS = {
    "frame_time": 0, "unix_tstamp": 1, "frame_len": 2, "ip_dst": 3,
    "dns_qry_name": 4, "dns_qry_class": 5, "dns_qry_type": 6,
    "dns_qry_rcode": 7,
}
NUM_DNS_COLUMNS = 8

# ISO country-code TLDs, verbatim from dns_pre_lda.scala:180 (including
# the stray empty string and "krd").
COUNTRY_CODES = frozenset(
    "ac ad ae af ag ai al am an ao aq ar as at au aw ax az ba bb bd be bf bg "
    "bh bi bj bm bn bo bq br bs bt bv bw by bz ca cc cd cf cg ch ci ck cl cm "
    "cn co cr cu cv cw cx cy cz de dj dk dm do dz ec ee eg eh er es et eu fi "
    "fj fk fm fo fr ga gb gd ge gf gg gh gi gl gm gn gp gq gr gs gt gu gw gy "
    "hk hm hn hr ht hu id ie il im in io iq ir is it je jm jo jp ke kg kh ki "
    "km kn kp kr krd kw ky kz la lb lc li lk lr ls lt lu lv ly ma mc md me "
    "mg mh mk ml mm mn mo mp mq mr ms mt mu mv mw mx my mz na nc ne nf ng ni "
    "nl no np nr nu nz om pa pe pf pg ph pk pl pm pn pr ps pt pw py qa re ro "
    "rs ru rw sa sb sc sd se sg sh si sj sk sl sm sn so sr ss st su sv sx sy "
    "sz tc td tf tg th tj tk tl tm tn to tp tr tt tv tw tz ua ug uk us uy uz "
    "va vc ve vg vi vn vu wf ws ye yt za zm zw".split()
) | {""}


def extract_subdomain(url: str) -> tuple[str, str, int, int]:
    """(domain, subdomain, subdomain_length, num_parts) —
    dns_pre_lda.scala:185-220.

    Reverse-DNS names (*.in-addr.arpa) and names with <= 2 parts keep
    domain/subdomain = "None".  A country-code TLD shifts the domain one
    label left (foo.co.uk -> domain "foo").
    """
    parts = url.split(".")
    # JVM String.split drops trailing empty strings ("a.b." -> [a, b]).
    while len(parts) > 1 and parts[-1] == "":
        parts.pop()
    n = len(parts)
    domain = "None"
    subdomain = "None"
    is_ip = n > 2 and parts[-1] == "arpa" and parts[-2] == "in-addr"
    if n > 2 and not is_ip:
        if parts[-1] in COUNTRY_CODES:
            domain = parts[-3]
            if n - 3 >= 1:
                subdomain = ".".join(parts[: n - 3])
        else:
            domain = parts[-2]
            subdomain = ".".join(parts[: n - 2])
    sub_len = len(subdomain) if subdomain != "None" else 0
    return domain, subdomain, sub_len, n


def shannon_entropy(s: str) -> float:
    """Character-level Shannon entropy in bits (dns_pre_lda.scala:278-284).
    entropy('') = 0; entropy of the literal 'None' placeholder = 2.0.

    The accumulation is an explicit Neumaier compensated sum — the same
    algorithm CPython 3.12+'s builtin sum() uses for floats — so the
    result is identical on every interpreter version AND bit-identical
    to the native featurizer's C++ implementation (which replicates this
    exact loop; tests/test_native_dns.py asserts equality)."""
    if not s:
        return 0.0
    n = len(s)
    hi = comp = 0.0
    for c in Counter(s).values():
        p = c / n
        x = -(p) * math.log2(p)
        t = hi + x
        if abs(hi) >= abs(x):
            comp += (hi - t) + x
        else:
            comp += (x - t) + hi
        hi = t
    return hi + comp


def load_top_domains(path: str) -> frozenset[str]:
    """Alexa top-1m.csv -> set of base domain names: field 1 of each
    'rank,domain' line, truncated at its first dot
    (dns_pre_lda.scala:62-66): '1,google.com' -> 'google'."""
    out = set()
    with _open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            parts = line.split(",")
            if len(parts) > 1:
                out.add(parts[1].split(".")[0])
    return frozenset(out)


@dataclass
class DnsFeatures:
    """Featurized day of DNS.  Scoring consumes `word` directly instead of
    re-featurizing (SURVEY §1)."""

    rows: list[list[str]]          # 8-col rows (incl. duplicated feedback)
    domain: list[str]
    subdomain: list[str]
    subdomain_length: np.ndarray   # [N] int
    num_periods: np.ndarray        # [N] int
    subdomain_entropy: np.ndarray  # [N] f64
    top_domain: np.ndarray         # [N] int (2 intel / 1 whitelisted / 0)
    word: list[str]
    # Events [num_raw_events:] are injected feedback duplicates: trained
    # on, never scored (the reference's post stage re-reads raw data only,
    # dns_post_lda.scala:108-116).
    num_raw_events: int = 0
    time_cuts: np.ndarray = field(default_factory=lambda: np.zeros(10))
    frame_length_cuts: np.ndarray = field(default_factory=lambda: np.zeros(10))
    subdomain_length_cuts: np.ndarray = field(default_factory=lambda: np.zeros(5))
    entropy_cuts: np.ndarray = field(default_factory=lambda: np.zeros(5))
    numperiods_cuts: np.ndarray = field(default_factory=lambda: np.zeros(5))

    @property
    def num_events(self) -> int:
        return len(self.rows)

    def client_ip(self, i: int) -> str:
        return self.rows[i][DNS_COLUMNS["ip_dst"]]

    def word_counts(self) -> list[tuple[str, str, int]]:
        """Per-client word counts keyed by ip_dst only
        (dns_pre_lda.scala:330), first-seen order."""
        agg: dict[tuple[str, str], int] = {}
        ip_col = DNS_COLUMNS["ip_dst"]
        for i, row in enumerate(self.rows):
            k = (row[ip_col], self.word[i])
            agg[k] = agg.get(k, 0) + 1
        return [(ip, w, c) for (ip, w), c in agg.items()]

    def word_count_columns(self):
        """Columnar word-count hand-off (dataplane/columns.py): the
        triples interned in first-seen order, so the streaming corpus
        builder assigns exactly the file contract's ids."""
        from ..dataplane.columns import intern_word_counts

        return intern_word_counts(self.word_counts())

    def featurized_row(self, i: int) -> list[str]:
        """Row as dns_post_lda sees it pre-scoring: 8 cols + domain,
        subdomain, subdomain.length, num.periods, subdomain.entropy,
        top_domain, word."""
        return self.rows[i] + [
            self.domain[i],
            self.subdomain[i],
            str(int(self.subdomain_length[i])),
            str(int(self.num_periods[i])),
            str(self.subdomain_entropy[i]),
            str(int(self.top_domain[i])),
            self.word[i],
        ]


def featurize_dns(
    rows_in: Iterable[Sequence[str]],
    top_domains: frozenset[str] = frozenset(),
    feedback_rows: Sequence[Sequence[str]] = (),
    precomputed_cuts: "tuple | None" = None,
) -> DnsFeatures:
    """Full DNS featurization pass over 8-column rows (already projected
    from CSV/parquet by the caller; io side is runner's job).
    `feedback_rows` are pre-duplicated 8-column rows from feedback.py.

    `precomputed_cuts` = (time_cuts, frame_length_cuts,
    subdomain_length_cuts, entropy_cuts, numperiods_cuts) skips the
    in-pass ECDF — the DNS analogue of flow's qtiles path
    (features/qtiles.py, SURVEY §2.7).  The serving path
    (oni_ml_tpu/serving) depends on it: a streamed micro-batch's own
    ECDF would assign different bins than the trained day's, silently
    unmapping every word from the model vocabulary."""
    rows = [list(r) for r in rows_in if len(r) == NUM_DNS_COLUMNS]
    num_raw_events = len(rows)
    rows += [list(r) for r in feedback_rows if len(r) == NUM_DNS_COLUMNS]
    c = DNS_COLUMNS

    domain: list[str] = []
    subdomain: list[str] = []
    sub_len = np.zeros(len(rows), dtype=np.int64)
    n_parts = np.zeros(len(rows), dtype=np.int64)
    entropy = np.zeros(len(rows), dtype=np.float64)
    # lint: ok(hot-path-event-loop, golden-oracle host featurizer — the byte-identity reference the device plane is pinned against)
    for i, row in enumerate(rows):
        d, s, sl, np_ = extract_subdomain(row[c["dns_qry_name"]])
        domain.append(d)
        subdomain.append(s)
        sub_len[i] = sl
        n_parts[i] = np_
        entropy[i] = shannon_entropy(s)

    # NaN-defaulting like the flow featurizer: a single malformed field
    # (e.g. a null parquet cell surfaced as "") must not abort the day.
    from .flow import _to_double

    tstamp = np.array(
        # lint: ok(hot-path-event-loop, golden-oracle host parse — the reference per-cell NaN-defaulting)
        [_to_double(r[c["unix_tstamp"]]) for r in rows], dtype=np.float64
    ) if rows else np.zeros(0)
    frame_len = np.array(
        # lint: ok(hot-path-event-loop, golden-oracle host parse — the reference per-cell NaN-defaulting)
        [_to_double(r[c["frame_len"]]) for r in rows], dtype=np.float64
    ) if rows else np.zeros(0)

    if precomputed_cuts is not None:
        (time_cuts, frame_length_cuts, subdomain_length_cuts,
         entropy_cuts, numperiods_cuts) = (
            np.asarray(x, dtype=np.float64) for x in precomputed_cuts
        )
    else:
        time_cuts = ecdf_cuts(tstamp, DECILES)
        frame_length_cuts = ecdf_cuts(frame_len, DECILES)
        # Quintile cuts over the strictly-positive subset
        # (dns_pre_lda.scala:298-305).
        subdomain_length_cuts = ecdf_cuts(sub_len[sub_len > 0], QUINTILES)
        entropy_cuts = ecdf_cuts(entropy[entropy > 0], QUINTILES)
        numperiods_cuts = ecdf_cuts(n_parts[n_parts > 0], QUINTILES)

    top = np.zeros(len(rows), dtype=np.int64)
    for i, d in enumerate(domain):
        top[i] = 2 if d == "intel" else (1 if d in top_domains else 0)

    if rows:
        b_len = bin_values(frame_len, frame_length_cuts)
        b_time = bin_values(tstamp, time_cuts)
        b_sub = bin_values(sub_len, subdomain_length_cuts)
        b_ent = bin_values(entropy, entropy_cuts)
        b_per = bin_values(n_parts, numperiods_cuts)
    else:
        b_len = b_time = b_sub = b_ent = b_per = np.zeros(0, dtype=np.int64)

    words = [
        f"{top[i]}_{b_len[i]}_{b_time[i]}_{b_sub[i]}_{b_ent[i]}_{b_per[i]}"
        f"_{rows[i][c['dns_qry_type']]}_{rows[i][c['dns_qry_rcode']]}"
        for i in range(len(rows))
    ]

    return DnsFeatures(
        rows=rows,
        domain=domain,
        subdomain=subdomain,
        subdomain_length=sub_len,
        num_periods=n_parts,
        subdomain_entropy=entropy,
        top_domain=top,
        word=words,
        time_cuts=time_cuts,
        frame_length_cuts=frame_length_cuts,
        subdomain_length_cuts=subdomain_length_cuts,
        entropy_cuts=entropy_cuts,
        numperiods_cuts=numperiods_cuts,
        num_raw_events=num_raw_events,
    )
