"""Netflow featurization — replaces flow_pre_lda.scala (and the duplicate
copy inside flow_post_lda.scala:64-224).

Per event (27-column netflow CSV row, schema flow_pre_lda.scala:46-72):
fractional-hour time is appended, decile cuts are taken over time and
ibyt and quintile cuts over ipkt (flow_pre_lda.scala:280-290), each value
is binned, and a word is constructed from a canonicalised port plus the
three bins (adjust_port, flow_pre_lda.scala:317-359).  Every event yields
TWO documents: the source IP sees `src_word`, the destination IP sees
`dest_word`, with a `-1_` prefix marking the side that received the
connection.

Reference quirks reproduced deliberately (word identity must match):
- adjust_port reads column 10 as "dport" and column 11 as "sport" even
  though the schema says 10=sport, 11=dport (flow_pre_lda.scala:321-322).
  Pre and post share the swap so it is self-consistent; we keep it so our
  words equal the reference's on identical data.
- word_port and the three bins are formatted as JVM doubles ("80.0",
  "333333.0", bins like "9.0") because adjust_port round-trips them
  through Double.toString (flow_pre_lda.scala:349).
- ip_pair's intended "canonical unordered pair" check `sip != 0` compares
  a string to an int and is therefore always true (flow_pre_lda.scala:329);
  effectively pair = "sip dip" if sip < dip lexicographically else
  "dip sip".  Computed but unused downstream, kept for row parity.

One deliberate divergence: the reference's feedback-row builder drops its
commas (`buf + ','` discards the result, flow_pre_lda.scala:243-245), so
injected feedback rows never survive the 27-field filter — the flow
feedback loop is silently dead upstream.  We implement the documented
intent (feedback.py builds real 27-column rows).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Sequence

import numpy as np

from .quantiles import DECILES, QUINTILES, bin_values, ecdf_cuts

# Column indices in the 27-column netflow schema (flow_pre_lda.scala:46-72).
FLOW_COLUMNS = {
    "time": 0, "year": 1, "month": 2, "day": 3, "hour": 4, "minute": 5,
    "second": 6, "tdur": 7, "sip": 8, "dip": 9, "sport": 10, "dport": 11,
    "proto": 12, "flag": 13, "fwd": 14, "stos": 15, "ipkt": 16, "ibyt": 17,
    "opkt": 18, "obyt": 19, "input": 20, "output": 21, "sas": 22, "das": 23,
    "dtos": 24, "dir": 25, "rip": 26,
}
NUM_FLOW_COLUMNS = 27


def _jvm_double(x: float) -> str:
    """Format like JVM Double.toString for the values that occur here
    (integral doubles -> '80.0'); Python's repr matches for those."""
    return str(float(x))


def _to_double(s: str) -> float:
    """toDouble with NaN default (flow_pre_lda.scala:15-19)."""
    try:
        return float(s)
    except (TypeError, ValueError):
        return float("nan")


@dataclass
class FlowFeatures:
    """Featurized day of netflow.  Everything scoring needs rides along so
    the post stage never re-featurizes (removing the SURVEY §1 duplication
    and its nondeterminism risk)."""

    rows: list[list[str]]         # 27-col rows (post-filter, incl. feedback)
    num_time: np.ndarray          # [N] f64 fractional hour
    ibyt_bin: np.ndarray          # [N] int
    ipkt_bin: np.ndarray          # [N] int
    time_bin: np.ndarray          # [N] int
    word_port: list[str]          # [N] JVM-double strings
    ip_pair: list[str]            # [N]
    src_word: list[str]           # [N]
    dest_word: list[str]          # [N]
    # Events [num_raw_events:] are injected feedback duplicates: they train
    # the model (word_counts) but are never scored — the reference's post
    # stage re-reads raw data only (flow_post_lda.scala:127-128).
    num_raw_events: int = 0
    time_cuts: np.ndarray = field(default_factory=lambda: np.zeros(10))
    ibyt_cuts: np.ndarray = field(default_factory=lambda: np.zeros(10))
    ipkt_cuts: np.ndarray = field(default_factory=lambda: np.zeros(5))

    @property
    def num_events(self) -> int:
        return len(self.rows)

    def sip(self, i: int) -> str:
        return self.rows[i][FLOW_COLUMNS["sip"]]

    def dip(self, i: int) -> str:
        return self.rows[i][FLOW_COLUMNS["dip"]]

    def word_counts(self) -> list[tuple[str, str, int]]:
        """Per-IP word counts, both endpoints documents
        (flow_pre_lda.scala:366-373): src counts first, then dest counts,
        each in first-seen order (Spark's reduceByKey order is partition-
        dependent; first-seen is our deterministic substitute)."""
        src: dict[tuple[str, str], int] = {}
        dst: dict[tuple[str, str], int] = {}
        s_col, d_col = FLOW_COLUMNS["sip"], FLOW_COLUMNS["dip"]
        for i, row in enumerate(self.rows):
            ks = (row[s_col], self.src_word[i])
            src[ks] = src.get(ks, 0) + 1
            kd = (row[d_col], self.dest_word[i])
            dst[kd] = dst.get(kd, 0) + 1
        return [(ip, w, c) for (ip, w), c in src.items()] + [
            (ip, w, c) for (ip, w), c in dst.items()
        ]

    def word_count_columns(self):
        """Columnar word-count hand-off (dataplane/columns.py): the
        triples interned in first-seen order, so the streaming corpus
        builder assigns exactly the file contract's ids."""
        from ..dataplane.columns import intern_word_counts

        return intern_word_counts(self.word_counts())

    def featurized_row(self, i: int) -> list[str]:
        """The row as flow_post_lda sees it pre-scoring: original 27 cols
        + num_time + ibyt_bin/ipkt_bin/time_bin + word_port/ip_pair/
        src_word/dest_word (cols 27-34)."""
        return self.rows[i] + [
            _jvm_double(self.num_time[i]),
            str(int(self.ibyt_bin[i])),
            str(int(self.ipkt_bin[i])),
            str(int(self.time_bin[i])),
            self.word_port[i],
            self.ip_pair[i],
            self.src_word[i],
            self.dest_word[i],
        ]


def _adjust_port_words(
    sip: str, dip: str, col10: float, col11: float,
    ibyt_bin: int, ipkt_bin: int, time_bin: int,
) -> tuple[str, str, str, str]:
    """Word construction (flow_pre_lda.scala:317-359).  col10/col11 keep
    the reference's swapped naming: dport := col10, sport := col11."""
    dport, sport = col10, col11
    if (
        (dport <= 1024 or sport <= 1024)
        and (dport > 1024 or sport > 1024)
        and min(dport, sport) != 0
    ):
        p_case, word_port = 2, min(dport, sport)
    elif dport > 1024 and sport > 1024:
        p_case, word_port = 3, 333333.0
    elif dport == 0 and sport != 0:
        p_case, word_port = 4, sport
    elif sport == 0 and dport != 0:
        p_case, word_port = 4, dport
    else:
        p_case = 1
        word_port = max(dport, sport) if min(dport, sport) == 0 else 111111.0

    # Bin order inside the word is time, ibyt, ipkt — all JVM doubles.
    word = (
        f"{_jvm_double(word_port)}_{_jvm_double(time_bin)}"
        f"_{_jvm_double(ibyt_bin)}_{_jvm_double(ipkt_bin)}"
    )
    src_word = dest_word = word
    if p_case == 2 and dport < sport:
        dest_word = "-1_" + dest_word
    elif p_case == 2 and sport < dport:
        src_word = "-1_" + src_word
    elif p_case == 4 and dport == 0:
        src_word = "-1_" + src_word
    elif p_case == 4 and sport == 0:
        dest_word = "-1_" + dest_word

    # ip_pair (flow_pre_lda.scala:328-329): the `sip != 0` arm is a
    # String-vs-Int comparison, always true on the JVM.
    ip_pair = f"{sip} {dip}" if sip < dip else f"{dip} {sip}"
    return _jvm_double(word_port), ip_pair, src_word, dest_word


def featurize_flow(
    lines: Iterable[str],
    feedback_rows: Sequence[str] = (),
    skip_header: bool = True,
    precomputed_cuts: tuple[np.ndarray, np.ndarray, np.ndarray] | None = None,
) -> FlowFeatures:
    """Full flow featurization pass.

    `lines` are raw CSV lines; the first distinct line is treated as a
    header and all its duplicates dropped (removeHeader,
    flow_pre_lda.scala:22-26).  `feedback_rows` are pre-built 27-column
    CSV strings (already duplicated DUPFACTOR times by feedback.py).
    `precomputed_cuts` = (time_cuts, ibyt_cuts, ipkt_cuts) skips the ECDF
    pass (the reference's vestigial flow_qtiles path, SURVEY §2.7).
    """
    rows: list[list[str]] = []
    header: str | None = None
    # lint: ok(hot-path-event-loop, golden-oracle admission parse — the batch reference; serving admits via admit once per event)
    for line in lines:
        if skip_header:
            if header is None:
                header = line
                continue
            if line == header:
                continue
        parts = line.strip().split(",")
        if len(parts) == NUM_FLOW_COLUMNS:
            rows.append(parts)
    num_raw_events = len(rows)
    for line in feedback_rows:
        parts = line.strip().split(",")
        if len(parts) == NUM_FLOW_COLUMNS:
            rows.append(parts)

    n = len(rows)
    c = FLOW_COLUMNS
    # Golden-oracle host parse: the reference per-cell NaN-defaulting
    # the device plane's vectorized parse is pinned byte-identical to.
    # lint: ok(hot-path-event-loop, golden-oracle host parse — see above)
    hour = np.array([_to_double(r[c["hour"]]) for r in rows])
    # lint: ok(hot-path-event-loop, golden-oracle host parse — see above)
    minute = np.array([_to_double(r[c["minute"]]) for r in rows])
    # lint: ok(hot-path-event-loop, golden-oracle host parse — see above)
    second = np.array([_to_double(r[c["second"]]) for r in rows])
    # lint: ok(hot-path-event-loop, golden-oracle host parse — see above)
    ipkt = np.array([_to_double(r[c["ipkt"]]) for r in rows])
    # lint: ok(hot-path-event-loop, golden-oracle host parse — see above)
    ibyt = np.array([_to_double(r[c["ibyt"]]) for r in rows])
    # lint: ok(hot-path-event-loop, golden-oracle host parse — see above)
    col10 = np.array([_to_double(r[c["sport"]]) for r in rows])
    # lint: ok(hot-path-event-loop, golden-oracle host parse — see above)
    col11 = np.array([_to_double(r[c["dport"]]) for r in rows])
    with np.errstate(invalid="ignore"):  # garbage rows carry NaN by design
        num_time = hour + minute / 60.0 + second / 3600.0

    if precomputed_cuts is not None:
        time_cuts, ibyt_cuts, ipkt_cuts = (
            np.asarray(x, dtype=np.float64) for x in precomputed_cuts
        )
    else:
        time_cuts = ecdf_cuts(num_time, DECILES)
        ibyt_cuts = ecdf_cuts(ibyt, DECILES)
        ipkt_cuts = ecdf_cuts(ipkt, QUINTILES)

    if n:
        ibyt_bin = bin_values(ibyt, ibyt_cuts)
        ipkt_bin = bin_values(ipkt, ipkt_cuts)
        time_bin = bin_values(num_time, time_cuts)
    else:
        ibyt_bin = ipkt_bin = time_bin = np.zeros(0, dtype=np.int64)

    word_port: list[str] = []
    ip_pair: list[str] = []
    src_word: list[str] = []
    dest_word: list[str] = []
    # lint: ok(hot-path-event-loop, golden-oracle word assembly — the byte-identity reference the device plane is pinned against)
    for i, row in enumerate(rows):
        wp, pair, sw, dw = _adjust_port_words(
            row[c["sip"]], row[c["dip"]], col10[i], col11[i],
            int(ibyt_bin[i]), int(ipkt_bin[i]), int(time_bin[i]),
        )
        word_port.append(wp)
        ip_pair.append(pair)
        src_word.append(sw)
        dest_word.append(dw)

    return FlowFeatures(
        rows=rows,
        num_time=num_time,
        ibyt_bin=ibyt_bin,
        ipkt_bin=ipkt_bin,
        time_bin=time_bin,
        word_port=word_port,
        ip_pair=ip_pair,
        src_word=src_word,
        dest_word=dest_word,
        time_cuts=time_cuts,
        ibyt_cuts=ibyt_cuts,
        ipkt_cuts=ipkt_cuts,
        num_raw_events=num_raw_events,
    )
