"""ctypes binding for the native flow featurizer (oni_ml_tpu/native_src/flow_featurize.cpp).

``featurize_flow_file`` is the production entry point for the flow pre
stage: it runs the parse/word-build/word-count passes in C++ when the
library is available (~20x the pure-Python throughput) and falls back to
``features.flow.featurize_flow`` otherwise.  Both produce objects with
the same API surface (the scoring stage and the runner duck-type it) and
identical featurization output — parity is pinned by
tests/test_native_flow.py.

The ECDF cuts are deliberately computed in Python from the native pass's
numeric arrays using quantiles.ecdf_cuts — the reference's quantile rule
has exactly one implementation in this codebase (SURVEY §7 hard part b).
"""

from __future__ import annotations

import ctypes
import os
from typing import Sequence

import numpy as np

from ..native_build import NativeLib, bytes_at, narrow_counts_i32
from .flow import FLOW_COLUMNS, FlowFeatures, _jvm_double, featurize_flow
from .quantiles import DECILES, QUINTILES, ecdf_cuts

_I32P = ctypes.POINTER(ctypes.c_int32)
_I64P = ctypes.POINTER(ctypes.c_int64)
_F64P = ctypes.POINTER(ctypes.c_double)


def _configure(lib: ctypes.CDLL) -> None:
    lib.ffz_create.restype = ctypes.c_void_p
    lib.ffz_create.argtypes = [ctypes.c_int]
    lib.ffz_destroy.argtypes = [ctypes.c_void_p]
    lib.ffz_error.restype = ctypes.c_char_p
    lib.ffz_error.argtypes = [ctypes.c_void_p]
    lib.ffz_ingest_file.restype = ctypes.c_int64
    lib.ffz_ingest_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ffz_ingest_file_parallel.restype = ctypes.c_int64
    lib.ffz_ingest_file_parallel.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.ffz_merge_ns.restype = ctypes.c_int64
    lib.ffz_merge_ns.argtypes = [ctypes.c_void_p]
    lib.ffz_ingest_buffer.restype = ctypes.c_int64
    lib.ffz_ingest_buffer.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.ffz_mark_raw.argtypes = [ctypes.c_void_p]
    for fn, res in [
        ("ffz_num_raw", ctypes.c_int64),
        ("ffz_num_events", ctypes.c_int64),
        ("ffz_lines_blob_len", ctypes.c_int64),
        ("ffz_wc_len", ctypes.c_int64),
    ]:
        getattr(lib, fn).restype = res
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    for fn in ("ffz_num_time", "ffz_ibyt", "ffz_ipkt"):
        getattr(lib, fn).restype = _F64P
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.ffz_finish.restype = ctypes.c_int
    lib.ffz_finish.argtypes = [
        ctypes.c_void_p, _F64P, ctypes.c_int, _F64P, ctypes.c_int, _F64P,
        ctypes.c_int,
    ]
    lib.ffz_finish_mt.restype = ctypes.c_int
    lib.ffz_finish_mt.argtypes = [
        ctypes.c_void_p, _F64P, ctypes.c_int, _F64P, ctypes.c_int, _F64P,
        ctypes.c_int, ctypes.c_int,
    ]
    for fn in ("ffz_bins", "ffz_ids"):
        getattr(lib, fn).restype = _I32P
        getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ffz_table_count.restype = ctypes.c_int64
    lib.ffz_table_count.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ffz_table_blob.restype = ctypes.c_void_p
    lib.ffz_table_blob.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ffz_table_blob_len.restype = ctypes.c_int64
    lib.ffz_table_blob_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ffz_table_offsets.restype = _I64P
    lib.ffz_table_offsets.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ffz_lines_blob.restype = ctypes.c_void_p
    lib.ffz_lines_blob.argtypes = [ctypes.c_void_p]
    lib.ffz_set_spill.restype = ctypes.c_int
    lib.ffz_set_spill.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ffz_spill_flush.restype = ctypes.c_int64
    lib.ffz_spill_flush.argtypes = [ctypes.c_void_p]
    lib.ffz_line_offsets.restype = _I64P
    lib.ffz_line_offsets.argtypes = [ctypes.c_void_p]
    for fn, res in [
        ("ffz_wc_ip", _I32P), ("ffz_wc_word", _I32P), ("ffz_wc_count", _I64P),
    ]:
        getattr(lib, fn).restype = res
        getattr(lib, fn).argtypes = [ctypes.c_void_p]


_LIB = NativeLib(
    os.path.join(
        os.path.dirname(__file__), "..", "native_src", "flow_featurize.cpp"
    ),
    os.path.join(os.path.dirname(__file__), "_native", "liboni_flow.so"),
    _configure,
    deps=(
        os.path.join(
            os.path.dirname(__file__), "..", "native_src", "common.h"
        ),
    ),
)


def available() -> bool:
    return _LIB.available()


def _copy(ptr, n, dtype):
    if n == 0:
        return np.zeros(0, dtype=dtype)
    return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)


_narrow_i32 = narrow_counts_i32   # shared guard (native_build)


def _table(lib, h, which: int) -> list[str]:
    cnt = lib.ffz_table_count(h, which)
    blob_len = lib.ffz_table_blob_len(h, which)
    blob = bytes_at(lib.ffz_table_blob(h, which), blob_len)
    off = _copy(lib.ffz_table_offsets(h, which), cnt + 1, np.int64)
    return [
        blob[off[i]:off[i + 1]].decode("utf-8", "surrogateescape")
        for i in range(cnt)
    ]


class NativeFlowFeatures:
    """FlowFeatures-compatible container backed by native arrays.

    Raw rows live in one bytes blob + offsets and are split lazily
    (``featurized_row`` is only called for rows under the scoring
    threshold); IPs and words are interned string tables with per-event
    id arrays.  Pickles without the native library present.
    """

    def __init__(self, *, lines_blob, line_off, ip_table, word_table,
                 sip_id, dip_id, wp_id, sw_id, dw_id, num_time, ibyt_bin,
                 ipkt_bin, time_bin, wc_ip, wc_word, wc_count,
                 num_raw_events, time_cuts, ibyt_cuts, ipkt_cuts):
        self.lines_blob = lines_blob
        self.line_off = line_off
        self.ip_table = ip_table
        self.word_table = word_table
        self.sip_id = sip_id
        self.dip_id = dip_id
        self.wp_id = wp_id
        self.sw_id = sw_id
        self.dw_id = dw_id
        self.num_time = num_time
        self.ibyt_bin = ibyt_bin
        self.ipkt_bin = ipkt_bin
        self.time_bin = time_bin
        self.wc_ip = wc_ip
        self.wc_word = wc_word
        self.wc_count = wc_count
        self.num_raw_events = num_raw_events
        self.time_cuts = time_cuts
        self.ibyt_cuts = ibyt_cuts
        self.ipkt_cuts = ipkt_cuts
        self._word_lists: dict[str, list[str]] = {}

    # -- FlowFeatures API ---------------------------------------------------

    @property
    def num_events(self) -> int:
        return len(self.sip_id)

    def row(self, i: int) -> list[str]:
        raw = self.lines_blob[self.line_off[i]:self.line_off[i + 1]]
        return raw.decode("utf-8", "surrogateescape").split(",")

    def sip(self, i: int) -> str:
        return self.ip_table[self.sip_id[i]]

    def dip(self, i: int) -> str:
        return self.ip_table[self.dip_id[i]]

    def _words(self, which: str) -> list[str]:
        if which not in self._word_lists:
            ids = {"wp": self.wp_id, "src": self.sw_id, "dst": self.dw_id}[
                which
            ]
            t = self.word_table
            self._word_lists[which] = [t[j] for j in ids]
        return self._word_lists[which]

    @property
    def word_port(self) -> list[str]:
        return self._words("wp")

    @property
    def src_word(self) -> list[str]:
        return self._words("src")

    @property
    def dest_word(self) -> list[str]:
        return self._words("dst")

    @property
    def ip_pair(self) -> list[str]:
        # Derived, not stored: pair = "min max" lexicographically
        # (features/flow.py ip_pair semantics).
        out = []
        for s_id, d_id in zip(self.sip_id, self.dip_id):
            s, d = self.ip_table[s_id], self.ip_table[d_id]
            out.append(f"{s} {d}" if s < d else f"{d} {s}")
        return out

    @property
    def rows(self) -> list[list[str]]:
        return [self.row(i) for i in range(self.num_events)]

    def featurized_row(self, i: int) -> list[str]:
        s, d = self.sip(i), self.dip(i)
        pair = f"{s} {d}" if s < d else f"{d} {s}"
        return self.row(i) + [
            _jvm_double(self.num_time[i]),
            str(int(self.ibyt_bin[i])),
            str(int(self.ipkt_bin[i])),
            str(int(self.time_bin[i])),
            self.word_table[self.wp_id[i]],
            pair,
            self.word_table[self.sw_id[i]],
            self.word_table[self.dw_id[i]],
        ]

    def word_counts(self) -> list[tuple[str, str, int]]:
        ips, words = self.ip_table, self.word_table
        return [
            (ips[i], words[w], int(c))
            for i, w, c in zip(self.wc_ip, self.wc_word, self.wc_count)
        ]

    def word_count_columns(self):
        """Columnar word-count hand-off (dataplane/columns.py): the
        aggregated table-id arrays straight from the native pass — no
        string materialization; the streaming corpus builder's
        first-seen remap reproduces `Corpus.from_features` exactly."""
        from ..dataplane.columns import make_word_count_columns

        return make_word_count_columns(
            self.wc_ip, self.wc_word, self.wc_count,
            self.ip_table, self.word_table,
        )

    def spill_lines(self, path: str) -> None:
        """Move the raw-lines blob to a mmap-backed file (features/blob.py)
        so pickling this container stores the path, not the bytes, and
        post-featurize RSS drops to the numeric arrays.  No-op when the
        blob was already spilled at ingest (featurize_flow_file
        spill_path)."""
        if isinstance(self.lines_blob, (bytes, bytearray)):
            from .blob import spill_bytes

            self.lines_blob = spill_bytes(self.lines_blob, path)

    # -- pickling (features.pkl survives without the native lib) ------------

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_word_lists")
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._word_lists = {}


def expand_flow_paths(path: str) -> list[str]:
    """A flow input spec -> ordered list of concrete CSV paths.

    The reference points FLOW_PATH at an HDFS location and Spark's
    textFile reads every part file under it (flow_pre_lda.scala:249);
    config 3's 30-day corpus is exactly such a multi-file ingest.  The
    spec is a comma-separated list whose pieces may be files,
    directories (every regular file inside, sorted), or globs (sorted
    expansion).  Listed order is preserved — the first-seen id
    contract depends on event order.  Directory and glob expansion
    skips names starting with '_' or '.' — Spark's hiddenFileFilter
    semantics, so a real job-output dir's _SUCCESS / .part-*.crc /
    _metadata markers never reach the featurizer.  Header semantics
    across files match the reference's removeHeader: the first line of
    the FIRST file is the header, and any later line equal to it is
    dropped (identical part-file headers vanish)."""
    import glob as _glob

    def visible(p: str) -> bool:
        return not os.path.basename(p).startswith(("_", "."))

    def expand_dir(d: str) -> list[str]:
        return [
            p for p in sorted(os.path.join(d, n) for n in os.listdir(d))
            if os.path.isfile(p) and visible(p)
        ]

    out: list[str] = []
    for piece in path.split(","):
        if not piece:
            continue
        if os.path.isdir(piece):
            out.extend(expand_dir(piece))
        elif _glob.has_magic(piece):
            # A glob may match day DIRECTORIES (/data/flow/2016*) —
            # expand each like the directory branch, never hand a
            # directory path to the reader.  A pattern whose basename
            # itself starts with '_'/'.' is a DELIBERATE selection of
            # hidden names (dir/_2016*.csv), so those matches pass.
            deliberate = os.path.basename(piece).startswith(("_", "."))
            for p in sorted(_glob.glob(piece)):
                if not (visible(p) or deliberate):
                    continue            # _logs/, _temporary/, .crc ...
                if os.path.isdir(p):
                    out.extend(expand_dir(p))
                else:
                    out.append(p)
        else:
            out.append(piece)      # explicitly named files always pass
    return out


def _featurize_native(
    lib,
    paths: Sequence[str],
    feedback_rows: Sequence[str],
    precomputed_cuts=None,
    spill_path: str | None = None,
    workers: int = 1,
    timings: "dict | None" = None,
) -> NativeFlowFeatures:
    import time as _time

    h = lib.ffz_create(1)
    try:
        if spill_path is not None and lib.ffz_set_spill(
            h, os.fsencode(spill_path)
        ) < 0:
            raise OSError(lib.ffz_error(h).decode("utf-8", "replace"))
        t0 = _time.perf_counter()
        for path in paths:
            # Parallel ingest shards EACH file (pass A) across
            # std::thread workers with a deterministic first-seen merge
            # — byte-identical to the sequential path, which workers=1
            # takes verbatim.
            rc = (
                lib.ffz_ingest_file_parallel(h, os.fsencode(path), workers)
                if workers > 1
                else lib.ffz_ingest_file(h, os.fsencode(path))
            )
            if rc < 0:
                raise OSError(lib.ffz_error(h).decode("utf-8", "replace"))
        lib.ffz_mark_raw(h)
        if feedback_rows:
            blob = ("\n".join(feedback_rows) + "\n").encode(
                "utf-8", "surrogateescape"
            )
            if lib.ffz_ingest_buffer(h, blob, len(blob)) < 0:
                raise OSError(lib.ffz_error(h).decode("utf-8", "replace"))
        t1 = _time.perf_counter()
        n = lib.ffz_num_events(h)
        num_time = _copy(lib.ffz_num_time(h), n, np.float64)
        ibyt = _copy(lib.ffz_ibyt(h), n, np.float64)
        ipkt = _copy(lib.ffz_ipkt(h), n, np.float64)
        if precomputed_cuts is not None:
            time_cuts, ibyt_cuts, ipkt_cuts = (
                np.ascontiguousarray(x, dtype=np.float64)
                for x in precomputed_cuts
            )
        else:
            # ECDF cuts keep their single global definition: computed
            # ONCE over the merged arrays whatever the worker count, so
            # sharding can never move a bin edge.
            time_cuts = ecdf_cuts(num_time, DECILES)
            ibyt_cuts = ecdf_cuts(ibyt, DECILES)
            ipkt_cuts = ecdf_cuts(ipkt, QUINTILES)
        t2 = _time.perf_counter()

        def fp(a):
            return a.ctypes.data_as(_F64P)

        if workers > 1:
            rc = lib.ffz_finish_mt(
                h, fp(time_cuts), len(time_cuts), fp(ibyt_cuts),
                len(ibyt_cuts), fp(ipkt_cuts), len(ipkt_cuts), workers,
            )
        else:
            rc = lib.ffz_finish(
                h, fp(time_cuts), len(time_cuts), fp(ibyt_cuts),
                len(ibyt_cuts), fp(ipkt_cuts), len(ipkt_cuts),
            )
        if rc < 0:
            raise ValueError(lib.ffz_error(h).decode("utf-8", "replace"))
        if timings is not None:
            timings.update(
                parse_s=round(t1 - t0, 3),
                cuts_s=round(t2 - t1, 3),
                word_build_s=round(_time.perf_counter() - t2, 3),
                merge_s=round(lib.ffz_merge_ns(h) / 1e9, 3),
            )
        nwc = lib.ffz_wc_len(h)
        if spill_path is not None:
            from .blob import MmapBlob

            if lib.ffz_spill_flush(h) < 0:  # short write: offsets would
                raise OSError(             # point past the end of the file
                    lib.ffz_error(h).decode("utf-8", "replace")
                )
            lines = MmapBlob(spill_path)
        else:
            lines = bytes_at(
                lib.ffz_lines_blob(h), lib.ffz_lines_blob_len(h)
            )
        return NativeFlowFeatures(
            lines_blob=lines,
            line_off=_copy(lib.ffz_line_offsets(h), n + 1, np.int64),
            ip_table=_table(lib, h, 0),
            word_table=_table(lib, h, 1),
            sip_id=_copy(lib.ffz_ids(h, 0), n, np.int32),
            dip_id=_copy(lib.ffz_ids(h, 1), n, np.int32),
            wp_id=_copy(lib.ffz_ids(h, 2), n, np.int32),
            sw_id=_copy(lib.ffz_ids(h, 3), n, np.int32),
            dw_id=_copy(lib.ffz_ids(h, 4), n, np.int32),
            num_time=num_time,
            # Bin values are 0-10: int16 storage shrinks features.pkl
            # by ~90 MB on a 5M-event day (native_emit widens back to
            # the C emitters' int64 at call time).
            ibyt_bin=_copy(lib.ffz_bins(h, 1), n, np.int16),
            ipkt_bin=_copy(lib.ffz_bins(h, 2), n, np.int16),
            time_bin=_copy(lib.ffz_bins(h, 0), n, np.int16),
            wc_ip=_copy(lib.ffz_wc_ip(h), nwc, np.int32),
            wc_word=_copy(lib.ffz_wc_word(h), nwc, np.int32),
            wc_count=_narrow_i32(_copy(lib.ffz_wc_count(h), nwc,
                                       np.int64)),
            num_raw_events=int(lib.ffz_num_raw(h)),
            time_cuts=time_cuts,
            ibyt_cuts=ibyt_cuts,
            ipkt_cuts=ipkt_cuts,
        )
    finally:
        lib.ffz_destroy(ctypes.c_void_p(h))


def featurize_flow_file(
    path: str,
    feedback_rows: Sequence[str] = (),
    precomputed_cuts=None,
    spill_path: str | None = None,
    workers: int = 1,
    timings: "dict | None" = None,
) -> "NativeFlowFeatures | FlowFeatures":
    """Featurize raw netflow CSV input, native when possible.

    `path` accepts a single file, a comma-separated list, a directory,
    or a glob (expand_flow_paths) — the reference's FLOW_PATH is an
    HDFS location whose every part file Spark reads, and config 3's
    30-day corpus is a multi-file ingest.  Quantile cuts are computed
    over the UNION of all files, exactly like one Spark RDD over the
    whole location.

    `spill_path` streams kept raw rows to that file during ingest
    instead of holding them in RAM (features/blob.py MmapBlob): RSS
    stays bounded by the numeric per-event arrays, and pickling the
    returned container stores the spill path, not the bytes.  The
    Python fallback keeps rows in memory (it exists for environments
    without a C++ toolchain, where day-scale data is not expected).

    `workers` shards each input file into line-aligned byte ranges and
    runs the parse/word-build passes concurrently (0 = auto from the
    host core count, 1 = the exact legacy sequential path); the
    deterministic first-seen merge keeps every output byte-identical
    across worker counts — pinned by tests/test_pre_parallel.py.
    `timings` (a dict, filled in place) receives the per-pass walls
    (parse_s / cuts_s / word_build_s) and the merge overhead (merge_s)
    for the runner's stage metrics."""
    from .shards import resolve_pre_workers

    workers = resolve_pre_workers(workers)
    paths = expand_flow_paths(path)
    if not paths:
        # An empty expansion (empty directory, unmatched glob, empty
        # spec) must not silently produce an empty day.
        raise OSError(f"no flow input files match {path!r}")
    lib = _LIB.load()
    if lib is not None:
        return _featurize_native(lib, paths, feedback_rows,
                                 precomputed_cuts, spill_path=spill_path,
                                 workers=workers, timings=timings)
    import time as _time

    from itertools import chain

    from .lineio import iter_raw_lines

    t0 = _time.perf_counter()
    if workers > 1:
        # Fallback parallelism: the shard plan reads/decodes/splits
        # concurrently ahead of the consumer with bounded buffering
        # (shards.py iter_lines_sharded); featurization itself stays
        # the one sequential pass over the ordered line stream, so the
        # output is the sequential output by construction.
        from .shards import iter_lines_sharded

        lines = iter_lines_sharded(paths, workers)
    else:
        lines = chain.from_iterable(iter_raw_lines(p) for p in paths)
    feats = featurize_flow(
        lines,
        feedback_rows=feedback_rows,
        precomputed_cuts=precomputed_cuts,
    )
    if timings is not None:
        timings["word_build_s"] = round(_time.perf_counter() - t0, 3)
    return feats
