"""Streaming raw-line reader shared by the featurizer fallback paths.

Matches the native ingest's line semantics exactly (native_src/common.h
stream_file + the featurizers' ingest): rows end at '\n' with ONE
optional preceding '\r' stripped.  Deliberately NOT Python universal
newlines — an embedded lone '\r' is a legal byte in a hostile DNS query
name (in security telemetry the weird names ARE the signal) and must
stay inside its field, not split the row.  Reads in bounded chunks so a
multi-GB day file never materializes in memory.
"""

from __future__ import annotations

from typing import Iterator


def iter_raw_lines(path: str, chunk_size: int = 1 << 22) -> Iterator[str]:
    """Yield decoded lines of `path` without their '\n' terminator,
    stripping one trailing '\r' per line (CRLF); empty lines included
    (callers filter), no terminator on the final line required."""
    with open(path, "rb") as f:
        pending = b""
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                break
            pending += chunk
            if b"\n" not in chunk:
                continue
            *lines, pending = pending.split(b"\n")
            for ln in lines:
                if ln.endswith(b"\r"):
                    ln = ln[:-1]
                yield ln.decode("utf-8", "surrogateescape")
        if pending:
            if pending.endswith(b"\r"):
                pending = pending[:-1]
            yield pending.decode("utf-8", "surrogateescape")
