"""ctypes binding for the native DNS featurizer (oni_ml_tpu/native_src/dns_featurize.cpp).

``featurize_dns_sources`` is the production entry point for the DNS pre
stage: CSV files stream straight through C++; parquet files (and
feedback rows) are projected to 8-column rows in Python and handed over
as an \\x1f-separated blob.  Falls back to ``features.dns.featurize_dns``
when the native library is unavailable.  Parity with the Python path is
pinned by tests/test_native_dns.py.

ECDF cuts are computed in Python from the native pass's arrays with
quantiles.ecdf_cuts (single implementation of the reference's quantile
rule), including the positive-subset quintiles for subdomain length /
entropy / period count (dns_pre_lda.scala:298-305).
"""

from __future__ import annotations

import ctypes
import os
from typing import Sequence

import numpy as np

from ..native_build import NativeLib, bytes_at, narrow_counts_i32
from .dns import DnsFeatures, featurize_dns
from .quantiles import DECILES, QUINTILES, ecdf_cuts

_I32P = ctypes.POINTER(ctypes.c_int32)
_I64P = ctypes.POINTER(ctypes.c_int64)
_F64P = ctypes.POINTER(ctypes.c_double)
_SEP = "\x1f"


def _configure(lib: ctypes.CDLL) -> None:
    lib.dfz_create.restype = ctypes.c_void_p
    lib.dfz_destroy.argtypes = [ctypes.c_void_p]
    lib.dfz_error.restype = ctypes.c_char_p
    lib.dfz_error.argtypes = [ctypes.c_void_p]
    lib.dfz_ingest_csv_file.restype = ctypes.c_int64
    lib.dfz_ingest_csv_file.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.dfz_ingest_csv_file_parallel.restype = ctypes.c_int64
    lib.dfz_ingest_csv_file_parallel.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
    ]
    lib.dfz_merge_ns.restype = ctypes.c_int64
    lib.dfz_merge_ns.argtypes = [ctypes.c_void_p]
    lib.dfz_ingest_rows.restype = ctypes.c_int64
    lib.dfz_ingest_rows.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.dfz_mark_raw.argtypes = [ctypes.c_void_p]
    lib.dfz_unsafe.restype = ctypes.c_int
    lib.dfz_unsafe.argtypes = [ctypes.c_void_p]
    for fn in ("dfz_num_raw", "dfz_num_events", "dfz_rows_blob_len",
               "dfz_wc_len"):
        getattr(lib, fn).restype = ctypes.c_int64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    for fn in ("dfz_tstamp", "dfz_frame_len", "dfz_entropy"):
        getattr(lib, fn).restype = _F64P
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    for fn in ("dfz_sublen", "dfz_nparts", "dfz_top"):
        getattr(lib, fn).restype = _I32P
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.dfz_finish.restype = ctypes.c_int
    lib.dfz_finish.argtypes = (
        [ctypes.c_void_p]
        + [_F64P, ctypes.c_int] * 5
        + [ctypes.c_char_p, ctypes.c_int64]
    )
    lib.dfz_finish_mt.restype = ctypes.c_int
    lib.dfz_finish_mt.argtypes = (
        [ctypes.c_void_p]
        + [_F64P, ctypes.c_int] * 5
        + [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int]
    )
    lib.dfz_ids.restype = _I32P
    lib.dfz_ids.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dfz_table_count.restype = ctypes.c_int64
    lib.dfz_table_count.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dfz_table_blob.restype = ctypes.c_void_p
    lib.dfz_table_blob.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dfz_table_blob_len.restype = ctypes.c_int64
    lib.dfz_table_blob_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dfz_table_offsets.restype = _I64P
    lib.dfz_table_offsets.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dfz_rows_blob.restype = ctypes.c_void_p
    lib.dfz_rows_blob.argtypes = [ctypes.c_void_p]
    lib.dfz_set_spill.restype = ctypes.c_int
    lib.dfz_set_spill.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dfz_spill_flush.restype = ctypes.c_int64
    lib.dfz_spill_flush.argtypes = [ctypes.c_void_p]
    lib.dfz_row_offsets.restype = _I64P
    lib.dfz_row_offsets.argtypes = [ctypes.c_void_p]
    for fn, res in [
        ("dfz_wc_ip", _I32P), ("dfz_wc_word", _I32P), ("dfz_wc_count", _I64P),
    ]:
        getattr(lib, fn).restype = res
        getattr(lib, fn).argtypes = [ctypes.c_void_p]


_LIB = NativeLib(
    os.path.join(
        os.path.dirname(__file__), "..", "native_src", "dns_featurize.cpp"
    ),
    os.path.join(os.path.dirname(__file__), "_native", "liboni_dns.so"),
    _configure,
    deps=(
        os.path.join(
            os.path.dirname(__file__), "..", "native_src", "common.h"
        ),
    ),
)


def available() -> bool:
    return _LIB.available()


def _copy(ptr, n, dtype):
    if n == 0:
        return np.zeros(0, dtype=dtype)
    return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)


_narrow_i32 = narrow_counts_i32   # shared guard (native_build)


def _table(lib, h, which: int) -> list[str]:
    cnt = lib.dfz_table_count(h, which)
    blob = bytes_at(
        lib.dfz_table_blob(h, which), lib.dfz_table_blob_len(h, which)
    )
    off = _copy(lib.dfz_table_offsets(h, which), cnt + 1, np.int64)
    return [
        blob[off[i]:off[i + 1]].decode("utf-8", "surrogateescape")
        for i in range(cnt)
    ]


class NativeDnsFeatures:
    """DnsFeatures-compatible container backed by native arrays (same
    duck-typed surface the scorer and runner consume; see
    NativeFlowFeatures for the design notes)."""

    def __init__(self, *, rows_blob, row_off, ip_table, domain_table,
                 subdomain_table, word_table, ip_id, dom_id, sub_id, word_id,
                 subdomain_length, num_periods, subdomain_entropy, top_domain,
                 wc_ip, wc_word, wc_count, num_raw_events, time_cuts,
                 frame_length_cuts, subdomain_length_cuts, entropy_cuts,
                 numperiods_cuts):
        self.rows_blob = rows_blob
        self.row_off = row_off
        self.ip_table = ip_table
        self.domain_table = domain_table
        self.subdomain_table = subdomain_table
        self.word_table = word_table
        self.ip_id = ip_id
        self.dom_id = dom_id
        self.sub_id = sub_id
        self.word_id = word_id
        self.subdomain_length = subdomain_length
        self.num_periods = num_periods
        self.subdomain_entropy = subdomain_entropy
        self.top_domain = top_domain
        self.wc_ip = wc_ip
        self.wc_word = wc_word
        self.wc_count = wc_count
        self.num_raw_events = num_raw_events
        self.time_cuts = time_cuts
        self.frame_length_cuts = frame_length_cuts
        self.subdomain_length_cuts = subdomain_length_cuts
        self.entropy_cuts = entropy_cuts
        self.numperiods_cuts = numperiods_cuts
        self._lists: dict[str, list[str]] = {}

    @property
    def num_events(self) -> int:
        return len(self.ip_id)

    def row(self, i: int) -> list[str]:
        raw = self.rows_blob[self.row_off[i]:self.row_off[i + 1]]
        return raw.decode("utf-8", "surrogateescape").split(_SEP)

    def client_ip(self, i: int) -> str:
        return self.ip_table[self.ip_id[i]]

    def _list(self, which: str) -> list[str]:
        if which not in self._lists:
            table, ids = {
                "domain": (self.domain_table, self.dom_id),
                "subdomain": (self.subdomain_table, self.sub_id),
                "word": (self.word_table, self.word_id),
            }[which]
            self._lists[which] = [table[j] for j in ids]
        return self._lists[which]

    @property
    def domain(self) -> list[str]:
        return self._list("domain")

    @property
    def subdomain(self) -> list[str]:
        return self._list("subdomain")

    @property
    def word(self) -> list[str]:
        return self._list("word")

    @property
    def rows(self) -> list[list[str]]:
        return [self.row(i) for i in range(self.num_events)]

    def word_counts(self) -> list[tuple[str, str, int]]:
        ips, words = self.ip_table, self.word_table
        return [
            (ips[i], words[w], int(c))
            for i, w, c in zip(self.wc_ip, self.wc_word, self.wc_count)
        ]

    def word_count_columns(self):
        """Columnar word-count hand-off (dataplane/columns.py): the
        aggregated table-id arrays straight from the native pass — no
        string materialization; the streaming corpus builder's
        first-seen remap reproduces `Corpus.from_features` exactly."""
        from ..dataplane.columns import make_word_count_columns

        return make_word_count_columns(
            self.wc_ip, self.wc_word, self.wc_count,
            self.ip_table, self.word_table,
        )

    def featurized_row(self, i: int) -> list[str]:
        return self.row(i) + [
            self.domain_table[self.dom_id[i]],
            self.subdomain_table[self.sub_id[i]],
            str(int(self.subdomain_length[i])),
            str(int(self.num_periods[i])),
            str(self.subdomain_entropy[i]),
            str(int(self.top_domain[i])),
            self.word_table[self.word_id[i]],
        ]

    def spill_rows(self, path: str) -> None:
        """Move the projected-rows blob to a mmap-backed file
        (features/blob.py): pickling stores the path, not the bytes.
        Post-hoc companion to the ingest-time spill
        (featurize_dns_sources(spill_path=...) / dfz_set_spill, which
        bounds the featurize peak itself) — use this when a container
        was built in memory and only the pickle/post-stage RSS needs
        bounding.  No-op when the blob is already spilled."""
        if isinstance(self.rows_blob, (bytes, bytearray)):
            from .blob import spill_bytes

            self.rows_blob = spill_bytes(self.rows_blob, path)

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_lists")
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lists = {}


def _rows_to_blob_checked(rows: Sequence[Sequence[str]]):
    """(blob | None): join rows for native ingest, detecting transport-
    byte collisions in the same pass — None means some field embeds
    '\\n', '\\r', or the '\\x1f' separator and the run must take the
    Python path.  The per-row checks ride C-speed str scans on the
    joined string (a field embedding the separator shows up as a
    separator-count mismatch), replacing a per-field Python scan that
    cost more than the native featurization itself."""
    if not rows:
        return b""
    parts = []
    sep = _SEP
    for r in rows:
        j = sep.join(r)
        if r and (
            "\n" in j or "\r" in j or j.count(sep) != len(r) - 1
        ):
            return None
        parts.append(j)
    try:
        return ("\n".join(parts) + "\n").encode("utf-8")
    except UnicodeEncodeError:
        # Lone surrogates (surrogateescape-decoded raw wire bytes) are
        # not UTF-8-encodable; route the run to the Python path, which
        # handles the str values directly.
        return None


def _featurize_native(
    lib,
    sources: Sequence,
    feedback_rows: Sequence[Sequence[str]],
    top_domains: frozenset,
    spill_path: str | None = None,
    workers: int = 1,
    timings: "dict | None" = None,
) -> "NativeDnsFeatures | None":
    """Run the native featurizer; returns None when ingest saw a CSV
    field embedding the \\x1f transport separator (the stored rows blob
    would re-split into misaligned columns) — the caller falls back to
    the Python path for the whole run."""
    # In-memory sources are joined + transport-byte-checked one at a
    # time as they are ingested (one blob alive at once — peak RSS
    # matters for multi-source days).  An unsafe field mid-run simply
    # returns None: the finally below destroys the half-ingested
    # handle and the caller falls back to the Python path.
    import time as _time

    h = lib.dfz_create()
    try:
        if spill_path is not None and lib.dfz_set_spill(
            h, os.fsencode(spill_path)
        ) < 0:
            raise OSError(lib.dfz_error(h).decode("utf-8", "replace"))
        t0 = _time.perf_counter()
        for src in sources:
            if isinstance(src, str):
                # Parallel ingest shards each CSV file (pass A) across
                # std::thread workers with a deterministic first-seen
                # merge; in-memory row blobs (parquet) stay sequential
                # — source order, and so the id contract, is unchanged.
                rc = (
                    lib.dfz_ingest_csv_file_parallel(
                        h, os.fsencode(src), 0, workers
                    )
                    if workers > 1
                    else lib.dfz_ingest_csv_file(h, os.fsencode(src), 0)
                )
                if rc < 0:
                    raise OSError(
                        lib.dfz_error(h).decode("utf-8", "replace")
                    )
            elif src:
                blob = _rows_to_blob_checked(src)
                if blob is None:
                    return None
                if lib.dfz_ingest_rows(h, blob, len(blob)) < 0:
                    raise OSError(
                        lib.dfz_error(h).decode("utf-8", "replace")
                    )
                del blob
        if lib.dfz_unsafe(h):
            return None
        lib.dfz_mark_raw(h)
        if feedback_rows:
            blob = _rows_to_blob_checked(feedback_rows)
            if blob is None:
                return None
            if lib.dfz_ingest_rows(h, blob, len(blob)) < 0:
                raise OSError(lib.dfz_error(h).decode("utf-8", "replace"))
            del blob

        t1 = _time.perf_counter()
        n = lib.dfz_num_events(h)
        tstamp = _copy(lib.dfz_tstamp(h), n, np.float64)
        frame_len = _copy(lib.dfz_frame_len(h), n, np.float64)
        entropy = _copy(lib.dfz_entropy(h), n, np.float64)
        # int32 — matching the C featurizer's own storage, so a
        # hostile >32767-char subdomain cannot wrap here while the C
        # binner sees the true value (the emit binding widens to int64
        # at call time; int64 storage was pure pickle bloat).
        sub_len = _copy(lib.dfz_sublen(h), n, np.int32)
        n_parts = _copy(lib.dfz_nparts(h), n, np.int32)

        # One global ECDF over the merged arrays, whatever the worker
        # count — sharding can never move a bin edge.
        time_cuts = ecdf_cuts(tstamp, DECILES)
        frame_length_cuts = ecdf_cuts(frame_len, DECILES)
        subdomain_length_cuts = ecdf_cuts(sub_len[sub_len > 0], QUINTILES)
        entropy_cuts = ecdf_cuts(entropy[entropy > 0], QUINTILES)
        numperiods_cuts = ecdf_cuts(n_parts[n_parts > 0], QUINTILES)

        top_blob = "\n".join(sorted(top_domains)).encode(
            "utf-8", "surrogateescape"
        )

        def fp(a):
            return np.ascontiguousarray(a, np.float64).ctypes.data_as(_F64P)

        t2 = _time.perf_counter()
        if workers > 1:
            rc = lib.dfz_finish_mt(
                h, fp(time_cuts), len(time_cuts),
                fp(frame_length_cuts), len(frame_length_cuts),
                fp(subdomain_length_cuts), len(subdomain_length_cuts),
                fp(entropy_cuts), len(entropy_cuts),
                fp(numperiods_cuts), len(numperiods_cuts),
                top_blob, len(top_blob), workers,
            )
        else:
            rc = lib.dfz_finish(
                h, fp(time_cuts), len(time_cuts),
                fp(frame_length_cuts), len(frame_length_cuts),
                fp(subdomain_length_cuts), len(subdomain_length_cuts),
                fp(entropy_cuts), len(entropy_cuts),
                fp(numperiods_cuts), len(numperiods_cuts),
                top_blob, len(top_blob),
            )
        if rc < 0:
            raise ValueError(lib.dfz_error(h).decode("utf-8", "replace"))
        if timings is not None:
            timings.update(
                parse_s=round(t1 - t0, 3),
                cuts_s=round(t2 - t1, 3),
                word_build_s=round(_time.perf_counter() - t2, 3),
                merge_s=round(lib.dfz_merge_ns(h) / 1e9, 3),
            )

        nwc = lib.dfz_wc_len(h)
        if spill_path is not None:
            from .blob import MmapBlob

            if lib.dfz_spill_flush(h) < 0:  # short write: offsets would
                raise OSError(             # point past the end of the file
                    lib.dfz_error(h).decode("utf-8", "replace")
                )
            rows_blob = MmapBlob(spill_path)
        else:
            rows_blob = bytes_at(
                lib.dfz_rows_blob(h), lib.dfz_rows_blob_len(h)
            )
        return NativeDnsFeatures(
            rows_blob=rows_blob,
            row_off=_copy(lib.dfz_row_offsets(h), n + 1, np.int64),
            ip_table=_table(lib, h, 0),
            domain_table=_table(lib, h, 1),
            subdomain_table=_table(lib, h, 2),
            word_table=_table(lib, h, 3),
            ip_id=_copy(lib.dfz_ids(h, 0), n, np.int32),
            dom_id=_copy(lib.dfz_ids(h, 1), n, np.int32),
            sub_id=_copy(lib.dfz_ids(h, 2), n, np.int32),
            word_id=_copy(lib.dfz_ids(h, 3), n, np.int32),
            subdomain_length=sub_len,
            num_periods=n_parts,
            subdomain_entropy=entropy,
            top_domain=_copy(lib.dfz_top(h), n, np.int16),   # {0,1,2}
            wc_ip=_copy(lib.dfz_wc_ip(h), nwc, np.int32),
            wc_word=_copy(lib.dfz_wc_word(h), nwc, np.int32),
            wc_count=_narrow_i32(_copy(lib.dfz_wc_count(h), nwc, np.int64)),
            num_raw_events=int(lib.dfz_num_raw(h)),
            time_cuts=time_cuts,
            frame_length_cuts=frame_length_cuts,
            subdomain_length_cuts=subdomain_length_cuts,
            entropy_cuts=entropy_cuts,
            numperiods_cuts=numperiods_cuts,
        )
    finally:
        lib.dfz_destroy(ctypes.c_void_p(h))


def featurize_dns_sources(
    sources: Sequence = (),
    top_domains: frozenset = frozenset(),
    feedback_rows: Sequence[Sequence[str]] = (),
    spill_path: str | None = None,
    workers: int = 1,
    timings: "dict | None" = None,
) -> "NativeDnsFeatures | DnsFeatures":
    """Featurize DNS events, native when possible.

    `spill_path` streams the stored rows blob to that file during
    ingest (features/blob.py MmapBlob) so the day's row bytes never
    accumulate in RAM and pickling the container stores the path.  The
    pure-Python fallback (and a native run that fell back over
    transport bytes) ignores it and keeps rows in memory — that path
    exists for correctness on hostile fields / toolchain-free hosts,
    not for day-scale data.  `NativeDnsFeatures.spill_rows` remains for
    post-hoc spilling of an in-memory native container.

    `sources` is an ORDERED sequence whose elements are CSV paths (str)
    or pre-projected 8-column row lists (parquet).  Events enter the
    corpus in exactly the listed order — first-seen doc/word id
    assignment (the words.dat/doc.dat line-number contract) and the
    results row order depend on it.

    Pre-projected rows whose fields embed the transport bytes ('\\n',
    '\\x1f', or '\\r' — possible in raw wire query names, and in security
    telemetry the weird names ARE the signal) cannot ride the native
    blob without corruption ('\\r' because ingest's CRLF handling strips
    a field-final CR), so their presence routes the whole run through
    the Python path instead of silently dropping events.  CSV files can
    likewise embed '\\x1f' inside a field; native ingest detects that
    and the run falls back the same way.

    `workers` shards each CSV source into line-aligned byte ranges and
    runs the parse/word-build passes concurrently (0 = auto from the
    host core count, 1 = the exact legacy sequential path); the
    deterministic merge keeps every output byte-identical across worker
    counts.  `timings` (dict, filled in place) receives per-pass walls
    and the merge overhead for the runner's stage metrics.
    """
    from .shards import resolve_pre_workers

    workers = resolve_pre_workers(workers)
    lib = _LIB.load()
    if lib is not None:
        # _featurize_native returns None when any in-memory field embeds
        # a transport byte ('\n', '\r', '\x1f') or native CSV ingest
        # detects an embedded separator — the whole run then falls back
        # (a partially-written spill file is simply left unreferenced).
        feats = _featurize_native(lib, sources, feedback_rows, top_domains,
                                  spill_path=spill_path, workers=workers,
                                  timings=timings)
        if feats is not None:
            return feats
    import time as _time

    from .lineio import iter_raw_lines

    t0 = _time.perf_counter()
    rows: list[list[str]] = []
    for src in sources:
        if isinstance(src, str):
            if workers > 1:
                # Fallback parallelism: concurrent shard reads with
                # bounded buffering (shards.py), order-preserving —
                # featurization below stays the one sequential pass.
                from .shards import iter_lines_sharded

                rows.extend(
                    line.split(",")
                    for line in iter_lines_sharded([src], workers)
                    if line
                )
            else:
                rows.extend(
                    line.split(",") for line in iter_raw_lines(src) if line
                )
        else:
            rows.extend(list(r) for r in src)
    if timings is not None:
        timings["parse_s"] = round(_time.perf_counter() - t0, 3)
    feats = featurize_dns(
        rows, top_domains=top_domains, feedback_rows=feedback_rows
    )
    if timings is not None:
        timings["word_build_s"] = round(
            _time.perf_counter() - t0 - timings["parse_s"], 3
        )
    return feats
