"""Precomputed quantile cuts — the reference's offline qtiles side-path
(SURVEY §2.7: gen_qtiles.sh + qtiles.py + flow_qtiles).

The reference's intended optimization: compute the flow binning cuts once
(offline, via Hive ntile() over a TABLESAMPLE) instead of full-data ECDF
shuffles every run, stored as one line

    <ibyt cuts>,<ipkt cuts>,<time cuts>

with each list space-separated (consumption contract: the commented-out
`CUT` path at flow_pre_lda.scala:95-98 / ml_ops.sh:48-49; field order
ibyt, ipkt, time).  Here the generator is exact (same ecdf_cuts as the
online path, not a 100-row sample) and the runner consumes the file via
``--qtiles``, which also pins word identity across days — the reference's
per-run recomputation meant the same event could map to different words
on different days (SURVEY §1 nondeterminism note).

CLI:  python -m oni_ml_tpu.features.qtiles raw_flow.csv flow_qtiles
"""

from __future__ import annotations

import sys
from typing import Iterable

import numpy as np

from .flow import FLOW_COLUMNS, NUM_FLOW_COLUMNS, _to_double
from .quantiles import DECILES, QUINTILES, ecdf_cuts
from ..io.formats import contract_open as _open


def write_flow_qtiles(
    path: str,
    time_cuts: np.ndarray,
    ibyt_cuts: np.ndarray,
    ipkt_cuts: np.ndarray,
) -> None:
    def fmt(xs):
        return " ".join(repr(float(x)) for x in xs)

    with open(path, "w") as f:
        f.write(f"{fmt(ibyt_cuts)},{fmt(ipkt_cuts)},{fmt(time_cuts)}\n")


def read_flow_qtiles(path: str):
    """Returns (time_cuts, ibyt_cuts, ipkt_cuts) — the argument order of
    featurize_flow's `precomputed_cuts`."""
    with _open(path) as f:
        line = f.read().strip()
    parts = line.split(",")
    if len(parts) != 3:
        raise ValueError(
            f"{path}: expected 3 comma-separated cut lists, got {len(parts)}"
        )
    ibyt, ipkt, time = (
        np.array([float(x) for x in p.split()], dtype=np.float64)
        for p in parts
    )
    return time, ibyt, ipkt


def compute_flow_qtiles(lines: Iterable[str], skip_header: bool = True):
    """One pass over raw flow CSV -> (time_cuts, ibyt_cuts, ipkt_cuts),
    identical semantics to the in-run ECDF (features/quantiles.py)."""
    c = FLOW_COLUMNS
    times, ibyts, ipkts = [], [], []
    header = None
    for line in lines:
        if skip_header:
            if header is None:
                header = line
                continue
            if line == header:
                continue
        parts = line.strip().split(",")
        if len(parts) != NUM_FLOW_COLUMNS:
            continue
        times.append(
            _to_double(parts[c["hour"]])
            + _to_double(parts[c["minute"]]) / 60.0
            + _to_double(parts[c["second"]]) / 3600.0
        )
        ibyts.append(_to_double(parts[c["ibyt"]]))
        ipkts.append(_to_double(parts[c["ipkt"]]))
    return (
        ecdf_cuts(np.array(times), DECILES),
        ecdf_cuts(np.array(ibyts), DECILES),
        ecdf_cuts(np.array(ipkts), QUINTILES),
    )


def main(argv: list[str] | None = None) -> int:
    args = sys.argv[1:] if argv is None else argv
    wants_help = bool(args) and args[0] in ("-h", "--help")
    if wants_help or len(args) != 2:
        print(
            "usage: python -m oni_ml_tpu.features.qtiles "
            "<raw_flow.csv> <out_qtiles>",
            file=sys.stdout if wants_help else sys.stderr,
        )
        return 0 if wants_help else 2
    with open(args[0]) as f:
        time_cuts, ibyt_cuts, ipkt_cuts = compute_flow_qtiles(
            line.rstrip("\n") for line in f
        )
    write_flow_qtiles(args[1], time_cuts, ibyt_cuts, ipkt_cuts)
    print(f"wrote {args[1]}: time={time_cuts} ibyt={ibyt_cuts} ipkt={ipkt_cuts}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
