"""Feature engineering — the framework's replacement for the reference's
Spark featurizer scripts (flow_pre_lda.scala, dns_pre_lda.scala).

Unlike the reference, which re-runs the identical featurization in its
post/scoring stage (flow_post_lda.scala:64-224 duplicates
flow_pre_lda.scala:102-362; see SURVEY.md §1), features here are computed
ONCE into a FeatureTable that both the corpus-building and scoring stages
consume.
"""

from .quantiles import ecdf_cuts, bin_values
from .flow import FlowFeatures, featurize_flow, FLOW_COLUMNS
from .native_flow import featurize_flow_file
from .shards import resolve_pre_workers
from .dns import (
    DnsFeatures,
    featurize_dns,
    extract_subdomain,
    shannon_entropy,
    load_top_domains,
    DNS_COLUMNS,
)
from .feedback import (
    read_flow_feedback_rows,
    read_dns_feedback_rows,
)

__all__ = [
    "ecdf_cuts",
    "bin_values",
    "FlowFeatures",
    "featurize_flow",
    "featurize_flow_file",
    "FLOW_COLUMNS",
    "DnsFeatures",
    "featurize_dns",
    "extract_subdomain",
    "shannon_entropy",
    "load_top_domains",
    "DNS_COLUMNS",
    "read_flow_feedback_rows",
    "read_dns_feedback_rows",
    "resolve_pre_workers",
]
