"""Analyst feedback ingestion — the human-in-the-loop mechanism.

Rows an analyst marked non-threatening (severity 3) are replicated
DUPFACTOR (default 1000) times into the corpus so their probability mass
rises above the suspicion threshold (ml_ops.sh:31,
flow_pre_lda.scala:253-268, dns_pre_lda.scala:80-139).

Flow note: the reference's 22-column feedback row -> 27-column flow row
converter loses its commas (`buf + ','` discards its result,
flow_pre_lda.scala:243-245), so upstream the injected rows fail the
27-field validity filter and the whole flow feedback path is dead code.
We build real comma-separated rows, implementing the documented intent;
unknown fields are the reference's "##" filler.
"""

from __future__ import annotations

import os
from ..io.formats import contract_open as _open

# flow_scores.csv schema (flow_pre_lda.scala:150-171)
_FLOW_FB_SEV = 0
_FLOW_FB_TSTART = 1
_FLOW_FB_SRCIP = 2
_FLOW_FB_DSTIP = 3
_FLOW_FB_SPORT = 4
_FLOW_FB_DPORT = 5
_FLOW_FB_IPKT = 8
_FLOW_FB_IBYT = 9
_FLOW_FB_NUM_FIELDS = 22

# dns_scores.csv schema (dns_pre_lda.scala:82-117)
_DNS_FB_FRAME_TIME = 0
_DNS_FB_FRAME_LEN = 1
_DNS_FB_IP_DST = 2
_DNS_FB_QRY_NAME = 3
_DNS_FB_QRY_CLASS = 4
_DNS_FB_QRY_TYPE = 5
_DNS_FB_QRY_RCODE = 6
_DNS_FB_SEV = 18
_DNS_FB_UNIX_TSTAMP = 23
_DNS_FB_NUM_FIELDS = 24


def _flow_feedback_to_flow_row(fields: list[str]) -> str:
    """22-col feedback row -> 27-col flow CSV
    (convert_feedback_row_to_flow_row, flow_pre_lda.scala:146-248).
    tstart is 'YYYY-MM-DD HH:MM:SS'; hour/min/sec land in cols 4-6."""
    hms = fields[_FLOW_FB_TSTART].split(" ")[1].split(":")
    out = ["##"] * 27
    out[4], out[5], out[6] = hms[0], hms[1], hms[2]
    out[8] = fields[_FLOW_FB_SRCIP]
    out[9] = fields[_FLOW_FB_DSTIP]
    out[10] = fields[_FLOW_FB_SPORT]
    out[11] = fields[_FLOW_FB_DPORT]
    out[16] = fields[_FLOW_FB_IPKT]
    out[17] = fields[_FLOW_FB_IBYT]
    return ",".join(out)


def read_flow_feedback_rows(
    path: str, dup_factor: int, severity: int = 3
) -> list[str]:
    """flow_scores.csv -> duplicated 27-column CSV rows.  Missing file ->
    no feedback (the reference checks existence, flow_pre_lda.scala:253)."""
    if not os.path.exists(path):
        return []
    with _open(path) as f:
        lines = f.read().splitlines()[1:]  # drop header
    out: list[str] = []
    for line in lines:
        fields = line.split(",")
        if len(fields) != _FLOW_FB_NUM_FIELDS:
            continue
        try:
            if int(fields[_FLOW_FB_SEV]) != severity:
                continue
            row = _flow_feedback_to_flow_row(fields)
        except (ValueError, IndexError):
            # Malformed severity or tstart ('YYYY-MM-DD HH:MM:SS'
            # expected): skip the row, don't abort the day.
            continue
        out.extend([row] * dup_factor)
    return out


def read_dns_feedback_rows(
    path: str, dup_factor: int, severity: int = 3
) -> list[list[str]]:
    """dns_scores.csv -> duplicated 8-column rows in the featurizer's
    input order (frame_time, unix_tstamp, frame_len, ip_dst, qry_name,
    qry_class, qry_type, qry_rcode — dns_pre_lda.scala:124-134)."""
    if not os.path.exists(path):
        return []
    with _open(path) as f:
        lines = f.read().splitlines()[1:]
    out: list[list[str]] = []
    for line in lines:
        fields = line.split(",")
        if len(fields) != _DNS_FB_NUM_FIELDS:
            continue
        try:
            if int(fields[_DNS_FB_SEV].strip()) != severity:
                continue
        except ValueError:
            continue
        row = [
            fields[_DNS_FB_FRAME_TIME],
            fields[_DNS_FB_UNIX_TSTAMP],
            fields[_DNS_FB_FRAME_LEN].strip(),
            fields[_DNS_FB_IP_DST],
            fields[_DNS_FB_QRY_NAME],
            fields[_DNS_FB_QRY_CLASS],
            fields[_DNS_FB_QRY_TYPE],
            fields[_DNS_FB_QRY_RCODE],
        ]
        out.extend([list(row) for _ in range(dup_factor)])
    return out
