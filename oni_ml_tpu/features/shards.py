"""Shard plan for the parallel pre stage.

The native featurizers shard a day file internally (std::thread workers
behind ``ffz_ingest_file_parallel`` / ``dfz_ingest_csv_file_parallel``,
native_src/common.h ``shard_bounds``); this module is the Python twin:
the same line-aligned byte-range plan, used by the pure-Python fallback
(`concurrent.futures` over shards) and by tests that pin the plan's
invariants.  Boundaries always land right after a ``\\n``, so a CRLF
pair or a multi-megabyte line is never torn across workers, and the
ranges concatenated in order cover the input exactly once — which is
what makes workers=N output byte-identical to workers=1.
"""

from __future__ import annotations

import os
from typing import Sequence


def resolve_pre_workers(workers: int, with_source: bool = False):
    """Config semantics of ``pre_workers``: 0 = auto, 1 = the exact
    legacy sequential path, N = that many shard workers.

    Auto consults the plan cache first (oni_ml_tpu/plans, host-scoped
    knob ``pre_workers`` — tools/pre_probe.py records the measured best
    for this host), falling back to one worker per host core.  Worker
    count never changes output bytes (the deterministic first-seen
    merge), so a plan entry here is a pure throughput decision.
    ``with_source=True`` additionally returns "config" | "plan" |
    "default" for the pre-stage record."""
    if workers < 0:
        raise ValueError(f"pre_workers must be >= 0, got {workers}")
    auto = max(1, os.cpu_count() or 1)
    if workers:
        out = (workers, "config")
    else:
        planned = None
        try:
            from ..plans import lookup_value

            planned = lookup_value("pre_workers")
        except Exception:
            planned = None
        # A plan entry is operator-editable data: accept it only inside
        # a sane band (a corrupt "1000000" must degrade to untuned, not
        # plan a million shards / spawn a million threads).  4x cores
        # covers every oversubscription a probe could legitimately win.
        if planned and 1 <= int(planned) <= 4 * auto:
            out = (int(planned), "plan")
        else:
            out = (auto, "default")
    return out if with_source else out[0]


def plan_file_shards(
    path: str, workers: int, data_start: int = 0
) -> list[tuple[int, int]]:
    """`workers` line-aligned [begin, end) byte ranges covering
    [data_start, size).  Each range begins at a line start (the byte
    after a ``\\n``; range 0 at data_start); ranges collapse to empty
    when one line spans several raw splits."""
    size = os.path.getsize(path)
    bounds = [data_start]
    span = size - data_start
    with open(path, "rb") as f:
        for i in range(1, workers):
            cand = max(data_start + span * i // workers, bounds[-1])
            f.seek(cand)
            bound = size
            pos = cand
            while pos < size:
                chunk = f.read(min(1 << 20, size - pos))
                if not chunk:
                    break
                j = chunk.find(b"\n")
                if j >= 0:
                    bound = pos + j + 1
                    break
                pos += len(chunk)
            bounds.append(bound)
    bounds.append(size)
    return [(bounds[i], bounds[i + 1]) for i in range(workers)]


def read_shard_lines(path: str, begin: int, end: int) -> list[str]:
    """Decoded lines of one byte range, with exactly
    ``lineio.iter_raw_lines`` semantics: ``\\n`` terminators dropped,
    ONE trailing ``\\r`` stripped per line, empty lines kept (callers
    filter), the final unterminated line included."""
    if begin >= end:
        return []
    with open(path, "rb") as f:
        f.seek(begin)
        data = f.read(end - begin)
    parts = data.split(b"\n")
    if parts and parts[-1] == b"":
        parts.pop()  # range ended right after a '\n', not mid-line
    return [
        (ln[:-1] if ln.endswith(b"\r") else ln).decode(
            "utf-8", "surrogateescape"
        )
        for ln in parts
    ]


def iter_lines_sharded(paths: Sequence[str], workers: int):
    """Ordered line stream over `paths`, each file read as concurrent
    shards — the fallback path's parallelism: read/decode/split overlap
    across shards while featurization stays one pass (the native entry
    points are the production parallel path).

    Shards are planned 4× finer than the worker count and consumed in
    submission order with at most workers+1 in flight, so the peak
    buffered text is a bounded fraction of the file — never the whole
    decoded day at once (the fallback serves toolchain-free hosts,
    where doubling peak memory is exactly the wrong trade)."""
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=workers) as ex:
        for path in paths:
            shards = plan_file_shards(path, workers * 4)
            pending: deque = deque()
            idx = 0
            while idx < len(shards) or pending:
                while idx < len(shards) and len(pending) <= workers:
                    b, e = shards[idx]
                    pending.append(ex.submit(read_shard_lines, path, b, e))
                    idx += 1
                yield from pending.popleft().result()
