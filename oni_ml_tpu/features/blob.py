"""Mmap-backed raw-line storage for the pre stage.

The featurizers keep every kept raw line so the scorer can re-emit the
original row for flagged events (reference behavior: the post stage
re-reads the raw day, flow_post_lda.scala:245-248).  For a single day
that blob fits RAM, but a config-3 30-day corpus (BASELINE.json) does
not — and round 2 pickled the whole blob into features.pkl besides
(VERDICT r2 weak-item 2).  MmapBlob replaces the in-memory bytes with a
file-backed window: the OS pages rows in at emit time only, RSS stays
bounded by the numeric arrays, and pickling stores just the path.

Both native featurizers write the spill during ingest (the blob never
exists in RAM: native_src/flow_featurize.cpp ffz_set_spill,
native_src/dns_featurize.cpp dfz_set_spill); spill_bytes() remains for
post-hoc spilling of a container that was built in memory.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np


class MmapBlob:
    """Read-only byte blob backed by a file via np.memmap.

    Supports the exact surface the feature containers use on their
    bytes blobs: len(), slicing (returns bytes), and a C pointer for
    the native emit path.  Pickles as the path — the spill file must
    travel with the day directory (features.pkl references it
    relatively to wherever the runner wrote it).
    """

    def __init__(self, path: str):
        self.path = path
        # Size at spill time, carried through the pickle: the runner's
        # post-move re-resolution uses it as an identity check, so a
        # stale same-named spill from an earlier interrupted run in a
        # copied day dir cannot be silently scored against mismatched
        # offsets (round-4 advisor finding).
        self.size: int | None = (
            os.path.getsize(path) if os.path.exists(path) else None
        )
        self._arr: np.ndarray | None = None

    def _a(self) -> np.ndarray:
        if self._arr is None:
            if os.path.getsize(self.path):
                self._arr = np.memmap(self.path, dtype=np.uint8, mode="r")
            else:
                self._arr = np.zeros(0, np.uint8)  # mmap rejects length 0
        return self._arr

    def __len__(self) -> int:
        return int(self._a().size)

    def __getitem__(self, key) -> bytes:
        return self._a()[key].tobytes()

    def as_c_char_p(self):
        """Pointer for ctypes calls (native emit).  numpy exposes the
        address of the read-only mapping directly — the C side only
        reads."""
        a = self._a()
        if a.size == 0:
            return b""
        return a.ctypes.data_as(ctypes.c_char_p)

    def __getstate__(self):
        return {"path": self.path, "size": self.size}

    def __setstate__(self, state):
        self.path = state["path"]
        self.size = state.get("size")  # pre-round-5 pickles lack it
        self._arr = None


def spill_bytes(blob: bytes, path: str) -> MmapBlob:
    """Write an in-memory blob to `path` and return its MmapBlob (the
    post-hoc spill used by the DNS container)."""
    with open(path, "wb") as f:
        f.write(blob)
    return MmapBlob(path)
