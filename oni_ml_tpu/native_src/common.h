// Shared helpers for the native featurizers/ingest (single header so a
// parity-critical fix can never land in one translation unit and miss
// the other — that drift already happened once during review).
#pragma once

#include <charconv>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <locale.h>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace oni {

// String interner: stable ids in first-seen order, arena-backed views so
// hot-path lookups never allocate, plus a lazily-built (blob, offsets)
// export for the ctypes side.  Interning invalidates any prior export.
struct Interner {
  std::unordered_map<std::string_view, int32_t> ids;
  std::deque<std::string> arena;
  std::string blob;
  std::vector<int64_t> offsets;

  int32_t intern(std::string_view s) {
    auto it = ids.find(s);
    if (it != ids.end()) return it->second;
    blob.clear();
    offsets.clear();
    arena.emplace_back(s);
    int32_t id = (int32_t)ids.size();
    ids.emplace(std::string_view(arena.back()), id);
    return id;
  }

  void build_export() {
    if (!offsets.empty()) return;
    offsets.push_back(0);
    size_t total = 0;
    for (const auto& s : arena) total += s.size();
    blob.reserve(total);
    for (const auto& s : arena) {
      blob += s;
      offsets.push_back((int64_t)blob.size());
    }
  }
};

// Open-addressing uint64 -> int64 map (linear probing, power-of-two
// capacity, 0.5 max load).  The featurizers' hot loops do several map
// operations per row; std::unordered_map's node allocations and
// pointer-chasing made the flow pass-B aggregation the pipeline's
// hottest block (~1.2 us/row of ~1.8).  Keys must never equal EMPTY
// (~0ull) — the packed (id << 32 | id) keys used here cannot.
struct FlatMap64 {
  static constexpr uint64_t EMPTY = ~0ull;
  std::vector<uint64_t> keys;
  std::vector<int64_t> vals;
  size_t count = 0, mask = 0;

  explicit FlatMap64(size_t expected = 16) {
    size_t cap = 16;
    while (cap < expected * 2) cap <<= 1;
    keys.assign(cap, EMPTY);
    vals.resize(cap);
    mask = cap - 1;
  }

  static uint64_t mix(uint64_t x) {  // splitmix64 finalizer
    x += 0x9e3779b97f4a7c15ull;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
    return x ^ (x >> 31);
  }

  void grow() {
    std::vector<uint64_t> ok = std::move(keys);
    std::vector<int64_t> ov = std::move(vals);
    size_t cap = (mask + 1) * 2;
    keys.assign(cap, EMPTY);
    vals.resize(cap);
    mask = cap - 1;
    for (size_t i = 0; i < ok.size(); i++) {
      if (ok[i] == EMPTY) continue;
      size_t p = mix(ok[i]) & mask;
      while (keys[p] != EMPTY) p = (p + 1) & mask;
      keys[p] = ok[i];
      vals[p] = ov[i];
    }
  }

  // Returns the slot's value reference; *inserted reports whether the
  // key was new (value then undefined — caller must set it).
  int64_t& probe(uint64_t key, bool* inserted) {
    if (count * 2 >= mask + 1) grow();
    size_t p = mix(key) & mask;
    while (keys[p] != EMPTY && keys[p] != key) p = (p + 1) & mask;
    *inserted = keys[p] == EMPTY;
    if (*inserted) {
      keys[p] = key;
      count++;
    }
    return vals[p];
  }
};

// ASCII whitespace exactly (' ', '\t', '\n', '\v', '\f', '\r').  NOT
// std::isspace: that is LC_CTYPE-locale-dependent (e.g. 0xA0 counts as
// space under a Latin-1 locale), which would make featurization depend
// on the host environment.  CPython's float() additionally strips some
// unicode spaces (U+0085/U+00A0...) — a documented divergence
// (flow_featurize.cpp header), same class as underscored numerals.
inline bool ascii_space(char c) {
  return c == ' ' || (c >= '\t' && c <= '\r');
}

// Floating-point charconv landed in GCC 11; GCC 10 (this container's
// toolchain) ships integer-only from_chars/to_chars, which used to
// fail the whole native build — every featurizer silently fell back to
// the ~20x-slower Python paths.  The compat branch below reproduces
// the exact semantics through strtod_l / correctly-rounded snprintf
// (glibc), pinned to the "C" locale per-thread via uselocale so a host
// process locale cannot change parsing or formatting; parity with
// CPython stays pinned by the native test suite, which now RUNS on
// GCC-10 hosts instead of skipping.
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
#define ONI_FP_CHARCONV 1
#else
#define ONI_FP_CHARCONV 0
#endif

inline locale_t c_locale() {
  static locale_t c_loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  return c_loc;
}

// Python float(): trimmed token, optional '+', decimal/exponent/inf/nan;
// out-of-range saturates to +-inf / +-0.0; anything else -> NaN.
// The saturation fallback pins LC_NUMERIC to "C" so a host process with
// a different locale can't change how the digits parse.
inline double to_double(std::string_view s) {
  size_t b = 0, e = s.size();
  while (b < e && ascii_space(s[b])) b++;
  while (e > b && ascii_space(s[e - 1])) e--;
  if (b == e) return NAN;
  std::string_view t = s.substr(b, e - b);
  if (t[0] == '+') t.remove_prefix(1);
  if (t.empty()) return NAN;
#if ONI_FP_CHARCONV
  double v;
  auto [p, ec] = std::from_chars(t.data(), t.data() + t.size(), v);
  if (ec == std::errc::result_out_of_range && p == t.data() + t.size()) {
    std::string z(t);
    return strtod_l(z.c_str(), nullptr, c_locale());
  }
  if (ec != std::errc() || p != t.data() + t.size()) return NAN;
  return v;
#else
  // strtod accepts three token shapes from_chars rejects; filter them
  // so both branches parse identically: a SECOND '+' (one was already
  // stripped), a hex prefix (from_chars consumes just the "0" and the
  // full-consumption check below turns that into NaN), and leading
  // whitespace can't occur (trimmed above).  Saturation on ERANGE is
  // strtod's native behavior — same as the charconv branch's fallback.
  if (t[0] == '+') return NAN;
  size_t d = (t[0] == '-') ? 1 : 0;
  if (t.size() > d + 1 && t[d] == '0' && (t[d + 1] == 'x' || t[d + 1] == 'X'))
    return NAN;
  std::string z(t);
  char* endp = nullptr;
  double v = strtod_l(z.c_str(), &endp, c_locale());
  if (endp != z.c_str() + z.size()) return NAN;
  return v;
#endif
}

// bin(v) = #{cuts c : v > c} (quantiles.bin_values; NaN > c is false).
inline int bin_of(double v, const double* cuts, int n) {
  int b = 0;
  for (int i = 0; i < n; i++) b += v > cuts[i];
  return b;
}

inline void append_int(std::string& s, int v) {
  char buf[16];
  auto [p, ec] = std::to_chars(buf, buf + sizeof(buf), v);
  (void)ec;
  s.append(buf, p);
}

// Constant-memory file streaming: reads 4MB chunks, carries the partial
// trailing line between chunks, and hands newline-complete buffers to
// `on_buffer(ptr, len)`.  Returns false (setting `err`) on open/read
// failure — fread reports EOF and I/O errors identically, so ferror is
// the only truncation signal.
template <class F>
inline bool stream_file(const char* path, std::string& err, F&& on_buffer) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    err = std::string("cannot open ") + path;
    return false;
  }
  std::string pending;
  std::vector<char> buf(1 << 22);
  size_t got;
  while ((got = fread(buf.data(), 1, buf.size(), f)) > 0) {
    size_t last_nl = got;
    while (last_nl > 0 && buf[last_nl - 1] != '\n') last_nl--;
    if (last_nl == 0) {
      pending.append(buf.data(), got);
      continue;
    }
    size_t start = 0;
    if (!pending.empty()) {
      const char* nl = (const char*)memchr(buf.data(), '\n', got);
      pending.append(buf.data(), (size_t)(nl - buf.data() + 1));
      on_buffer(pending.data(), (int64_t)pending.size());
      pending.clear();
      start = (size_t)(nl - buf.data() + 1);
    }
    on_buffer(buf.data() + start, (int64_t)(last_nl - start));
    if (last_nl < got) pending.assign(buf.data() + last_nl, got - last_nl);
  }
  if (ferror(f)) {
    err = std::string("read error on ") + path;
    fclose(f);
    return false;
  }
  fclose(f);
  if (!pending.empty()) on_buffer(pending.data(), (int64_t)pending.size());
  return true;
}

inline int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

inline int64_t file_size_of(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  if (fseeko(f, 0, SEEK_END) != 0) {
    fclose(f);
    return -1;
  }
  int64_t n = (int64_t)ftello(f);
  fclose(f);
  return n;
}

// First line of `path`, exactly as sequential ingest would see it: the
// bytes before the first '\n', with ONE trailing '\r' stripped.
// *end_off is the offset just past that '\n' (where data begins for a
// skip-header shard plan).  Returns false with err EMPTY when the file
// holds no '\n' at all (single-line/empty file — callers take the
// sequential path), false with err SET on I/O failure.
inline bool read_first_line(const char* path, std::string& out,
                            int64_t* end_off, std::string& err) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    err = std::string("cannot open ") + path;
    return false;
  }
  out.clear();
  int64_t pos = 0;
  std::vector<char> buf(1 << 20);
  size_t got;
  bool found = false;
  while (!found && (got = fread(buf.data(), 1, buf.size(), f)) > 0) {
    const char* nl = (const char*)memchr(buf.data(), '\n', got);
    if (nl) {
      out.append(buf.data(), (size_t)(nl - buf.data()));
      pos += (int64_t)(nl - buf.data()) + 1;
      found = true;
    } else {
      out.append(buf.data(), got);
      pos += (int64_t)got;
    }
  }
  if (ferror(f)) {
    err = std::string("read error on ") + path;
    fclose(f);
    return false;
  }
  fclose(f);
  if (!found) return false;
  if (end_off) *end_off = pos;
  if (!out.empty() && out.back() == '\r') out.pop_back();
  return true;
}

// Line-aligned shard plan for parallel ingest: `workers`+1 offsets
// bounding [data_start, size) into [b[i], b[i+1]) ranges, each range
// beginning at a line start (the byte after a '\n'; b[0] = data_start).
// Adjacent ranges can collapse to empty when one line spans several
// raw splits — concatenated in order the ranges always cover the input
// exactly once, so a CRLF pair or a multi-megabyte line is never torn
// across workers.  Empty vector with err set on I/O failure.
inline std::vector<int64_t> shard_bounds(const char* path,
                                         int64_t data_start, int64_t size,
                                         int workers, std::string& err) {
  std::vector<int64_t> b{data_start};
  FILE* f = fopen(path, "rb");
  if (!f) {
    err = std::string("cannot open ") + path;
    return {};
  }
  std::vector<char> buf(1 << 20);
  int64_t span = size - data_start;
  for (int i = 1; i < workers; i++) {
    int64_t cand = data_start + span * i / workers;
    if (cand < b.back()) cand = b.back();
    int64_t pos = cand, bound = size;
    if (fseeko(f, pos, SEEK_SET) != 0) {
      err = std::string("cannot seek in ") + path;
      fclose(f);
      return {};
    }
    while (pos < size) {
      size_t want = (size_t)std::min<int64_t>((int64_t)buf.size(),
                                              size - pos);
      size_t got = fread(buf.data(), 1, want, f);
      if (got == 0) break;
      const char* nl = (const char*)memchr(buf.data(), '\n', got);
      if (nl) {
        bound = pos + (int64_t)(nl - buf.data()) + 1;
        break;
      }
      pos += (int64_t)got;
    }
    if (ferror(f)) {
      err = std::string("read error on ") + path;
      fclose(f);
      return {};
    }
    b.push_back(bound);
  }
  fclose(f);
  b.push_back(size);
  return b;
}

// stream_file restricted to the byte range [begin, end): same chunked
// reads and partial-line carry, so a worker sees newline-complete
// buffers for exactly its shard.  The trailing unterminated line is
// flushed at range end — only the LAST shard of a file can hold one
// (every other range ends right after a '\n' by shard_bounds
// construction).
template <class F>
inline bool stream_file_range(const char* path, int64_t begin, int64_t end,
                              std::string& err, F&& on_buffer) {
  if (begin >= end) return true;
  FILE* f = fopen(path, "rb");
  if (!f) {
    err = std::string("cannot open ") + path;
    return false;
  }
  if (fseeko(f, begin, SEEK_SET) != 0) {
    err = std::string("cannot seek in ") + path;
    fclose(f);
    return false;
  }
  std::string pending;
  std::vector<char> buf(1 << 22);
  int64_t remaining = end - begin;
  size_t got;
  while (remaining > 0 &&
         (got = fread(buf.data(), 1,
                      (size_t)std::min<int64_t>((int64_t)buf.size(),
                                                remaining),
                      f)) > 0) {
    remaining -= (int64_t)got;
    size_t last_nl = got;
    while (last_nl > 0 && buf[last_nl - 1] != '\n') last_nl--;
    if (last_nl == 0) {
      pending.append(buf.data(), got);
      continue;
    }
    size_t start = 0;
    if (!pending.empty()) {
      const char* nl = (const char*)memchr(buf.data(), '\n', got);
      pending.append(buf.data(), (size_t)(nl - buf.data() + 1));
      on_buffer(pending.data(), (int64_t)pending.size());
      pending.clear();
      start = (size_t)(nl - buf.data() + 1);
    }
    on_buffer(buf.data() + start, (int64_t)(last_nl - start));
    if (last_nl < got) pending.assign(buf.data() + last_nl, got - last_nl);
  }
  if (ferror(f)) {
    err = std::string("read error on ") + path;
    fclose(f);
    return false;
  }
  fclose(f);
  if (!pending.empty()) on_buffer(pending.data(), (int64_t)pending.size());
  return true;
}

// str(float): CPython repr — shortest round-trip digits, fixed notation
// for decimal exponents in [-4, 16), scientific ("1e+16", "1e-05",
// two-plus exponent digits, explicit sign) outside, ".0" suffix on
// integral fixed values.  std::to_chars' shortest *general* format
// picks scientific wherever it is shorter (1e15 -> "1e+15",
// 0.0001 -> "1e-04"), which diverges from Python inside that window —
// so take shortest-scientific digits and re-format per Python's rule.
inline std::string jvm_double(double v) {
  char buf[64];
  if (!std::isfinite(v)) {
    // "inf" / "-inf" / "nan" == str(float); NaN sign/payload dropped
    // like to_chars (and Python).
    if (std::isnan(v)) return "nan";
    return v < 0 ? "-inf" : "inf";
  }
#if ONI_FP_CHARCONV
  auto [p, ec] = std::to_chars(buf, buf + sizeof(buf), v,
                               std::chars_format::scientific);
  (void)ec;
  std::string_view s(buf, (size_t)(p - buf));
#else
  // Shortest-round-trip scientific digits without float to_chars:
  // correctly-rounded %.*e (glibc) at increasing precision until the
  // value round-trips.  Minimal precision implies a nonzero last digit
  // (a trailing zero would round-trip one digit shorter), so the digit
  // string below matches to_chars' shortest output; the C locale is
  // pinned per-thread so '.' is the radix regardless of host locale.
  locale_t old_loc = uselocale(c_locale());
  int len = 0;
  for (int prec = 0; prec <= 17; prec++) {
    len = snprintf(buf, sizeof(buf), "%.*e", prec, v);
    if (strtod(buf, nullptr) == v) break;
  }
  uselocale(old_loc);
  std::string_view s(buf, (size_t)len);
#endif
  bool neg = s.front() == '-';
  if (neg) s.remove_prefix(1);
  size_t epos = s.find('e');
  std::string digits(1, s[0]);
  if (epos > 1) digits.append(s.substr(2, epos - 2));  // skip the '.'
  int exp10 = 0;
  std::from_chars(s.data() + epos + 1 + (s[epos + 1] == '+'),
                  s.data() + s.size(), exp10);
  std::string out;
  if (neg) out += '-';
  if (exp10 >= -4 && exp10 < 16) {
    if (exp10 < 0) {
      out += "0.";
      out.append((size_t)(-exp10 - 1), '0');
      out += digits;
    } else if ((size_t)exp10 + 1 >= digits.size()) {
      out += digits;
      out.append((size_t)exp10 + 1 - digits.size(), '0');
      out += ".0";
    } else {
      out.append(digits, 0, (size_t)exp10 + 1);
      out += '.';
      out.append(digits, (size_t)exp10 + 1, std::string::npos);
    }
  } else {
    out += digits[0];
    if (digits.size() > 1) {
      out += '.';
      out.append(digits, 1, std::string::npos);
    }
    out += 'e';
    out += exp10 < 0 ? '-' : '+';
    int ae = exp10 < 0 ? -exp10 : exp10;
    if (ae < 10) out += '0';
    append_int(out, ae);
  }
  return out;
}

}  // namespace oni
