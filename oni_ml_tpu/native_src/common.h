// Shared helpers for the native featurizers/ingest (single header so a
// parity-critical fix can never land in one translation unit and miss
// the other — that drift already happened once during review).
#pragma once

#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <locale.h>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace oni {

// String interner: stable ids in first-seen order, arena-backed views so
// hot-path lookups never allocate, plus a lazily-built (blob, offsets)
// export for the ctypes side.  Interning invalidates any prior export.
struct Interner {
  std::unordered_map<std::string_view, int32_t> ids;
  std::deque<std::string> arena;
  std::string blob;
  std::vector<int64_t> offsets;

  int32_t intern(std::string_view s) {
    auto it = ids.find(s);
    if (it != ids.end()) return it->second;
    blob.clear();
    offsets.clear();
    arena.emplace_back(s);
    int32_t id = (int32_t)ids.size();
    ids.emplace(std::string_view(arena.back()), id);
    return id;
  }

  void build_export() {
    if (!offsets.empty()) return;
    offsets.push_back(0);
    size_t total = 0;
    for (const auto& s : arena) total += s.size();
    blob.reserve(total);
    for (const auto& s : arena) {
      blob += s;
      offsets.push_back((int64_t)blob.size());
    }
  }
};

// Open-addressing uint64 -> int64 map (linear probing, power-of-two
// capacity, 0.5 max load).  The featurizers' hot loops do several map
// operations per row; std::unordered_map's node allocations and
// pointer-chasing made the flow pass-B aggregation the pipeline's
// hottest block (~1.2 us/row of ~1.8).  Keys must never equal EMPTY
// (~0ull) — the packed (id << 32 | id) keys used here cannot.
struct FlatMap64 {
  static constexpr uint64_t EMPTY = ~0ull;
  std::vector<uint64_t> keys;
  std::vector<int64_t> vals;
  size_t count = 0, mask = 0;

  explicit FlatMap64(size_t expected = 16) {
    size_t cap = 16;
    while (cap < expected * 2) cap <<= 1;
    keys.assign(cap, EMPTY);
    vals.resize(cap);
    mask = cap - 1;
  }

  static uint64_t mix(uint64_t x) {  // splitmix64 finalizer
    x += 0x9e3779b97f4a7c15ull;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
    return x ^ (x >> 31);
  }

  void grow() {
    std::vector<uint64_t> ok = std::move(keys);
    std::vector<int64_t> ov = std::move(vals);
    size_t cap = (mask + 1) * 2;
    keys.assign(cap, EMPTY);
    vals.resize(cap);
    mask = cap - 1;
    for (size_t i = 0; i < ok.size(); i++) {
      if (ok[i] == EMPTY) continue;
      size_t p = mix(ok[i]) & mask;
      while (keys[p] != EMPTY) p = (p + 1) & mask;
      keys[p] = ok[i];
      vals[p] = ov[i];
    }
  }

  // Returns the slot's value reference; *inserted reports whether the
  // key was new (value then undefined — caller must set it).
  int64_t& probe(uint64_t key, bool* inserted) {
    if (count * 2 >= mask + 1) grow();
    size_t p = mix(key) & mask;
    while (keys[p] != EMPTY && keys[p] != key) p = (p + 1) & mask;
    *inserted = keys[p] == EMPTY;
    if (*inserted) {
      keys[p] = key;
      count++;
    }
    return vals[p];
  }
};

// ASCII whitespace exactly (' ', '\t', '\n', '\v', '\f', '\r').  NOT
// std::isspace: that is LC_CTYPE-locale-dependent (e.g. 0xA0 counts as
// space under a Latin-1 locale), which would make featurization depend
// on the host environment.  CPython's float() additionally strips some
// unicode spaces (U+0085/U+00A0...) — a documented divergence
// (flow_featurize.cpp header), same class as underscored numerals.
inline bool ascii_space(char c) {
  return c == ' ' || (c >= '\t' && c <= '\r');
}

// Python float(): trimmed token, optional '+', decimal/exponent/inf/nan;
// out-of-range saturates to +-inf / +-0.0; anything else -> NaN.
// The saturation fallback pins LC_NUMERIC to "C" so a host process with
// a different locale can't change how the digits parse.
inline double to_double(std::string_view s) {
  size_t b = 0, e = s.size();
  while (b < e && ascii_space(s[b])) b++;
  while (e > b && ascii_space(s[e - 1])) e--;
  if (b == e) return NAN;
  std::string_view t = s.substr(b, e - b);
  if (t[0] == '+') t.remove_prefix(1);
  if (t.empty()) return NAN;
  double v;
  auto [p, ec] = std::from_chars(t.data(), t.data() + t.size(), v);
  if (ec == std::errc::result_out_of_range && p == t.data() + t.size()) {
    static locale_t c_loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
    std::string z(t);
    return strtod_l(z.c_str(), nullptr, c_loc);
  }
  if (ec != std::errc() || p != t.data() + t.size()) return NAN;
  return v;
}

// bin(v) = #{cuts c : v > c} (quantiles.bin_values; NaN > c is false).
inline int bin_of(double v, const double* cuts, int n) {
  int b = 0;
  for (int i = 0; i < n; i++) b += v > cuts[i];
  return b;
}

inline void append_int(std::string& s, int v) {
  char buf[16];
  auto [p, ec] = std::to_chars(buf, buf + sizeof(buf), v);
  (void)ec;
  s.append(buf, p);
}

// Constant-memory file streaming: reads 4MB chunks, carries the partial
// trailing line between chunks, and hands newline-complete buffers to
// `on_buffer(ptr, len)`.  Returns false (setting `err`) on open/read
// failure — fread reports EOF and I/O errors identically, so ferror is
// the only truncation signal.
template <class F>
inline bool stream_file(const char* path, std::string& err, F&& on_buffer) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    err = std::string("cannot open ") + path;
    return false;
  }
  std::string pending;
  std::vector<char> buf(1 << 22);
  size_t got;
  while ((got = fread(buf.data(), 1, buf.size(), f)) > 0) {
    size_t last_nl = got;
    while (last_nl > 0 && buf[last_nl - 1] != '\n') last_nl--;
    if (last_nl == 0) {
      pending.append(buf.data(), got);
      continue;
    }
    size_t start = 0;
    if (!pending.empty()) {
      const char* nl = (const char*)memchr(buf.data(), '\n', got);
      pending.append(buf.data(), (size_t)(nl - buf.data() + 1));
      on_buffer(pending.data(), (int64_t)pending.size());
      pending.clear();
      start = (size_t)(nl - buf.data() + 1);
    }
    on_buffer(buf.data() + start, (int64_t)(last_nl - start));
    if (last_nl < got) pending.assign(buf.data() + last_nl, got - last_nl);
  }
  if (ferror(f)) {
    err = std::string("read error on ") + path;
    fclose(f);
    return false;
  }
  fclose(f);
  if (!pending.empty()) on_buffer(pending.data(), (int64_t)pending.size());
  return true;
}

// str(float): CPython repr — shortest round-trip digits, fixed notation
// for decimal exponents in [-4, 16), scientific ("1e+16", "1e-05",
// two-plus exponent digits, explicit sign) outside, ".0" suffix on
// integral fixed values.  std::to_chars' shortest *general* format
// picks scientific wherever it is shorter (1e15 -> "1e+15",
// 0.0001 -> "1e-04"), which diverges from Python inside that window —
// so take shortest-scientific digits and re-format per Python's rule.
inline std::string jvm_double(double v) {
  char buf[64];
  if (!std::isfinite(v)) {
    auto [p, ec] = std::to_chars(buf, buf + sizeof(buf), v);
    (void)ec;
    return std::string(buf, p);  // "inf" / "-inf" / "nan" == str(float)
  }
  auto [p, ec] = std::to_chars(buf, buf + sizeof(buf), v,
                               std::chars_format::scientific);
  (void)ec;
  std::string_view s(buf, (size_t)(p - buf));
  bool neg = s.front() == '-';
  if (neg) s.remove_prefix(1);
  size_t epos = s.find('e');
  std::string digits(1, s[0]);
  if (epos > 1) digits.append(s.substr(2, epos - 2));  // skip the '.'
  int exp10 = 0;
  std::from_chars(s.data() + epos + 1 + (s[epos + 1] == '+'),
                  s.data() + s.size(), exp10);
  std::string out;
  if (neg) out += '-';
  if (exp10 >= -4 && exp10 < 16) {
    if (exp10 < 0) {
      out += "0.";
      out.append((size_t)(-exp10 - 1), '0');
      out += digits;
    } else if ((size_t)exp10 + 1 >= digits.size()) {
      out += digits;
      out.append((size_t)exp10 + 1 - digits.size(), '0');
      out += ".0";
    } else {
      out.append(digits, 0, (size_t)exp10 + 1);
      out += '.';
      out.append(digits, (size_t)exp10 + 1, std::string::npos);
    }
  } else {
    out += digits[0];
    if (digits.size() > 1) {
      out += '.';
      out.append(digits, 1, std::string::npos);
    }
    out += 'e';
    out += exp10 < 0 ? '-' : '+';
    int ae = exp10 < 0 ? -exp10 : exp10;
    if (ae < 10) out += '0';
    append_int(out, ae);
  }
  return out;
}

}  // namespace oni
