// Native corpus ingest — the C++ replacement for the reference's
// single-node Python corpus build (lda_pre.py:30-94, SURVEY.md §2.4),
// which is the pipeline's host-side scalability bottleneck: three
// sequential interpreter passes over doc_wc.dat with per-line dict
// lookups.  Here it is one buffered pass in C++ with first-seen-order id
// assignment (the reference's words.dat/doc.dat line-number contract) and
// CSR output ready for device batching.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).  Semantics
// match oni_ml_tpu/io/formats.read_word_counts + Corpus.from_word_counts
// exactly: lines are "ip,word,count" split from the RIGHT (rsplit ',', 2),
// empty lines skipped, tokens grouped per document in first-seen doc
// order, duplicate (doc, word) pairs kept as separate tokens.

#include "common.h"

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

using oni::Interner;

struct Ingest {
  Interner words;
  Interner docs;
  std::vector<std::vector<std::pair<int32_t, int32_t>>> doc_tokens;
  int64_t nnz = 0;
  std::string error;
};

// Parse one line [b, e) as "ip,word,count" (rsplit from the right).
// Returns false (and sets err) on malformed input.
bool parse_line(const char* b, const char* e, Ingest& st, int64_t lineno) {
  const char* last = static_cast<const char*>(memrchr(b, ',', e - b));
  if (last == nullptr) {
    st.error = "line " + std::to_string(lineno) + ": expected ip,word,count";
    return false;
  }
  const char* mid = static_cast<const char*>(memrchr(b, ',', last - b));
  if (mid == nullptr) {
    st.error = "line " + std::to_string(lineno) + ": expected ip,word,count";
    return false;
  }
  // count: strict non-negative integer like Python int()
  int64_t count = 0;
  const char* p = last + 1;
  if (p == e) {
    st.error = "line " + std::to_string(lineno) + ": empty count";
    return false;
  }
  bool neg = false;
  if (*p == '-' || *p == '+') { neg = (*p == '-'); ++p; }
  if (p == e) {
    st.error = "line " + std::to_string(lineno) + ": bad count";
    return false;
  }
  for (; p != e; ++p) {
    if (*p < '0' || *p > '9') {
      st.error = "line " + std::to_string(lineno) + ": bad count";
      return false;
    }
    count = count * 10 + (*p - '0');
    if (count > INT32_MAX) {  // counts land in an int32 CSR array
      st.error = "line " + std::to_string(lineno) + ": count out of range";
      return false;
    }
  }
  if (neg) count = -count;

  int32_t w = st.words.intern(std::string_view(mid + 1, last - mid - 1));
  int32_t d = st.docs.intern(std::string_view(b, mid - b));
  // A fresh doc id always equals the previous doc count (first-seen ids).
  if ((size_t)d == st.doc_tokens.size()) st.doc_tokens.emplace_back();
  st.doc_tokens[d].emplace_back(w, (int32_t)count);
  ++st.nnz;
  return true;
}

}  // namespace

extern "C" {

void* oni_ingest_create() { return new Ingest(); }

void oni_ingest_destroy(void* h) { delete static_cast<Ingest*>(h); }

// Ingest one word_counts file; callable repeatedly (the reference `cat`s
// part-* files together, ml_ops.sh:61 — here concatenation is implicit).
// Returns number of triples ingested, or -1 on error (see oni_last_error).
int64_t oni_ingest_file(void* h, const char* path) {
  Ingest& st = *static_cast<Ingest*>(h);
  FILE* f = fopen(path, "rb");
  if (!f) {
    st.error = std::string("cannot open ") + path;
    return -1;
  }
  int64_t ingested = 0, lineno = 0;
  std::string carry;
  std::vector<char> buf(1 << 20);
  size_t n;
  bool skip_lf = false;  // pending LF of a CRLF split across chunks
  while ((n = fread(buf.data(), 1, buf.size(), f)) > 0) {
    const char* p = buf.data();
    const char* end = p + n;
    if (skip_lf) {
      if (*p == '\n') ++p;
      skip_lf = false;
    }
    // Universal newlines like Python text mode: LF, CRLF, or lone CR.
    // The CR probe is cached per chunk — recomputing it per line would
    // rescan the whole chunk for every line of a CR-free file.
    const char* cr = static_cast<const char*>(memchr(p, '\r', end - p));
    while (p < end) {
      const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
      if (cr != nullptr && cr < p)
        cr = static_cast<const char*>(memchr(p, '\r', end - p));
      const char* term = (nl && cr) ? (nl < cr ? nl : cr) : (nl ? nl : cr);
      if (term == nullptr) {
        carry.append(p, end - p);
        break;
      }
      ++lineno;
      const char *b, *e;
      if (!carry.empty()) {
        carry.append(p, term - p);
        b = carry.data();
        e = b + carry.size();
      } else {
        b = p;
        e = term;
      }
      if (e > b) {  // skip empty lines like the Python reader
        if (!parse_line(b, e, st, lineno)) {
          fclose(f);
          return -1;
        }
        ++ingested;
      }
      carry.clear();
      p = term + 1;
      if (*term == '\r') {
        if (p < end) {
          if (*p == '\n') ++p;
        } else {
          skip_lf = true;
        }
      }
    }
  }
  bool read_err = ferror(f) != 0;
  fclose(f);
  if (read_err) {
    st.error = std::string("read error on ") + path;
    return -1;
  }
  if (!carry.empty()) {  // final line without trailing newline
    ++lineno;
    if (!parse_line(carry.data(), carry.data() + carry.size(), st, lineno))
      return -1;
    ++ingested;
  }
  return ingested;
}

const char* oni_last_error(void* h) {
  return static_cast<Ingest*>(h)->error.c_str();
}

int64_t oni_num_docs(void* h) {
  return (int64_t)static_cast<Ingest*>(h)->docs.arena.size();
}

int64_t oni_num_terms(void* h) {
  return (int64_t)static_cast<Ingest*>(h)->words.arena.size();
}

int64_t oni_nnz(void* h) { return static_cast<Ingest*>(h)->nnz; }

// Fill caller-allocated CSR arrays: doc_ptr [D+1] i64, word_idx [NNZ] i32,
// counts [NNZ] i32 — token order per doc = file first-seen order.
void oni_fill_csr(void* h, int64_t* doc_ptr, int32_t* word_idx,
                  int32_t* counts) {
  Ingest& st = *static_cast<Ingest*>(h);
  int64_t pos = 0;
  doc_ptr[0] = 0;
  for (size_t d = 0; d < st.doc_tokens.size(); ++d) {
    for (auto& [w, c] : st.doc_tokens[d]) {
      word_idx[pos] = w;
      counts[pos] = c;
      ++pos;
    }
    doc_ptr[d + 1] = pos;
  }
}

// Names are returned '\n'-joined (neither ips nor words may contain '\n'
// — they came from '\n'-terminated lines).  which: 0 = doc names, 1 = vocab.
int64_t oni_names_bytes(void* h, int32_t which) {
  Ingest& st = *static_cast<Ingest*>(h);
  auto& v = which == 0 ? st.docs.arena : st.words.arena;
  int64_t total = 0;
  for (auto& s : v) total += (int64_t)s.size() + 1;
  return total;
}

void oni_fill_names(void* h, int32_t which, char* buf) {
  Ingest& st = *static_cast<Ingest*>(h);
  auto& v = which == 0 ? st.docs.arena : st.words.arena;
  for (auto& s : v) {
    memcpy(buf, s.data(), s.size());
    buf += s.size();
    *buf++ = '\n';
  }
}

}  // extern "C"
