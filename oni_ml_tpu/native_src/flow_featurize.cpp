// Native netflow featurizer — the C++ fast path for the flow "pre"
// stage (flow_pre_lda.scala featurization, reimplemented in
// oni_ml_tpu/features/flow.py).  The Python path runs ~140k rows/s;
// a 30-day corpus (BASELINE config 3) needs millions of rows/s, which
// is exactly the scale the reference threw a Spark/YARN cluster at
// (SURVEY.md §2.2).  This does the same work in one process: parse +
// filter, numeric extraction, binning, word construction, and per-IP
// word-count aggregation.
//
// Split of responsibilities with Python (oni_ml_tpu/features/native_flow.py):
//   pass A (ingest_*): line filtering (removeHeader + 27-field check),
//     numeric columns (fractional time, ibyt, ipkt, the swapped
//     port columns), IP interning, raw-line retention.
//   cuts: Python computes ECDF cuts from pass-A arrays with the SAME
//     quantiles.ecdf_cuts used by the Python path — one semantics, one
//     implementation (SURVEY §7 hard part (b)).
//   pass B (finish): bin by cuts, adjust_port word construction with
//     JVM-double formatting, word interning, first-seen-order word
//     counts (src docs then dest docs, flow_pre_lda.scala:366-373).
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).  All
// pointers returned by getters stay valid until ffz_destroy.
//
// Known deliberate divergences from Python float():  underscored
// numerals ("1_0") and unusual unicode whitespace are rejected (NaN) —
// neither occurs in netflow CSVs.

#include "common.h"

#include <charconv>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <memory>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using oni::jvm_double;

constexpr int NCOLS = 27;
// Column indices (flow_pre_lda.scala:46-72); 10/11 keep the reference's
// swapped dport/sport naming (oni_ml_tpu/features/flow.py docstring).
constexpr int C_HOUR = 4, C_MIN = 5, C_SEC = 6, C_SIP = 8, C_DIP = 9;
constexpr int C_10 = 10, C_11 = 11, C_IPKT = 16, C_IBYT = 17;

using oni::Interner;
using oni::to_double;
using oni::bin_of;

struct Ffz {
  bool skip_header;
  bool have_header = false;
  std::string header;

  std::string lines;                   // stripped kept rows, concatenated
  FILE* spill = nullptr;               // when set, rows stream here
  int64_t spill_len = 0;               // instead of the in-RAM blob
  bool spill_err = false;              // short write (ENOSPC etc.)
  std::vector<int64_t> line_off{0};
  std::vector<double> time_, ibyt_, ipkt_, c10_, c11_;
  Interner ips;
  std::vector<int32_t> sip_id, dip_id;
  int64_t num_raw = -1;

  // finish() outputs
  std::vector<int32_t> tbin, bbin, pbin;
  Interner words;
  std::vector<int32_t> wp_id, sw_id, dw_id;
  std::vector<int32_t> wc_ip, wc_word;
  std::vector<int64_t> wc_cnt;

  // Wall spent in the DETERMINISTIC merges of the parallel paths
  // (pass-A shard-table remap + pass-B word/count merge) — the
  // sequential-overhead term the runner reports as merge_wall_s.
  int64_t merge_ns = 0;

  std::string error;

  void add_line(std::string_view raw) {
    // Mirror the Python path: lines are compared for removeHeader
    // before strip, then stripped and split.
    if (skip_header) {
      if (!have_header) {
        header.assign(raw);
        have_header = true;
        return;
      }
      if (raw == header) return;
    }
    size_t b = 0, e = raw.size();
    while (b < e && oni::ascii_space(raw[b])) b++;
    while (e > b && oni::ascii_space(raw[e - 1])) e--;
    std::string_view line = raw.substr(b, e - b);

    std::string_view f[NCOLS];
    int nf = 0;
    size_t start = 0;
    for (size_t i = 0; i <= line.size(); i++) {
      if (i == line.size() || line[i] == ',') {
        if (nf < NCOLS) f[nf] = line.substr(start, i - start);
        nf++;
        start = i + 1;
      }
    }
    if (nf != NCOLS) return;

    if (spill) {
      // Raw rows are only re-read at emit time (for flagged events);
      // streaming them to the spill file keeps RSS bounded by the
      // numeric arrays however many days are ingested.  A short write
      // (ENOSPC mid-way through a 30-day ingest) must surface as an
      // error, not as offsets pointing past the end of the file.
      if (fwrite(line.data(), 1, line.size(), spill) != line.size()) {
        spill_err = true;
        error = "short write to raw-lines spill file (disk full?)";
      }
      spill_len += (int64_t)line.size();
      line_off.push_back(spill_len);
    } else {
      lines.append(line.data(), line.size());
      line_off.push_back((int64_t)lines.size());
    }
    double h = to_double(f[C_HOUR]), m = to_double(f[C_MIN]),
           s = to_double(f[C_SEC]);
    time_.push_back(h + m / 60.0 + s / 3600.0);
    ibyt_.push_back(to_double(f[C_IBYT]));
    ipkt_.push_back(to_double(f[C_IPKT]));
    c10_.push_back(to_double(f[C_10]));
    c11_.push_back(to_double(f[C_11]));
    sip_id.push_back(ips.intern(f[C_SIP]));
    dip_id.push_back(ips.intern(f[C_DIP]));
  }

  void ingest_buffer(const char* buf, int64_t len) {
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
      const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
      const char* stop = nl ? nl : end;
      // Drop one trailing '\r' (text files from Windows exports).
      const char* s2 = stop;
      if (s2 > p && s2[-1] == '\r') s2--;
      add_line(std::string_view(p, (size_t)(s2 - p)));
      p = nl ? nl + 1 : end;
    }
  }
};

// Pass-B state over one contiguous event range: binning, adjust_port
// word construction, and first-seen (doc, word) aggregation.  The
// sequential path runs ONE PassB over all events with `words` bound to
// h->words; the parallel path runs one per shard with a shard-local
// interner, then merges deterministically in shard order — both walk
// each event through exactly this code, so the per-event logic cannot
// drift between the two paths.
struct PassB {
  Ffz* h;
  Interner& words;
  const double* tc;
  const double* bc;
  const double* pc;
  int ntc, nbc, npc;
  // First-seen-order (doc, word) counts; src map emitted before dest
  // (flow_pre_lda.scala:366-373 union order).  FlatMap64 (common.h):
  // unordered_map's node churn made these probes the hottest block of
  // the whole pipeline.
  oni::FlatMap64 src_pos, dst_pos;
  std::vector<int32_t> s_ip, s_w, d_ip, d_w;  // word ids are in `words`
  std::vector<int64_t> s_c, d_c;
  // Words are a function of (word_port, time_bin, ibyt_bin, ipkt_bin):
  // the unique combinations number in the thousands while rows number
  // in the millions, so cache (wp_id, bins) -> (base, prefixed) word
  // ids and skip the string building on the hot path.  Port doubles
  // are keyed by bit pattern (our NaNs are the single NAN constant
  // from to_double).
  oni::FlatMap64 wp_cache;    // port bits -> wp_id
  oni::FlatMap64 word_cache;  // wp_id+bins -> (base, prefixed) packed
  std::string word;           // scratch

  PassB(Ffz* h_, Interner& w, size_t expected)
      : h(h_), words(w), src_pos(expected / 2), dst_pos(expected / 2) {}

  void event(size_t i) {
    int tb = bin_of(h->time_[i], tc, ntc);
    int bb = bin_of(h->ibyt_[i], bc, nbc);
    int pb = bin_of(h->ipkt_[i], pc, npc);
    h->tbin[i] = tb;
    h->bbin[i] = bb;
    h->pbin[i] = pb;

    // adjust_port (flow_pre_lda.scala:317-359; see features/flow.py for
    // the case table).  dport := col10, sport := col11 (reference swap).
    double dport = h->c10_[i], sport = h->c11_[i];
    double lo = (sport < dport) ? sport : dport;  // std::min semantics
    double hi = (dport < sport) ? sport : dport;  // std::max semantics
    int p_case;
    double word_port;
    if ((dport <= 1024 || sport <= 1024) && (dport > 1024 || sport > 1024) &&
        lo != 0) {
      p_case = 2;
      word_port = lo;
    } else if (dport > 1024 && sport > 1024) {
      p_case = 3;
      word_port = 333333.0;
    } else if (dport == 0 && sport != 0) {
      p_case = 4;
      word_port = sport;
    } else if (sport == 0 && dport != 0) {
      p_case = 4;
      word_port = dport;
    } else {
      p_case = 1;
      word_port = (lo == 0) ? hi : 111111.0;
    }

    uint64_t wp_bits;
    memcpy(&wp_bits, &word_port, 8);
    int32_t wp_id;
    if (wp_bits == oni::FlatMap64::EMPTY) {
      // A hostile "-nan(0xf...f)" field bit-patterns to the map's empty
      // sentinel; skip the cache (the interner still dedupes).
      wp_id = words.intern(jvm_double(word_port));
    } else {
      bool fresh;
      int64_t& slot = wp_cache.probe(wp_bits, &fresh);
      if (fresh) slot = words.intern(jvm_double(word_port));
      wp_id = (int32_t)slot;
    }

    bool src_prefixed =
        (p_case == 2 && sport < dport) || (p_case == 4 && dport == 0);
    bool dst_prefixed =
        (p_case == 2 && dport < sport) || (p_case == 4 && sport == 0);

    // Bins are bounded by the cut counts; the finish entry points
    // reject cut lists that would overflow the 12-bit fields.  A wp_id
    // past 28 bits (>268M distinct port strings) skips the cache
    // instead of aliasing.
    uint64_t wkey = ((uint64_t)(uint32_t)wp_id << 36) |
                    ((uint64_t)tb << 24) | ((uint64_t)bb << 12) | (uint64_t)pb;
    bool cacheable = (uint32_t)wp_id < (1u << 28) &&
                     wkey != oni::FlatMap64::EMPTY;
    bool fresh = true;
    int64_t* wslot = nullptr;
    if (cacheable) wslot = &word_cache.probe(wkey, &fresh);
    struct WordIds {
      int32_t base, prefixed;
    } wi;
    if (!fresh) {
      wi.base = (int32_t)(uint32_t)(*wslot >> 32);
      wi.prefixed = (int32_t)(uint32_t)*wslot;
    } else {
      word.clear();
      word += words.arena[(size_t)wp_id];
      word += '_';
      word += jvm_double((double)tb);
      word += '_';
      word += jvm_double((double)bb);
      word += '_';
      word += jvm_double((double)pb);
      wi.base = words.intern(word);
      wi.prefixed = words.intern("-1_" + word);
      if (wslot)
        *wslot = ((int64_t)(uint32_t)wi.base << 32) | (uint32_t)wi.prefixed;
    }
    int32_t src_wid = src_prefixed ? wi.prefixed : wi.base;
    int32_t dst_wid = dst_prefixed ? wi.prefixed : wi.base;
    h->wp_id[i] = wp_id;
    h->sw_id[i] = src_wid;
    h->dw_id[i] = dst_wid;

    uint64_t ks = ((uint64_t)(uint32_t)h->sip_id[i] << 32) |
                  (uint32_t)src_wid;
    int64_t& sslot = src_pos.probe(ks, &fresh);
    if (fresh) {
      sslot = (int64_t)s_c.size();
      s_ip.push_back(h->sip_id[i]);
      s_w.push_back(src_wid);
      s_c.push_back(1);
    } else {
      s_c[(size_t)sslot]++;
    }
    uint64_t kd = ((uint64_t)(uint32_t)h->dip_id[i] << 32) |
                  (uint32_t)dst_wid;
    int64_t& dslot = dst_pos.probe(kd, &fresh);
    if (fresh) {
      dslot = (int64_t)d_c.size();
      d_ip.push_back(h->dip_id[i]);
      d_w.push_back(dst_wid);
      d_c.push_back(1);
    } else {
      d_c[(size_t)dslot]++;
    }
  }
};

using oni::now_ns;

}  // namespace

extern "C" {

void* ffz_create(int skip_header) {
  Ffz* h = new Ffz();
  h->skip_header = skip_header != 0;
  return h;
}
void ffz_destroy(void* hv) {
  Ffz* h = (Ffz*)hv;
  if (h->spill) fclose(h->spill);
  delete h;
}

// Route kept raw rows to `path` instead of RAM.  Must be called once,
// before any ingest — line offsets are absolute positions in ONE
// store, so retargeting mid-run (or after in-RAM rows exist) would
// make them read past EOF / wrong bytes at emit; -1 with ffz_error set
// on misuse or when the file can't open.  ffz_spill_flush makes the
// bytes visible to a reader (mmap) — the handle stays open so later
// ingests (feedback rows) keep appending.
int ffz_set_spill(void* hv, const char* path) {
  Ffz* h = (Ffz*)hv;
  if (!h->time_.empty() || h->spill) {
    h->error = "ffz_set_spill must be called once, before any ingest";
    return -1;
  }
  h->spill = fopen(path, "wb");
  if (!h->spill) {
    h->error = std::string("cannot open spill file ") + path;
    return -1;
  }
  return 0;
}

// Returns the spilled byte count, or -1 when any write/flush failed
// (ffz_error describes it) — callers must not mmap a short file.
int64_t ffz_spill_flush(void* hv) {
  Ffz* h = (Ffz*)hv;
  if (h->spill) {
    if (fflush(h->spill) != 0 || ferror(h->spill)) {
      h->spill_err = true;
      if (h->error.empty())
        h->error = "flush of raw-lines spill file failed (disk full?)";
    }
  }
  return h->spill_err ? -1 : h->spill_len;
}
const char* ffz_error(void* h) { return ((Ffz*)h)->error.c_str(); }

int64_t ffz_ingest_file(void* hv, const char* path) {
  Ffz* h = (Ffz*)hv;
  bool ok = oni::stream_file(path, h->error, [h](const char* p, int64_t n) {
    h->ingest_buffer(p, n);
  });
  return (ok && !h->spill_err) ? (int64_t)h->time_.size() : -1;
}

int64_t ffz_ingest_buffer(void* hv, const char* buf, int64_t len) {
  Ffz* h = (Ffz*)hv;
  h->ingest_buffer(buf, len);
  return h->spill_err ? -1 : (int64_t)h->time_.size();
}

void ffz_mark_raw(void* hv) {
  Ffz* h = (Ffz*)hv;
  h->num_raw = (int64_t)h->time_.size();
}
int64_t ffz_num_raw(void* hv) {
  Ffz* h = (Ffz*)hv;
  return h->num_raw >= 0 ? h->num_raw : (int64_t)h->time_.size();
}
int64_t ffz_num_events(void* hv) { return (int64_t)((Ffz*)hv)->time_.size(); }

const double* ffz_num_time(void* h) { return ((Ffz*)h)->time_.data(); }
const double* ffz_ibyt(void* h) { return ((Ffz*)h)->ibyt_.data(); }
const double* ffz_ipkt(void* h) { return ((Ffz*)h)->ipkt_.data(); }

// Shard the file into line-aligned byte ranges and run pass A over
// them on `workers` std::threads, each into its own shard-local Ffz
// (own interner, own arrays, rows buffered in RAM), then merge in
// shard order: shard-local ip ids are re-interned into the parent in
// local first-seen order, which reproduces the SEQUENTIAL first-seen
// order exactly — every merged array, table, and downstream artifact
// is byte-identical to ffz_ingest_file's.  The header contract is
// preserved by pre-reading the first line of the first file into the
// parent (workers then drop equal lines, including shard 0's copy).
// With a spill file active, kept rows buffer per shard and append to
// the spill at merge time — peak RSS grows by roughly ONE file's kept
// bytes (freed shard-by-shard), not the whole multi-file corpus.
int64_t ffz_ingest_file_parallel(void* hv, const char* path, int workers) {
  Ffz* h = (Ffz*)hv;
  if (workers <= 1) return ffz_ingest_file(hv, path);
  int64_t size = oni::file_size_of(path);
  if (size < 0) {
    h->error = std::string("cannot open ") + path;
    return -1;
  }
  if (h->skip_header && !h->have_header) {
    std::string hdr, err;
    if (!oni::read_first_line(path, hdr, nullptr, err)) {
      if (!err.empty()) {
        h->error = err;
        return -1;
      }
      // No '\n' anywhere: the whole file is one line — sequential
      // semantics (it becomes the header) with none of the threading.
      return ffz_ingest_file(hv, path);
    }
    h->header = hdr;
    h->have_header = true;
  }
  std::string err;
  std::vector<int64_t> bounds =
      oni::shard_bounds(path, 0, size, workers, err);
  if (bounds.empty()) {
    h->error = err;
    return -1;
  }
  std::vector<std::unique_ptr<Ffz>> shards((size_t)workers);
  std::vector<int> ok((size_t)workers, 1);
  std::vector<std::thread> threads;
  for (int k = 0; k < workers; k++) {
    shards[(size_t)k] = std::make_unique<Ffz>();
    Ffz* w = shards[(size_t)k].get();
    w->skip_header = h->skip_header;
    w->have_header = h->have_header;
    w->header = h->header;
    int64_t lo = bounds[(size_t)k], hi = bounds[(size_t)k + 1];
    threads.emplace_back([w, path, lo, hi, &ok, k] {
      ok[(size_t)k] = oni::stream_file_range(
                          path, lo, hi, w->error,
                          [w](const char* p, int64_t n) {
                            w->ingest_buffer(p, n);
                          })
                          ? 1
                          : 0;
    });
  }
  for (auto& t : threads) t.join();
  for (int k = 0; k < workers; k++) {
    if (!ok[(size_t)k]) {
      h->error = shards[(size_t)k]->error;
      return -1;
    }
  }

  int64_t t0 = now_ns();
  {
    size_t tot_ev = 0, tot_bytes = 0;
    for (int k = 0; k < workers; k++) {
      tot_ev += shards[(size_t)k]->time_.size();
      tot_bytes += shards[(size_t)k]->lines.size();
    }
    h->time_.reserve(h->time_.size() + tot_ev);
    h->ibyt_.reserve(h->ibyt_.size() + tot_ev);
    h->ipkt_.reserve(h->ipkt_.size() + tot_ev);
    h->c10_.reserve(h->c10_.size() + tot_ev);
    h->c11_.reserve(h->c11_.size() + tot_ev);
    h->sip_id.reserve(h->sip_id.size() + tot_ev);
    h->dip_id.reserve(h->dip_id.size() + tot_ev);
    h->line_off.reserve(h->line_off.size() + tot_ev);
    if (!h->spill) h->lines.reserve(h->lines.size() + tot_bytes);
  }
  for (int k = 0; k < workers; k++) {
    Ffz* w = shards[(size_t)k].get();
    std::vector<int32_t> ipmap(w->ips.arena.size());
    for (size_t j = 0; j < w->ips.arena.size(); j++)
      ipmap[j] = h->ips.intern(w->ips.arena[j]);
    size_t wn = w->time_.size();
    h->time_.insert(h->time_.end(), w->time_.begin(), w->time_.end());
    h->ibyt_.insert(h->ibyt_.end(), w->ibyt_.begin(), w->ibyt_.end());
    h->ipkt_.insert(h->ipkt_.end(), w->ipkt_.begin(), w->ipkt_.end());
    h->c10_.insert(h->c10_.end(), w->c10_.begin(), w->c10_.end());
    h->c11_.insert(h->c11_.end(), w->c11_.begin(), w->c11_.end());
    h->sip_id.reserve(h->sip_id.size() + wn);
    h->dip_id.reserve(h->dip_id.size() + wn);
    for (size_t i = 0; i < wn; i++) {
      h->sip_id.push_back(ipmap[(size_t)w->sip_id[i]]);
      h->dip_id.push_back(ipmap[(size_t)w->dip_id[i]]);
    }
    if (h->spill) {
      if (!w->lines.empty() &&
          fwrite(w->lines.data(), 1, w->lines.size(), h->spill) !=
              w->lines.size()) {
        h->spill_err = true;
        h->error = "short write to raw-lines spill file (disk full?)";
      }
      for (size_t j = 1; j < w->line_off.size(); j++)
        h->line_off.push_back(h->spill_len + w->line_off[j]);
      h->spill_len += (int64_t)w->lines.size();
    } else {
      int64_t base = (int64_t)h->lines.size();
      h->lines += w->lines;
      for (size_t j = 1; j < w->line_off.size(); j++)
        h->line_off.push_back(base + w->line_off[j]);
    }
    shards[(size_t)k].reset();  // free shard memory as the merge walks
  }
  h->merge_ns += now_ns() - t0;
  return h->spill_err ? -1 : (int64_t)h->time_.size();
}

int64_t ffz_merge_ns(void* hv) { return ((Ffz*)hv)->merge_ns; }

int ffz_finish(void* hv, const double* tc, int ntc, const double* bc,
               int nbc, const double* pc, int npc) {
  Ffz* h = (Ffz*)hv;
  // Bin values are at most the cut count; the word-cache key packs each
  // into 12 bits and wp_id into 28.
  if (ntc > 4095 || nbc > 4095 || npc > 4095) {
    h->error = "cut lists longer than 4095 are not supported";
    return -1;
  }
  size_t n = h->time_.size();
  h->tbin.resize(n);
  h->bbin.resize(n);
  h->pbin.resize(n);
  h->wp_id.resize(n);
  h->sw_id.resize(n);
  h->dw_id.resize(n);

  PassB p(h, h->words, n);
  p.tc = tc;
  p.bc = bc;
  p.pc = pc;
  p.ntc = ntc;
  p.nbc = nbc;
  p.npc = npc;
  for (size_t i = 0; i < n; i++) p.event(i);

  h->wc_ip = std::move(p.s_ip);
  h->wc_ip.insert(h->wc_ip.end(), p.d_ip.begin(), p.d_ip.end());
  h->wc_word = std::move(p.s_w);
  h->wc_word.insert(h->wc_word.end(), p.d_w.begin(), p.d_w.end());
  h->wc_cnt = std::move(p.s_c);
  h->wc_cnt.insert(h->wc_cnt.end(), p.d_c.begin(), p.d_c.end());
  return 0;
}

// Pass B over `workers` contiguous event ranges, each through its own
// PassB with a shard-local word interner, then a deterministic merge:
// walking shard word tables in shard order re-interns every word in
// its global first-intern order, and walking the shard-local
// first-seen (doc, word) maps in shard order (all src, then all dst)
// reproduces the sequential aggregation order with counts summed
// across shards.  Byte-identical to ffz_finish given identical cuts.
int ffz_finish_mt(void* hv, const double* tc, int ntc, const double* bc,
                  int nbc, const double* pc, int npc, int workers) {
  Ffz* h = (Ffz*)hv;
  size_t n = h->time_.size();
  if (workers <= 1 || n < 2)
    return ffz_finish(hv, tc, ntc, bc, nbc, pc, npc);
  if (ntc > 4095 || nbc > 4095 || npc > 4095) {
    h->error = "cut lists longer than 4095 are not supported";
    return -1;
  }
  if ((size_t)workers > n) workers = (int)n;
  h->tbin.resize(n);
  h->bbin.resize(n);
  h->pbin.resize(n);
  h->wp_id.resize(n);
  h->sw_id.resize(n);
  h->dw_id.resize(n);

  std::vector<std::unique_ptr<Interner>> local_words((size_t)workers);
  std::vector<std::unique_ptr<PassB>> passes((size_t)workers);
  std::vector<std::thread> threads;
  for (int k = 0; k < workers; k++) {
    size_t lo = n * (size_t)k / (size_t)workers;
    size_t hi = n * ((size_t)k + 1) / (size_t)workers;
    local_words[(size_t)k] = std::make_unique<Interner>();
    passes[(size_t)k] =
        std::make_unique<PassB>(h, *local_words[(size_t)k], hi - lo);
    PassB* p = passes[(size_t)k].get();
    p->tc = tc;
    p->bc = bc;
    p->pc = pc;
    p->ntc = ntc;
    p->nbc = nbc;
    p->npc = npc;
    threads.emplace_back([p, lo, hi] {
      for (size_t i = lo; i < hi; i++) p->event(i);
    });
  }
  for (auto& t : threads) t.join();

  int64_t t0 = now_ns();
  // Word merge order is the id contract, so the interning walk is
  // sequential; the per-event id rewrites only READ the finished wmaps
  // and touch disjoint ranges, so they fan back out across threads.
  std::vector<std::vector<int32_t>> wmaps((size_t)workers);
  for (int k = 0; k < workers; k++) {
    Interner& lw = *local_words[(size_t)k];
    std::vector<int32_t>& wmap = wmaps[(size_t)k];
    wmap.resize(lw.arena.size());
    for (size_t j = 0; j < lw.arena.size(); j++)
      wmap[j] = h->words.intern(lw.arena[j]);
  }
  {
    std::vector<std::thread> rewrite;
    for (int k = 0; k < workers; k++) {
      const std::vector<int32_t>* wmap = &wmaps[(size_t)k];
      size_t lo = n * (size_t)k / (size_t)workers;
      size_t hi = n * ((size_t)k + 1) / (size_t)workers;
      rewrite.emplace_back([h, wmap, lo, hi] {
        for (size_t i = lo; i < hi; i++) {
          h->wp_id[i] = (*wmap)[(size_t)h->wp_id[i]];
          h->sw_id[i] = (*wmap)[(size_t)h->sw_id[i]];
          h->dw_id[i] = (*wmap)[(size_t)h->dw_id[i]];
        }
      });
    }
    for (auto& t : rewrite) t.join();
  }
  // Size the merge maps for the REAL entry totals up front: growing
  // from n/2 through repeated rehashes was the hottest block of the
  // merge on high-cardinality days (pairs-per-event near 1).
  size_t tot_s = 0, tot_d = 0;
  for (int k = 0; k < workers; k++) {
    tot_s += passes[(size_t)k]->s_c.size();
    tot_d += passes[(size_t)k]->d_c.size();
  }
  std::vector<int32_t> s_ip, s_w, d_ip, d_w;
  std::vector<int64_t> s_c, d_c;
  // The src and dst aggregations are independent streams with separate
  // maps and outputs, so their (inherently sequential, shard-ordered)
  // merges run concurrently on two threads — each walks shards in
  // order, preserving its stream's first-seen contract.
  std::thread src_merge([&] {
    oni::FlatMap64 src_pos(tot_s);
    s_ip.reserve(tot_s);
    s_w.reserve(tot_s);
    s_c.reserve(tot_s);
    for (int k = 0; k < workers; k++) {
      PassB& p = *passes[(size_t)k];
      const std::vector<int32_t>& wmap = wmaps[(size_t)k];
      for (size_t e = 0; e < p.s_c.size(); e++) {
        int32_t gw = wmap[(size_t)p.s_w[e]];
        uint64_t key =
            ((uint64_t)(uint32_t)p.s_ip[e] << 32) | (uint32_t)gw;
        bool fresh;
        int64_t& slot = src_pos.probe(key, &fresh);
        if (fresh) {
          slot = (int64_t)s_c.size();
          s_ip.push_back(p.s_ip[e]);
          s_w.push_back(gw);
          s_c.push_back(p.s_c[e]);
        } else {
          s_c[(size_t)slot] += p.s_c[e];
        }
      }
    }
  });
  {
    oni::FlatMap64 dst_pos(tot_d);
    d_ip.reserve(tot_d);
    d_w.reserve(tot_d);
    d_c.reserve(tot_d);
    for (int k = 0; k < workers; k++) {
      PassB& p = *passes[(size_t)k];
      const std::vector<int32_t>& wmap = wmaps[(size_t)k];
      for (size_t e = 0; e < p.d_c.size(); e++) {
        int32_t gw = wmap[(size_t)p.d_w[e]];
        uint64_t key =
            ((uint64_t)(uint32_t)p.d_ip[e] << 32) | (uint32_t)gw;
        bool fresh;
        int64_t& slot = dst_pos.probe(key, &fresh);
        if (fresh) {
          slot = (int64_t)d_c.size();
          d_ip.push_back(p.d_ip[e]);
          d_w.push_back(gw);
          d_c.push_back(p.d_c[e]);
        } else {
          d_c[(size_t)slot] += p.d_c[e];
        }
      }
    }
  }
  src_merge.join();
  for (int k = 0; k < workers; k++) passes[(size_t)k].reset();
  h->wc_ip = std::move(s_ip);
  h->wc_ip.insert(h->wc_ip.end(), d_ip.begin(), d_ip.end());
  h->wc_word = std::move(s_w);
  h->wc_word.insert(h->wc_word.end(), d_w.begin(), d_w.end());
  h->wc_cnt = std::move(s_c);
  h->wc_cnt.insert(h->wc_cnt.end(), d_c.begin(), d_c.end());
  h->merge_ns += now_ns() - t0;
  return 0;
}

const int32_t* ffz_bins(void* hv, int which) {
  Ffz* h = (Ffz*)hv;
  switch (which) {
    case 0: return h->tbin.data();
    case 1: return h->bbin.data();
    default: return h->pbin.data();
  }
}

const int32_t* ffz_ids(void* hv, int which) {
  Ffz* h = (Ffz*)hv;
  switch (which) {
    case 0: return h->sip_id.data();
    case 1: return h->dip_id.data();
    case 2: return h->wp_id.data();
    case 3: return h->sw_id.data();
    default: return h->dw_id.data();
  }
}

static Interner& table_of(void* hv, int which) {
  Ffz* h = (Ffz*)hv;
  return which == 0 ? h->ips : h->words;
}
int64_t ffz_table_count(void* hv, int which) {
  return (int64_t)table_of(hv, which).arena.size();
}
const char* ffz_table_blob(void* hv, int which) {
  Interner& t = table_of(hv, which);
  t.build_export();
  return t.blob.data();
}
int64_t ffz_table_blob_len(void* hv, int which) {
  Interner& t = table_of(hv, which);
  t.build_export();
  return (int64_t)t.blob.size();
}
const int64_t* ffz_table_offsets(void* hv, int which) {
  Interner& t = table_of(hv, which);
  t.build_export();
  return t.offsets.data();
}

const char* ffz_lines_blob(void* hv) {
  Ffz* h = (Ffz*)hv;
  return h->spill ? nullptr : h->lines.data();  // spilled: read the file
}
int64_t ffz_lines_blob_len(void* hv) {
  Ffz* h = (Ffz*)hv;
  return h->spill ? h->spill_len : (int64_t)h->lines.size();
}
const int64_t* ffz_line_offsets(void* hv) {
  return ((Ffz*)hv)->line_off.data();
}

int64_t ffz_wc_len(void* hv) { return (int64_t)((Ffz*)hv)->wc_cnt.size(); }
const int32_t* ffz_wc_ip(void* hv) { return ((Ffz*)hv)->wc_ip.data(); }
const int32_t* ffz_wc_word(void* hv) { return ((Ffz*)hv)->wc_word.data(); }
const int64_t* ffz_wc_count(void* hv) { return ((Ffz*)hv)->wc_cnt.data(); }

}  // extern "C"
