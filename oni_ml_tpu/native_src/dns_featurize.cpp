// Native DNS featurizer — the C++ fast path for the DNS "pre" stage
// (dns_pre_lda.scala featurization, reimplemented in
// oni_ml_tpu/features/dns.py).  This is the stage the reference's
// authors sized a 62-executor x 12-core Spark cluster for
// (dns_pre_lda.scala:1-2, SURVEY.md §6).
//
// Split of responsibilities with Python (features/native_dns.py), same
// shape as the flow featurizer:
//   pass A (ingest_*): row filtering (8 fields), unix_tstamp/frame_len
//     numeric extraction, subdomain extraction (reverse-DNS +
//     country-code TLD handling), Shannon entropy, interning of
//     client IPs / domains / subdomains / qry_type / qry_rcode.
//   cuts: Python computes the five ECDF cut lists (deciles over
//     tstamp/frame_len, quintiles over the positive subsets) with
//     quantiles.ecdf_cuts — single implementation of the quantile rule.
//   pass B (finish): binning, whitelist flag, word construction
//     ("top_blen_btime_bsub_bent_bper_type_rcode"), first-seen-order
//     per-client word counts (dns_pre_lda.scala:330).
//
// Rows are exchanged and stored with the ASCII unit separator \x1f so
// parquet-sourced fields containing commas (frame_time!) survive; CSV
// files are split on ',' at ingest and re-joined with \x1f.
//
// Entropy matches Python bit-for-bit: character counts accumulate in
// first-seen order (Counter's iteration order) and the sum uses the
// same -(c/n)*log2(c/n) expression, so identical libm gives identical
// doubles.  Known divergence: characters are bytes here, codepoints in
// Python — identical for the ASCII/punycode names DNS carries.

#include "common.h"

#include <charconv>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <memory>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ISO country-code TLDs, verbatim from dns_pre_lda.scala:180 (including
// the stray empty string and "krd") — mirrors features/dns.py.
using oni::Interner;
using oni::to_double;
using oni::bin_of;
using oni::append_int;

const char* kCountryCodes =
    "ac ad ae af ag ai al am an ao aq ar as at au aw ax az ba bb bd be bf bg "
    "bh bi bj bm bn bo bq br bs bt bv bw by bz ca cc cd cf cg ch ci ck cl cm "
    "cn co cr cu cv cw cx cy cz de dj dk dm do dz ec ee eg eh er es et eu fi "
    "fj fk fm fo fr ga gb gd ge gf gg gh gi gl gm gn gp gq gr gs gt gu gw gy "
    "hk hm hn hr ht hu id ie il im in io iq ir is it je jm jo jp ke kg kh ki "
    "km kn kp kr krd kw ky kz la lb lc li lk lr ls lt lu lv ly ma mc md me "
    "mg mh mk ml mm mn mo mp mq mr ms mt mu mv mw mx my mz na nc ne nf ng ni "
    "nl no np nr nu nz om pa pe pf pg ph pk pl pm pn pr ps pt pw py qa re ro "
    "rs ru rw sa sb sc sd se sg sh si sj sk sl sm sn so sr ss st su sv sx sy "
    "sz tc td tf tg th tj tk tl tm tn to tp tr tt tv tw tz ua ug uk us uy uz "
    "va vc ve vg vi vn vu wf ws ye yt za zm zw";

const std::unordered_set<std::string>& country_codes() {
  static const std::unordered_set<std::string>* set = [] {
    auto* s = new std::unordered_set<std::string>;
    const char* p = kCountryCodes;
    while (*p) {
      const char* q = p;
      while (*q && *q != ' ') q++;
      s->emplace(p, (size_t)(q - p));
      p = *q ? q + 1 : q;
    }
    s->emplace("");  // the reference set contains the empty string
    return s;
  }();
  return *set;
}

// Shannon entropy with Python's exact summation: counts in first-seen
// character order (Counter iteration order) and CPython 3.12+ builtin
// sum()'s Neumaier compensated accumulation (Python/bltinmodule.c) —
// plain left-to-right accumulation differs in the last ulp.
double entropy_of(std::string_view s) {
  if (s.empty()) return 0.0;
  int32_t count[256] = {0};
  unsigned char order[256];
  int n_distinct = 0;
  for (unsigned char c : s) {
    if (count[c]++ == 0) order[n_distinct++] = c;
  }
  double n = (double)s.size();
  double hi = 0.0, comp = 0.0;
  for (int i = 0; i < n_distinct; i++) {
    double p = (double)count[order[i]] / n;
    double x = -(p)*log2(p);
    double t = hi + x;
    if (fabs(hi) >= fabs(x))
      comp += (hi - t) + x;
    else
      comp += (x - t) + hi;
    hi = t;
  }
  return hi + comp;
}

constexpr int NCOLS = 8;
// Field indices (dns_pre_lda.scala:149; features/dns.py DNS_COLUMNS).
constexpr int C_TSTAMP = 1, C_FLEN = 2, C_IPDST = 3, C_QNAME = 4;
constexpr int C_QTYPE = 6, C_QRCODE = 7;
constexpr char SEP = '\x1f';

struct Dfz {
  std::string rows;                   // \x1f-joined fields, rows appended
  FILE* spill = nullptr;              // when set, rows stream here
  int64_t spill_len = 0;              // instead of the in-RAM blob
  bool spill_err = false;             // short write (ENOSPC etc.)
  std::string rowbuf;                 // reused per-row join buffer
  std::vector<int64_t> row_off{0};
  std::vector<double> tstamp_, flen_, entropy_;
  std::vector<int32_t> sublen_, nparts_;
  Interner ips, domains, subdomains, qtypes, qrcodes;
  std::vector<int32_t> ip_id, dom_id, sub_id, qtype_id, qrcode_id;
  int64_t num_raw = -1;
  // A CSV-sourced field containing the \x1f transport separator would
  // split into extra columns when the stored rows blob is re-split on
  // the Python side; flag it so the caller can discard this handle and
  // re-run through the pure-Python path instead of emitting misaligned
  // results rows.
  bool unsafe = false;

  // finish() outputs
  std::vector<int32_t> top;
  Interner words;
  std::vector<int32_t> word_id;
  std::vector<int32_t> wc_ip, wc_word;
  std::vector<int64_t> wc_cnt;

  // Wall spent in the deterministic merges of the parallel paths
  // (pass-A shard-table remap + pass-B word/count merge).
  int64_t merge_ns = 0;

  std::string error;

  void add_row(const std::string_view* f) {
    if (spill) {
      // Stored rows are only re-read at emit time; streaming them to
      // the spill file keeps RSS bounded by the numeric/interned
      // arrays.  Short writes must surface as errors, not as offsets
      // past the end of the file.
      rowbuf.clear();
      for (int i = 0; i < NCOLS; i++) {
        if (i) rowbuf += SEP;
        rowbuf.append(f[i].data(), f[i].size());
      }
      if (fwrite(rowbuf.data(), 1, rowbuf.size(), spill)
          != rowbuf.size()) {
        spill_err = true;
        error = "short write to rows spill file (disk full?)";
      }
      spill_len += (int64_t)rowbuf.size();
      row_off.push_back(spill_len);
    } else {
      for (int i = 0; i < NCOLS; i++) {
        if (i) rows += SEP;
        rows.append(f[i].data(), f[i].size());
      }
      row_off.push_back((int64_t)rows.size());
    }

    tstamp_.push_back(to_double(f[C_TSTAMP]));
    flen_.push_back(to_double(f[C_FLEN]));
    ip_id.push_back(ips.intern(f[C_IPDST]));
    qtype_id.push_back(qtypes.intern(f[C_QTYPE]));
    qrcode_id.push_back(qrcodes.intern(f[C_QRCODE]));

    // extract_subdomain (dns_pre_lda.scala:185-220 / features/dns.py).
    std::string_view url = f[C_QNAME];
    std::vector<std::string_view> parts;
    size_t start = 0;
    for (size_t i = 0; i <= url.size(); i++) {
      if (i == url.size() || url[i] == '.') {
        parts.push_back(url.substr(start, i - start));
        start = i + 1;
      }
    }
    while (parts.size() > 1 && parts.back().empty()) parts.pop_back();
    size_t n = parts.size();
    std::string_view domain = "None";
    std::string sub = "None";
    bool is_ip = n > 2 && parts[n - 1] == "arpa" && parts[n - 2] == "in-addr";
    if (n > 2 && !is_ip) {
      bool cc = country_codes().count(std::string(parts[n - 1])) > 0;
      size_t keep = cc ? n - 3 : n - 2;
      domain = parts[keep];
      if (keep >= 1) {
        sub.clear();
        for (size_t i = 0; i < keep; i++) {
          if (i) sub += '.';
          sub.append(parts[i].data(), parts[i].size());
        }
      } else if (!cc) {
        sub.clear();  // unreachable (keep = n-2 >= 1 when n > 2)
      }
    }
    dom_id.push_back(domains.intern(domain));
    sub_id.push_back(subdomains.intern(sub));
    sublen_.push_back(sub != "None" ? (int32_t)sub.size() : 0);
    nparts_.push_back((int32_t)n);
    entropy_.push_back(entropy_of(sub));
  }

  // Split a line on `sep`; keep iff exactly 8 fields.
  void add_line(std::string_view line, char sep) {
    // A CSV-sourced \x1f would re-split the stored rows blob.  An
    // embedded lone '\r' is fine here: rows are recovered by offsets,
    // not delimiters, and the Python fallback reader uses the same
    // line semantics (split on '\n', strip one trailing '\r'), so both
    // engines preserve it in the field.
    if (sep != SEP && line.find(SEP) != std::string_view::npos)
      unsafe = true;
    std::string_view f[NCOLS];
    int nf = 0;
    size_t start = 0;
    for (size_t i = 0; i <= line.size(); i++) {
      if (i == line.size() || line[i] == sep) {
        if (nf < NCOLS) f[nf] = line.substr(start, i - start);
        nf++;
        start = i + 1;
      }
    }
    if (nf == NCOLS) add_row(f);
  }

  void ingest(const char* buf, int64_t len, char sep, bool skip_empty) {
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
      const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
      const char* stop = nl ? nl : end;
      const char* s2 = stop;
      if (s2 > p && s2[-1] == '\r') s2--;
      std::string_view line(p, (size_t)(s2 - p));
      if (!(skip_empty && line.empty())) add_line(line, sep);
      p = nl ? nl + 1 : end;
    }
  }
};

// Pass-B state over one contiguous event range: binning, whitelist
// flag, word construction, first-seen per-client aggregation.  The
// sequential path runs one PassD over all events with `words` bound to
// h->words; the parallel path runs one per shard with a shard-local
// interner and merges deterministically in shard order — both walk
// each event through exactly this code (the flow featurizer's PassB
// design).
struct PassD {
  Dfz* h;
  Interner& words;
  const double* tc;
  const double* lc;
  const double* sc;
  const double* ec;
  const double* pc;
  int ntc, nlc, nsc, nec, npc;
  const std::vector<int32_t>& dom_top;

  oni::FlatMap64 pos;
  std::vector<int32_t> w_ip, w_w;  // word ids are in `words`
  std::vector<int64_t> w_c;

  // The word is a pure function of (top, 5 bins, qtype, qrcode); unique
  // combinations number far below the row count, so cache the interned
  // id behind a packed integer key and skip the per-row string build.
  // Packing limits (bins < 256, interner ids < 2048, top in 0..3) hold
  // for any real day; rows beyond them fall back to building the word.
  oni::FlatMap64 word_cache;
  std::string word;  // scratch

  PassD(Dfz* h_, Interner& w, const std::vector<int32_t>& dt,
        size_t expected)
      : h(h_), words(w), dom_top(dt), pos(expected / 2) {}

  void event(size_t i) {
    int bt = bin_of(h->tstamp_[i], tc, ntc);
    int bl = bin_of((double)h->flen_[i], lc, nlc);
    int bs = bin_of((double)h->sublen_[i], sc, nsc);
    int be = bin_of(h->entropy_[i], ec, nec);
    int bp = bin_of((double)h->nparts_[i], pc, npc);
    int tp = dom_top[(size_t)h->dom_id[i]];
    h->top[i] = tp;

    int32_t qt = h->qtype_id[i], qr = h->qrcode_id[i];
    bool cacheable =
        (unsigned)bt < 256 && (unsigned)bl < 256 && (unsigned)bs < 256 &&
        (unsigned)be < 256 && (unsigned)bp < 256 && (unsigned)tp < 4 &&
        (uint32_t)qt < 2048 && (uint32_t)qr < 2048;
    uint64_t wkey = 0;
    int64_t* wslot = nullptr;
    bool fresh = true;
    if (cacheable) {
      wkey = ((uint64_t)tp << 62) | ((uint64_t)bt << 54) |
             ((uint64_t)bl << 46) | ((uint64_t)bs << 38) |
             ((uint64_t)be << 30) | ((uint64_t)bp << 22) |
             ((uint64_t)(uint32_t)qt << 11) | (uint64_t)(uint32_t)qr;
      if (wkey != oni::FlatMap64::EMPTY)
        wslot = &word_cache.probe(wkey, &fresh);
    }
    int32_t wid;
    if (!fresh) {
      wid = (int32_t)*wslot;
    } else {
      // word = top_blen_btime_bsub_bent_bper_type_rcode
      // (dns_pre_lda.scala:320-327; raw type/rcode field text).
      word.clear();
      append_int(word, tp);
      word += '_';
      append_int(word, bl);
      word += '_';
      append_int(word, bt);
      word += '_';
      append_int(word, bs);
      word += '_';
      append_int(word, be);
      word += '_';
      append_int(word, bp);
      word += '_';
      word += h->qtypes.arena[(size_t)h->qtype_id[i]];
      word += '_';
      word += h->qrcodes.arena[(size_t)h->qrcode_id[i]];
      wid = words.intern(word);
      if (wslot) *wslot = wid;
    }
    h->word_id[i] = wid;

    uint64_t key = ((uint64_t)(uint32_t)h->ip_id[i] << 32) | (uint32_t)wid;
    int64_t& slot = pos.probe(key, &fresh);
    if (fresh) {
      slot = (int64_t)w_c.size();
      w_ip.push_back(h->ip_id[i]);
      w_w.push_back(wid);
      w_c.push_back(1);
    } else {
      w_c[(size_t)slot]++;
    }
  }
};

using oni::now_ns;

}  // namespace

extern "C" {

void* dfz_create() { return new Dfz(); }
void dfz_destroy(void* hv) {
  Dfz* h = (Dfz*)hv;
  if (h->spill) fclose(h->spill);
  delete h;
}
const char* dfz_error(void* h) { return ((Dfz*)h)->error.c_str(); }

// Route stored rows to `path` instead of RAM.  Must be called before
// any ingest — row offsets are absolute positions in ONE store, so
// retargeting mid-run (or after in-RAM rows exist) would make them
// read past EOF / wrong bytes at emit.  -1 with dfz_error set on
// misuse or when the file can't open.
int dfz_set_spill(void* hv, const char* path) {
  Dfz* h = (Dfz*)hv;
  if (!h->tstamp_.empty() || h->spill) {
    h->error = "dfz_set_spill must be called once, before any ingest";
    return -1;
  }
  h->spill = fopen(path, "wb");
  if (!h->spill) {
    h->error = std::string("cannot open spill file ") + path;
    return -1;
  }
  return 0;
}

// Returns the spilled byte count, or -1 when any write/flush failed.
int64_t dfz_spill_flush(void* hv) {
  Dfz* h = (Dfz*)hv;
  if (h->spill) {
    if (fflush(h->spill) != 0 || ferror(h->spill)) {
      h->spill_err = true;
      if (h->error.empty())
        h->error = "flush of rows spill file failed (disk full?)";
    }
  }
  return h->spill_err ? -1 : h->spill_len;
}

int64_t dfz_ingest_csv_file(void* hv, const char* path, int skip_header) {
  Dfz* h = (Dfz*)hv;
  bool skipping = skip_header != 0;
  bool ok = oni::stream_file(
      path, h->error, [h, &skipping](const char* p, int64_t n) {
        if (skipping) {
          const char* nl = (const char*)memchr(p, '\n', (size_t)n);
          if (!nl) return;  // header longer than this buffer
          skipping = false;
          n -= (nl + 1 - p);
          p = nl + 1;
        }
        h->ingest(p, n, ',', /*skip_empty=*/true);
      });
  return (ok && !h->spill_err) ? (int64_t)h->tstamp_.size() : -1;
}

// Rows pre-split by the caller (parquet, feedback): fields joined by
// \x1f, rows by \n.
int64_t dfz_ingest_rows(void* hv, const char* buf, int64_t len) {
  Dfz* h = (Dfz*)hv;
  h->ingest(buf, len, SEP, /*skip_empty=*/true);
  return h->spill_err ? -1 : (int64_t)h->tstamp_.size();
}

int dfz_unsafe(void* hv) { return ((Dfz*)hv)->unsafe ? 1 : 0; }

void dfz_mark_raw(void* hv) {
  Dfz* h = (Dfz*)hv;
  h->num_raw = (int64_t)h->tstamp_.size();
}
int64_t dfz_num_raw(void* hv) {
  Dfz* h = (Dfz*)hv;
  return h->num_raw >= 0 ? h->num_raw : (int64_t)h->tstamp_.size();
}
int64_t dfz_num_events(void* hv) {
  return (int64_t)((Dfz*)hv)->tstamp_.size();
}

const double* dfz_tstamp(void* h) { return ((Dfz*)h)->tstamp_.data(); }
const double* dfz_frame_len(void* h) { return ((Dfz*)h)->flen_.data(); }
const double* dfz_entropy(void* h) { return ((Dfz*)h)->entropy_.data(); }
const int32_t* dfz_sublen(void* h) { return ((Dfz*)h)->sublen_.data(); }
const int32_t* dfz_nparts(void* h) { return ((Dfz*)h)->nparts_.data(); }

// Shard the CSV file into line-aligned byte ranges and run pass A over
// them on `workers` std::threads, each into its own shard-local Dfz,
// then merge in shard order: every shard-local interner (client IPs,
// domains, subdomains, qtypes, qrcodes) re-interns into the parent in
// local first-seen order, reproducing the sequential first-seen order
// exactly (flow_featurize.cpp ffz_ingest_file_parallel design notes;
// spill handling and RSS tradeoff identical).
int64_t dfz_ingest_csv_file_parallel(void* hv, const char* path,
                                     int skip_header, int workers) {
  Dfz* h = (Dfz*)hv;
  if (workers <= 1) return dfz_ingest_csv_file(hv, path, skip_header);
  int64_t size = oni::file_size_of(path);
  if (size < 0) {
    h->error = std::string("cannot open ") + path;
    return -1;
  }
  int64_t data_start = 0;
  if (skip_header) {
    std::string hdr, err;
    if (!oni::read_first_line(path, hdr, &data_start, err)) {
      if (!err.empty()) {
        h->error = err;
        return -1;
      }
      // No '\n' at all: the whole file is the header — nothing to
      // ingest (the sequential path drops it the same way).
      return (int64_t)h->tstamp_.size();
    }
  }
  std::string err;
  std::vector<int64_t> bounds =
      oni::shard_bounds(path, data_start, size, workers, err);
  if (bounds.empty()) {
    h->error = err;
    return -1;
  }
  std::vector<std::unique_ptr<Dfz>> shards((size_t)workers);
  std::vector<int> ok((size_t)workers, 1);
  std::vector<std::thread> threads;
  for (int k = 0; k < workers; k++) {
    shards[(size_t)k] = std::make_unique<Dfz>();
    Dfz* w = shards[(size_t)k].get();
    int64_t lo = bounds[(size_t)k], hi = bounds[(size_t)k + 1];
    threads.emplace_back([w, path, lo, hi, &ok, k] {
      ok[(size_t)k] = oni::stream_file_range(
                          path, lo, hi, w->error,
                          [w](const char* p, int64_t n) {
                            w->ingest(p, n, ',', /*skip_empty=*/true);
                          })
                          ? 1
                          : 0;
    });
  }
  for (auto& t : threads) t.join();
  for (int k = 0; k < workers; k++) {
    if (!ok[(size_t)k]) {
      h->error = shards[(size_t)k]->error;
      return -1;
    }
  }

  int64_t t0 = now_ns();
  {
    size_t tot_ev = 0, tot_bytes = 0;
    for (int k = 0; k < workers; k++) {
      tot_ev += shards[(size_t)k]->tstamp_.size();
      tot_bytes += shards[(size_t)k]->rows.size();
    }
    h->tstamp_.reserve(h->tstamp_.size() + tot_ev);
    h->flen_.reserve(h->flen_.size() + tot_ev);
    h->entropy_.reserve(h->entropy_.size() + tot_ev);
    h->sublen_.reserve(h->sublen_.size() + tot_ev);
    h->nparts_.reserve(h->nparts_.size() + tot_ev);
    h->row_off.reserve(h->row_off.size() + tot_ev);
    if (!h->spill) h->rows.reserve(h->rows.size() + tot_bytes);
  }
  for (int k = 0; k < workers; k++) {
    Dfz* w = shards[(size_t)k].get();
    h->unsafe = h->unsafe || w->unsafe;
    // Remap every shard-local interner into the parent (local
    // first-seen order -> global first-seen order).
    Interner* locals[5] = {&w->ips, &w->domains, &w->subdomains,
                           &w->qtypes, &w->qrcodes};
    Interner* globals[5] = {&h->ips, &h->domains, &h->subdomains,
                            &h->qtypes, &h->qrcodes};
    std::vector<int32_t>* ids[5] = {&w->ip_id, &w->dom_id, &w->sub_id,
                                    &w->qtype_id, &w->qrcode_id};
    std::vector<int32_t>* outs[5] = {&h->ip_id, &h->dom_id, &h->sub_id,
                                     &h->qtype_id, &h->qrcode_id};
    for (int t = 0; t < 5; t++) {
      std::vector<int32_t> map(locals[t]->arena.size());
      for (size_t j = 0; j < locals[t]->arena.size(); j++)
        map[j] = globals[t]->intern(locals[t]->arena[j]);
      outs[t]->reserve(outs[t]->size() + ids[t]->size());
      for (int32_t lid : *ids[t])
        outs[t]->push_back(map[(size_t)lid]);
    }
    h->tstamp_.insert(h->tstamp_.end(), w->tstamp_.begin(),
                      w->tstamp_.end());
    h->flen_.insert(h->flen_.end(), w->flen_.begin(), w->flen_.end());
    h->entropy_.insert(h->entropy_.end(), w->entropy_.begin(),
                       w->entropy_.end());
    h->sublen_.insert(h->sublen_.end(), w->sublen_.begin(),
                      w->sublen_.end());
    h->nparts_.insert(h->nparts_.end(), w->nparts_.begin(),
                      w->nparts_.end());
    if (h->spill) {
      if (!w->rows.empty() &&
          fwrite(w->rows.data(), 1, w->rows.size(), h->spill) !=
              w->rows.size()) {
        h->spill_err = true;
        h->error = "short write to rows spill file (disk full?)";
      }
      for (size_t j = 1; j < w->row_off.size(); j++)
        h->row_off.push_back(h->spill_len + w->row_off[j]);
      h->spill_len += (int64_t)w->rows.size();
    } else {
      int64_t base = (int64_t)h->rows.size();
      h->rows += w->rows;
      for (size_t j = 1; j < w->row_off.size(); j++)
        h->row_off.push_back(base + w->row_off[j]);
    }
    shards[(size_t)k].reset();  // free shard memory as the merge walks
  }
  h->merge_ns += now_ns() - t0;
  return h->spill_err ? -1 : (int64_t)h->tstamp_.size();
}

int64_t dfz_merge_ns(void* hv) { return ((Dfz*)hv)->merge_ns; }

static void build_dom_top(Dfz* h, const char* top_blob, int64_t top_len,
                          std::vector<int32_t>& dom_top) {
  std::unordered_set<std::string_view> top_set;
  const char* p = top_blob;
  const char* end = top_blob + top_len;
  while (p < end) {
    const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
    const char* stop = nl ? nl : end;
    if (stop > p) top_set.emplace(p, (size_t)(stop - p));
    p = nl ? nl + 1 : end;
  }
  // Whitelist flag per unique domain, not per row.
  dom_top.resize(h->domains.arena.size());
  for (size_t i = 0; i < h->domains.arena.size(); i++) {
    const std::string& d = h->domains.arena[i];
    dom_top[i] = d == "intel" ? 2 : (top_set.count(d) ? 1 : 0);
  }
}

// top_blob: '\n'-joined whitelisted base-domain names (load_top_domains
// output), decoded into a set for the flag pass.
int dfz_finish(void* hv, const double* tc, int ntc, const double* lc,
               int nlc, const double* sc, int nsc, const double* ec, int nec,
               const double* pc, int npc, const char* top_blob,
               int64_t top_len) {
  Dfz* h = (Dfz*)hv;
  size_t n = h->tstamp_.size();

  std::vector<int32_t> dom_top;
  build_dom_top(h, top_blob, top_len, dom_top);

  h->top.resize(n);
  h->word_id.resize(n);

  PassD p(h, h->words, dom_top, n);
  p.tc = tc;
  p.lc = lc;
  p.sc = sc;
  p.ec = ec;
  p.pc = pc;
  p.ntc = ntc;
  p.nlc = nlc;
  p.nsc = nsc;
  p.nec = nec;
  p.npc = npc;
  for (size_t i = 0; i < n; i++) p.event(i);

  h->wc_ip = std::move(p.w_ip);
  h->wc_word = std::move(p.w_w);
  h->wc_cnt = std::move(p.w_c);
  return 0;
}

// Pass B over `workers` contiguous event ranges (shard-local word
// interners + first-seen maps), merged deterministically in shard
// order — byte-identical output to dfz_finish given identical cuts
// (flow_featurize.cpp ffz_finish_mt design notes).
int dfz_finish_mt(void* hv, const double* tc, int ntc, const double* lc,
                  int nlc, const double* sc, int nsc, const double* ec,
                  int nec, const double* pc, int npc, const char* top_blob,
                  int64_t top_len, int workers) {
  Dfz* h = (Dfz*)hv;
  size_t n = h->tstamp_.size();
  if (workers <= 1 || n < 2)
    return dfz_finish(hv, tc, ntc, lc, nlc, sc, nsc, ec, nec, pc, npc,
                      top_blob, top_len);
  if ((size_t)workers > n) workers = (int)n;

  std::vector<int32_t> dom_top;
  build_dom_top(h, top_blob, top_len, dom_top);
  h->top.resize(n);
  h->word_id.resize(n);

  std::vector<std::unique_ptr<Interner>> local_words((size_t)workers);
  std::vector<std::unique_ptr<PassD>> passes((size_t)workers);
  std::vector<std::thread> threads;
  for (int k = 0; k < workers; k++) {
    size_t lo = n * (size_t)k / (size_t)workers;
    size_t hi = n * ((size_t)k + 1) / (size_t)workers;
    local_words[(size_t)k] = std::make_unique<Interner>();
    passes[(size_t)k] = std::make_unique<PassD>(
        h, *local_words[(size_t)k], dom_top, hi - lo);
    PassD* p = passes[(size_t)k].get();
    p->tc = tc;
    p->lc = lc;
    p->sc = sc;
    p->ec = ec;
    p->pc = pc;
    p->ntc = ntc;
    p->nlc = nlc;
    p->nsc = nsc;
    p->nec = nec;
    p->npc = npc;
    threads.emplace_back([p, lo, hi] {
      for (size_t i = lo; i < hi; i++) p->event(i);
    });
  }
  for (auto& t : threads) t.join();

  int64_t t0 = now_ns();
  // Sequential word interning (order is the id contract), parallel
  // per-range id rewrites, merge maps pre-sized for the real entry
  // totals (flow ffz_finish_mt design notes).
  std::vector<std::vector<int32_t>> wmaps((size_t)workers);
  for (int k = 0; k < workers; k++) {
    Interner& lw = *local_words[(size_t)k];
    std::vector<int32_t>& wmap = wmaps[(size_t)k];
    wmap.resize(lw.arena.size());
    for (size_t j = 0; j < lw.arena.size(); j++)
      wmap[j] = h->words.intern(lw.arena[j]);
  }
  {
    std::vector<std::thread> rewrite;
    for (int k = 0; k < workers; k++) {
      const std::vector<int32_t>* wmap = &wmaps[(size_t)k];
      size_t lo = n * (size_t)k / (size_t)workers;
      size_t hi = n * ((size_t)k + 1) / (size_t)workers;
      rewrite.emplace_back([h, wmap, lo, hi] {
        for (size_t i = lo; i < hi; i++)
          h->word_id[i] = (*wmap)[(size_t)h->word_id[i]];
      });
    }
    for (auto& t : rewrite) t.join();
  }
  size_t tot = 0;
  for (int k = 0; k < workers; k++) tot += passes[(size_t)k]->w_c.size();
  oni::FlatMap64 pos(tot);
  std::vector<int32_t> w_ip, w_w;
  std::vector<int64_t> w_c;
  w_ip.reserve(tot);
  w_w.reserve(tot);
  w_c.reserve(tot);
  for (int k = 0; k < workers; k++) {
    const std::vector<int32_t>& wmap = wmaps[(size_t)k];
    PassD& p = *passes[(size_t)k];
    for (size_t e = 0; e < p.w_c.size(); e++) {
      int32_t gw = wmap[(size_t)p.w_w[e]];
      uint64_t key =
          ((uint64_t)(uint32_t)p.w_ip[e] << 32) | (uint32_t)gw;
      bool fresh;
      int64_t& slot = pos.probe(key, &fresh);
      if (fresh) {
        slot = (int64_t)w_c.size();
        w_ip.push_back(p.w_ip[e]);
        w_w.push_back(gw);
        w_c.push_back(p.w_c[e]);
      } else {
        w_c[(size_t)slot] += p.w_c[e];
      }
    }
    passes[(size_t)k].reset();
  }
  h->wc_ip = std::move(w_ip);
  h->wc_word = std::move(w_w);
  h->wc_cnt = std::move(w_c);
  h->merge_ns += now_ns() - t0;
  return 0;
}

const int32_t* dfz_top(void* h) { return ((Dfz*)h)->top.data(); }

const int32_t* dfz_ids(void* hv, int which) {
  Dfz* h = (Dfz*)hv;
  switch (which) {
    case 0: return h->ip_id.data();
    case 1: return h->dom_id.data();
    case 2: return h->sub_id.data();
    case 3: return h->word_id.data();
    default: return nullptr;
  }
}

static Interner& dtable_of(void* hv, int which) {
  Dfz* h = (Dfz*)hv;
  switch (which) {
    case 0: return h->ips;
    case 1: return h->domains;
    case 2: return h->subdomains;
    default: return h->words;
  }
}
int64_t dfz_table_count(void* hv, int which) {
  return (int64_t)dtable_of(hv, which).arena.size();
}
const char* dfz_table_blob(void* hv, int which) {
  Interner& t = dtable_of(hv, which);
  t.build_export();
  return t.blob.data();
}
int64_t dfz_table_blob_len(void* hv, int which) {
  Interner& t = dtable_of(hv, which);
  t.build_export();
  return (int64_t)t.blob.size();
}
const int64_t* dfz_table_offsets(void* hv, int which) {
  Interner& t = dtable_of(hv, which);
  t.build_export();
  return t.offsets.data();
}

const char* dfz_rows_blob(void* hv) {
  Dfz* h = (Dfz*)hv;
  return h->spill ? nullptr : h->rows.data();  // spilled: read the file
}
int64_t dfz_rows_blob_len(void* hv) {
  Dfz* h = (Dfz*)hv;
  return h->spill ? h->spill_len : (int64_t)h->rows.size();
}
const int64_t* dfz_row_offsets(void* hv) {
  return ((Dfz*)hv)->row_off.data();
}

int64_t dfz_wc_len(void* hv) { return (int64_t)((Dfz*)hv)->wc_cnt.size(); }
const int32_t* dfz_wc_ip(void* hv) { return ((Dfz*)hv)->wc_ip.data(); }
const int32_t* dfz_wc_word(void* hv) { return ((Dfz*)hv)->wc_word.data(); }
const int64_t* dfz_wc_count(void* hv) { return ((Dfz*)hv)->wc_cnt.data(); }

}  // extern "C"
