// Native DNS featurizer — the C++ fast path for the DNS "pre" stage
// (dns_pre_lda.scala featurization, reimplemented in
// oni_ml_tpu/features/dns.py).  This is the stage the reference's
// authors sized a 62-executor x 12-core Spark cluster for
// (dns_pre_lda.scala:1-2, SURVEY.md §6).
//
// Split of responsibilities with Python (features/native_dns.py), same
// shape as the flow featurizer:
//   pass A (ingest_*): row filtering (8 fields), unix_tstamp/frame_len
//     numeric extraction, subdomain extraction (reverse-DNS +
//     country-code TLD handling), Shannon entropy, interning of
//     client IPs / domains / subdomains / qry_type / qry_rcode.
//   cuts: Python computes the five ECDF cut lists (deciles over
//     tstamp/frame_len, quintiles over the positive subsets) with
//     quantiles.ecdf_cuts — single implementation of the quantile rule.
//   pass B (finish): binning, whitelist flag, word construction
//     ("top_blen_btime_bsub_bent_bper_type_rcode"), first-seen-order
//     per-client word counts (dns_pre_lda.scala:330).
//
// Rows are exchanged and stored with the ASCII unit separator \x1f so
// parquet-sourced fields containing commas (frame_time!) survive; CSV
// files are split on ',' at ingest and re-joined with \x1f.
//
// Entropy matches Python bit-for-bit: character counts accumulate in
// first-seen order (Counter's iteration order) and the sum uses the
// same -(c/n)*log2(c/n) expression, so identical libm gives identical
// doubles.  Known divergence: characters are bytes here, codepoints in
// Python — identical for the ASCII/punycode names DNS carries.

#include "common.h"

#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <string>
#include <string_view>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ISO country-code TLDs, verbatim from dns_pre_lda.scala:180 (including
// the stray empty string and "krd") — mirrors features/dns.py.
using oni::Interner;
using oni::to_double;
using oni::bin_of;
using oni::append_int;

const char* kCountryCodes =
    "ac ad ae af ag ai al am an ao aq ar as at au aw ax az ba bb bd be bf bg "
    "bh bi bj bm bn bo bq br bs bt bv bw by bz ca cc cd cf cg ch ci ck cl cm "
    "cn co cr cu cv cw cx cy cz de dj dk dm do dz ec ee eg eh er es et eu fi "
    "fj fk fm fo fr ga gb gd ge gf gg gh gi gl gm gn gp gq gr gs gt gu gw gy "
    "hk hm hn hr ht hu id ie il im in io iq ir is it je jm jo jp ke kg kh ki "
    "km kn kp kr krd kw ky kz la lb lc li lk lr ls lt lu lv ly ma mc md me "
    "mg mh mk ml mm mn mo mp mq mr ms mt mu mv mw mx my mz na nc ne nf ng ni "
    "nl no np nr nu nz om pa pe pf pg ph pk pl pm pn pr ps pt pw py qa re ro "
    "rs ru rw sa sb sc sd se sg sh si sj sk sl sm sn so sr ss st su sv sx sy "
    "sz tc td tf tg th tj tk tl tm tn to tp tr tt tv tw tz ua ug uk us uy uz "
    "va vc ve vg vi vn vu wf ws ye yt za zm zw";

const std::unordered_set<std::string>& country_codes() {
  static const std::unordered_set<std::string>* set = [] {
    auto* s = new std::unordered_set<std::string>;
    const char* p = kCountryCodes;
    while (*p) {
      const char* q = p;
      while (*q && *q != ' ') q++;
      s->emplace(p, (size_t)(q - p));
      p = *q ? q + 1 : q;
    }
    s->emplace("");  // the reference set contains the empty string
    return s;
  }();
  return *set;
}

// Shannon entropy with Python's exact summation: counts in first-seen
// character order (Counter iteration order) and CPython 3.12+ builtin
// sum()'s Neumaier compensated accumulation (Python/bltinmodule.c) —
// plain left-to-right accumulation differs in the last ulp.
double entropy_of(std::string_view s) {
  if (s.empty()) return 0.0;
  int32_t count[256] = {0};
  unsigned char order[256];
  int n_distinct = 0;
  for (unsigned char c : s) {
    if (count[c]++ == 0) order[n_distinct++] = c;
  }
  double n = (double)s.size();
  double hi = 0.0, comp = 0.0;
  for (int i = 0; i < n_distinct; i++) {
    double p = (double)count[order[i]] / n;
    double x = -(p)*log2(p);
    double t = hi + x;
    if (fabs(hi) >= fabs(x))
      comp += (hi - t) + x;
    else
      comp += (x - t) + hi;
    hi = t;
  }
  return hi + comp;
}

constexpr int NCOLS = 8;
// Field indices (dns_pre_lda.scala:149; features/dns.py DNS_COLUMNS).
constexpr int C_TSTAMP = 1, C_FLEN = 2, C_IPDST = 3, C_QNAME = 4;
constexpr int C_QTYPE = 6, C_QRCODE = 7;
constexpr char SEP = '\x1f';

struct Dfz {
  std::string rows;                   // \x1f-joined fields, rows appended
  FILE* spill = nullptr;              // when set, rows stream here
  int64_t spill_len = 0;              // instead of the in-RAM blob
  bool spill_err = false;             // short write (ENOSPC etc.)
  std::string rowbuf;                 // reused per-row join buffer
  std::vector<int64_t> row_off{0};
  std::vector<double> tstamp_, flen_, entropy_;
  std::vector<int32_t> sublen_, nparts_;
  Interner ips, domains, subdomains, qtypes, qrcodes;
  std::vector<int32_t> ip_id, dom_id, sub_id, qtype_id, qrcode_id;
  int64_t num_raw = -1;
  // A CSV-sourced field containing the \x1f transport separator would
  // split into extra columns when the stored rows blob is re-split on
  // the Python side; flag it so the caller can discard this handle and
  // re-run through the pure-Python path instead of emitting misaligned
  // results rows.
  bool unsafe = false;

  // finish() outputs
  std::vector<int32_t> top;
  Interner words;
  std::vector<int32_t> word_id;
  std::vector<int32_t> wc_ip, wc_word;
  std::vector<int64_t> wc_cnt;

  std::string error;

  void add_row(const std::string_view* f) {
    if (spill) {
      // Stored rows are only re-read at emit time; streaming them to
      // the spill file keeps RSS bounded by the numeric/interned
      // arrays.  Short writes must surface as errors, not as offsets
      // past the end of the file.
      rowbuf.clear();
      for (int i = 0; i < NCOLS; i++) {
        if (i) rowbuf += SEP;
        rowbuf.append(f[i].data(), f[i].size());
      }
      if (fwrite(rowbuf.data(), 1, rowbuf.size(), spill)
          != rowbuf.size()) {
        spill_err = true;
        error = "short write to rows spill file (disk full?)";
      }
      spill_len += (int64_t)rowbuf.size();
      row_off.push_back(spill_len);
    } else {
      for (int i = 0; i < NCOLS; i++) {
        if (i) rows += SEP;
        rows.append(f[i].data(), f[i].size());
      }
      row_off.push_back((int64_t)rows.size());
    }

    tstamp_.push_back(to_double(f[C_TSTAMP]));
    flen_.push_back(to_double(f[C_FLEN]));
    ip_id.push_back(ips.intern(f[C_IPDST]));
    qtype_id.push_back(qtypes.intern(f[C_QTYPE]));
    qrcode_id.push_back(qrcodes.intern(f[C_QRCODE]));

    // extract_subdomain (dns_pre_lda.scala:185-220 / features/dns.py).
    std::string_view url = f[C_QNAME];
    std::vector<std::string_view> parts;
    size_t start = 0;
    for (size_t i = 0; i <= url.size(); i++) {
      if (i == url.size() || url[i] == '.') {
        parts.push_back(url.substr(start, i - start));
        start = i + 1;
      }
    }
    while (parts.size() > 1 && parts.back().empty()) parts.pop_back();
    size_t n = parts.size();
    std::string_view domain = "None";
    std::string sub = "None";
    bool is_ip = n > 2 && parts[n - 1] == "arpa" && parts[n - 2] == "in-addr";
    if (n > 2 && !is_ip) {
      bool cc = country_codes().count(std::string(parts[n - 1])) > 0;
      size_t keep = cc ? n - 3 : n - 2;
      domain = parts[keep];
      if (keep >= 1) {
        sub.clear();
        for (size_t i = 0; i < keep; i++) {
          if (i) sub += '.';
          sub.append(parts[i].data(), parts[i].size());
        }
      } else if (!cc) {
        sub.clear();  // unreachable (keep = n-2 >= 1 when n > 2)
      }
    }
    dom_id.push_back(domains.intern(domain));
    sub_id.push_back(subdomains.intern(sub));
    sublen_.push_back(sub != "None" ? (int32_t)sub.size() : 0);
    nparts_.push_back((int32_t)n);
    entropy_.push_back(entropy_of(sub));
  }

  // Split a line on `sep`; keep iff exactly 8 fields.
  void add_line(std::string_view line, char sep) {
    // A CSV-sourced \x1f would re-split the stored rows blob.  An
    // embedded lone '\r' is fine here: rows are recovered by offsets,
    // not delimiters, and the Python fallback reader uses the same
    // line semantics (split on '\n', strip one trailing '\r'), so both
    // engines preserve it in the field.
    if (sep != SEP && line.find(SEP) != std::string_view::npos)
      unsafe = true;
    std::string_view f[NCOLS];
    int nf = 0;
    size_t start = 0;
    for (size_t i = 0; i <= line.size(); i++) {
      if (i == line.size() || line[i] == sep) {
        if (nf < NCOLS) f[nf] = line.substr(start, i - start);
        nf++;
        start = i + 1;
      }
    }
    if (nf == NCOLS) add_row(f);
  }

  void ingest(const char* buf, int64_t len, char sep, bool skip_empty) {
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
      const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
      const char* stop = nl ? nl : end;
      const char* s2 = stop;
      if (s2 > p && s2[-1] == '\r') s2--;
      std::string_view line(p, (size_t)(s2 - p));
      if (!(skip_empty && line.empty())) add_line(line, sep);
      p = nl ? nl + 1 : end;
    }
  }
};

}  // namespace

extern "C" {

void* dfz_create() { return new Dfz(); }
void dfz_destroy(void* hv) {
  Dfz* h = (Dfz*)hv;
  if (h->spill) fclose(h->spill);
  delete h;
}
const char* dfz_error(void* h) { return ((Dfz*)h)->error.c_str(); }

// Route stored rows to `path` instead of RAM.  Must be called before
// any ingest — row offsets are absolute positions in ONE store, so
// retargeting mid-run (or after in-RAM rows exist) would make them
// read past EOF / wrong bytes at emit.  -1 with dfz_error set on
// misuse or when the file can't open.
int dfz_set_spill(void* hv, const char* path) {
  Dfz* h = (Dfz*)hv;
  if (!h->tstamp_.empty() || h->spill) {
    h->error = "dfz_set_spill must be called once, before any ingest";
    return -1;
  }
  h->spill = fopen(path, "wb");
  if (!h->spill) {
    h->error = std::string("cannot open spill file ") + path;
    return -1;
  }
  return 0;
}

// Returns the spilled byte count, or -1 when any write/flush failed.
int64_t dfz_spill_flush(void* hv) {
  Dfz* h = (Dfz*)hv;
  if (h->spill) {
    if (fflush(h->spill) != 0 || ferror(h->spill)) {
      h->spill_err = true;
      if (h->error.empty())
        h->error = "flush of rows spill file failed (disk full?)";
    }
  }
  return h->spill_err ? -1 : h->spill_len;
}

int64_t dfz_ingest_csv_file(void* hv, const char* path, int skip_header) {
  Dfz* h = (Dfz*)hv;
  bool skipping = skip_header != 0;
  bool ok = oni::stream_file(
      path, h->error, [h, &skipping](const char* p, int64_t n) {
        if (skipping) {
          const char* nl = (const char*)memchr(p, '\n', (size_t)n);
          if (!nl) return;  // header longer than this buffer
          skipping = false;
          n -= (nl + 1 - p);
          p = nl + 1;
        }
        h->ingest(p, n, ',', /*skip_empty=*/true);
      });
  return (ok && !h->spill_err) ? (int64_t)h->tstamp_.size() : -1;
}

// Rows pre-split by the caller (parquet, feedback): fields joined by
// \x1f, rows by \n.
int64_t dfz_ingest_rows(void* hv, const char* buf, int64_t len) {
  Dfz* h = (Dfz*)hv;
  h->ingest(buf, len, SEP, /*skip_empty=*/true);
  return h->spill_err ? -1 : (int64_t)h->tstamp_.size();
}

int dfz_unsafe(void* hv) { return ((Dfz*)hv)->unsafe ? 1 : 0; }

void dfz_mark_raw(void* hv) {
  Dfz* h = (Dfz*)hv;
  h->num_raw = (int64_t)h->tstamp_.size();
}
int64_t dfz_num_raw(void* hv) {
  Dfz* h = (Dfz*)hv;
  return h->num_raw >= 0 ? h->num_raw : (int64_t)h->tstamp_.size();
}
int64_t dfz_num_events(void* hv) {
  return (int64_t)((Dfz*)hv)->tstamp_.size();
}

const double* dfz_tstamp(void* h) { return ((Dfz*)h)->tstamp_.data(); }
const double* dfz_frame_len(void* h) { return ((Dfz*)h)->flen_.data(); }
const double* dfz_entropy(void* h) { return ((Dfz*)h)->entropy_.data(); }
const int32_t* dfz_sublen(void* h) { return ((Dfz*)h)->sublen_.data(); }
const int32_t* dfz_nparts(void* h) { return ((Dfz*)h)->nparts_.data(); }

// top_blob: '\n'-joined whitelisted base-domain names (load_top_domains
// output), decoded into a set for the flag pass.
int dfz_finish(void* hv, const double* tc, int ntc, const double* lc,
               int nlc, const double* sc, int nsc, const double* ec, int nec,
               const double* pc, int npc, const char* top_blob,
               int64_t top_len) {
  Dfz* h = (Dfz*)hv;
  size_t n = h->tstamp_.size();

  std::unordered_set<std::string_view> top_set;
  const char* p = top_blob;
  const char* end = top_blob + top_len;
  while (p < end) {
    const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
    const char* stop = nl ? nl : end;
    if (stop > p) top_set.emplace(p, (size_t)(stop - p));
    p = nl ? nl + 1 : end;
  }
  // Whitelist flag per unique domain, not per row.
  std::vector<int32_t> dom_top(h->domains.arena.size());
  for (size_t i = 0; i < h->domains.arena.size(); i++) {
    const std::string& d = h->domains.arena[i];
    dom_top[i] = d == "intel" ? 2 : (top_set.count(d) ? 1 : 0);
  }

  h->top.resize(n);
  h->word_id.resize(n);

  oni::FlatMap64 pos(n / 2);
  std::vector<int32_t> w_ip, w_w;
  std::vector<int64_t> w_c;

  // The word is a pure function of (top, 5 bins, qtype, qrcode); unique
  // combinations number far below the row count, so cache the interned
  // id behind a packed integer key and skip the per-row string build.
  // Packing limits (bins < 256, interner ids < 2048, top in 0..3) hold
  // for any real day; rows beyond them fall back to building the word.
  oni::FlatMap64 word_cache;

  std::string word;
  for (size_t i = 0; i < n; i++) {
    int bt = bin_of(h->tstamp_[i], tc, ntc);
    int bl = bin_of((double)h->flen_[i], lc, nlc);
    int bs = bin_of((double)h->sublen_[i], sc, nsc);
    int be = bin_of(h->entropy_[i], ec, nec);
    int bp = bin_of((double)h->nparts_[i], pc, npc);
    int tp = dom_top[(size_t)h->dom_id[i]];
    h->top[i] = tp;

    int32_t qt = h->qtype_id[i], qr = h->qrcode_id[i];
    bool cacheable =
        (unsigned)bt < 256 && (unsigned)bl < 256 && (unsigned)bs < 256 &&
        (unsigned)be < 256 && (unsigned)bp < 256 && (unsigned)tp < 4 &&
        (uint32_t)qt < 2048 && (uint32_t)qr < 2048;
    uint64_t wkey = 0;
    int64_t* wslot = nullptr;
    bool fresh = true;
    if (cacheable) {
      wkey = ((uint64_t)tp << 62) | ((uint64_t)bt << 54) |
             ((uint64_t)bl << 46) | ((uint64_t)bs << 38) |
             ((uint64_t)be << 30) | ((uint64_t)bp << 22) |
             ((uint64_t)(uint32_t)qt << 11) | (uint64_t)(uint32_t)qr;
      if (wkey != oni::FlatMap64::EMPTY)
        wslot = &word_cache.probe(wkey, &fresh);
    }
    int32_t wid;
    if (!fresh) {
      wid = (int32_t)*wslot;
    } else {
      // word = top_blen_btime_bsub_bent_bper_type_rcode
      // (dns_pre_lda.scala:320-327; raw type/rcode field text).
      word.clear();
      append_int(word, tp);
      word += '_';
      append_int(word, bl);
      word += '_';
      append_int(word, bt);
      word += '_';
      append_int(word, bs);
      word += '_';
      append_int(word, be);
      word += '_';
      append_int(word, bp);
      word += '_';
      word += h->qtypes.arena[(size_t)h->qtype_id[i]];
      word += '_';
      word += h->qrcodes.arena[(size_t)h->qrcode_id[i]];
      wid = h->words.intern(word);
      if (wslot) *wslot = wid;
    }
    h->word_id[i] = wid;

    uint64_t key = ((uint64_t)(uint32_t)h->ip_id[i] << 32) | (uint32_t)wid;
    int64_t& slot = pos.probe(key, &fresh);
    if (fresh) {
      slot = (int64_t)w_c.size();
      w_ip.push_back(h->ip_id[i]);
      w_w.push_back(wid);
      w_c.push_back(1);
    } else {
      w_c[(size_t)slot]++;
    }
  }
  h->wc_ip = std::move(w_ip);
  h->wc_word = std::move(w_w);
  h->wc_cnt = std::move(w_c);
  return 0;
}

const int32_t* dfz_top(void* h) { return ((Dfz*)h)->top.data(); }

const int32_t* dfz_ids(void* hv, int which) {
  Dfz* h = (Dfz*)hv;
  switch (which) {
    case 0: return h->ip_id.data();
    case 1: return h->dom_id.data();
    case 2: return h->sub_id.data();
    case 3: return h->word_id.data();
    default: return nullptr;
  }
}

static Interner& dtable_of(void* hv, int which) {
  Dfz* h = (Dfz*)hv;
  switch (which) {
    case 0: return h->ips;
    case 1: return h->domains;
    case 2: return h->subdomains;
    default: return h->words;
  }
}
int64_t dfz_table_count(void* hv, int which) {
  return (int64_t)dtable_of(hv, which).arena.size();
}
const char* dfz_table_blob(void* hv, int which) {
  Interner& t = dtable_of(hv, which);
  t.build_export();
  return t.blob.data();
}
int64_t dfz_table_blob_len(void* hv, int which) {
  Interner& t = dtable_of(hv, which);
  t.build_export();
  return (int64_t)t.blob.size();
}
const int64_t* dfz_table_offsets(void* hv, int which) {
  Interner& t = dtable_of(hv, which);
  t.build_export();
  return t.offsets.data();
}

const char* dfz_rows_blob(void* hv) {
  Dfz* h = (Dfz*)hv;
  return h->spill ? nullptr : h->rows.data();  // spilled: read the file
}
int64_t dfz_rows_blob_len(void* hv) {
  Dfz* h = (Dfz*)hv;
  return h->spill ? h->spill_len : (int64_t)h->rows.size();
}
const int64_t* dfz_row_offsets(void* hv) {
  return ((Dfz*)hv)->row_off.data();
}

int64_t dfz_wc_len(void* hv) { return (int64_t)((Dfz*)hv)->wc_cnt.size(); }
const int32_t* dfz_wc_ip(void* hv) { return ((Dfz*)hv)->wc_ip.data(); }
const int32_t* dfz_wc_word(void* hv) { return ((Dfz*)hv)->wc_word.data(); }
const int64_t* dfz_wc_count(void* hv) { return ((Dfz*)hv)->wc_cnt.data(); }

}  // extern "C"
