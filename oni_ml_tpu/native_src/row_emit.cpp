// Native CSV emit for the scoring stage (scoring/score.py).
//
// Profiling the score stage on a 400k-event day: the device dot
// products cost ~0.05s while Python row assembly — featurized_row()
// per kept event (blob slice, decode, split, list concat, str() per
// float) — cost ~1.8s, >90% of the stage (VERDICT r1 item 5; the stage
// it replaces is the reference's executor-side CSV write,
// flow_post_lda.scala:245-248).  This TU assembles the entire output
// buffer in one pass over the kept-row order instead.
//
// Inputs are the arena blobs/offset arrays and per-event numeric
// columns that NativeFlowFeatures / NativeDnsFeatures already hold as
// numpy arrays + bytes (features/native_flow.py, native_dns.py) — no
// featurizer handle needed, so this works on unpickled features too.
// Output bytes are BIT-IDENTICAL to the Python emit loop: jvm_double
// (common.h) reproduces str(float) exactly, integer columns print via
// to_chars, and string ordering/min-max pairing is bytewise like
// Python's str comparison (UTF-8 preserves code-point order).
//
// The returned buffer is heap-allocated; the caller frees it with
// emit_free.

#include "common.h"

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>

namespace {

using oni::append_int;
using oni::jvm_double;

inline std::string_view seg(const char* blob, const int64_t* off, int64_t i) {
  return std::string_view(blob + off[i], (size_t)(off[i + 1] - off[i]));
}

inline void append_i64(std::string& s, int64_t v) {
  char buf[24];
  auto [p, ec] = std::to_chars(buf, buf + sizeof(buf), v);
  (void)ec;
  s.append(buf, p);
}

char* to_heap(const std::string& s, int64_t* out_len) {
  char* buf = new char[s.size()];
  memcpy(buf, s.data(), s.size());
  *out_len = (int64_t)s.size();
  return buf;
}

}  // namespace

extern "C" {

void emit_free(char* buf) { delete[] buf; }

// Flow scored rows: for each event i in `order`, the raw comma-joined
// line + 8 featurized columns + src/dest scores, newline-terminated
// (NativeFlowFeatures.featurized_row + score_flow's emit).
char* flow_emit(
    const char* lines_blob, const int64_t* line_off,
    const char* ip_blob, const int64_t* ip_off,
    const char* word_blob, const int64_t* word_off,
    const int32_t* sip_id, const int32_t* dip_id,
    const int32_t* wp_id, const int32_t* sw_id, const int32_t* dw_id,
    const double* num_time, const int64_t* ibyt_bin,
    const int64_t* ipkt_bin, const int64_t* time_bin,
    const double* src_scores, const double* dest_scores,
    const int64_t* order, int64_t n_out, int64_t* out_len) {
  std::string out;
  out.reserve((size_t)n_out * 192);
  for (int64_t j = 0; j < n_out; j++) {
    int64_t i = order[j];
    out.append(seg(lines_blob, line_off, i));
    out += ',';
    out += jvm_double(num_time[i]);
    out += ',';
    append_i64(out, ibyt_bin[i]);
    out += ',';
    append_i64(out, ipkt_bin[i]);
    out += ',';
    append_i64(out, time_bin[i]);
    out += ',';
    out.append(seg(word_blob, word_off, wp_id[i]));
    out += ',';
    std::string_view s = seg(ip_blob, ip_off, sip_id[i]);
    std::string_view d = seg(ip_blob, ip_off, dip_id[i]);
    if (d < s) std::swap(s, d);
    out.append(s);
    out += ' ';
    out.append(d);
    out += ',';
    out.append(seg(word_blob, word_off, sw_id[i]));
    out += ',';
    out.append(seg(word_blob, word_off, dw_id[i]));
    out += ',';
    out += jvm_double(src_scores[i]);
    out += ',';
    out += jvm_double(dest_scores[i]);
    out += '\n';
  }
  return to_heap(out, out_len);
}

// DNS scored rows: the stored row fields (\x1f-joined) re-joined with
// ',' + 7 featurized columns + score (NativeDnsFeatures.featurized_row
// + score_dns's emit).
char* dns_emit(
    const char* rows_blob, const int64_t* row_off,
    const char* dom_blob, const int64_t* dom_off,
    const char* sub_blob, const int64_t* sub_off,
    const char* word_blob, const int64_t* word_off,
    const int32_t* dom_id, const int32_t* sub_id, const int32_t* word_id,
    const int64_t* sublen, const int64_t* nparts, const double* entropy,
    const int64_t* top, const double* scores,
    const int64_t* order, int64_t n_out, int64_t* out_len) {
  std::string out;
  out.reserve((size_t)n_out * 128);
  for (int64_t j = 0; j < n_out; j++) {
    int64_t i = order[j];
    size_t start = out.size();
    out.append(seg(rows_blob, row_off, i));
    // \x1f -> ',' as a plain byte loop: separators land every ~8
    // bytes in a DNS row, so a memchr-per-hit scan is SLOWER here
    // (measured 0.87s vs 0.69s on the 400k-event scoring stage —
    // per-call overhead dominates at that hit density).
    for (size_t q = start; q < out.size(); q++)
      if (out[q] == '\x1f') out[q] = ',';
    out += ',';
    out.append(seg(dom_blob, dom_off, dom_id[i]));
    out += ',';
    out.append(seg(sub_blob, sub_off, sub_id[i]));
    out += ',';
    append_i64(out, sublen[i]);
    out += ',';
    append_i64(out, nparts[i]);
    out += ',';
    out += jvm_double(entropy[i]);
    out += ',';
    append_i64(out, top[i]);
    out += ',';
    out.append(seg(word_blob, word_off, word_id[i]));
    out += ',';
    out += jvm_double(scores[i]);
    out += '\n';
  }
  return to_heap(out, out_len);
}

// Fused gather-dot for event scoring: out[i] = <theta[ip_idx[i]],
// p[w_idx[i]]> in float64, accumulated k=0..K-1 in index order —
// bit-identical to the sequential k-order fold (the reference's
// zip/map/sum).  NOT einsum: np.einsum's SIMD partial sums round in
// a different order in the last ulp (that is why score.py replaced
// it and the golden CSVs moved).  The
// numpy path materializes two [N, K] float64 gather temporaries
// (~1.6 GB at a 5M-event day) before the dot; this reads the two rows
// and writes one double per event.  flow_post_lda.scala:227-239's
// per-event Map lookup + dot, minus the lookups (ids are pre-resolved
// against the interned tables by score.py's O(unique) LUT).
// No FMA fusion (both build paths pass -ffp-contract=off globally):
// a fused multiply-add rounds once where numpy rounds twice, and the
// golden scoring bytes (str(score)) must not move.
void score_dot(
    const double* theta, const double* p, int64_t k,
    const int32_t* ip_idx, const int32_t* w_idx, int64_t n,
    double* out) {
  for (int64_t i = 0; i < n; i++) {
    const double* a = theta + (int64_t)ip_idx[i] * k;
    const double* b = p + (int64_t)w_idx[i] * k;
    double s = 0.0;
    for (int64_t j = 0; j < k; j++) s += a[j] * b[j];
    out[i] = s;
  }
}

// model.dat (LDA-C corpus): "N w1:c1 ... wN:cN" per document from the
// CSR arrays (formats.write_model_dat layout, lda_pre.py:84-94).  The
// Python writer built ~9.4M "w:c" fragments through a list — 9 s of a
// 5M-event day's corpus stage.
char* model_emit(
    const int64_t* doc_ptr, int64_t n_docs,
    const int32_t* word_idx, const int64_t* counts,
    int64_t* out_len) {
  std::string out;
  out.reserve((size_t)(n_docs ? doc_ptr[n_docs] : 0) * 12 + n_docs * 8);
  for (int64_t d = 0; d < n_docs; d++) {
    int64_t lo = doc_ptr[d], hi = doc_ptr[d + 1];
    append_i64(out, hi - lo);
    for (int64_t j = lo; j < hi; j++) {
      out += ' ';
      append_i64(out, word_idx[j]);
      out += ':';
      append_i64(out, counts[j]);
    }
    out += '\n';
  }
  return to_heap(out, out_len);
}

// word_counts file ("ip,word,count" one line per aggregated pair,
// formats.write_word_counts layout): built as one buffer from the
// interned string tables + the featurizer's aggregated id arrays.
// stage_pre previously materialized ~1.5M Python (str,str,int) tuples
// and wrote one line at a time — half the pre stage's wall-clock on a
// 2M-event day.
char* wc_emit(
    const char* ip_blob, const int64_t* ip_off,
    const char* word_blob, const int64_t* word_off,
    const int32_t* wc_ip, const int32_t* wc_word, const int64_t* wc_count,
    int64_t n, int64_t* out_len) {
  std::string out;
  out.reserve((size_t)n * 48);
  for (int64_t i = 0; i < n; i++) {
    out.append(seg(ip_blob, ip_off, wc_ip[i]));
    out += ',';
    out.append(seg(word_blob, word_off, wc_word[i]));
    out += ',';
    append_i64(out, wc_count[i]);
    out += '\n';
  }
  return to_heap(out, out_len);
}

}  // extern "C"
