from .allreduce import (
    Collective,
    PeerFailure,
    get_collective,
    reduce_partials,
    tree_combine,
)
from .membership import (
    FileKVClient,
    HeartbeatPublisher,
    MembershipClient,
    kv_list,
)
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    beta_sharding,
    initialize_distributed,
    is_local_mesh,
    local_mesh,
    make_mesh,
    mesh_from_spec,
    replicated,
    vocab_sharding,
)
from .shard_plan import ShardPlan, plan_shards, resolve_em_shards
from .tiers import sync_capacity_tier
from .sharded import (
    make_data_parallel_e_step,
    make_sharded_score_fn,
    make_vocab_sharded_dense_e_step,
    make_vocab_sharded_fns,
    pad_vocab,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "mesh_from_spec",
    "local_mesh",
    "is_local_mesh",
    "initialize_distributed",
    "batch_sharding",
    "beta_sharding",
    "replicated",
    "vocab_sharding",
    "Collective",
    "FileKVClient",
    "HeartbeatPublisher",
    "MembershipClient",
    "kv_list",
    "PeerFailure",
    "get_collective",
    "reduce_partials",
    "tree_combine",
    "ShardPlan",
    "plan_shards",
    "resolve_em_shards",
    "sync_capacity_tier",
    "make_data_parallel_e_step",
    "make_sharded_score_fn",
    "make_vocab_sharded_dense_e_step",
    "make_vocab_sharded_fns",
    "pad_vocab",
]
