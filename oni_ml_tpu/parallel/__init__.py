from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    beta_sharding,
    initialize_distributed,
    make_mesh,
    mesh_from_spec,
    replicated,
    vocab_sharding,
)
from .sharded import (
    make_data_parallel_e_step,
    make_sharded_score_fn,
    make_vocab_sharded_dense_e_step,
    make_vocab_sharded_fns,
    pad_vocab,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "mesh_from_spec",
    "initialize_distributed",
    "batch_sharding",
    "beta_sharding",
    "replicated",
    "vocab_sharding",
    "make_data_parallel_e_step",
    "make_sharded_score_fn",
    "make_vocab_sharded_dense_e_step",
    "make_vocab_sharded_fns",
    "pad_vocab",
]
