"""Deterministic document shard plans for pod-scale distributed EM.

The reference's entire reason for MPI was splitting the corpus across
nodes (README.md:121: 20 ranks, one contiguous document block each).
This module is that split made explicit and *rank-count invariant*: a
plan is derived from the corpus alone — a fixed number of contiguous
document shards (power of two, default 8) that does NOT change with the
process count — and processes own contiguous, aligned runs of shards.

Why the shard count is corpus-derived rather than ``num_shards ==
num_procs``: the cross-shard sufficient-statistics reduction
(parallel/allreduce.py ``tree_combine``) is a fixed pairwise tree over
the *shard* axis.  Because the shards and the tree are identical no
matter how many processes execute them, a 2-rank run reduces the exact
same f32 partials in the exact same association order as a 1-rank run —
which is what makes the coordinator's artifacts byte-identical across
rank counts (the distributed-EM acceptance contract,
tests/test_multihost.py).  Per-shard E-step results are themselves
bitwise reproducible: each shard is bucketed and batched independently,
so its compiled programs and inputs do not depend on which rank runs it.

Alignment: when ``num_procs`` divides ``num_shards`` and both are powers
of two, every rank's contiguous shard run is a node of the canonical
reduction tree, so ranks exchange ONE subtree root each; otherwise they
exchange per-shard partials (correct, more bytes — ``aligned`` tells the
reducer which).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

# Default shard count: a power of two small enough that the per-shard
# batching overhead is negligible and large enough to cover the rank
# counts a CPU-process or small-pod run plausibly uses (1/2/4/8 all
# divide it, keeping the subtree-root exchange aligned).
DEFAULT_EM_SHARDS = 8


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def resolve_em_shards(config_value: int = 0, num_procs: int = 1) -> int:
    """The run's shard count: an explicit LDAConfig.em_shards (or
    ONI_ML_TPU_EM_SHARDS env) wins; 0 = auto — DEFAULT_EM_SHARDS, grown
    to the next power of two >= num_procs when more processes than
    default shards show up.  Byte-identity across rank counts holds
    exactly when the two runs resolve the SAME shard count — which auto
    guarantees for any rank counts <= DEFAULT_EM_SHARDS."""
    env = os.environ.get("ONI_ML_TPU_EM_SHARDS", "")
    if env:
        config_value = int(env)
    if config_value:
        if config_value < num_procs:
            raise ValueError(
                f"em_shards={config_value} < {num_procs} processes: every "
                "process must own at least one document shard"
            )
        return int(config_value)
    return max(DEFAULT_EM_SHARDS, _next_pow2(num_procs))


@dataclass(frozen=True)
class ShardPlan:
    """Contiguous document shards + their rank assignment.

    bounds[s] = (start, stop) document range of shard s; the bounds
    partition range(num_docs) in order.  owners[s] is the rank that
    computes shard s this run — the only field that depends on the
    process count; the bounds (and therefore every per-shard
    computation and the reduction tree) do not.
    """

    num_docs: int
    num_procs: int
    bounds: tuple          # tuple[(start, stop), ...]
    owners: tuple          # tuple[int, ...] — shard -> rank

    @property
    def num_shards(self) -> int:
        return len(self.bounds)

    @property
    def aligned(self) -> bool:
        """True when every rank's shard run is a node of the canonical
        pairwise reduction tree (equal contiguous runs, powers of two)
        — the reducer then exchanges one subtree root per rank instead
        of per-shard partials."""
        s, p = self.num_shards, self.num_procs
        return _is_pow2(s) and _is_pow2(p) and s % p == 0

    def owned(self, rank: int) -> list:
        """Shard indices rank computes, in shard order."""
        return [s for s, o in enumerate(self.owners) if o == rank]

    def record(self, rank: int) -> dict:
        """Journal form ({"kind": "shard_plan"} payload) — enough to
        reconstruct the exact split a run trained under post-hoc."""
        owned = self.owned(rank)
        return {
            "kind": "shard_plan",
            "num_docs": self.num_docs,
            "num_procs": self.num_procs,
            "num_shards": self.num_shards,
            "bounds": [list(b) for b in self.bounds],
            "rank": rank,
            "owned_shards": owned,
            "local_docs": sum(
                self.bounds[s][1] - self.bounds[s][0] for s in owned
            ),
            "aligned": self.aligned,
        }


def plan_shards(num_docs: int, num_procs: int = 1,
                num_shards: int = 0) -> ShardPlan:
    """Build the deterministic plan: `num_shards` contiguous document
    shards (sizes differing by at most one, larger shards first) owned
    by `num_procs` ranks in contiguous runs (shard runs per rank also
    differ by at most one).  Pure arithmetic — identical on every rank
    and across rank counts for the same (num_docs, num_shards)."""
    if num_docs < 0:
        raise ValueError(f"num_docs must be >= 0, got {num_docs}")
    if num_procs < 1:
        raise ValueError(f"num_procs must be >= 1, got {num_procs}")
    s = num_shards or resolve_em_shards(0, num_procs)
    if s < num_procs:
        raise ValueError(
            f"{s} shards cannot cover {num_procs} processes"
        )
    base, rem = divmod(num_docs, s)
    bounds = []
    start = 0
    for i in range(s):
        n = base + (1 if i < rem else 0)
        bounds.append((start, start + n))
        start += n
    pb, prem = divmod(s, num_procs)
    owners = []
    for r in range(num_procs):
        owners.extend([r] * (pb + (1 if r < prem else 0)))
    return ShardPlan(
        num_docs=num_docs, num_procs=num_procs,
        bounds=tuple(bounds), owners=tuple(owners),
    )
