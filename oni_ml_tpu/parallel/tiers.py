"""Rank-synchronized vocabulary capacity tiers for distributed
window training.

The continuous window (dataplane/window.py) pads its vocabulary to
power-of-two capacity tiers so vocab churn inside a tier never changes
a compiled [K, V] shape.  Distributed refreshes add a cross-rank
hazard: each rank's window grows its vocabulary from the slices IT
ingested, so two ranks can legally sit in different tiers — and the
distributed EM driver's allreduce ships [V, K] sufficient statistics
whose byte layout every rank must agree on, while the parity assert
requires bit-identical models.  A rank-divergent tier is therefore not
a performance bug but a correctness failure.

`sync_capacity_tier` closes it: every rank contributes its LOCAL
requirement (live vocab under its floor), the maximum wins, and every
rank reserves that tier in its window (`CorpusWindow.
reserve_capacity`) BEFORE the snapshot — so all ranks snapshot, build
trainers, and compile at the same [K, V].  Tiers are monotone
(capacity never shrinks while a service runs), so one slow rank can
only ever pull the fleet UP a tier, never bounce it.
"""

from __future__ import annotations


def sync_capacity_tier(collective, local_vocab: int, floor: int, *,
                       tag: str, journal=None) -> int:
    """Agree on one pow2 vocab capacity tier across all ranks.

    Returns the agreed capacity (== the local one when single-process
    or already in the max tier).  Journals `{"kind": "tier_sync"}`
    when the sync actually RAISED this rank's tier — the event that
    explains a retrace-free run suddenly minting a new program family.
    """
    from ..dataplane.window import pow2_capacity

    local = pow2_capacity(int(local_vocab), int(floor))
    if collective is None or collective.num_processes == 1:
        return local
    tiers = collective.allgather_obj(local, tag)
    agreed = max(int(t) for t in tiers)
    if agreed != local and journal is not None:
        journal = getattr(journal, "journal", journal)
        try:
            journal.append({
                "kind": "tier_sync", "tag": tag, "local": local,
                "agreed": agreed, "rank": collective.rank,
                "nprocs": collective.num_processes,
            })
        except Exception:
            pass     # telemetry must never take down the service
    return agreed
