"""Sharded E/M steps: shard_map wrappers over ops/estep building blocks.

Two execution plans, both SPMD over the (data, model) mesh:

1. **Data-parallel** (`make_data_parallel_e_step`) — the direct analogue
   of the reference's 20-rank MPI document sharding (README.md:121):
   batches shard over `data`, beta replicates, suff-stats/likelihood
   `psum` over ICI.  This is the default whenever beta fits per device.

2. **Vocab-sharded** (`make_vocab_sharded_fns`) — for huge-V corpora
   (BASELINE.json config 4: high-cardinality DNS vocab).  beta [K, V] and
   suff-stats [V, K] shard their vocabulary axis over `model`; each shard
   gathers the beta slab for the tokens whose words it owns and a
   `psum` over `model` assembles the full [B, L, K] slab (one collective
   per batch — the slab, not beta, so HBM never holds another full copy).
   The fixed point then runs identically on every model shard; suff-stats
   scatter only into the locally-owned vocab slice.  The M-step
   renormalizes with a `psum` of per-topic totals over `model`.

Both plans compose: a (8, 4) mesh runs 8-way document parallelism with
4-way vocabulary sharding.

Scope since the distributed-EM restructure: these shard_map plans are
HOST-LOCAL — the mesh spans one process's devices
(`parallel.local_mesh`), and their psums ride that host's ICI only.
Cross-PROCESS reduction is no longer expressed here at all: one
global-mesh SPMD program spanning processes is unexecutable on the CPU
runtime and forced the sparse engine dense, so the process dimension
moved to the explicit sufficient-statistics allreduce
(`parallel/allreduce.py`) over corpus-derived document shards
(`parallel/shard_plan.py`).  A multi-host run composes the two layers:
shard_map within the host, collective across hosts.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:                                   # jax >= 0.5 exports it top-level
    shard_map = jax.shard_map
except AttributeError:                 # 0.4.x keeps it in experimental
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        """0.4.x compat: the varying-mesh-axes check is spelled
        check_rep there, and its replication checker has no rule for
        while_loop — which every in-package E-step kernel contains —
        so when the caller didn't ask for the check it is disabled
        (the documented workaround; purely a static verification,
        numerics are unchanged)."""
        kw.setdefault("check_rep",
                      False if check_vma is None else check_vma)
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)


def _pcast_varying(x, axis):
    """lax.pcast(to="varying") where the jax version has it; 0.4.x has
    no varying-axes type system, so the value passes through unchanged
    (the compat shard_map above runs with the check disabled there)."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axis, to="varying")

from ..ops import estep
from ..ops.stop import fp_continue
from .mesh import DATA_AXIS, MODEL_AXIS


def _fresh_warm_fill(log_beta, word_idx):
    """Default (gamma_prev, warm) for fresh-start calls: zeros that are
    never read back (warm=0).  One definition so the sharded plans
    cannot drift on the fresh-start convention."""
    return (
        jnp.zeros((word_idx.shape[0], log_beta.shape[0]), log_beta.dtype),
        jnp.asarray(0, jnp.int32),
    )


def make_data_parallel_e_step(mesh: Mesh):
    """e_step-compatible callable: inputs batch-sharded over `data`,
    outputs gamma sharded / reductions replicated."""

    def local(log_beta, alpha, word_idx, counts, doc_mask, gamma_prev,
              warm, var_max_iters, var_tol):
        res = estep.e_step(
            log_beta, alpha, word_idx, counts, doc_mask, var_max_iters,
            var_tol, gamma_prev=gamma_prev, warm=warm,
        )
        return estep.EStepResult(
            gamma=res.gamma,
            suff_stats=jax.lax.psum(res.suff_stats, DATA_AXIS),
            alpha_ss=jax.lax.psum(res.alpha_ss, DATA_AXIS),
            likelihood=jax.lax.psum(res.likelihood, DATA_AXIS),
            vi_iters=jax.lax.pmax(res.vi_iters, DATA_AXIS),
        )

    def wrapped(log_beta, alpha, word_idx, counts, doc_mask,
                var_max_iters, var_tol, gamma_prev=None, warm=None):
        if gamma_prev is None:
            gamma_prev, warm = _fresh_warm_fill(log_beta, word_idx)
        fn = shard_map(
            partial(local, var_max_iters=var_max_iters, var_tol=var_tol),
            mesh=mesh,
            in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS), P()),
            out_specs=estep.EStepResult(
                gamma=P(DATA_AXIS),
                suff_stats=P(),
                alpha_ss=P(),
                likelihood=P(),
                vi_iters=P(),
            ),
        )
        return fn(log_beta, alpha, word_idx, counts, doc_mask, gamma_prev,
                  warm)

    wrapped._oni_data_parallel = True  # lets the trainer's dense-mode
    wrapped._oni_warm_capable = True   # check recognize its own wrapper
    return wrapped


def make_data_parallel_dense_e_step(mesh: Mesh, wmajor: bool = False,
                                    precision: str = "f32"):
    """Dense-corpus E-step (ops/dense_estep.py) over batch-sharded dense
    counts: each data shard runs the MXU kernel on its local documents,
    suff-stats/likelihood psum over ICI — the dense analogue of
    make_data_parallel_e_step, so multi-chip runs keep the flagship
    kernel instead of falling back to the sparse path.

    `dense` is the full densified batch ([B, W] row-major or [W, B]
    W-major); the local batch is B / data_size, so dense feasibility
    (pick_block / pick_block_w) must be checked against the PER-SHARD
    batch by the caller.  gamma_prev/warm thread the warm-start state
    exactly as in the single-device path."""
    from ..ops import dense_estep

    batch_axis = 1 if wmajor else 0

    def local(log_beta, alpha, dense, doc_mask, gamma_prev, warm,
              var_max_iters, var_tol, interpret):
        res = dense_estep.e_step_dense(
            log_beta, alpha, dense, doc_mask,
            var_max_iters=var_max_iters, var_tol=var_tol,
            interpret=interpret, wmajor=wmajor,
            gamma_prev=gamma_prev, warm=warm, precision=precision,
        )
        return estep.EStepResult(
            gamma=res.gamma,
            suff_stats=jax.lax.psum(res.suff_stats, DATA_AXIS),
            alpha_ss=jax.lax.psum(res.alpha_ss, DATA_AXIS),
            likelihood=jax.lax.psum(res.likelihood, DATA_AXIS),
            vi_iters=jax.lax.pmax(res.vi_iters, DATA_AXIS),
        )

    dense_spec = (
        P(None, DATA_AXIS) if wmajor else P(DATA_AXIS, None)
    )

    def wrapped(log_beta, alpha, dense, doc_mask, gamma_prev, warm,
                var_max_iters, var_tol, interpret=False):
        if dense.shape[batch_axis] % mesh.shape[DATA_AXIS]:
            raise ValueError(
                f"batch {dense.shape[batch_axis]} not divisible by data "
                f"axis {mesh.shape[DATA_AXIS]}"
            )
        fn = shard_map(
            partial(local, var_max_iters=var_max_iters, var_tol=var_tol,
                    interpret=interpret),
            mesh=mesh,
            in_specs=(P(), P(), dense_spec, P(DATA_AXIS), P(DATA_AXIS),
                      P()),
            out_specs=estep.EStepResult(
                gamma=P(DATA_AXIS),
                suff_stats=P(),
                alpha_ss=P(),
                likelihood=P(),
                vi_iters=P(),
            ),
            # pallas_call's out_shape carries no varying-mesh-axes info,
            # so shard_map's vma check cannot see through it.
            check_vma=False,
        )
        return fn(log_beta, alpha, dense, doc_mask, gamma_prev, warm)

    return wrapped


def make_vocab_sharded_dense_e_step(mesh: Mesh, precision: str = "f32"):
    """Dense-corpus E-step with the VOCABULARY sharded over `model` and
    documents over `data` — BASELINE.json config 4 (high-cardinality DNS
    vocab, dns_pre_lda.scala:320-326) at MXU density.

    Each device owns C_l [B/d, W/m] (its doc rows x its vocab columns)
    and beta_l [K, W/m]; the densified corpus never exists whole on any
    chip, so huge-V corpora that blow the single-chip HBM budget shard
    down to fit.  Per fixed-point iteration the only collective is the
    gamma-update contraction s = psum_model(ratio_l @ beta_l^T) — a
    [B/d, K] array (K=20: a few KB), riding ICI — because q[b, w] and
    ratio[b, w] are local to the vocab shard that owns column w, while
    gamma/exp_et are replicated across the model axis (every shard in a
    model group computes them identically from the psum'd s, so no
    broadcast is ever materialized).  This mirrors the sparse
    vocab-sharded plan's slab psum (local_e_step above) but moves the
    arithmetic from gather/scatter to XLA matmuls on the MXU; at config-4
    scale the corpus streams from HBM each iteration regardless, so an
    XLA-level loop costs nothing over a Pallas kernel and composes with
    sharding for free.

    The batch trainer selects this plan automatically
    (models/lda.py _use_dense_vocab_sharded) when the trainer is
    vocab-sharded, dense_em allows it, and the per-device corpus slices
    fit the HBM budget; the per-EM-iteration semantics are pinned to the
    unwrapped dense kernel by
    tests/test_sharded.py::test_vocab_sharded_DENSE_e_step_parity and
    end-to-end by test_full_training_parity_vocab_sharded_dense.

    Semantics match ops/dense_estep.e_step_dense (same fresh init, same
    q + 1e-30 guard, same masked-delta stop, full-f32 tail with in-loop
    optional bf16 operand storage, warm start via gamma_prev/warm).
    Requirements: dense width == log_beta width, both divisible by the
    model-axis size; batch divisible by the data-axis size.  Pad the
    vocab with pad_vocab + LOG_ZERO beta columns — padded C columns are
    zero so every contraction over them is exact.
    """
    from jax.scipy.special import digamma, gammaln

    from ..ops import dense_estep

    d_sz, m_sz = mesh.shape[DATA_AXIS], mesh.shape[MODEL_AXIS]
    dense_estep._check_precision(precision)
    cast = dense_estep._cast_for(precision)

    def local(log_beta_l, alpha, c_l, doc_mask, gamma_prev, warm,
              var_max_iters, var_tol):
        k = log_beta_l.shape[0]
        beta_l = jnp.exp(log_beta_l)               # [K, W_l]
        beta_m = cast(beta_l)
        mask_col = doc_mask[:, None]
        # f32 accumulation: the corpus may be STORED bf16
        # (dense_estep.corpus_dtype) and is consumed via f32-promoting
        # ops throughout.
        n_d = jax.lax.psum(
            jnp.sum(c_l, axis=1, dtype=jnp.float32), MODEL_AXIS
        )                                          # [B_l]
        # Relative stop normalizer, identical across the model group
        # (n_d is psum'd), so the stop stays collective-consistent.
        inv_scale = 1.0 / (alpha + n_d / k)        # [B_l]

        def e_log_theta(gamma):
            return digamma(gamma) - digamma(gamma.sum(1, keepdims=True))

        def qmat(exp_et, b):
            return jax.lax.dot_general(
                exp_et, b, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) + 1e-30

        def body(state):
            gamma, it, delta_old, _ = state
            exp_et = jnp.exp(e_log_theta(gamma))   # [B_l, K] (replicated
            q = qmat(cast(exp_et), beta_m)         #  across model)
            ratio = c_l / q
            s = jax.lax.psum(                      # [B_l, K]: THE collective
                jax.lax.dot_general(
                    cast(ratio), beta_m, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ),
                MODEL_AXIS,
            )
            gamma_new = alpha + exp_et * s
            # gamma is bit-identical across the model group, so every
            # shard reaches the same stop decision — the psum inside the
            # loop stays collective-consistent.
            delta = jnp.max(
                jnp.mean(jnp.abs(gamma_new - gamma), axis=1)
                * inv_scale * doc_mask
            )
            return gamma_new, it + 1, delta, delta_old

        def cond(state):
            # var_tol or gated stagnation — the shared rule
            # (ops/stop.py), identical across the model group.
            _, it, delta, prev = state
            return fp_continue(it, delta, prev, var_max_iters, var_tol)

        fresh0 = alpha + (n_d / k)[:, None] + jnp.zeros(
            (c_l.shape[0], k), jnp.float32
        )
        gamma0 = jnp.where(warm != 0, gamma_prev, fresh0)
        # delta varies over `data` (each data row stops independently);
        # the initial scalar must carry the same varying-axes type.
        delta0 = _pcast_varying(
            jnp.asarray(jnp.inf, jnp.float32), DATA_AXIS
        )
        gamma, iters, _, _ = jax.lax.while_loop(
            cond, body,
            (gamma0, jnp.asarray(0, jnp.int32), delta0, delta0),
        )

        # Full-f32 tail off the converged gamma (dense-kernel semantics).
        e_lt = e_log_theta(gamma)
        exp_et = jnp.exp(e_lt)
        q = qmat(exp_et, beta_l)
        ratio = (c_l / q) * mask_col
        t_l = jax.lax.dot_general(                 # [K, W_l]
            exp_et * mask_col, ratio, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        suff_l = (beta_l * t_l).T                  # [W_l, K]
        # Token ELBO term spans the sharded vocab axis: psum over model.
        # The gamma-Dirichlet terms and alpha_ss are per-doc quantities
        # computed identically on every model shard: psum over data ONLY
        # (a model psum would count them m times).
        tok = jax.lax.psum(
            jnp.sum(c_l * jnp.log(q) * mask_col), MODEL_AXIS
        )
        core = jnp.sum(
            (
                jnp.sum((alpha - gamma) * e_lt + gammaln(gamma), axis=1)
                - gammaln(gamma.sum(axis=1))
            )
            * doc_mask
        )
        alpha_const = gammaln(k * alpha) - k * gammaln(alpha)
        ll = core + tok + doc_mask.sum() * alpha_const
        ass = jnp.sum(e_lt.sum(axis=1) * doc_mask)
        return estep.EStepResult(
            gamma=gamma,
            suff_stats=jax.lax.psum(suff_l, DATA_AXIS),
            alpha_ss=jax.lax.psum(ass, DATA_AXIS),
            likelihood=jax.lax.psum(ll, DATA_AXIS),
            vi_iters=jax.lax.pmax(iters, DATA_AXIS),
        )

    def wrapped(log_beta, alpha, dense, doc_mask, gamma_prev, warm,
                var_max_iters, var_tol):
        b, w = dense.shape
        if b % d_sz:
            raise ValueError(
                f"batch {b} not divisible by data axis {d_sz}"
            )
        if w % m_sz:
            raise ValueError(
                f"dense width {w} not divisible by model axis {m_sz} "
                "(pad with parallel.pad_vocab)"
            )
        if log_beta.shape[1] != w:
            raise ValueError(
                f"log_beta width {log_beta.shape[1]} != dense width {w} "
                "(pad log_beta with LOG_ZERO columns to match)"
            )
        fn = shard_map(
            partial(local, var_max_iters=var_max_iters, var_tol=var_tol),
            mesh=mesh,
            in_specs=(P(None, MODEL_AXIS), P(), P(DATA_AXIS, MODEL_AXIS),
                      P(DATA_AXIS), P(DATA_AXIS), P()),
            out_specs=estep.EStepResult(
                gamma=P(DATA_AXIS),
                suff_stats=P(MODEL_AXIS, None),
                alpha_ss=P(),
                likelihood=P(),
                vi_iters=P(),
            ),
        )
        return fn(log_beta, alpha, dense, doc_mask, gamma_prev, warm)

    return wrapped


def make_vocab_sharded_fns(mesh: Mesh):
    """Returns (e_step_fn, m_step_fn) with beta/suff-stats vocab-sharded
    over `model` and batches sharded over `data`.

    Global shapes stay [K, V] / [V, K]; shard_map sees per-device slices
    [K, V/m] / [V/m, K].  V must be divisible by the model-axis size
    (pad the vocabulary — padded words never appear in word_idx, so their
    suff-stats stay zero and m_step floors them to LOG_ZERO).
    """
    m = mesh.shape[MODEL_AXIS]

    def local_e_step(log_beta_l, alpha, word_idx, counts, doc_mask,
                     gamma_prev, warm, var_max_iters, var_tol):
        K, v_local = log_beta_l.shape
        shard = jax.lax.axis_index(MODEL_AXIS)
        offset = shard * v_local
        # Gather only locally-owned words, zero elsewhere; psum over the
        # model axis assembles the full [B, L, K] slab.
        local_idx = word_idx - offset
        owned = (local_idx >= 0) & (local_idx < v_local)
        safe_idx = jnp.clip(local_idx, 0, v_local - 1)
        slab_l = estep.gather_beta(log_beta_l, safe_idx)   # [B, L, K]
        slab_l = jnp.where(owned[..., None], slab_l, 0.0)
        beta_bt = jax.lax.psum(slab_l, MODEL_AXIS)

        gamma, iters = estep.fixed_point(
            beta_bt, alpha, counts, doc_mask, var_max_iters, var_tol,
            gamma_prev=gamma_prev, warm=warm,
        )
        phi_c, phinorm = estep.phi_weighted(beta_bt, gamma, counts, doc_mask)
        # Scatter only into the owned vocab slice.
        phi_c = jnp.where(owned[..., None], phi_c, 0.0)
        ss_l = estep.suff_stats(phi_c, safe_idx, v_local)  # [V/m, K]
        likelihood, alpha_ss = estep.batch_likelihood(
            gamma, phinorm, counts, alpha, doc_mask
        )
        return estep.EStepResult(
            gamma=gamma,
            suff_stats=jax.lax.psum(ss_l, DATA_AXIS),
            alpha_ss=jax.lax.psum(alpha_ss, DATA_AXIS),
            likelihood=jax.lax.psum(likelihood, DATA_AXIS),
            vi_iters=jax.lax.pmax(iters, DATA_AXIS),
        )

    def e_step_fn(log_beta, alpha, word_idx, counts, doc_mask,
                  var_max_iters, var_tol, gamma_prev=None, warm=None):
        if log_beta.shape[1] % m:
            raise ValueError(
                f"vocab size {log_beta.shape[1]} not divisible by model axis {m}"
            )
        if gamma_prev is None:
            gamma_prev, warm = _fresh_warm_fill(log_beta, word_idx)
        fn = shard_map(
            partial(local_e_step, var_max_iters=var_max_iters, var_tol=var_tol),
            mesh=mesh,
            in_specs=(P(None, MODEL_AXIS), P(), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS), P(DATA_AXIS), P()),
            out_specs=estep.EStepResult(
                gamma=P(DATA_AXIS),
                suff_stats=P(MODEL_AXIS, None),
                alpha_ss=P(),
                likelihood=P(),
                vi_iters=P(),
            ),
        )
        return fn(log_beta, alpha, word_idx, counts, doc_mask, gamma_prev,
                  warm)

    def local_m_step(ss_l):
        # ss_l: [V/m, K].  Per-topic totals need the full vocab, so psum
        # the local sums over the model axis and hand the dense m_step
        # the global normalizer.
        total = jax.lax.psum(ss_l.T.sum(-1, keepdims=True), MODEL_AXIS)
        return estep.m_step(ss_l, topic_total=total)

    def m_step_fn(suff):
        fn = shard_map(
            local_m_step,
            mesh=mesh,
            in_specs=(P(MODEL_AXIS, None),),
            out_specs=P(None, MODEL_AXIS),
        )
        return fn(suff)

    # Lets the trainer's dense-mode check recognize this package's own
    # vocab-sharded plan (a user's custom e_step_fn must never be
    # silently bypassed by the dense path).
    e_step_fn._oni_vocab_sharded = True
    e_step_fn._oni_warm_capable = True
    m_step_fn._oni_vocab_sharded = True
    return e_step_fn, m_step_fn


def pad_vocab(v: int, model_size: int) -> int:
    """Smallest padded vocab size divisible by the model axis."""
    return -(-v // model_size) * model_size


def make_sharded_score_fn(mesh: Mesh):
    """Data-parallel event SCORING over the same (data, model) mesh the
    training side holds: the event axis (int32 model-row index arrays)
    shards over `data`, theta/p replicate, and each device runs the
    two-gather dot on its own slice — the scoring analogue of the
    reference's 20-rank document split, with no collective at all (the
    per-event dot is embarrassingly parallel).

    Returns a jitted (theta [D+1, K], p [V+1, K], ip_idx [N], word_idx
    [N]) -> scores [N] with the output sharded over `data`; the scoring
    pipeline (scoring/pipeline.py) drives it chunk by chunk for
    multi-device grants and composes on-device threshold compaction on
    the sharded scores.  N must divide by the data-axis size (the
    pipeline's chunker guarantees it).  Parity with the single-device
    scorer is pinned by tests/test_scoring_pipeline.py and executed in
    the driver's dryrun_multichip — which is why the per-shard body is
    the scoring pipeline's own kernel, not a local copy."""
    from ..scoring.pipeline import score_dot_rows

    return jax.jit(shard_map(
        score_dot_rows,
        mesh=mesh,
        in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
    ))
