"""Sharded E/M steps: shard_map wrappers over ops/estep building blocks.

Two execution plans, both SPMD over the (data, model) mesh:

1. **Data-parallel** (`make_data_parallel_e_step`) — the direct analogue
   of the reference's 20-rank MPI document sharding (README.md:121):
   batches shard over `data`, beta replicates, suff-stats/likelihood
   `psum` over ICI.  This is the default whenever beta fits per device.

2. **Vocab-sharded** (`make_vocab_sharded_fns`) — for huge-V corpora
   (BASELINE.json config 4: high-cardinality DNS vocab).  beta [K, V] and
   suff-stats [V, K] shard their vocabulary axis over `model`; each shard
   gathers the beta slab for the tokens whose words it owns and a
   `psum` over `model` assembles the full [B, L, K] slab (one collective
   per batch — the slab, not beta, so HBM never holds another full copy).
   The fixed point then runs identically on every model shard; suff-stats
   scatter only into the locally-owned vocab slice.  The M-step
   renormalizes with a `psum` of per-topic totals over `model`.

Both plans compose: a (8, 4) mesh runs 8-way document parallelism with
4-way vocabulary sharding.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import estep
from .mesh import DATA_AXIS, MODEL_AXIS


def make_data_parallel_e_step(mesh: Mesh):
    """e_step-compatible callable: inputs batch-sharded over `data`,
    outputs gamma sharded / reductions replicated."""

    def local(log_beta, alpha, word_idx, counts, doc_mask, var_max_iters, var_tol):
        res = estep.e_step(
            log_beta, alpha, word_idx, counts, doc_mask, var_max_iters, var_tol
        )
        return estep.EStepResult(
            gamma=res.gamma,
            suff_stats=jax.lax.psum(res.suff_stats, DATA_AXIS),
            alpha_ss=jax.lax.psum(res.alpha_ss, DATA_AXIS),
            likelihood=jax.lax.psum(res.likelihood, DATA_AXIS),
            vi_iters=jax.lax.pmax(res.vi_iters, DATA_AXIS),
        )

    def wrapped(log_beta, alpha, word_idx, counts, doc_mask,
                var_max_iters, var_tol):
        fn = jax.shard_map(
            partial(local, var_max_iters=var_max_iters, var_tol=var_tol),
            mesh=mesh,
            in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=estep.EStepResult(
                gamma=P(DATA_AXIS),
                suff_stats=P(),
                alpha_ss=P(),
                likelihood=P(),
                vi_iters=P(),
            ),
        )
        return fn(log_beta, alpha, word_idx, counts, doc_mask)

    wrapped._oni_data_parallel = True  # lets the trainer's dense-mode
    return wrapped                     # check recognize its own wrapper


def make_data_parallel_dense_e_step(mesh: Mesh, wmajor: bool = False,
                                    precision: str = "f32"):
    """Dense-corpus E-step (ops/dense_estep.py) over batch-sharded dense
    counts: each data shard runs the MXU kernel on its local documents,
    suff-stats/likelihood psum over ICI — the dense analogue of
    make_data_parallel_e_step, so multi-chip runs keep the flagship
    kernel instead of falling back to the sparse path.

    `dense` is the full densified batch ([B, W] row-major or [W, B]
    W-major); the local batch is B / data_size, so dense feasibility
    (pick_block / pick_block_w) must be checked against the PER-SHARD
    batch by the caller.  gamma_prev/warm thread the warm-start state
    exactly as in the single-device path."""
    from ..ops import dense_estep

    batch_axis = 1 if wmajor else 0

    def local(log_beta, alpha, dense, doc_mask, gamma_prev, warm,
              var_max_iters, var_tol, interpret):
        res = dense_estep.e_step_dense(
            log_beta, alpha, dense, doc_mask,
            var_max_iters=var_max_iters, var_tol=var_tol,
            interpret=interpret, wmajor=wmajor,
            gamma_prev=gamma_prev, warm=warm, precision=precision,
        )
        return estep.EStepResult(
            gamma=res.gamma,
            suff_stats=jax.lax.psum(res.suff_stats, DATA_AXIS),
            alpha_ss=jax.lax.psum(res.alpha_ss, DATA_AXIS),
            likelihood=jax.lax.psum(res.likelihood, DATA_AXIS),
            vi_iters=jax.lax.pmax(res.vi_iters, DATA_AXIS),
        )

    dense_spec = (
        P(None, DATA_AXIS) if wmajor else P(DATA_AXIS, None)
    )

    def wrapped(log_beta, alpha, dense, doc_mask, gamma_prev, warm,
                var_max_iters, var_tol, interpret=False):
        if dense.shape[batch_axis] % mesh.shape[DATA_AXIS]:
            raise ValueError(
                f"batch {dense.shape[batch_axis]} not divisible by data "
                f"axis {mesh.shape[DATA_AXIS]}"
            )
        fn = jax.shard_map(
            partial(local, var_max_iters=var_max_iters, var_tol=var_tol,
                    interpret=interpret),
            mesh=mesh,
            in_specs=(P(), P(), dense_spec, P(DATA_AXIS), P(DATA_AXIS),
                      P()),
            out_specs=estep.EStepResult(
                gamma=P(DATA_AXIS),
                suff_stats=P(),
                alpha_ss=P(),
                likelihood=P(),
                vi_iters=P(),
            ),
            # pallas_call's out_shape carries no varying-mesh-axes info,
            # so shard_map's vma check cannot see through it.
            check_vma=False,
        )
        return fn(log_beta, alpha, dense, doc_mask, gamma_prev, warm)

    return wrapped


def make_vocab_sharded_fns(mesh: Mesh):
    """Returns (e_step_fn, m_step_fn) with beta/suff-stats vocab-sharded
    over `model` and batches sharded over `data`.

    Global shapes stay [K, V] / [V, K]; shard_map sees per-device slices
    [K, V/m] / [V/m, K].  V must be divisible by the model-axis size
    (pad the vocabulary — padded words never appear in word_idx, so their
    suff-stats stay zero and m_step floors them to LOG_ZERO).
    """
    m = mesh.shape[MODEL_AXIS]

    def local_e_step(log_beta_l, alpha, word_idx, counts, doc_mask,
                     var_max_iters, var_tol):
        K, v_local = log_beta_l.shape
        shard = jax.lax.axis_index(MODEL_AXIS)
        offset = shard * v_local
        # Gather only locally-owned words, zero elsewhere; psum over the
        # model axis assembles the full [B, L, K] slab.
        local_idx = word_idx - offset
        owned = (local_idx >= 0) & (local_idx < v_local)
        safe_idx = jnp.clip(local_idx, 0, v_local - 1)
        slab_l = estep.gather_beta(log_beta_l, safe_idx)   # [B, L, K]
        slab_l = jnp.where(owned[..., None], slab_l, 0.0)
        beta_bt = jax.lax.psum(slab_l, MODEL_AXIS)

        gamma, iters = estep.fixed_point(
            beta_bt, alpha, counts, doc_mask, var_max_iters, var_tol
        )
        phi_c, phinorm = estep.phi_weighted(beta_bt, gamma, counts, doc_mask)
        # Scatter only into the owned vocab slice.
        phi_c = jnp.where(owned[..., None], phi_c, 0.0)
        ss_l = estep.suff_stats(phi_c, safe_idx, v_local)  # [V/m, K]
        likelihood, alpha_ss = estep.batch_likelihood(
            gamma, phinorm, counts, alpha, doc_mask
        )
        return estep.EStepResult(
            gamma=gamma,
            suff_stats=jax.lax.psum(ss_l, DATA_AXIS),
            alpha_ss=jax.lax.psum(alpha_ss, DATA_AXIS),
            likelihood=jax.lax.psum(likelihood, DATA_AXIS),
            vi_iters=jax.lax.pmax(iters, DATA_AXIS),
        )

    def e_step_fn(log_beta, alpha, word_idx, counts, doc_mask,
                  var_max_iters, var_tol):
        if log_beta.shape[1] % m:
            raise ValueError(
                f"vocab size {log_beta.shape[1]} not divisible by model axis {m}"
            )
        fn = jax.shard_map(
            partial(local_e_step, var_max_iters=var_max_iters, var_tol=var_tol),
            mesh=mesh,
            in_specs=(P(None, MODEL_AXIS), P(), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS)),
            out_specs=estep.EStepResult(
                gamma=P(DATA_AXIS),
                suff_stats=P(MODEL_AXIS, None),
                alpha_ss=P(),
                likelihood=P(),
                vi_iters=P(),
            ),
        )
        return fn(log_beta, alpha, word_idx, counts, doc_mask)

    def local_m_step(ss_l):
        # ss_l: [V/m, K].  Per-topic totals need the full vocab, so psum
        # the local sums over the model axis and hand the dense m_step
        # the global normalizer.
        total = jax.lax.psum(ss_l.T.sum(-1, keepdims=True), MODEL_AXIS)
        return estep.m_step(ss_l, topic_total=total)

    def m_step_fn(suff):
        fn = jax.shard_map(
            local_m_step,
            mesh=mesh,
            in_specs=(P(MODEL_AXIS, None),),
            out_specs=P(None, MODEL_AXIS),
        )
        return fn(suff)

    return e_step_fn, m_step_fn


def pad_vocab(v: int, model_size: int) -> int:
    """Smallest padded vocab size divisible by the model axis."""
    return -(-v // model_size) * model_size
