"""Explicit cross-process collectives for distributed EM.

PR 9's sparse engine (and every E-step engine before it) is a
single-process program; the old multi-host story ran ONE global-mesh
SPMD program spanning processes, which the CPU runtime cannot execute
at all (`XlaRuntimeError: Multiprocess computations aren't implemented
on the CPU backend`) and which forced the sparse engine back to dense.
The restructure (ROADMAP item 1): each process runs the full E-step
*host-locally* over its document shards (parallel/shard_plan.py), and
the [V, K] beta sufficient statistics, the alpha suff-stats scalar, and
the ELBO scalar cross processes through THIS layer — an explicit,
pluggable allreduce in the spirit of DrJAX's MapReduce-as-JAX-
primitives (arXiv:2403.07128), instead of collectives hidden inside a
sharded training program.

Transports (``Collective.transport``):

- ``local`` — process_count == 1: every op is the identity.
- ``psum`` — a real multi-device runtime (TPU pods): rank payloads are
  committed into a process-sharded global array and a jitted identity
  with replicated ``out_shardings`` lowers the gather onto ICI/DCN.
- ``kvring`` — the portable process-group ring for CPU multi-process:
  a classic ring allgather over the ``jax.distributed`` coordination
  client's key-value store, chunked (``max_chunk_bytes``) and bounded
  (``timeout_s``, with peer-failure polling between wait slices).

The REDUCTION is deliberately transport-independent and host-side:
gather the per-rank partials, then ``tree_combine`` — a fixed pairwise
association tree in shard order.  Because the tree is anchored to the
corpus-derived shard plan (not the process count), the reduced f32
bytes are identical on every rank AND invariant to how many processes
computed the partials — the byte-identical-artifacts contract of
tests/test_multihost.py.

Failure semantics (the PR 4 ``BackendLost``/rc=3 machinery): a rank
that fails mid-stage posts a failure key (``Collective.fail``); every
peer's blocked wait polls it between slices and raises ``PeerFailure``
("failed on another rank") — a ``BackendLost`` subclass, so
``ml_ops`` exits with the structured rc=3 payload instead of a raw
XLA traceback.  A peer that dies without posting (SIGKILL) surfaces as
a bounded ``PeerFailure`` timeout instead of a hang.

Every data-plane op is priced like a dataplane stall: the wait rides an
``allreduce.wait`` span and a ``{"kind": "allreduce"}`` journal record
carries per-op bytes, rounds, and wall.
"""

from __future__ import annotations

import base64
import functools
import os
import pickle
import time

import numpy as np

from ..telemetry.heartbeat import BackendLost
from ..telemetry.spans import current_recorder, maybe_span


class PeerFailure(BackendLost):
    """A collective op observed another rank's failure (or a peer's
    death via timeout).  Subclasses BackendLost so the runner's
    structured rc=3 exit path (runner/ml_ops.py main) applies."""


# Per-KV-value chunk bound (characters of the base64 text actually
# stored): the coordination service is a control-plane store with a
# 4 MiB gRPC message cap, so bulk payloads ship in bounded slices
# instead of one arbitrarily large message.
#
# Why base64 text at all: jaxlib 0.4.36's *_bytes KV variants crash the
# process (SIGSEGV/abort in the watch callback) whenever the value
# arrives while the get is BLOCKED — exactly the allreduce wait
# pattern — while the string variants deliver mid-wait arrivals
# reliably (verified empirically; the multihost suite would be
# unrunnable on the bytes API).  The ~4/3 size overhead is priced into
# the journaled byte counts.
DEFAULT_MAX_CHUNK_BYTES = 2 << 20
# Bound on any single collective wait.  Ranks run EM iterations in
# lockstep, so legitimate skew is one iteration's wall-clock variance;
# the default leaves room for a slow host without turning a dead peer
# into an indefinite hang.  ONI_ML_TPU_ALLREDUCE_TIMEOUT_S overrides
# (the failure-injection tests tighten it).
DEFAULT_TIMEOUT_S = 600.0
# Wait-slice length: between slices the blocked rank polls the failure
# key, so a cooperative peer failure surfaces within one slice.
POLL_SLICE_S = 2.0
# How long a rank that has ALREADY posted its own failure keeps trying
# to complete in-flight collectives (letting the outcome barrier drain
# cleanly when peers are still forwarding) before giving up: without
# this cap, at >= 3 ranks the failed rank can wait the FULL collective
# timeout for ring blocks its (already-aborted) peers will never send.
FAIL_DRAIN_S = 5.0


def _bf16_pack(arr: np.ndarray) -> np.ndarray:
    """f32/f64 -> bf16 bit pattern as uint16, round-to-nearest-even —
    the standard truncate-with-carry trick on the f32 view.  Used to
    HALVE the kvring wire bytes of a float payload; accumulation after
    the matching unpack stays f32, so only the per-rank partials lose
    mantissa, never the reduction arithmetic."""
    a = np.asarray(arr, np.float32)
    # Round-trip through flat 1-d: .view() is shape-preserving only on
    # contiguous data, and ascontiguousarray would silently promote a
    # 0-d scalar (the likelihood/alpha suff-stats) to shape (1,).
    u = np.ascontiguousarray(a).reshape(-1).view(np.uint32)
    rounded = ((u + np.uint32(0x7FFF) + ((u >> np.uint32(16))
                                         & np.uint32(1)))
               >> np.uint32(16)).astype(np.uint16)
    # NaN guard: the carry add wraps high-payload NaN bit patterns
    # into +/-0.0 — a diverged rank's suff-stats must stay NaN on the
    # wire so the fit fails loudly, exactly like the f32 wire would.
    is_nan = ((u & np.uint32(0x7F800000)) == np.uint32(0x7F800000)) \
        & ((u & np.uint32(0x007FFFFF)) != 0)
    if is_nan.any():
        quiet = (((u >> np.uint32(16)) & np.uint32(0x8000))
                 | np.uint32(0x7FC0)).astype(np.uint16)
        rounded = np.where(is_nan, quiet, rounded)
    return rounded.reshape(a.shape)


def _bf16_unpack(u16: np.ndarray) -> np.ndarray:
    """uint16 bf16 bit pattern -> f32 (exact: bf16 embeds in f32)."""
    u16 = np.asarray(u16)
    return ((u16.reshape(-1).astype(np.uint32) << np.uint32(16))
            .view(np.float32).reshape(u16.shape))


# Wire marker for a bf16-compressed array inside a pickled payload.
# Self-describing per VALUE, so every rank decompresses whatever
# arrives identically — the reduced bytes stay rank-identical even if
# (misconfigured) ranks disagree on the compression knob.
_BF16_TAG = "__oni_bf16__"


def _compress_named(named: dict, precision: str) -> dict:
    if precision != "bf16":
        return named
    return {
        k: (_BF16_TAG, _bf16_pack(v))
        if np.asarray(v).dtype.kind == "f" else v
        for k, v in named.items()
    }


def _decompress_named(named: dict) -> dict:
    out = {}
    for k, v in named.items():
        if isinstance(v, tuple) and len(v) == 2 and v[0] == _BF16_TAG:
            out[k] = _bf16_unpack(v[1])
        else:
            out[k] = v
    return out


def tree_combine(parts):
    """Deterministic pairwise-tree sum of a list of pytrees of arrays
    (np or jnp): adjacent pairs combine level by level, an odd tail
    carries up unchanged.  For a contiguous, power-of-two-aligned block
    of leaves this reproduces the canonical tree's subtree node exactly
    — the property the cross-rank reduction leans on for byte-identical
    results across rank counts (see parallel/shard_plan.py)."""
    parts = list(parts)
    if not parts:
        raise ValueError("tree_combine of no parts")
    while len(parts) > 1:
        nxt = []
        for i in range(0, len(parts) - 1, 2):
            a, b = parts[i], parts[i + 1]
            if isinstance(a, dict):
                nxt.append({k: a[k] + b[k] for k in a})
            else:
                nxt.append(a + b)
        if len(parts) % 2:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0]


@functools.lru_cache(maxsize=8)
def _psum_programs(nprocs: int):
    """(row_sharding, jitted identity-reshard) for the psum transport,
    cached per process count: the devices of a process are fixed for
    its lifetime, and rebuilding the mesh + a fresh jit wrapper per
    call would re-trace the gather on every EM iteration of the one op
    sitting on the distributed critical path."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = np.asarray(jax.devices())
    mesh = Mesh(devs.reshape(nprocs, -1), ("proc", "local"))
    row = NamedSharding(mesh, PartitionSpec("proc"))
    rep = NamedSharding(mesh, PartitionSpec())
    # jit entry point registered in telemetry/roofline.py
    # HARVEST_COVERAGE (control-plane collective, not a dispatch phase).
    return row, jax.jit(lambda x: x, out_shardings=rep)


def _psum_gather(local: np.ndarray, nprocs: int) -> np.ndarray:
    """[*shape] per-rank payload -> [nprocs, *shape] stacked gather over
    the runtime's own interconnect: the local row commits into a
    process-sharded global array and a jitted identity with replicated
    out_shardings lowers the reshard to an all-gather riding ICI/DCN
    (the DrJAX pattern).  Single-process this degenerates to a copy —
    which is how the CPU suite and the dryrun exercise the code path;
    multi-host numbers are projections until the next TPU grant."""
    import jax

    local = np.asarray(local)
    # Bit-exact transport for 8-byte dtypes: without x64 enabled, jax
    # canonicalizes float64/int64 commits down to 32 bits — which would
    # silently round the f64 gamma merge on the pod path while the
    # kvring transport (pickle) preserved it.  View as uint32 pairs,
    # gather, view back: the gather moves bytes, never arithmetic.
    wide_dtype = local.dtype if local.dtype.itemsize == 8 else None
    if wide_dtype is not None:
        if local.ndim == 0:
            raise ValueError(
                "psum transport cannot bit-cast a 0-d 8-byte scalar; "
                "reshape it to (1,) first"
            )
        local = np.ascontiguousarray(local).view(np.uint32)
    row, gather = _psum_programs(nprocs)
    glob = jax.make_array_from_process_local_data(row, local[None, ...])
    gathered = np.asarray(gather(glob))
    if wide_dtype is not None:
        gathered = gathered.view(wide_dtype)
    return gathered


class Collective:
    """One process's handle on the run's process group.

    Every method is COLLECTIVE: all ranks must call the same ops in the
    same order (the key-sequence counter advances in lockstep).  The
    control plane (broadcast/allgather of small pickled objects,
    barriers, failure relay) always rides the coordination client's KV
    store — it exists on every multi-process backend, CPU included;
    only the bulk array plane switches transports.
    """

    def __init__(self, client=None, rank: "int | None" = None,
                 nprocs: "int | None" = None, *,
                 transport: "str | None" = None,
                 timeout_s: "float | None" = None,
                 max_chunk_bytes: int = DEFAULT_MAX_CHUNK_BYTES,
                 namespace: str = "oni/ar",
                 payload_precision: "str | None" = None):
        import jax

        self.rank = jax.process_index() if rank is None else rank
        self.num_processes = (
            jax.process_count() if nprocs is None else nprocs
        )
        if client is None and self.num_processes > 1:
            from jax._src import distributed

            client = distributed.global_state.client
            if client is None:
                raise RuntimeError(
                    "multi-process collective without an initialized "
                    "jax.distributed client — call "
                    "parallel.initialize_distributed() first"
                )
        self._client = client
        env_t = os.environ.get("ONI_ML_TPU_ALLREDUCE_TIMEOUT_S", "")
        self.timeout_s = (
            float(env_t) if env_t
            else (DEFAULT_TIMEOUT_S if timeout_s is None else timeout_s)
        )
        self.max_chunk_bytes = max_chunk_bytes
        self._ns = namespace
        self._seq = 0
        if transport is None:
            transport = os.environ.get("ONI_ML_TPU_ALLREDUCE", "")
        if not transport:
            if self.num_processes == 1:
                transport = "local"
            else:
                transport = (
                    "kvring" if jax.default_backend() == "cpu" else "psum"
                )
        if transport not in ("local", "kvring", "psum"):
            raise ValueError(
                f"unknown allreduce transport {transport!r}: expected "
                "local, kvring, or psum"
            )
        self.transport = transport
        if payload_precision is None:
            payload_precision = os.environ.get(
                "ONI_ML_TPU_ALLREDUCE_PRECISION", "") or "f32"
        if payload_precision not in ("f32", "bf16"):
            raise ValueError(
                f"unknown allreduce payload_precision "
                f"{payload_precision!r}: expected f32 or bf16"
            )
        # Default WIRE precision for float payloads on the kvring
        # transport: "bf16" halves the per-iteration KV-ring bytes
        # (round-to-nearest-even pack, exact f32 unpack, f32
        # accumulation in the reduction tree).  Per-call overrides let
        # the trainer compress the bulk suff-stats while the f64 gamma
        # merge stays exact.  The psum transport ignores it: its
        # payloads ride ICI as device arrays, not pickled KV chunks.
        self.payload_precision = payload_precision
        self._failed_reason: "str | None" = None
        # Process-local accounting (bench distributed_em reads it):
        # cumulative data-plane ops, payload bytes out/in, wall.
        self.stats = {"ops": 0, "bytes_out": 0, "bytes_in": 0,
                      "wall_s": 0.0}

    def applied_precision(self, precision: "str | None" = None) -> str:
        """The wire precision an allgather with this `precision`
        request would ACTUALLY use — the one rule, shared by the
        data-plane op and every provenance record: bf16 compresses
        only multi-process kvring payloads (psum rides ICI as device
        arrays; a single process never touches the wire at all)."""
        if precision is None:
            precision = self.payload_precision
        return ("bf16" if precision == "bf16"
                and self.transport == "kvring"
                and self.num_processes > 1 else "f32")

    # -- failure relay ----------------------------------------------------

    def fail(self, reason: str) -> None:
        """Post this rank's failure for every peer's wait-slice poll to
        observe.  Best-effort (the process is on its way out).  Also
        marks THIS collective as failed, which caps its own later waits
        at FAIL_DRAIN_S — a rank that already failed must not block the
        full timeout on barriers its peers have abandoned."""
        self._failed_reason = str(reason)[:500]
        if self._client is None:
            return
        try:
            self._client.key_value_set(
                self._ns + "/fail",
                base64.b64encode(
                    pickle.dumps((self.rank, str(reason)[:500]))
                ).decode("ascii"),
                allow_overwrite=True,
            )
        except Exception:
            pass

    def check_peer_failure(self) -> None:
        """Raise PeerFailure if any OTHER rank posted a failure."""
        if self._client is None:
            return
        try:
            raw = self._client.blocking_key_value_get(
                self._ns + "/fail", 1
            )
        except Exception:
            return
        rank, reason = pickle.loads(base64.b64decode(raw))
        if rank == self.rank:
            return
        raise PeerFailure(
            f"distributed run failed on another rank "
            f"(rank {rank}: {reason})"
        )

    # -- KV primitives ----------------------------------------------------

    def _next_base(self, tag: str) -> str:
        self._seq += 1
        return f"{self._ns}/{self._seq}-{tag}"

    def _kv_get(self, key: str) -> str:
        """Blocking get with a bounded deadline and peer-failure polling
        between wait slices — the coordination-client health barrier of
        the failure-relay contract."""
        budget = (
            min(self.timeout_s, FAIL_DRAIN_S)
            if self._failed_reason is not None else self.timeout_s
        )
        deadline = time.monotonic() + budget
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                if self._failed_reason is not None:
                    raise PeerFailure(
                        "abandoning collective drain after this rank's "
                        f"own failure: {self._failed_reason}"
                    )
                raise PeerFailure(
                    f"collective wait for {key!r} timed out after "
                    f"{self.timeout_s:.0f}s — a peer rank is stalled or "
                    "died without posting a failure"
                )
            slice_ms = max(1, int(min(POLL_SLICE_S, remaining) * 1000))
            try:
                return self._client.blocking_key_value_get(key, slice_ms)
            except Exception as e:
                if "DEADLINE_EXCEEDED" not in str(e):
                    raise
                self.check_peer_failure()

    def _put_chunked(self, key: str, data: bytes) -> None:
        """Publish `data` under `key` in bounded base64 chunks; the
        chunk-count marker lands LAST so a reader never observes a
        partial value."""
        enc = base64.b64encode(data).decode("ascii")
        n = -(-len(enc) // self.max_chunk_bytes) if enc else 0
        for i in range(n):
            self._client.key_value_set(
                f"{key}/c{i}",
                enc[i * self.max_chunk_bytes:(i + 1) * self.max_chunk_bytes],
            )
        self._client.key_value_set(f"{key}/n", str(n))

    def _get_chunked(self, key: str, delete: bool = False) -> bytes:
        n = int(self._kv_get(f"{key}/n"))
        parts = [self._kv_get(f"{key}/c{i}") for i in range(n)]
        if delete:
            # Single-reader keys (ring messages): the consumer retires
            # them so the coordination service's store stays bounded.
            try:
                for i in range(n):
                    self._client.key_value_delete(f"{key}/c{i}")
                self._client.key_value_delete(f"{key}/n")
            except Exception:
                pass
        return base64.b64decode("".join(parts))

    # -- control plane ----------------------------------------------------

    def broadcast_obj(self, obj, tag: str):
        """Coordinator (rank 0) -> all: the stage-decision primitive.
        Works on every backend (pure KV), unlike the old XLA
        broadcast_one_to_all that required cross-process computations."""
        if self.num_processes == 1:
            return obj
        base = self._next_base(tag)
        if self.rank == 0:
            self._put_chunked(base, pickle.dumps(obj, protocol=4))
            return obj
        return pickle.loads(self._get_chunked(base))

    def allgather_obj(self, obj, tag: str) -> list:
        """Every rank's object, in rank order, on every rank."""
        if self.num_processes == 1:
            return [obj]
        payload = pickle.dumps(obj, protocol=4)
        blocks, *_ = self._ring_allgather(payload, tag)
        return [pickle.loads(b) for b in blocks]

    def barrier(self, tag: str) -> None:
        """All ranks reach this point (with failure relay while
        waiting); returns when every rank has."""
        self.allgather_obj(True, tag)

    # -- data plane -------------------------------------------------------

    def _ring_allgather(self, payload: bytes, tag: str):
        """Classic ring allgather over the KV store: P-1 rounds, each
        rank forwarding one block per round to its successor (a
        single-reader key, retired after the read).  Returns
        (blocks_by_rank, bytes_out, bytes_in, rounds)."""
        base = self._next_base(tag)
        p, r = self.num_processes, self.rank
        blocks: list = [None] * p
        blocks[r] = payload
        bytes_out = bytes_in = 0
        for s in range(p - 1):
            send = (r - s) % p
            self._put_chunked(f"{base}/s{s}/r{r}", blocks[send])
            bytes_out += len(blocks[send])
            got = self._get_chunked(
                f"{base}/s{s}/r{(r - 1) % p}", delete=True
            )
            blocks[(r - s - 1) % p] = got
            bytes_in += len(got)
        return blocks, bytes_out, bytes_in, p - 1

    def allgather_arrays(self, named: "dict[str, np.ndarray]",
                         tag: str, *,
                         precision: "str | None" = None
                         ) -> "list[dict[str, np.ndarray]]":
        """The bulk primitive: every rank's named-array dict, in rank
        order, on every rank.  Journaled as {"kind": "allreduce"} with
        per-op bytes/rounds/wall, the wait priced under an
        allreduce.wait span like a dataplane stall.

        `precision` overrides the collective's payload_precision for
        this op.  Under "bf16" on the kvring transport, float arrays
        ship as round-to-nearest-even bf16 bit patterns (half the wire
        bytes) and EVERY rank — including the sender reading its own
        block — unpacks them to f32 before use, so the reduction sees
        identical f32 inputs everywhere and the reduced bytes stay
        rank-identical.  Non-float arrays and non-kvring transports
        pass through untouched."""
        named = {k: np.asarray(v) for k, v in named.items()}
        applied = self.applied_precision(precision)
        if self.num_processes == 1:
            return [named]
        t0 = time.monotonic()
        with maybe_span("allreduce.wait", tag=tag,
                        transport=self.transport):
            if self.transport == "psum":
                stacked = {
                    k: _psum_gather(v, self.num_processes)
                    for k, v in named.items()
                }
                out = [
                    {k: stacked[k][p] for k in stacked}
                    for p in range(self.num_processes)
                ]
                bytes_out = sum(v.nbytes for v in named.values())
                bytes_in = bytes_out * (self.num_processes - 1)
                rounds = 1
            else:
                payload = pickle.dumps(
                    _compress_named(named, applied), protocol=4
                )
                blocks, bytes_out, bytes_in, rounds = (
                    self._ring_allgather(payload, tag)
                )
                out = [_decompress_named(pickle.loads(b))
                       for b in blocks]
        wall = time.monotonic() - t0
        self.stats["ops"] += 1
        self.stats["bytes_out"] += bytes_out
        self.stats["bytes_in"] += bytes_in
        self.stats["wall_s"] += wall
        rec = current_recorder()
        if rec is not None:
            rec.journal_record({
                "kind": "allreduce",
                "tag": tag,
                "transport": self.transport,
                "nprocs": self.num_processes,
                "rounds": rounds,
                "precision": applied,
                "bytes_out": bytes_out,
                "bytes_in": bytes_in,
                "wall_s": round(wall, 6),
            })
        return out


def reduce_partials(coll: Collective, plan, shard_stats: "dict[int, dict]",
                    tag: str, *,
                    precision: "str | None" = None
                    ) -> "dict[str, np.ndarray]":
    """The sufficient-statistics allreduce: per-shard partial stats in,
    globally-reduced stats out — identical bytes on every rank, and
    invariant to the rank count for a fixed shard plan.

    `shard_stats` maps this rank's OWNED shard indices to named-array
    dicts.  Aligned plans (rank runs are canonical tree nodes) exchange
    one pre-combined subtree root per rank; unaligned plans exchange
    per-shard partials so the canonical shard-order tree can still be
    applied identically everywhere.

    `precision="bf16"` compresses the wire payload (kvring transport:
    half the bytes per EM iteration) with f32 accumulation after the
    unpack; the reduced bytes are still rank-identical and
    rank-count-invariant — just bf16-tolerance vs the f32 wire, not
    bit-equal to it (the PR 9 sparse-engine precision contract)."""
    owned = sorted(shard_stats)
    if plan.aligned:
        local = tree_combine([shard_stats[s] for s in owned])
        gathered = coll.allgather_arrays(local, tag,
                                         precision=precision)
        return tree_combine(gathered)
    flat: "dict[str, np.ndarray]" = {}
    for s in owned:
        for k, v in shard_stats[s].items():
            flat[f"{s}:{k}"] = v
    gathered = coll.allgather_arrays(flat, tag, precision=precision)
    by_shard: "dict[int, dict]" = {}
    for g in gathered:
        for key, v in g.items():
            s, name = key.split(":", 1)
            by_shard.setdefault(int(s), {})[name] = v
    return tree_combine([by_shard[s] for s in sorted(by_shard)])


_COLLECTIVE: "Collective | None" = None


def get_collective() -> Collective:
    """The process-wide collective (one per process so the KV key
    sequence stays in lockstep across every consumer: the trainer's
    suff-stats reduce, the runner's stage decisions, the streaming
    trainer's lambda reduce)."""
    global _COLLECTIVE
    if _COLLECTIVE is None:
        _COLLECTIVE = Collective()
    return _COLLECTIVE


def _reset_collective_for_tests() -> None:
    global _COLLECTIVE
    _COLLECTIVE = None
