"""KV-backed membership, heartbeats, and failure relay for the
replicated serving fleet.

PR 11 built the process-group control plane for distributed EM on the
``jax.distributed`` coordination client's KV store (parallel/
allreduce.py): bounded blocking gets, a fail key every blocked peer
polls, chunked base64 values.  Replicated serving (ROADMAP item 5)
needs the same three primitives — who is in the fleet, who is still
alive, who failed — but for *elastic* membership: serve replicas join,
drain, and die independently, which the fixed-rank jax.distributed
world cannot express.  This module reuses the CLIENT INTERFACE (so the
same code runs over the coordination service, the in-memory test KV,
or the file store below) and layers membership on top:

``FileKVClient``
    A same-host, cross-process KV store with the coordination client's
    exact surface (``key_value_set`` / ``blocking_key_value_get`` /
    ``key_value_delete``) plus ``key_value_list`` for membership
    enumeration.  One file per key (name = urlsafe base64 of the key,
    so arbitrary key strings never escape the root), atomic
    tmp+``os.replace`` publication, polling blocking gets with the
    DEADLINE_EXCEEDED error contract Collective._kv_get expects.  This
    is what `ml_ops route` uses to coordinate replica subprocesses —
    no coordination service to stand up, nothing to clean beyond the
    directory.

``MembershipClient``
    register / deregister / members / heartbeat / alive / fail over
    any such KV client.  Heartbeats are wall-clock stamped (they
    compare across PROCESSES, where monotonic clocks share no epoch)
    and carry a per-publisher sequence number so a reader can tell a
    fresh heartbeat from a re-read.  The fail key is per-replica —
    a failing replica posts its reason; the router's monitor polls
    failures between heartbeat checks exactly like the allreduce
    wait-slice poll.

``KVServer`` / ``TcpKVClient``
    The cross-host transport: a tiny TCP KV daemon speaking
    length-prefixed JSON frames, and a client with the exact same
    surface as ``FileKVClient``.  ``ml_ops route --kv-listen`` runs the
    server next to one router; every other router and replica connects
    with ``--kv-connect host:port``, so membership, promotion claims,
    and failure relay all work across machines with zero extra
    coordination (replica placement stays a pure function of the
    roster).

``HeartbeatPublisher``
    The replica-side daemon thread publishing liveness every
    ``interval_s`` until ``stop()``.

Records are JSON (base64-wrapped to honour the string-value KV
convention) — the membership plane carries no pickle, which is what
lets the ``no-pickle-wire`` graftlint rule cover this module.
"""

from __future__ import annotations

import base64
import json
import os
import socket
import struct
import threading
import time


class FileKVClient:
    """Directory-backed KV store satisfying the coordination-client
    interface for same-host multi-process fleets.  Values are strings
    (the Collective/base64 convention); a set is atomic via
    tmp+rename, so a reader never observes a torn value."""

    # Poll cadence for blocking gets: coarse enough to stay invisible
    # in CPU profiles, fine enough that a heartbeat-interval wait
    # never quantizes noticeably.
    _POLL_S = 0.005

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        name = base64.urlsafe_b64encode(key.encode("utf-8")).decode(
            "ascii")
        return os.path.join(self.root, name)

    def key_value_set(self, key: str, value: str,
                      allow_overwrite: bool = False) -> None:
        path = self._path(key)
        if not allow_overwrite and os.path.exists(path):
            raise RuntimeError(f"ALREADY_EXISTS: {key}")
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            f.write(value)
        os.replace(tmp, path)

    def blocking_key_value_get(self, key: str,
                               timeout_in_ms: int) -> str:
        deadline = time.monotonic() + timeout_in_ms / 1000.0
        path = self._path(key)
        while True:
            try:
                with open(path) as f:
                    return f.read()
            except FileNotFoundError:
                pass
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(f"DEADLINE_EXCEEDED: {key}")
            time.sleep(min(self._POLL_S, remaining))

    def key_value_delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def key_value_list(self, prefix: str) -> "dict[str, str]":
        """Every (key, value) whose key starts with `prefix` — the
        membership-enumeration extension (the in-memory test KV
        mirrors it; jaxlib's client spells it key_value_dir_get)."""
        out: "dict[str, str]" = {}
        for name in os.listdir(self.root):
            if name.endswith(".tmp") or ".tmp." in name:
                continue
            try:
                key = base64.urlsafe_b64decode(
                    name.encode("ascii")).decode("utf-8")
            except Exception:
                continue
            if not key.startswith(prefix):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    out[key] = f.read()
            except FileNotFoundError:
                continue
        return out


def kv_list(client, prefix: str) -> "dict[str, str]":
    """Prefix enumeration over whichever client we were handed:
    FileKVClient/_MemKV spell it key_value_list; the jaxlib
    coordination client spells it key_value_dir_get (pair list)."""
    lister = getattr(client, "key_value_list", None)
    if lister is not None:
        return dict(lister(prefix))
    dir_get = getattr(client, "key_value_dir_get", None)
    if dir_get is not None:
        return {k: v for k, v in dir_get(prefix)}
    raise RuntimeError(
        f"KV client {type(client).__name__} supports neither "
        "key_value_list nor key_value_dir_get — membership "
        "enumeration needs one"
    )


_KVLEN = struct.Struct("!I")
_KV_MAX_FRAME = 16 << 20  # a KV value is a roster record, not a payload


def _kv_send(sock: socket.socket, obj, lock=None) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    data = _KVLEN.pack(len(payload)) + payload
    if lock is not None:
        with lock:
            sock.sendall(data)
    else:
        sock.sendall(data)


def _kv_recv(sock: socket.socket):
    buf = b""
    while len(buf) < _KVLEN.size:
        chunk = sock.recv(_KVLEN.size - len(buf))
        if not chunk:
            raise ConnectionError("KV peer closed")
        buf += chunk
    (n,) = _KVLEN.unpack(buf)
    if n > _KV_MAX_FRAME:
        raise ConnectionError(f"oversized KV frame: {n} bytes")
    parts, got = [], 0
    while got < n:
        chunk = sock.recv(min(65536, n - got))
        if not chunk:
            raise ConnectionError("KV peer closed mid-frame")
        parts.append(chunk)
        got += len(chunk)
    return json.loads(b"".join(parts).decode("utf-8"))


class KVServer:
    """A TCP daemon exposing the coordination-client KV surface to the
    whole fleet — the cross-host replacement for FileKVClient's shared
    directory.  One in-memory dict under a lock; requests are
    length-prefixed JSON frames (op/key/value), one response per
    request, one thread per connection (fleet control traffic is a few
    ops per heartbeat interval, nowhere near thread-pool territory).
    Run it next to one router (``ml_ops route --kv-listen``); everyone
    else connects a TcpKVClient."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._store: "dict[str, str]" = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._listener = socket.create_server((host, port))
        self.host, self.port = self._listener.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept, name="oni-kv-server", daemon=True)
        self._accept_thread.start()

    def _accept(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._closed.is_set():
                req = _kv_recv(conn)
                _kv_send(conn, self._apply(req))
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _apply(self, req: dict) -> dict:
        op = req.get("op")
        key = req.get("key", "")
        with self._lock:
            if op == "set":
                if not req.get("overwrite") and key in self._store:
                    return {"ok": False, "err": f"ALREADY_EXISTS: {key}"}
                self._store[key] = req.get("value", "")
                return {"ok": True}
            if op == "get":
                if key in self._store:
                    return {"ok": True, "value": self._store[key]}
                return {"ok": False, "err": f"NOT_FOUND: {key}"}
            if op == "delete":
                self._store.pop(key, None)
                return {"ok": True}
            if op == "list":
                prefix = req.get("prefix", "")
                return {"ok": True,
                        "items": {k: v for k, v in self._store.items()
                                  if k.startswith(prefix)}}
        return {"ok": False, "err": f"UNKNOWN_OP: {op}"}

    def close(self) -> None:
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass


class TcpKVClient:
    """FileKVClient's surface over one KVServer connection.  Blocking
    gets poll client-side (same contract, same DEADLINE_EXCEEDED
    error) so the server never parks a thread per waiter.  Thread-safe:
    one socket, one lock around each request/response exchange."""

    _POLL_S = 0.005

    def __init__(self, host: str, port: int,
                 connect_timeout_s: float = 5.0) -> None:
        self._sock = socket.create_connection(
            (host, port), timeout=connect_timeout_s)
        self._sock.settimeout(30.0)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def _call(self, req: dict) -> dict:
        with self._lock:
            _kv_send(self._sock, req)
            return _kv_recv(self._sock)

    def key_value_set(self, key: str, value: str,
                      allow_overwrite: bool = False) -> None:
        rsp = self._call({"op": "set", "key": key, "value": value,
                          "overwrite": bool(allow_overwrite)})
        if not rsp.get("ok"):
            raise RuntimeError(rsp.get("err", "KV set failed"))

    def blocking_key_value_get(self, key: str,
                               timeout_in_ms: int) -> str:
        deadline = time.monotonic() + timeout_in_ms / 1000.0
        while True:
            rsp = self._call({"op": "get", "key": key})
            if rsp.get("ok"):
                return rsp["value"]
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(f"DEADLINE_EXCEEDED: {key}")
            time.sleep(min(self._POLL_S, remaining))

    def key_value_delete(self, key: str) -> None:
        rsp = self._call({"op": "delete", "key": key})
        if not rsp.get("ok"):
            raise RuntimeError(rsp.get("err", "KV delete failed"))

    def key_value_list(self, prefix: str) -> "dict[str, str]":
        rsp = self._call({"op": "list", "prefix": prefix})
        if not rsp.get("ok"):
            raise RuntimeError(rsp.get("err", "KV list failed"))
        return dict(rsp.get("items", {}))

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _enc(obj) -> str:
    """JSON-in-base64: keeps the string-value KV convention of the
    coordination client while staying pickle-free (roster records are
    plain dicts of scalars, so JSON is lossless here)."""
    return base64.b64encode(
        json.dumps(obj, sort_keys=True, separators=(",", ":"))
        .encode("utf-8")).decode("ascii")


def _dec(value: str):
    return json.loads(base64.b64decode(value).decode("utf-8"))


class MembershipClient:
    """The fleet roster over one KV namespace.  Thread-safe: every
    method is a single KV op (plus a per-instance heartbeat sequence
    counter under its own lock)."""

    def __init__(self, kv, namespace: str = "oni/fleet") -> None:
        self._kv = kv
        self._ns = namespace.rstrip("/")
        self._lock = threading.Lock()
        self._hb_seq = 0

    # -- roster -----------------------------------------------------------

    def register(self, replica_id: str, meta: "dict | None" = None) -> None:
        """Announce one replica (idempotent — re-registration
        overwrites, which is what a respawned replica under the same
        id wants).  Wall-clock stamped: registration times compare
        across processes."""
        rec = {"meta": dict(meta or {}),
               "t": time.time()}  # lint: ok(monotonic-clock, cross-process roster stamps must share the wall-clock epoch)
        self._kv.key_value_set(f"{self._ns}/m/{replica_id}", _enc(rec),
                               allow_overwrite=True)

    def deregister(self, replica_id: str) -> None:
        self._kv.key_value_delete(f"{self._ns}/m/{replica_id}")
        self._kv.key_value_delete(f"{self._ns}/hb/{replica_id}")

    def members(self) -> "dict[str, dict]":
        out = {}
        prefix = f"{self._ns}/m/"
        for key, value in kv_list(self._kv, prefix).items():
            try:
                out[key[len(prefix):]] = _dec(value)
            except Exception:
                continue
        return out

    # -- liveness ---------------------------------------------------------

    def heartbeat(self, replica_id: str,
                  payload: "dict | None" = None) -> None:
        with self._lock:
            self._hb_seq += 1
            seq = self._hb_seq
        rec = {"seq": seq, **(payload or {}),
               "t": time.time()}  # lint: ok(monotonic-clock, heartbeat freshness is judged by ANOTHER process's clock)
        self._kv.key_value_set(f"{self._ns}/hb/{replica_id}", _enc(rec),
                               allow_overwrite=True)

    def heartbeats(self) -> "dict[str, dict]":
        out = {}
        prefix = f"{self._ns}/hb/"
        for key, value in kv_list(self._kv, prefix).items():
            try:
                out[key[len(prefix):]] = _dec(value)
            except Exception:
                continue
        return out

    def alive(self, ttl_s: float) -> "dict[str, dict]":
        """Members whose last heartbeat is younger than `ttl_s` (by
        THIS process's wall clock — same-host deployments share it;
        cross-host ones need NTP-grade agreement, stated in docs)."""
        now = time.time()  # lint: ok(monotonic-clock, compared against peer processes' wall stamps)
        return {
            rid: hb for rid, hb in self.heartbeats().items()
            if now - hb.get("t", 0.0) <= ttl_s
        }

    # -- failure relay ----------------------------------------------------

    def fail(self, replica_id: str, reason: str) -> None:
        """Post one replica's failure for every monitor poll to see —
        the serving twin of Collective.fail.  Best-effort: the
        process is usually on its way out."""
        try:
            self._kv.key_value_set(
                f"{self._ns}/fail/{replica_id}",
                _enc({"reason": str(reason)[:500],
                      "t": time.time()}),  # lint: ok(monotonic-clock, failure stamps are read by other processes)
                allow_overwrite=True,
            )
        except Exception:
            pass

    def failures(self) -> "dict[str, dict]":
        out = {}
        prefix = f"{self._ns}/fail/"
        for key, value in kv_list(self._kv, prefix).items():
            try:
                out[key[len(prefix):]] = _dec(value)
            except Exception:
                continue
        return out

    def clear_failure(self, replica_id: str) -> None:
        self._kv.key_value_delete(f"{self._ns}/fail/{replica_id}")

    # -- promotion claims -------------------------------------------------

    def claim_promotion(self, replica_id: str, router_id: str) -> bool:
        """First-writer-wins claim on failing over `replica_id`.  With
        N routers watching the same fleet, every one of them sees the
        same dead link; exactly one should re-push tenant state to the
        promoted successors.  The claim is an overwrite-forbidden set —
        the KV's ALREADY_EXISTS is the election: True means this router
        owns the backfill, False means a peer already claimed it (the
        loser still promotes locally, placement being a pure function
        of membership, and just skips the pushes).

        Only a genuine ALREADY_EXISTS loses the election.  A transport
        error (KV server unreachable or timing out — likely in exactly
        the degraded scenario failover exists for) claims by DEFAULT:
        if every router treated it as a loss, none would push the
        promoted tenants' models and the new primaries would serve
        nothing.  Duplicate pushes are safe (replica add_tenant is
        router_version-idempotent); zero pushes are silent data-path
        loss."""
        try:
            self._kv.key_value_set(
                f"{self._ns}/promote/{replica_id}",
                _enc({"router": router_id,
                      "t": time.time()}),  # lint: ok(monotonic-clock, claim stamps are read by peer routers)
                allow_overwrite=False,
            )
            return True
        except Exception as e:
            if "ALREADY_EXISTS" in str(e):
                return False
            return True

    def clear_promotion(self, replica_id: str) -> None:
        """Forget a settled claim so a future respawn under the same id
        can fail over again (called when a router [re]connects the
        replica)."""
        self._kv.key_value_delete(f"{self._ns}/promote/{replica_id}")


class HeartbeatPublisher:
    """Replica-side liveness daemon: publish a heartbeat every
    `interval_s` until stop().  `payload_fn` (optional) contributes
    live stats to each beat (queue depth, events scored) so the
    router's monitor reads load without an extra RPC."""

    def __init__(self, membership: MembershipClient, replica_id: str,
                 interval_s: float, payload_fn=None) -> None:
        self._membership = membership
        self._replica_id = replica_id
        self._interval_s = interval_s
        self._payload_fn = payload_fn
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"oni-hb-{replica_id}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                payload = self._payload_fn() if self._payload_fn else None
            except Exception:
                # The payload hook is the publisher's health gate: a
                # raise means the replica declared itself unhealthy
                # (serving/replica.py posts the fail key first) — stop
                # beating, so the heartbeat SILENCE corroborates the
                # fail key instead of contradicting it.
                return
            try:
                self._membership.heartbeat(self._replica_id, payload)
            except Exception:
                # A failed beat is indistinguishable from a late one to
                # the monitor; keep trying until stopped.
                pass
            self._stop.wait(self._interval_s)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
