"""KV-backed membership, heartbeats, and failure relay for the
replicated serving fleet.

PR 11 built the process-group control plane for distributed EM on the
``jax.distributed`` coordination client's KV store (parallel/
allreduce.py): bounded blocking gets, a fail key every blocked peer
polls, chunked base64 values.  Replicated serving (ROADMAP item 5)
needs the same three primitives — who is in the fleet, who is still
alive, who failed — but for *elastic* membership: serve replicas join,
drain, and die independently, which the fixed-rank jax.distributed
world cannot express.  This module reuses the CLIENT INTERFACE (so the
same code runs over the coordination service, the in-memory test KV,
or the file store below) and layers membership on top:

``FileKVClient``
    A same-host, cross-process KV store with the coordination client's
    exact surface (``key_value_set`` / ``blocking_key_value_get`` /
    ``key_value_delete``) plus ``key_value_list`` for membership
    enumeration.  One file per key (name = urlsafe base64 of the key,
    so arbitrary key strings never escape the root), atomic
    tmp+``os.replace`` publication, polling blocking gets with the
    DEADLINE_EXCEEDED error contract Collective._kv_get expects.  This
    is what `ml_ops route` uses to coordinate replica subprocesses —
    no coordination service to stand up, nothing to clean beyond the
    directory.

``MembershipClient``
    register / deregister / members / heartbeat / alive / fail over
    any such KV client.  Heartbeats are wall-clock stamped (they
    compare across PROCESSES, where monotonic clocks share no epoch)
    and carry a per-publisher sequence number so a reader can tell a
    fresh heartbeat from a re-read.  The fail key is per-replica —
    a failing replica posts its reason; the router's monitor polls
    failures between heartbeat checks exactly like the allreduce
    wait-slice poll.

``HeartbeatPublisher``
    The replica-side daemon thread publishing liveness every
    ``interval_s`` until ``stop()``.
"""

from __future__ import annotations

import base64
import os
import pickle
import threading
import time


class FileKVClient:
    """Directory-backed KV store satisfying the coordination-client
    interface for same-host multi-process fleets.  Values are strings
    (the Collective/base64 convention); a set is atomic via
    tmp+rename, so a reader never observes a torn value."""

    # Poll cadence for blocking gets: coarse enough to stay invisible
    # in CPU profiles, fine enough that a heartbeat-interval wait
    # never quantizes noticeably.
    _POLL_S = 0.005

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        name = base64.urlsafe_b64encode(key.encode("utf-8")).decode(
            "ascii")
        return os.path.join(self.root, name)

    def key_value_set(self, key: str, value: str,
                      allow_overwrite: bool = False) -> None:
        path = self._path(key)
        if not allow_overwrite and os.path.exists(path):
            raise RuntimeError(f"ALREADY_EXISTS: {key}")
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            f.write(value)
        os.replace(tmp, path)

    def blocking_key_value_get(self, key: str,
                               timeout_in_ms: int) -> str:
        deadline = time.monotonic() + timeout_in_ms / 1000.0
        path = self._path(key)
        while True:
            try:
                with open(path) as f:
                    return f.read()
            except FileNotFoundError:
                pass
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(f"DEADLINE_EXCEEDED: {key}")
            time.sleep(min(self._POLL_S, remaining))

    def key_value_delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def key_value_list(self, prefix: str) -> "dict[str, str]":
        """Every (key, value) whose key starts with `prefix` — the
        membership-enumeration extension (the in-memory test KV
        mirrors it; jaxlib's client spells it key_value_dir_get)."""
        out: "dict[str, str]" = {}
        for name in os.listdir(self.root):
            if name.endswith(".tmp") or ".tmp." in name:
                continue
            try:
                key = base64.urlsafe_b64decode(
                    name.encode("ascii")).decode("utf-8")
            except Exception:
                continue
            if not key.startswith(prefix):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    out[key] = f.read()
            except FileNotFoundError:
                continue
        return out


def kv_list(client, prefix: str) -> "dict[str, str]":
    """Prefix enumeration over whichever client we were handed:
    FileKVClient/_MemKV spell it key_value_list; the jaxlib
    coordination client spells it key_value_dir_get (pair list)."""
    lister = getattr(client, "key_value_list", None)
    if lister is not None:
        return dict(lister(prefix))
    dir_get = getattr(client, "key_value_dir_get", None)
    if dir_get is not None:
        return {k: v for k, v in dir_get(prefix)}
    raise RuntimeError(
        f"KV client {type(client).__name__} supports neither "
        "key_value_list nor key_value_dir_get — membership "
        "enumeration needs one"
    )


def _enc(obj) -> str:
    return base64.b64encode(pickle.dumps(obj, protocol=4)).decode(
        "ascii")


def _dec(value: str):
    return pickle.loads(base64.b64decode(value))


class MembershipClient:
    """The fleet roster over one KV namespace.  Thread-safe: every
    method is a single KV op (plus a per-instance heartbeat sequence
    counter under its own lock)."""

    def __init__(self, kv, namespace: str = "oni/fleet") -> None:
        self._kv = kv
        self._ns = namespace.rstrip("/")
        self._lock = threading.Lock()
        self._hb_seq = 0

    # -- roster -----------------------------------------------------------

    def register(self, replica_id: str, meta: "dict | None" = None) -> None:
        """Announce one replica (idempotent — re-registration
        overwrites, which is what a respawned replica under the same
        id wants).  Wall-clock stamped: registration times compare
        across processes."""
        rec = {"meta": dict(meta or {}),
               "t": time.time()}  # lint: ok(monotonic-clock, cross-process roster stamps must share the wall-clock epoch)
        self._kv.key_value_set(f"{self._ns}/m/{replica_id}", _enc(rec),
                               allow_overwrite=True)

    def deregister(self, replica_id: str) -> None:
        self._kv.key_value_delete(f"{self._ns}/m/{replica_id}")
        self._kv.key_value_delete(f"{self._ns}/hb/{replica_id}")

    def members(self) -> "dict[str, dict]":
        out = {}
        prefix = f"{self._ns}/m/"
        for key, value in kv_list(self._kv, prefix).items():
            try:
                out[key[len(prefix):]] = _dec(value)
            except Exception:
                continue
        return out

    # -- liveness ---------------------------------------------------------

    def heartbeat(self, replica_id: str,
                  payload: "dict | None" = None) -> None:
        with self._lock:
            self._hb_seq += 1
            seq = self._hb_seq
        rec = {"seq": seq, **(payload or {}),
               "t": time.time()}  # lint: ok(monotonic-clock, heartbeat freshness is judged by ANOTHER process's clock)
        self._kv.key_value_set(f"{self._ns}/hb/{replica_id}", _enc(rec),
                               allow_overwrite=True)

    def heartbeats(self) -> "dict[str, dict]":
        out = {}
        prefix = f"{self._ns}/hb/"
        for key, value in kv_list(self._kv, prefix).items():
            try:
                out[key[len(prefix):]] = _dec(value)
            except Exception:
                continue
        return out

    def alive(self, ttl_s: float) -> "dict[str, dict]":
        """Members whose last heartbeat is younger than `ttl_s` (by
        THIS process's wall clock — same-host deployments share it;
        cross-host ones need NTP-grade agreement, stated in docs)."""
        now = time.time()  # lint: ok(monotonic-clock, compared against peer processes' wall stamps)
        return {
            rid: hb for rid, hb in self.heartbeats().items()
            if now - hb.get("t", 0.0) <= ttl_s
        }

    # -- failure relay ----------------------------------------------------

    def fail(self, replica_id: str, reason: str) -> None:
        """Post one replica's failure for every monitor poll to see —
        the serving twin of Collective.fail.  Best-effort: the
        process is usually on its way out."""
        try:
            self._kv.key_value_set(
                f"{self._ns}/fail/{replica_id}",
                _enc({"reason": str(reason)[:500],
                      "t": time.time()}),  # lint: ok(monotonic-clock, failure stamps are read by other processes)
                allow_overwrite=True,
            )
        except Exception:
            pass

    def failures(self) -> "dict[str, dict]":
        out = {}
        prefix = f"{self._ns}/fail/"
        for key, value in kv_list(self._kv, prefix).items():
            try:
                out[key[len(prefix):]] = _dec(value)
            except Exception:
                continue
        return out

    def clear_failure(self, replica_id: str) -> None:
        self._kv.key_value_delete(f"{self._ns}/fail/{replica_id}")


class HeartbeatPublisher:
    """Replica-side liveness daemon: publish a heartbeat every
    `interval_s` until stop().  `payload_fn` (optional) contributes
    live stats to each beat (queue depth, events scored) so the
    router's monitor reads load without an extra RPC."""

    def __init__(self, membership: MembershipClient, replica_id: str,
                 interval_s: float, payload_fn=None) -> None:
        self._membership = membership
        self._replica_id = replica_id
        self._interval_s = interval_s
        self._payload_fn = payload_fn
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"oni-hb-{replica_id}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                payload = self._payload_fn() if self._payload_fn else None
            except Exception:
                # The payload hook is the publisher's health gate: a
                # raise means the replica declared itself unhealthy
                # (serving/replica.py posts the fail key first) — stop
                # beating, so the heartbeat SILENCE corroborates the
                # fail key instead of contradicting it.
                return
            try:
                self._membership.heartbeat(self._replica_id, payload)
            except Exception:
                # A failed beat is indistinguishable from a late one to
                # the monitor; keep trying until stopped.
                pass
            self._stop.wait(self._interval_s)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
