"""Device mesh construction — the framework's answer to the reference's
three transports (SURVEY.md §5.8: MPI for the EM reduce, HDFS for Spark
interchange, scp/rsync for corpus fan-out).

One logical 2-D mesh covers every scale the reference ran at and beyond:

- axis ``data``  — documents are sharded across it; the E-step's
  sufficient-statistics reduction is a ``psum`` over this axis riding ICI
  (DCN between slices), replacing the 20-rank ``MPI_Reduce`` at
  ml_ops.sh:80 / README.md:121.
- axis ``model`` — the vocabulary dimension of beta/suff-stats is sharded
  across it for huge-V corpora (BASELINE.json config 4, DNS vocab), the
  analogue the reference never had (its beta was replicated per rank).

Single device is the (1, 1) mesh; nothing else in the stack branches on
scale.  Multi-host: call `initialize_distributed()` once per process
before building the mesh, and the same code runs over every host's local
devices (jax.distributed handles DCN bootstrap, where the reference used
`scp` + machinefile).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(data: int = -1, model: int = 1, devices=None) -> Mesh:
    """Build the (data, model) mesh.  data=-1 means "all remaining
    devices"."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if data == -1:
        if n % model:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model > n:
        raise ValueError(f"mesh {data}x{model} needs {data*model} devices, have {n}")
    if data * model < n:
        import warnings

        warnings.warn(
            f"mesh {data}x{model} uses {data*model} of {n} devices; "
            f"{n - data*model} left idle",
            stacklevel=2,
        )
    grid = devices[: data * model].reshape(data, model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def local_mesh(data: int = -1, model: int = 1) -> Mesh:
    """The (data, model) mesh over THIS process's devices only — the
    host-local training mesh of distributed EM (parallel/allreduce.py):
    each rank runs its E-step shards over its own devices and the
    cross-process reduction is an explicit collective, never a global
    mesh spanning processes (which the CPU runtime cannot execute and
    which forced the sparse engine dense).  data=-1 means all local
    devices."""
    return make_mesh(data=data, model=model, devices=jax.local_devices())


def is_local_mesh(mesh: Mesh) -> bool:
    """True when every device of `mesh` belongs to this process — the
    only meshes the distributed host-local trainers accept."""
    pid = jax.process_index()
    return all(d.process_index == pid for d in mesh.devices.flat)


def mesh_from_spec(spec: str) -> tuple[Mesh, bool]:
    """Parse a "DATA,MODEL" mesh spec (CLI flag / env var) into a mesh
    plus whether the vocabulary should shard (model axis > 1)."""
    parts = spec.split(",")
    if len(parts) != 2:
        raise ValueError(
            f"mesh spec must be 'DATA,MODEL' (e.g. '8,1'), got {spec!r}"
        )
    try:
        data, model = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"mesh spec must be two integers 'DATA,MODEL', got {spec!r}"
        ) from None
    if data < 1 or model < 1:
        raise ValueError(
            f"mesh spec 'DATA,MODEL' axes must be >= 1, got {spec!r}"
        )
    return make_mesh(data=data, model=model), model > 1


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host bootstrap.  On TPU pods all three arguments are inferred
    from the runtime environment; on other platforms pass them explicitly.
    Must run before any other JAX call in the process."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Documents (leading batch axis) sharded over `data`."""
    return NamedSharding(mesh, P(DATA_AXIS))


def stacked_batch_sharding(mesh: Mesh) -> NamedSharding:
    """[N, B, ...] stacks of micro-batches: docs (axis 1) sharded over
    `data`, the stack axis replicated (each scan step consumes one
    full micro-batch)."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def vocab_sharding(mesh: Mesh) -> NamedSharding:
    """[V, K] suff-stats sharded over `model` on the vocab axis."""
    return NamedSharding(mesh, P(MODEL_AXIS, None))


def beta_sharding(mesh: Mesh) -> NamedSharding:
    """[K, V] beta sharded over `model` on the vocab axis."""
    return NamedSharding(mesh, P(None, MODEL_AXIS))
