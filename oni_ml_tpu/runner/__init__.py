"""End-to-end pipeline runner — the framework's replacement for
ml_ops.sh."""

from .ml_ops import MissingArtifactError, run_pipeline, Stage

__all__ = ["run_pipeline", "Stage", "MissingArtifactError"]
