"""Continuous ingestion — the standing service that kills the day
boundary (ROADMAP item 3; `ml_ops continuous`).

The batch pipeline's unit of work is one FINISHED day: an event at
00:05 is servable ~24 h later, and every day pays a full
EM-from-scratch even when the topics barely moved.  This runner
generalizes the PR 8 streaming dataplane into a standing loop on one
process — the same devices the serving fleet scores from:

    raw slices ──► featurization ──► CorpusWindow (ring-buffered CSR,
       │                              first-seen vocab growth,
       │                              O(evicted) retirement)
       └────────► FleetScorer (events scored under the CURRENT model
                  the moment they arrive — servable in seconds)

    every refresh_every_s of event time:
        window.advance ─► snapshot (pow2 vocab capacity tier)
        ─► WindowTrainer.fit  (warm-started from the previous
           published topics; the f64 convergence check early-exits
           after the few iterations the stream actually moved)
        ─► DriftDetector.evaluate/check  (held-out per-token LL vs
           the journal's rolling history)
        ─► publish gate: drifted models are VETOED and never reach
           FleetRegistry — serving keeps the prior version
           bit-identically; healthy models hot-swap in.

Zero post-warmup retraces by construction: the window pads its
vocabulary to pow2 capacity tiers (the compiled [K, V] family is
keyed by tier, not census), window batches pad to the full batch
size, the refresh reuses ONE WindowTrainer's jitted programs, and the
fleet's capacity-tiered stack keys the serving dispatch by capacity.
The freshness ledger (event arrival → a model covering the event
published) is the headline the streaming_freshness bench reports.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import tempfile
import threading
import time
from contextlib import nullcontext
from dataclasses import dataclass, field

import numpy as np

from ..config import PipelineConfig
from ..io import formats
from ..sources import get as get_source
from ..sources import names as source_names


@dataclass
class IngestSlice:
    """One paced ingest unit: raw event lines covering [t0, t1) of
    EVENT time, stamped with the wall clock it was delivered at."""

    lines: list
    t0: float
    t1: float
    arrival_wall: float = 0.0
    index: int = 0

    @property
    def events(self) -> int:
        return len(self.lines)


def event_time_s(line: str, dsource: str) -> float:
    """Event-time seconds for one raw CSV line, through the source
    spec's clock hook (flow: h/m/s columns; dns: unix_tstamp; declared
    sources: their `time_field`)."""
    return get_source(dsource).event_time_s(line)


def slice_events(
    lines, dsource: str, slice_s: float, *, t_base: "float | None" = None
) -> "list[IngestSlice]":
    """Order raw lines by event time and cut them into fixed
    `slice_s`-second slices — the replay decomposition of a historical
    day into the stream the day never was.  Deterministic: stable sort
    by event time, empty slices dropped.  Lines whose time columns do
    not parse (the reference day files' header row, truncated tails)
    are skipped, matching the featurizers' garbage-row tolerance."""
    if slice_s <= 0:
        raise ValueError(f"slice_s must be > 0, got {slice_s}")
    rows = []
    parsed = []
    # lint: ok(hot-path-event-loop, ingest-time slice ordering — one time-field parse per line at admission, off the flush path)
    for ln in lines:
        if not ln.strip():
            continue
        try:
            parsed.append(event_time_s(ln, dsource))
        except (ValueError, IndexError):
            continue          # header / malformed row: not an event
        rows.append(ln)
    times = np.asarray(parsed, np.float64)
    order = np.argsort(times, kind="stable")
    if t_base is None:
        t_base = float(times[order[0]]) if len(order) else 0.0
    slices: list[IngestSlice] = []
    cur: list = []
    cur_idx = 0
    for j in order:
        idx = int((times[j] - t_base) // slice_s)
        if cur and idx != cur_idx:
            slices.append(IngestSlice(
                lines=cur, t0=t_base + cur_idx * slice_s,
                t1=t_base + (cur_idx + 1) * slice_s, index=len(slices),
            ))
            cur = []
        if not cur:
            cur_idx = idx
        cur.append(rows[int(j)])
    if cur:
        slices.append(IngestSlice(
            lines=cur, t0=t_base + cur_idx * slice_s,
            t1=t_base + (cur_idx + 1) * slice_s, index=len(slices),
        ))
    return slices


def paced_slices(slices, speed: float, *, sleep=time.sleep):
    """Deliver slices at ×`speed` real time: the wall gap between
    consecutive slices is their event-time gap divided by `speed`.
    Stamps each slice's `arrival_wall` at delivery.  `speed=inf` (or
    any non-positive sleep result) delivers as fast as downstream
    consumes — the no-sleep test/bench mode."""
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    t_wall0 = time.perf_counter()
    t_sim0 = None
    for sl in slices:
        if t_sim0 is None:
            t_sim0 = sl.t1
        due = t_wall0 + (sl.t1 - t_sim0) / speed
        delay = due - time.perf_counter()
        if delay > 0 and np.isfinite(delay):
            sleep(delay)
        sl.arrival_wall = time.perf_counter()
        yield sl


@dataclass
class _SliceLedger:
    """Freshness bookkeeping for one ingested slice: arrival wall
    stamp, event count, event-time span end.  The service keeps only
    slices not yet covered by a publish (covered entries drop at the
    publish that covers them — they can never be re-covered)."""

    index: int
    arrival_wall: float
    events: int
    t1: float


@dataclass
class ContinuousResult:
    """run_continuous' payload (also what `ml_ops continuous`
    prints)."""

    payload: dict = field(default_factory=dict)


def _featurize_slice(lines, dsource: str, cuts):
    """One slice through the source's batch featurizer with PINNED cuts
    (a slice's own ECDF would bin values differently slice-over-slice
    and churn the vocabulary for nothing — serving/events.py's rule)."""
    return get_source(dsource).featurize(
        lines, skip_header=False, precomputed_cuts=cuts
    )


def _derive_cuts(lines, dsource: str, qtiles_path: str = ""):
    """Pin the stream's quantile cuts: from a qtiles file when the
    source supports one (stable word identity across service restarts),
    else from the bootstrap slice's own ECDF."""
    return get_source(dsource).derive_cuts(lines, qtiles_path)


class ContinuousService:
    """The standing train-and-serve loop.  Drive it with
    `run(slices)` (a paced IngestSlice iterable) or slice-by-slice via
    `ingest_slice` + `maybe_refresh` — tests inject drift that way."""

    def __init__(
        self,
        config: PipelineConfig,
        dsource: str,
        *,
        out_dir: str,
        tenant: str = "stream",
        fresh_control: bool = False,
        warmup_refreshes: "int | None" = None,
        journal=None,
        recorder=None,
        coscheduler=None,
        collective=None,
        publisher=None,
        freshness_sink=None,
    ) -> None:
        """Standalone by default; the composed (fleet) mode injects
        shared infrastructure:

        journal/recorder
            ONE RunJournal/Recorder shared by every per-tenant service
            (the fleet orchestrator owns their lifecycle; this service
            then scopes its histogram names by tenant and never calls
            run_start/run_end).
        coscheduler
            serving.CoScheduler — refresh fits run as preemptible
            chunks (the trainer's yield hook), slice scoring takes the
            high-priority serve slot.
        collective
            parallel.Collective — window refreshes train DISTRIBUTED
            (suff-stats allreduce, warm-start broadcast, vocab capacity
            tiers rank-synchronized so compiled shapes agree).
        publisher
            RouterBinding — publishes fan out through the replicated
            FleetRouter instead of the in-process FleetRegistry, and
            slice scoring rides the router's replicas.
        freshness_sink
            callable(wall_s, event_s) per covered slice — the fleet's
            cross-tenant freshness aggregate.
        """
        if dsource not in source_names():
            raise ValueError(
                f"dsource must be one of {'|'.join(source_names())}, "
                f"got {dsource!r}"
            )
        self.config = config
        self.cc = config.continuous
        self.dsource = dsource
        self.out_dir = formats.ensure_dir(out_dir)
        self.tenant = tenant
        self.fresh_control = fresh_control
        if warmup_refreshes is None:
            # "Post-warmup" starts once the window first reaches steady
            # state: while it is still FILLING (the first
            # window_s/refresh_every_s refreshes), each refresh can
            # legitimately meet a novel doc-length bucket and trace it
            # — that is startup, not churn.
            warmup_refreshes = int(
                np.ceil(self.cc.window_s
                        / max(self.cc.refresh_every_s, 1e-9))
            ) + 1
        self.warmup_refreshes = int(warmup_refreshes)

        from ..dataplane import CorpusWindow
        from ..models.drift import DriftDetector
        from ..serving import FleetRegistry, TenantSpec
        from ..telemetry import Journal, Recorder, RunJournal

        self.cosched = coscheduler
        self.collective = collective
        self.publisher = publisher
        self._freshness_sink = freshness_sink
        # Ingest (window growth, ledger append, scoring) and refresh
        # (advance/snapshot, ledger resolution) run on DIFFERENT
        # threads in the composed mode; this lock covers exactly the
        # window+ledger mutations.  Uncontended in the classic
        # single-thread drive.
        self._lock = threading.Lock()
        tel = config.telemetry
        self._owns_journal = journal is None
        # Fleet composition (shared out_dir, maybe-shared recorder):
        # scope histogram names and the metrics filename by tenant so
        # N services never collide.
        self._shared = (journal is not None or publisher is not None
                        or freshness_sink is not None)
        self.journal = None
        self.recorder = None
        if journal is not None:
            self.journal = journal
            self.recorder = recorder
            replayed = []
        elif tel.journal:
            jpath = os.path.join(self.out_dir, "run_journal.jsonl")
            replayed = Journal.replay(jpath)
            self.journal = RunJournal(
                Journal(jpath, fsync_every=tel.journal_fsync_every)
            )
            self.journal.run_start(
                mode="continuous", dsource=dsource, tenant=tenant,
                window_s=self.cc.window_s,
                refresh_every_s=self.cc.refresh_every_s,
                replayed_records=len(replayed),
            )
            self.recorder = Recorder(journal=self.journal.journal)
        else:
            replayed = []
        raw_journal = (
            self.journal.journal if self.journal is not None else None
        )
        self.window = CorpusWindow(
            self.cc.window_s, vocab_floor=self.cc.vocab_floor,
            recorder=self.recorder, journal=raw_journal,
        )
        self.drift = DriftDetector(
            tol_nats=self.cc.drift_tol_nats,
            history=self.cc.drift_history,
            min_history=self.cc.drift_min_history,
            journal=raw_journal, recorder=self.recorder,
        )
        # A restarted service resumes its drift baseline from the
        # journal instead of re-learning it over min_history refreshes.
        self.drift.prime(replayed)
        self._replayed = replayed
        self._qgate = None          # built lazily once cuts are pinned
        self.fleet = FleetRegistry(
            journal=raw_journal, recorder=self.recorder,
            capacity_tiers=True,
        )
        self.fleet.add_tenant(TenantSpec(tenant=tenant, dsource=dsource))
        self.scorer = None          # created at first publish
        self.cuts = None            # pinned at bootstrap
        self.trainer = None         # one per vocab capacity tier
        self.tier_rebuilds = 0
        self._prev_probs = None     # last PUBLISHED [V_real, K]
        self._prev_alpha = None
        self._last_fresh_iters = None
        self._next_refresh_t = None
        self._ledger: list[_SliceLedger] = []
        from ..telemetry.spans import Recorder as _Recorder

        rec = self.recorder or _Recorder()
        # Shared-recorder (fleet) mode scopes histogram names by tenant
        # — N services on one Recorder must not fold their ledgers into
        # one histogram (the per-tenant freshness contract).
        scope = f".{tenant}" if self._shared else ""
        # Two freshness ledgers: wall-clock (what THIS replay measured,
        # speed-dependent) and event-time (cadence lag + refresh wall —
        # what a real-time deployment would deliver, speed-invariant).
        self._freshness = rec.histogram("continuous.freshness_s" + scope)
        self._freshness_event = rec.histogram(
            "continuous.freshness_event_s" + scope
        )
        # Slice-level serve wall (submit→flush return), split by
        # whether a refresh fit was active at entry: the co-scheduler's
        # acceptance number is the refresh-active tail vs the idle one.
        self._serve_idle_ms = rec.histogram(
            "continuous.serve_idle_ms" + scope
        )
        self._serve_refresh_ms = rec.histogram(
            "continuous.serve_refresh_ms" + scope
        )
        self._freshness_count = 0
        self._tier_syncs = 0
        # A standing service runs indefinitely: per-refresh detail is
        # bounded (the journal holds the full history); aggregates are
        # running sums.
        from collections import deque as _deque

        self.refresh_records: "_deque[dict]" = _deque(maxlen=1024)
        self.refresh_count = 0
        self._fit_agg = {
            True: {"fits": 0, "wall_s": 0.0, "em_iters": 0},
            False: {"fits": 0, "wall_s": 0.0, "em_iters": 0},
        }
        self.events = 0
        self.slices = 0
        self.events_rejected = 0
        self.flagged = 0
        self.skipped_refreshes = 0
        self.control_record = None
        self._warmup_counts = None
        self._lda_cfg = None
        self._flagged_file = None
        self._last_ll = None

    # -- per-slice ingest ------------------------------------------------

    def ingest_slice(self, sl: IngestSlice) -> None:
        from ..dataplane import word_count_columns

        if sl.arrival_wall == 0.0:
            sl.arrival_wall = time.perf_counter()
        if self.cuts is None:
            self.cuts = _derive_cuts(sl.lines, self.dsource,
                                     self.config.qtiles_path)
        feats = _featurize_slice(sl.lines, self.dsource, self.cuts)
        with self._lock:
            self.window.ingest(word_count_columns(feats), sl.t0, sl.t1)
            self._ledger.append(_SliceLedger(
                index=sl.index, arrival_wall=sl.arrival_wall,
                events=sl.events, t1=sl.t1,
            ))
        if self._next_refresh_t is None:
            self._next_refresh_t = sl.t1 + self.cc.refresh_every_s
        self.slices += 1
        self.events += sl.events
        self._score_slice(sl)

    def _score_slice(self, sl: IngestSlice) -> None:
        """Scored-the-moment-they-arrive: every event rides the
        serving path under the CURRENT published model — the local
        FleetScorer (classic mode) or the replicated router (composed
        mode).  Under the co-scheduler this is the HIGH-priority side:
        the serve slot is claimed before submitting, so a refresh fit
        mid-flight yields at its next chunk boundary and this flush
        wins the next dispatch slot.  `refresh_active` is sampled
        BEFORE the slot wait — a slice arriving while a fit held the
        device is a during-refresh sample even though it scores after
        the yield.  Flagged (suspicious) events land through the
        scorer's on_batch sink (_start_scorer); a malformed event is
        shed and counted, never allowed to kill the standing service
        (serve mode's contract)."""
        via_router = (self.publisher is not None
                      and self.publisher.ready(self.tenant))
        if not via_router and self.scorer is None:
            return               # nothing published yet: ledger only
        refresh_active = (self.cosched.refresh_active
                          if self.cosched is not None else False)
        # In-process scoring shares ONE dispatch stream with the
        # trainer, so the slot waits out the in-flight chunk; router
        # scoring is remote (no shared stream), so the slot registers
        # pressure without blocking — the flush dispatches now and the
        # trainer defers its NEXT chunk.
        slot = (self.cosched.serve_slot(wait=not via_router)
                if self.cosched is not None else nullcontext())
        t0 = time.perf_counter()
        with slot:
            if via_router:
                self.publisher.submit_slice(
                    self.tenant, sl, refresh_active=refresh_active)
            else:
                for ln in sl.lines:
                    try:
                        self.scorer.submit(self.tenant, ln)
                    except ValueError:
                        self.events_rejected += 1
                self.scorer.flush()
        wall_ms = (time.perf_counter() - t0) * 1e3
        (self._serve_refresh_ms if refresh_active
         else self._serve_idle_ms).observe(wall_ms)

    def refresh_due(self, now_sim: float) -> bool:
        """Advance the cadence clock; True if `now_sim` crossed a
        refresh boundary.  Ingest-thread only (the composed mode's
        worker never touches the cadence clock) — the caller owns
        actually running `refresh`, possibly on another thread."""
        if (self._next_refresh_t is None
                or now_sim < self._next_refresh_t):
            return False
        while (self._next_refresh_t is not None
               and now_sim >= self._next_refresh_t):
            self._next_refresh_t += self.cc.refresh_every_s
        return True

    def maybe_refresh(self, now_sim: float) -> "dict | None":
        """Run one refresh if `now_sim` crossed the cadence boundary."""
        if not self.refresh_due(now_sim):
            return None
        return self.refresh(now_sim)

    # -- the refresh -----------------------------------------------------

    def _lda_config(self):
        if self._lda_cfg is None:
            import dataclasses

            cc = self.cc
            self._lda_cfg = dataclasses.replace(
                self.config.lda,
                batch_size=cc.batch_size,
                min_bucket_len=cc.min_bucket_len,
                fused_em_chunk=cc.fused_em_chunk,
            )
        return self._lda_cfg

    def refresh(self, now_sim: float) -> dict:
        from ..models.lda import WindowTrainer

        idx = self.refresh_count + self.skipped_refreshes + 1
        with self._lock:
            self.window.advance(now_sim)
            if self.collective is not None:
                # Distributed refresh: every rank grew its vocabulary
                # from the slices IT ingested, so agree on one pow2
                # capacity tier (the max) BEFORE the snapshot — all
                # ranks then compile and allreduce at the same [K, V].
                from ..parallel import sync_capacity_tier

                self._tier_syncs += 1
                agreed = sync_capacity_tier(
                    self.collective, self.window.vocab_size,
                    self.cc.vocab_floor,
                    tag=f"{self.tenant}.tier{self._tier_syncs}",
                    journal=self.journal,
                )
                self.window.reserve_capacity(agreed)
            snap = self.window.snapshot()
        corpus = snap.corpus
        if corpus.num_docs < self.cc.min_refresh_docs:
            self.skipped_refreshes += 1
            return {"refresh": idx, "skipped": "window_too_small",
                    "docs": corpus.num_docs}
        cfg = self._lda_config()
        if (self.trainer is None
                or self.trainer.num_terms != corpus.num_terms):
            # One program family per vocabulary capacity tier: churn
            # inside a tier retraces nothing; crossing a boundary
            # mints exactly one new trainer (and family).
            self.trainer = WindowTrainer(
                cfg, corpus.num_terms,
                collective=self.collective,
                yield_hook=(self.cosched.yield_hook
                            if self.cosched is not None else None),
            )
            self.tier_rebuilds += 1
        mode = self._train_mode()
        seed_probs = self._prev_probs if mode == "warm" else None
        seed_alpha = self._prev_alpha if mode == "warm" else None
        refresh_wall0 = time.perf_counter()
        t0 = time.perf_counter()
        # The fit bracket marks this service refresh-active: scoring
        # that lands inside it is a "during refresh" latency sample,
        # and the co-scheduler journals the fit's chunk/yield rollup
        # at exit.
        fit_ctx = (self.cosched.train_fit(self.tenant)
                   if self.cosched is not None else nullcontext())
        with fit_ctx:
            result = self.trainer.fit(
                corpus, topic_probs=seed_probs, alpha=seed_alpha,
            )
        train_wall = time.perf_counter() - t0
        ll, held_docs = self.drift.evaluate(
            result.log_beta, result.alpha, corpus,
            holdout_frac=self.cc.holdout_frac,
            batch_size=cfg.batch_size,
            min_bucket_len=cfg.min_bucket_len,
            var_max_iters=cfg.var_max_iters, var_tol=cfg.var_tol,
        )
        decision = self.drift.check(
            ll, held_docs=held_docs, docs=corpus.num_docs,
            window_t0=round(snap.t0, 3), window_t1=round(snap.t1, 3),
        )
        version = self._version()
        ok = self.drift.gate(
            decision, version=version, tenant=self.tenant,
            mode=mode, em_iters=result.em_iters,
        )
        publish_wall = None
        quality_info = {}
        if ok:
            model = self._build_model(snap, result)
            qgate = self._quality_gate()
            if qgate is not None:
                qdec = qgate.check(model)
                ok = qgate.gate(
                    qdec, version=version, tenant=self.tenant,
                )
                quality_info = {
                    "quality_recall": round(qdec.recall, 6),
                    "quality_regressed": qdec.regressed,
                }
            if ok:
                self._publish(model, snap)
                publish_wall = time.perf_counter()
                self._prev_probs = np.asarray(
                    model.p[:-1], np.float64
                )  # drop fallback row: the [V_real, K] warm-start seed
                self._prev_alpha = result.alpha
        if mode == "fresh":
            self._last_fresh_iters = result.em_iters
        iters_saved = (
            self._last_fresh_iters - result.em_iters
            if mode == "warm" and self._last_fresh_iters is not None
            else None
        )
        fresh = self._freshness_record(publish_wall, now_sim,
                                       refresh_wall0)
        record = {
            "refresh": idx,
            "mode": mode,
            "warm_start": mode == "warm",
            "em_iters": result.em_iters,
            "iters_saved": iters_saved,
            "train_wall_s": round(train_wall, 4),
            "held_out_ll": round(ll, 6),
            "held_docs": held_docs,
            "drifted": decision.drifted,
            "published": ok,
            "version": self._version(),
            "docs": corpus.num_docs,
            "vocab": snap.real_vocab,
            "vocab_capacity": snap.vocab_capacity,
            "window_chunks": snap.chunks,
            **quality_info,
            **fresh,
        }
        self.refresh_records.append(record)
        self.refresh_count += 1
        agg = self._fit_agg[mode == "warm"]
        agg["fits"] += 1
        agg["wall_s"] += train_wall
        agg["em_iters"] += result.em_iters
        self._last_ll = ll
        if (self.fresh_control and self.control_record is None
                and mode == "warm" and ok
                and idx > self.warmup_refreshes):
            self.control_record = self._run_fresh_control(
                corpus, record, seed_probs, seed_alpha
            )
        if (self._warmup_counts is None
                and idx >= self.warmup_refreshes):
            from ..plans import warmup as plans_warmup

            self._warmup_counts = plans_warmup.compile_counts()
        return record

    def _train_mode(self) -> str:
        cc = self.cc
        if cc.warm_start not in ("auto", "always", "never"):
            raise ValueError(
                f"ContinuousConfig.warm_start={cc.warm_start!r}: "
                "expected 'auto', 'always', or 'never'"
            )
        if self._prev_probs is None or cc.warm_start == "never":
            return "fresh"
        if cc.warm_start == "always":
            return "warm"
        return self.drift.mode        # fresh right after a veto

    def _build_model(self, snap, result):
        from ..scoring import ScoringModel

        fallback = get_source(self.dsource).fallback(self.config.scoring)
        corpus = snap.corpus
        # The published model covers the REAL vocabulary only: the
        # tier's pad words never occur in an event and must not ride
        # into word_index.
        return ScoringModel.from_lda(
            corpus.doc_names,
            result.gamma,
            corpus.vocab[: snap.real_vocab],
            result.log_beta[:, : snap.real_vocab],
            fallback,
        )

    def _version(self) -> int:
        if self.publisher is not None:
            return self.publisher.version(self.tenant)
        return (
            self.fleet.version(self.tenant)
            if self.tenant in self.fleet.tenants() else 0
        )

    def _publish(self, model, snap) -> None:
        source = f"window@{round(snap.t1, 1)}"
        if self.publisher is not None:
            # Composed mode: the refreshed model fans out through the
            # replicated router (primary AND shadow) instead of the
            # in-process registry.
            self.publisher.publish(self, model, source)
            return
        self.fleet.publish(self.tenant, model, source=source)
        if self.scorer is None:
            self._start_scorer()

    def _quality_gate(self):
        """The detection-quality publish gate, built lazily: the
        injection suite needs the stream's pinned cuts, which exist
        only after the bootstrap slice.  Off unless
        ContinuousConfig.quality_gate."""
        if not self.cc.quality_gate:
            return None
        if self._qgate is None:
            from ..models.drift import QualityGate
            from ..sources.quality import QualitySuite

            cc = self.cc
            suite = QualitySuite(
                self.dsource, self.cuts,
                n_events=cc.quality_events, seed=cc.quality_seed,
                attack_events=cc.quality_attack_events, k=cc.quality_k,
            )
            raw_journal = (
                self.journal.journal if self.journal is not None
                else None
            )
            if raw_journal is not None:
                # The suite's provenance record: what was injected,
                # under which seed — the ground truth every subsequent
                # quality_gate record is judged against.
                raw_journal.append(suite.manifest)
            self._qgate = QualityGate(
                suite,
                tol=cc.quality_tol,
                history=cc.quality_history,
                min_history=cc.quality_min_history,
                journal=raw_journal, recorder=self.recorder,
            )
            self._qgate.prime(self._replayed)
        return self._qgate

    def _start_scorer(self) -> None:
        from ..serving import FleetScorer

        fz = get_source(self.dsource).event_featurizer(self.cuts)
        # Flagged-event product sink: the scored output IS the
        # pipeline's purpose — suspicious connects stream to
        # flagged_events.jsonl as they score (serve mode's on_batch
        # contract), not just into the freshness ledger.
        self._flagged_file = open(
            os.path.join(self.out_dir, "flagged_events.jsonl"), "a"
        )

        def on_batch(tenant, snapshot, feats, scores):
            threshold = self.scorer.tenant_threshold(tenant)
            for i in np.where(scores < threshold)[0]:
                self.flagged += 1
                self._flagged_file.write(json.dumps({
                    "tenant": tenant,
                    "flagged": feats.featurized_row(int(i)),
                    "score": float(scores[i]),
                    "model_version": snapshot.version,
                }) + "\n")
            self._flagged_file.flush()

        self.scorer = FleetScorer(
            self.fleet, {self.tenant: fz}, self.config.serving,
            on_batch=on_batch, journal=self.journal,
        )

    def _freshness_record(self, publish_wall: "float | None",
                          now_sim: float,
                          refresh_wall0: float) -> dict:
        """Resolve the freshness ledger at a successful publish: every
        not-yet-covered slice's events became servable under a model
        trained on a window containing them.  Wall freshness is what
        THIS replay measured (speed-dependent); event-time freshness
        is the cadence lag plus the refresh's own wall — what a
        real-time deployment would deliver, invariant to the replay
        speed."""
        if publish_wall is None:
            return {"freshness_slices": 0}
        refresh_cost = publish_wall - refresh_wall0
        n = 0
        wall_max = 0.0
        event_max = 0.0
        with self._lock:
            covered, self._ledger = self._ledger, []
        # Covered entries can never be re-covered: they were swapped
        # out above, so a standing service's ledger holds only the
        # slices since the last successful publish (bounded, and each
        # publish's scan is O(new slices), not O(slices ever)).
        for entry in covered:
            wall = publish_wall - entry.arrival_wall
            event_s = max(now_sim - entry.t1, 0.0) + refresh_cost
            n += 1
            wall_max = max(wall_max, wall)
            event_max = max(event_max, event_s)
            self._freshness_count += 1
            self._freshness.observe(wall)
            self._freshness_event.observe(event_s)
            if self._freshness_sink is not None:
                self._freshness_sink(wall, event_s)
        if n and self.journal is not None:
            # The freshness-latency lane trace_view plots: per publish,
            # the worst newly-covered slice's arrival→servable gap.
            # Tenant-keyed: the fleet journal interleaves N ledgers.
            self.journal.append({
                "kind": "freshness",
                "tenant": self.tenant,
                "slices": n,
                "wall_max_s": round(wall_max, 3),
                "event_max_s": round(event_max, 3),
            })
        return {"freshness_slices": n}

    def _run_fresh_control(self, corpus, record, seed_probs,
                           seed_alpha):
        """The apples-to-apples warm-vs-fresh measurement: re-run the
        warm fit AND one fresh fit back-to-back on the exact snapshot
        a warm refresh just trained (neither is published) — same
        data, same shapes, both on already-traced programs, so the
        bench's warm_start_speedup compares pure EM walls at matched
        held-out likelihood, not a compile against a cache hit."""
        cfg = self._lda_config()

        def _eval(result):
            ll, _ = self.drift.evaluate(
                result.log_beta, result.alpha, corpus,
                holdout_frac=self.cc.holdout_frac,
                batch_size=cfg.batch_size,
                min_bucket_len=cfg.min_bucket_len,
                var_max_iters=cfg.var_max_iters, var_tol=cfg.var_tol,
            )
            return ll

        t0 = time.perf_counter()
        warm_res = self.trainer.fit(
            corpus, topic_probs=seed_probs, alpha=seed_alpha
        )
        warm_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        fresh_res = self.trainer.fit(corpus)
        fresh_wall = time.perf_counter() - t0
        warm_ll = _eval(warm_res)
        fresh_ll = _eval(fresh_res)
        self._last_fresh_iters = fresh_res.em_iters
        return {
            "at_refresh": record["refresh"],
            "warm_wall_s": round(warm_wall, 4),
            "fresh_wall_s": round(fresh_wall, 4),
            "warm_em_iters": warm_res.em_iters,
            "fresh_em_iters": fresh_res.em_iters,
            "warm_start_speedup": round(
                fresh_wall / max(warm_wall, 1e-9), 3
            ),
            "held_out_ll_warm": round(warm_ll, 6),
            "held_out_ll_fresh": round(fresh_ll, 6),
            "held_out_ll_delta": round(warm_ll - fresh_ll, 6),
        }

    # -- drive + close ---------------------------------------------------

    def run(self, slices) -> dict:
        """Consume a paced slice stream to exhaustion, then close."""
        try:
            for sl in slices:
                self.ingest_slice(sl)
                self.maybe_refresh(sl.t1)
        finally:
            payload = self.close()
        return payload

    def close(self) -> dict:
        if self.scorer is not None:
            self.scorer.close(timeout=60.0)
            self.scorer = None
        if self._flagged_file is not None:
            self._flagged_file.close()
            self._flagged_file = None
        payload = self.summary()
        with self._lock:
            journal, self.journal = self.journal, None
        if journal is not None and self._owns_journal:
            journal.run_end(
                ok=True, publishes=self.drift.publishes,
                vetoes=self.drift.vetoes,
            )
            journal.close()
        # shared journal: the fleet closes it
        metrics_name = (
            f"continuous_metrics.{self.tenant}.json" if self._shared
            else "continuous_metrics.json"
        )
        with open(os.path.join(self.out_dir, metrics_name), "w") as f:
            json.dump(payload, f, indent=1)
        return payload

    def summary(self) -> dict:
        def _fit_stats(warm: bool) -> dict:
            agg = self._fit_agg[warm]
            if not agg["fits"]:
                return {"fits": 0}
            return {
                "fits": agg["fits"],
                "mean_wall_s": round(agg["wall_s"] / agg["fits"], 4),
                "mean_em_iters": round(
                    agg["em_iters"] / agg["fits"], 2
                ),
            }

        fresh_q = {}
        if self._freshness_count:
            fresh_q = {
                "freshness_p50_s": round(
                    self._freshness.quantile(0.50), 3
                ),
                "freshness_p99_s": round(
                    self._freshness.quantile(0.99), 3
                ),
                "freshness_event_p50_min": round(
                    self._freshness_event.quantile(0.50) / 60.0, 3
                ),
                "freshness_event_p99_min": round(
                    self._freshness_event.quantile(0.99) / 60.0, 3
                ),
            }
        serve_q = {}
        if self._serve_idle_ms.count:
            serve_q["serve_idle_p99_ms"] = round(
                self._serve_idle_ms.quantile(0.99), 3
            )
        if self._serve_refresh_ms.count:
            serve_q["serve_refresh_p99_ms"] = round(
                self._serve_refresh_ms.quantile(0.99), 3
            )
        retraces = None
        if self._warmup_counts is not None:
            from ..plans import warmup as plans_warmup

            delta = plans_warmup.counts_delta(self._warmup_counts)
            retraces = delta.get("traces", 0)
        return {
            "dsource": self.dsource,
            "tenant": self.tenant,
            "slices": self.slices,
            "events": self.events,
            "events_rejected": self.events_rejected,
            "flagged": self.flagged,
            "refreshes": self.refresh_count,
            "skipped_refreshes": self.skipped_refreshes,
            "publishes": self.drift.publishes,
            "vetoes": self.drift.vetoes,
            "quality_checks": (
                self._qgate.checks if self._qgate is not None else 0
            ),
            "quality_vetoes": (
                self._qgate.vetoes if self._qgate is not None else 0
            ),
            "version": self._version(),
            **fresh_q,
            **serve_q,
            "freshness_samples": self._freshness_count,
            "uncovered_slices": len(self._ledger),
            "warm": _fit_stats(True),
            "fresh": _fit_stats(False),
            "fresh_control": self.control_record,
            "held_out_ll": self._last_ll,
            "vocab": self.window.vocab_size,
            "vocab_capacity": self.window.vocab_capacity(),
            "tier_rebuilds": self.tier_rebuilds,
            "evicted_chunks": self.window.evicted_chunks,
            "retraces_after_warmup": retraces,
            # Bounded recent detail (maxlen 1024); the journal holds
            # the full history.
            "refresh_records": list(self.refresh_records),
        }


def run_continuous(
    config: PipelineConfig,
    dsource: str,
    slices,
    *,
    out_dir: str,
    tenant: str = "stream",
    fresh_control: bool = False,
    warmup_refreshes: "int | None" = None,
) -> dict:
    """Convenience wrapper: stand up a ContinuousService, wire the
    persistent compilation cache (the zero-retrace counters count
    nothing without it), and drive the slice stream to exhaustion."""
    from ..plans import warmup as plans_warmup

    if config.plans.compilation_cache:
        plans_warmup.setup_compilation_cache(
            cache_dir=config.plans.compilation_cache_dir
        )
    plans_warmup._ensure_listener()
    service = ContinuousService(
        config, dsource, out_dir=out_dir, tenant=tenant,
        fresh_control=fresh_control, warmup_refreshes=warmup_refreshes,
    )
    return service.run(slices)


# ---------------------------------------------------------------------------
# the composed standing service: N tenants, one co-scheduler, one fleet
# ---------------------------------------------------------------------------


class RouterBinding:
    """Publishing and scoring for N per-tenant services through ONE
    replicated FleetRouter.

    Bootstrap: the router computes placement once at start() over the
    full tenant census, so the binding HOLDS each tenant's first
    published model until every expected tenant has one, then
    add_tenant()s the census and start()s the router.  Until then
    `ready()` is False and services only ledger their slices — exactly
    the classic mode's pre-first-publish behavior.  Later publishes
    fan out live through router.publish (primary AND shadow, with the
    drain/publish-race convergence loop).

    Scoring: submit_slice ships a slice as one submit_many frame and
    hands the futures to a FIFO resolver thread — ingest never blocks
    on a score round-trip; each event's submit→resolve latency lands
    in the idle or during-refresh histogram by the refresh_active flag
    sampled at submit.  `failed` counts futures that errored: the
    chaos contract is that a replica SIGKILL leaves it at ZERO (the
    router resubmits in-flight hops to the promoted shadow)."""

    def __init__(self, router, tenants, *, journal=None,
                 recorder=None) -> None:
        from collections import deque as _deque

        from ..telemetry.spans import Recorder as _Recorder

        self.router = router
        self.expected = set(tenants)
        self._journal = getattr(journal, "journal", journal)
        rec = recorder if recorder is not None else _Recorder()
        self._serve_idle_ms = rec.histogram("route.serve_idle_ms")
        self._serve_refresh_ms = rec.histogram("route.serve_refresh_ms")
        self._lock = threading.Lock()
        self._started = False
        self._pending: dict = {}    # tenant -> (service, model) pre-start
        self._versions: dict = {}
        self.resolved = 0
        self.failed = 0
        self._cond = threading.Condition()
        self._queue = _deque()      # (future, t_submit, refresh_active)
        self._stopped = False
        self._resolver = threading.Thread(
            target=self._resolve_loop, name="oni-cont-resolver",
            daemon=True)
        self._resolver.start()

    def ready(self, tenant: str) -> bool:
        with self._lock:
            return self._started

    def version(self, tenant: str) -> int:
        with self._lock:
            return self._versions.get(tenant, 0)

    def publish(self, service, model, source: str) -> int:
        from ..serving import TenantSpec

        tenant = service.tenant
        with self._lock:
            if not self._started:
                self._pending[tenant] = (service, model)
                self._versions[tenant] = (
                    self._versions.get(tenant, 0) + 1
                )
                if set(self._pending) == self.expected:
                    for t, (svc, m) in sorted(self._pending.items()):
                        self.router.add_tenant(
                            TenantSpec(tenant=t, dsource=svc.dsource),
                            svc.cuts, m,
                        )
                    self._pending.clear()
                    self.router.start()
                    self._started = True
                return self._versions[tenant]
        v = self.router.publish(tenant, model, source=source)
        with self._lock:
            self._versions[tenant] = v
            return v

    def submit_slice(self, tenant: str, sl: IngestSlice, *,
                     refresh_active: bool = False) -> None:
        futs = self.router.submit_many(tenant, list(sl.lines))
        self.router.flush()
        t0 = time.perf_counter()
        with self._cond:
            for f in futs:
                self._queue.append((f, t0, refresh_active))
            self._cond.notify_all()

    def _resolve_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._cond.wait()
                if not self._queue:
                    return      # stopped AND drained: close() semantics
                fut, t0, during = self._queue.popleft()
            try:
                fut.result(timeout=120.0)
            except Exception:
                with self._cond:
                    self.failed += 1
                continue
            wall_ms = (time.perf_counter() - t0) * 1e3
            (self._serve_refresh_ms if during
             else self._serve_idle_ms).observe(wall_ms)
            with self._cond:
                self.resolved += 1

    def close(self, timeout_s: float = 300.0) -> None:
        """Stop accepting and drain every queued future first — a
        clean shutdown must resolve (not drop) in-flight scores."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._resolver.join(timeout=timeout_s)

    def stats(self) -> dict:
        with self._lock:
            out = {
                "started": self._started,
                "versions": dict(self._versions),
            }
        with self._cond:
            out["events_scored"] = self.resolved
            out["failed_futures"] = self.failed
            out["pending"] = len(self._queue)
        for key, h in (("serve_idle_p99_ms", self._serve_idle_ms),
                       ("serve_refresh_p99_ms", self._serve_refresh_ms)):
            if h.count:
                out[key] = round(h.quantile(0.99), 3)
        return out


class FleetContinuousService:
    """One standing service: N per-tenant ContinuousServices composed
    over ONE journal/recorder, ONE train/serve co-scheduler, an
    optional collective (distributed refreshes), and — when
    `replicated`/`router` — the replicated serving fleet.

    The perf core is the priority split: ingest + scoring stay on the
    caller's thread (high priority, serve slots), refresh fits run on
    ONE background worker (low priority, preemptible chunks), so a
    tenant's fit never blocks another tenant's — or its own — scoring
    beyond a chunk boundary.  Cadence that outruns the fit coalesces
    (the queued refresh trains on a window containing the newer slices
    anyway) instead of building an unbounded backlog.

    Drive with `run(tagged)` where tagged yields (tenant, IngestSlice)
    in event-time order (`interleave_streams` + `paced_tagged`), or
    slice-by-slice via `ingest`."""

    def __init__(
        self,
        config: PipelineConfig,
        streams: "dict[str, str]",
        *,
        out_dir: str,
        replicated: int = 0,
        router=None,
        coscheduler: bool = True,
        collective=None,
        warmup_refreshes: "int | None" = None,
        replica_extra: "list[str] | None" = None,
    ) -> None:
        from ..serving import CoScheduler
        from ..telemetry import Journal, Recorder, RunJournal
        from ..telemetry.spans import Recorder as _Recorder

        if not streams:
            raise ValueError("streams must name at least one tenant")
        self.config = config
        self.out_dir = formats.ensure_dir(out_dir)
        self.streams = dict(streams)
        # Created before _spawn_fleet so every cross-thread attribute
        # write below can take it.
        self._plock = threading.Lock()
        tel = config.telemetry
        self.journal = None
        self.recorder = None
        if tel.journal:
            jpath = os.path.join(self.out_dir, "run_journal.jsonl")
            self.journal = RunJournal(
                Journal(jpath, fsync_every=tel.journal_fsync_every)
            )
            self.journal.run_start(
                mode="continuous_fleet", tenants=sorted(self.streams),
                replicated=int(replicated or (router is not None)),
                cosched=bool(coscheduler),
                window_s=config.continuous.window_s,
                refresh_every_s=config.continuous.refresh_every_s,
            )
            self.recorder = Recorder(journal=self.journal.journal)
        raw_journal = (
            self.journal.journal if self.journal is not None else None
        )
        # coscheduler=False is OBSERVE-ONLY, not absent: the control
        # leg of the composed bench still needs the refresh-active tag
        # on serve latency and the chunk/slot counters — it just never
        # waits (no arbitration).
        self.cosched = CoScheduler(
            recorder=self.recorder, journal=raw_journal,
            enabled=bool(coscheduler),
        )
        rec = self.recorder or _Recorder()
        # Fleet-wide freshness aggregate next to the per-tenant
        # ledgers: the composed bench's headline quantiles.
        self._fresh_wall = rec.histogram("fleet.freshness_s")
        self._fresh_event = rec.histogram("fleet.freshness_event_s")

        self.router = router
        self._owns_router = False
        self.replica_procs: dict = {}
        self._workdir = None
        if self.router is None and replicated:
            self._spawn_fleet(int(replicated), replica_extra or [])
        self.binding = None
        if self.router is not None:
            self.binding = RouterBinding(
                self.router, self.streams,
                journal=self.journal, recorder=self.recorder,
            )

        self.services: "dict[str, ContinuousService]" = {}
        for tenant, dsource in sorted(self.streams.items()):
            self.services[tenant] = ContinuousService(
                config, dsource, out_dir=self.out_dir, tenant=tenant,
                warmup_refreshes=warmup_refreshes,
                journal=self.journal, recorder=self.recorder,
                coscheduler=self.cosched, collective=collective,
                publisher=self.binding,
                freshness_sink=self._observe_freshness,
            )

        self.coalesced_refreshes = 0
        self.refresh_errors = 0
        self._warm0 = None
        self._closed = False
        self._payload = None
        self._refresh_pending: "dict[str, bool]" = {}
        self._rq: "queue.Queue" = queue.Queue()
        self._worker = threading.Thread(
            target=self._refresh_loop, name="oni-continuous-refresh",
            daemon=True)
        self._worker.start()

    def _spawn_fleet(self, n: int, extra: list) -> None:
        from ..parallel import FileKVClient
        from ..serving import FleetRouter
        from .route import _spawn_replica

        workdir = tempfile.mkdtemp(prefix="oni_cont_fleet_")
        with self._plock:
            self._workdir = workdir
        kv_dir = os.path.join(workdir, "kv")
        os.makedirs(kv_dir, exist_ok=True)
        router = FleetRouter(
            self.config.serving, journal=self.journal,
            recorder=self.recorder, kv=FileKVClient(kv_dir),
        )
        for i in range(n):
            rid = f"r{i}"
            proc, host, port = _spawn_replica(
                rid, kv_dir, workdir, list(extra))
            self.replica_procs[rid] = proc
            router.connect_replica(rid, host, port)
        with self._plock:
            self.router = router
            self._owns_router = True

    def kill_replica(self, rid: str) -> None:
        """Chaos hook: SIGKILL a spawned replica subprocess — no
        drain, no goodbye.  The recovery contract (zero failed score
        futures, publishes converging through the promoted shadow) is
        what the composed bench and the chaos test pin."""
        proc = self.replica_procs[rid]
        proc.kill()
        proc.wait(timeout=30.0)

    def _observe_freshness(self, wall_s: float, event_s: float) -> None:
        self._fresh_wall.observe(wall_s)
        self._fresh_event.observe(event_s)

    # -- drive ----------------------------------------------------------

    def ingest(self, tenant: str, sl: IngestSlice) -> None:
        svc = self.services[tenant]
        svc.ingest_slice(sl)
        if svc.refresh_due(sl.t1):
            with self._plock:
                if self._refresh_pending.get(tenant):
                    # Cadence outran the fit: coalesce — the queued
                    # refresh trains on a window that will contain
                    # this slice anyway.
                    self.coalesced_refreshes += 1
                    return
                self._refresh_pending[tenant] = True
            self._rq.put((tenant, sl.t1))

    def _refresh_loop(self) -> None:
        from ..plans import warmup as plans_warmup

        while True:
            item = self._rq.get()
            try:
                if item is None:
                    return
                tenant, now_sim = item
                try:
                    self.services[tenant].refresh(now_sim)
                except Exception as e:
                    # An abandoned refresh must not kill the standing
                    # fleet: nothing was published (the gate never
                    # ran), the ledger keeps its uncovered slices, and
                    # the next cadence boundary retries over a window
                    # that still contains them.
                    with self._plock:
                        self.refresh_errors += 1
                    if self.journal is not None:
                        try:
                            self.journal.append({
                                "kind": "refresh_abandon",
                                "tenant": tenant,
                                "error": repr(e)[:200],
                            })
                        except Exception:
                            pass
                if self._warm0 is None and all(
                    s._warmup_counts is not None
                    for s in self.services.values()
                ):
                    # Every tenant crossed ITS warmup boundary: traces
                    # from here on are the fleet's retrace count (the
                    # compile counters are process-global, so summing
                    # per-tenant deltas would double-count).
                    with self._plock:
                        self._warm0 = plans_warmup.compile_counts()
            finally:
                if item is not None:
                    with self._plock:
                        self._refresh_pending[item[0]] = False
                self._rq.task_done()

    def run(self, tagged) -> dict:
        """Consume an event-time-ordered (tenant, slice) stream to
        exhaustion, then close."""
        try:
            for tenant, sl in tagged:
                self.ingest(tenant, sl)
        finally:
            payload = self.close()
        return payload

    def close(self) -> dict:
        with self._plock:
            if self._closed:
                return self._payload
            self._closed = True
        self._rq.join()            # every queued refresh lands first
        self._rq.put(None)
        self._worker.join(timeout=600.0)
        if self.binding is not None:
            self.binding.close()   # resolve every in-flight future
        tenants = {
            t: svc.close() for t, svc in sorted(self.services.items())
        }
        payload = self.summary(tenants)
        if self._owns_router and self.router is not None:
            try:
                self.router.close()
            except Exception:
                pass
            for proc in self.replica_procs.values():
                if proc.poll() is None:
                    proc.terminate()
            for proc in self.replica_procs.values():
                try:
                    proc.wait(timeout=30.0)
                except Exception:
                    proc.kill()
        with self._plock:
            journal, self.journal = self.journal, None
        if journal is not None:
            journal.run_end(
                ok=True,
                refreshes=payload["refreshes"],
                publishes=payload["publishes"],
                refresh_errors=self.refresh_errors,
            )
            journal.close()
        with open(os.path.join(self.out_dir,
                               "fleet_continuous_metrics.json"),
                  "w") as f:
            json.dump(payload, f, indent=1)
        with self._plock:
            self._payload = payload
        return payload

    def summary(self, tenants: "dict | None" = None) -> dict:
        if tenants is None:
            tenants = {
                t: svc.summary()
                for t, svc in sorted(self.services.items())
            }
        fresh = {}
        if self._fresh_wall.count:
            fresh = {
                "freshness_p50_s": round(
                    self._fresh_wall.quantile(0.50), 3),
                "freshness_p99_s": round(
                    self._fresh_wall.quantile(0.99), 3),
                "freshness_event_p50_min": round(
                    self._fresh_event.quantile(0.50) / 60.0, 3),
                "freshness_event_p99_min": round(
                    self._fresh_event.quantile(0.99) / 60.0, 3),
            }
        retraces = None
        if self._warm0 is not None:
            from ..plans import warmup as plans_warmup

            retraces = plans_warmup.counts_delta(self._warm0).get(
                "traces", 0)
        out = {
            "tenants": tenants,
            "events": sum(t["events"] for t in tenants.values()),
            "slices": sum(t["slices"] for t in tenants.values()),
            "refreshes": sum(t["refreshes"] for t in tenants.values()),
            "publishes": sum(t["publishes"] for t in tenants.values()),
            "coalesced_refreshes": self.coalesced_refreshes,
            "refresh_errors": self.refresh_errors,
            "retraces_after_warmup": retraces,
            **fresh,
        }
        if self.cosched is not None:
            out["cosched"] = self.cosched.summary()
        if self.binding is not None:
            out["serving"] = self.binding.stats()
        if self.router is not None:
            try:
                out["router"] = self.router.stats()
            except Exception:
                pass
        return out


def interleave_streams(per_tenant: "dict[str, list]") -> list:
    """Merge per-tenant slice lists into ONE event-time-ordered
    (tenant, slice) replay — the multi-tenant day the fleet relives.
    Deterministic: ties break by tenant name."""
    tagged = [
        (t, sl) for t in sorted(per_tenant) for sl in per_tenant[t]
    ]
    tagged.sort(key=lambda p: (p[1].t1, p[0]))
    return tagged


def paced_tagged(tagged, speed: float, *, sleep=time.sleep):
    """`paced_slices` for a tagged (tenant, slice) stream: one shared
    event clock paces every tenant, preserving their relative gap
    structure at ×`speed` real time."""
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    t_wall0 = time.perf_counter()
    t_sim0 = None
    for tenant, sl in tagged:
        if t_sim0 is None:
            t_sim0 = sl.t1
        due = t_wall0 + (sl.t1 - t_sim0) / speed
        delay = due - time.perf_counter()
        if delay > 0 and np.isfinite(delay):
            sleep(delay)
        sl.arrival_wall = time.perf_counter()
        yield tenant, sl


def run_fleet_continuous(
    config: PipelineConfig,
    streams: "dict[str, str]",
    tagged,
    *,
    out_dir: str,
    replicated: int = 0,
    router=None,
    coscheduler: bool = True,
    collective=None,
    warmup_refreshes: "int | None" = None,
) -> dict:
    """Convenience wrapper for the composed mode: compilation cache +
    compile counters, then drive the tagged stream to exhaustion."""
    from ..plans import warmup as plans_warmup

    if config.plans.compilation_cache:
        plans_warmup.setup_compilation_cache(
            cache_dir=config.plans.compilation_cache_dir
        )
    plans_warmup._ensure_listener()
    fleet = FleetContinuousService(
        config, streams, out_dir=out_dir, replicated=replicated,
        router=router, coscheduler=coscheduler, collective=collective,
        warmup_refreshes=warmup_refreshes,
    )
    return fleet.run(tagged)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ml_ops continuous",
        description="continuous ingestion: windowed streaming corpus, "
        "warm-start EM refreshes, drift-gated fleet publishes — "
        "freshness in minutes, not next-day (tools/day_replay.py "
        "paces a historical day into this mode)",
    )
    p.add_argument("dsource", nargs="?", default=None,
                   choices=list(source_names()),
                   help="single-tenant stream source (omit when using "
                   "--stream fleet mode)")
    p.add_argument("--stream", action="append", default=[],
                   metavar="TENANT=DSOURCE:PATH",
                   help="fleet mode: one tenant stream (repeatable) — "
                   "N tenants compose into ONE standing service "
                   "sharing the journal, the train/serve co-scheduler "
                   "and (with --replicated) the serving fleet")
    p.add_argument("--replicated", type=int, default=0, metavar="N",
                   help="serve through the fleet router over N "
                   "spawned replica subprocesses (ml_ops replica) "
                   "instead of the in-process scorer")
    p.add_argument("--multihost", action="store_true",
                   help="distributed window refreshes over the "
                   "ambient collective (parallel/allreduce env "
                   "bootstrap; rank-synchronized vocab tiers, "
                   "suff-stats allreduce, warm-start broadcast)")
    p.add_argument("--no-cosched", action="store_true",
                   help="disable the train/serve co-scheduler "
                   "(control mode: refresh fits run unpreemptible)")
    p.add_argument("--flow-path", default=None,
                   help="raw netflow CSV to replay (FLOW_PATH env)")
    p.add_argument("--dns-path", default=None,
                   help="raw DNS CSV to replay (DNS_PATH env)")
    p.add_argument("--proxy-path", default=None,
                   help="raw proxy/HTTP log CSV to replay (PROXY_PATH "
                   "env)")
    p.add_argument("--quality-gate", action="store_true",
                   help="veto publishes that regress recall@k on the "
                   "labeled-injection suite (sources/inject.py)")
    p.add_argument("--data-dir", default=None,
                   help="output/journal directory (LPATH env)")
    p.add_argument("--qtiles", default=None,
                   help="pinned flow quantile cuts (stable word "
                   "identity across restarts)")
    p.add_argument("--speed", type=float, default=60.0,
                   help="replay speed multiplier over event time "
                   "(60 = an hour of events per wall minute)")
    p.add_argument("--slice-s", type=float, default=300.0,
                   help="ingest slice span in EVENT seconds")
    p.add_argument("--window-s", type=float, default=None,
                   help="override ContinuousConfig.window_s")
    p.add_argument("--refresh-s", type=float, default=None,
                   help="override ContinuousConfig.refresh_every_s")
    p.add_argument("--tenant", default="stream")
    p.add_argument("--fresh-control", action="store_true",
                   help="measure one fresh fit against a warm refresh's "
                   "snapshot (the warm_start_speedup number)")
    p.add_argument("--no-sleep", action="store_true",
                   help="deliver slices as fast as consumed (tests/CI)")
    return p


def _parse_stream_specs(specs: "list[str]") -> "dict[str, tuple]":
    """Parse repeated --stream TENANT=DSOURCE:PATH flags."""
    out: dict = {}
    for spec in specs:
        tenant, eq, rest = spec.partition("=")
        dsource, colon, path = rest.partition(":")
        if not eq or not colon or not tenant or not path:
            raise ValueError(
                f"--stream expects TENANT=DSOURCE:PATH, got {spec!r}"
            )
        if dsource not in source_names():
            raise ValueError(
                f"--stream {spec!r}: dsource must be one of "
                f"{'|'.join(source_names())}"
            )
        if tenant in out:
            raise ValueError(f"--stream: duplicate tenant {tenant!r}")
        out[tenant] = (dsource, path)
    return out


def _main_fleet(args, config: PipelineConfig) -> int:
    streams = _parse_stream_specs(args.stream)
    per_tenant = {}
    for tenant, (dsource, path) in streams.items():
        if not os.path.exists(path):
            print(f"continuous: no input file at {path!r}", flush=True)
            return 2
        with open(path) as f:
            lines = f.readlines()
        per_tenant[tenant] = slice_events(lines, dsource, args.slice_s)
    collective = None
    if args.multihost:
        from ..parallel import get_collective

        collective = get_collective()
    speed = float("inf") if args.no_sleep else args.speed
    tagged = paced_tagged(interleave_streams(per_tenant), speed)
    payload = run_fleet_continuous(
        config, {t: ds for t, (ds, _) in streams.items()}, tagged,
        out_dir=os.path.join(config.data_dir, "continuous_fleet"),
        replicated=args.replicated, collective=collective,
        coscheduler=not args.no_cosched,
    )
    print(json.dumps(payload), flush=True)
    return 0


def main(argv: "list[str] | None" = None) -> int:
    import dataclasses

    args = build_parser().parse_args(argv)
    env = os.environ
    config = PipelineConfig(
        data_dir=args.data_dir or env.get("LPATH", "."),
        qtiles_path=args.qtiles or "",
    )
    cc = config.continuous
    overrides = {}
    if args.window_s is not None:
        overrides["window_s"] = args.window_s
    if args.refresh_s is not None:
        overrides["refresh_every_s"] = args.refresh_s
    if args.quality_gate:
        overrides["quality_gate"] = True
    if overrides:
        config = config.replace(
            continuous=dataclasses.replace(cc, **overrides)
        )
    if args.stream:
        return _main_fleet(args, config)
    if args.dsource is None:
        print("continuous: a DSOURCE argument or --stream flags are "
              "required", flush=True)
        return 2
    path = (
        getattr(args, f"{args.dsource}_path", None)
        or env.get(f"{args.dsource.upper()}_PATH", "")
    )
    if not path or not os.path.exists(path):
        print(f"continuous: no input file at {path!r}", flush=True)
        return 2
    with open(path) as f:
        lines = f.readlines()
    slices = slice_events(lines, args.dsource, args.slice_s)
    speed = float("inf") if args.no_sleep else args.speed
    payload = run_continuous(
        config, args.dsource, paced_slices(slices, speed),
        out_dir=os.path.join(config.data_dir, "continuous"),
        tenant=args.tenant, fresh_control=args.fresh_control,
    )
    print(json.dumps(payload), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
