"""Continuous ingestion — the standing service that kills the day
boundary (ROADMAP item 3; `ml_ops continuous`).

The batch pipeline's unit of work is one FINISHED day: an event at
00:05 is servable ~24 h later, and every day pays a full
EM-from-scratch even when the topics barely moved.  This runner
generalizes the PR 8 streaming dataplane into a standing loop on one
process — the same devices the serving fleet scores from:

    raw slices ──► featurization ──► CorpusWindow (ring-buffered CSR,
       │                              first-seen vocab growth,
       │                              O(evicted) retirement)
       └────────► FleetScorer (events scored under the CURRENT model
                  the moment they arrive — servable in seconds)

    every refresh_every_s of event time:
        window.advance ─► snapshot (pow2 vocab capacity tier)
        ─► WindowTrainer.fit  (warm-started from the previous
           published topics; the f64 convergence check early-exits
           after the few iterations the stream actually moved)
        ─► DriftDetector.evaluate/check  (held-out per-token LL vs
           the journal's rolling history)
        ─► publish gate: drifted models are VETOED and never reach
           FleetRegistry — serving keeps the prior version
           bit-identically; healthy models hot-swap in.

Zero post-warmup retraces by construction: the window pads its
vocabulary to pow2 capacity tiers (the compiled [K, V] family is
keyed by tier, not census), window batches pad to the full batch
size, the refresh reuses ONE WindowTrainer's jitted programs, and the
fleet's capacity-tiered stack keys the serving dispatch by capacity.
The freshness ledger (event arrival → a model covering the event
published) is the headline the streaming_freshness bench reports.
"""

from __future__ import annotations

import argparse
import json
import os
import time
from dataclasses import dataclass, field

import numpy as np

from ..config import PipelineConfig
from ..io import formats
from ..sources import get as get_source
from ..sources import names as source_names


@dataclass
class IngestSlice:
    """One paced ingest unit: raw event lines covering [t0, t1) of
    EVENT time, stamped with the wall clock it was delivered at."""

    lines: list
    t0: float
    t1: float
    arrival_wall: float = 0.0
    index: int = 0

    @property
    def events(self) -> int:
        return len(self.lines)


def event_time_s(line: str, dsource: str) -> float:
    """Event-time seconds for one raw CSV line, through the source
    spec's clock hook (flow: h/m/s columns; dns: unix_tstamp; declared
    sources: their `time_field`)."""
    return get_source(dsource).event_time_s(line)


def slice_events(
    lines, dsource: str, slice_s: float, *, t_base: "float | None" = None
) -> "list[IngestSlice]":
    """Order raw lines by event time and cut them into fixed
    `slice_s`-second slices — the replay decomposition of a historical
    day into the stream the day never was.  Deterministic: stable sort
    by event time, empty slices dropped.  Lines whose time columns do
    not parse (the reference day files' header row, truncated tails)
    are skipped, matching the featurizers' garbage-row tolerance."""
    if slice_s <= 0:
        raise ValueError(f"slice_s must be > 0, got {slice_s}")
    rows = []
    parsed = []
    # lint: ok(hot-path-event-loop, ingest-time slice ordering — one time-field parse per line at admission, off the flush path)
    for ln in lines:
        if not ln.strip():
            continue
        try:
            parsed.append(event_time_s(ln, dsource))
        except (ValueError, IndexError):
            continue          # header / malformed row: not an event
        rows.append(ln)
    times = np.asarray(parsed, np.float64)
    order = np.argsort(times, kind="stable")
    if t_base is None:
        t_base = float(times[order[0]]) if len(order) else 0.0
    slices: list[IngestSlice] = []
    cur: list = []
    cur_idx = 0
    for j in order:
        idx = int((times[j] - t_base) // slice_s)
        if cur and idx != cur_idx:
            slices.append(IngestSlice(
                lines=cur, t0=t_base + cur_idx * slice_s,
                t1=t_base + (cur_idx + 1) * slice_s, index=len(slices),
            ))
            cur = []
        if not cur:
            cur_idx = idx
        cur.append(rows[int(j)])
    if cur:
        slices.append(IngestSlice(
            lines=cur, t0=t_base + cur_idx * slice_s,
            t1=t_base + (cur_idx + 1) * slice_s, index=len(slices),
        ))
    return slices


def paced_slices(slices, speed: float, *, sleep=time.sleep):
    """Deliver slices at ×`speed` real time: the wall gap between
    consecutive slices is their event-time gap divided by `speed`.
    Stamps each slice's `arrival_wall` at delivery.  `speed=inf` (or
    any non-positive sleep result) delivers as fast as downstream
    consumes — the no-sleep test/bench mode."""
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    t_wall0 = time.perf_counter()
    t_sim0 = None
    for sl in slices:
        if t_sim0 is None:
            t_sim0 = sl.t1
        due = t_wall0 + (sl.t1 - t_sim0) / speed
        delay = due - time.perf_counter()
        if delay > 0 and np.isfinite(delay):
            sleep(delay)
        sl.arrival_wall = time.perf_counter()
        yield sl


@dataclass
class _SliceLedger:
    """Freshness bookkeeping for one ingested slice: arrival wall
    stamp, event count, event-time span end.  The service keeps only
    slices not yet covered by a publish (covered entries drop at the
    publish that covers them — they can never be re-covered)."""

    index: int
    arrival_wall: float
    events: int
    t1: float


@dataclass
class ContinuousResult:
    """run_continuous' payload (also what `ml_ops continuous`
    prints)."""

    payload: dict = field(default_factory=dict)


def _featurize_slice(lines, dsource: str, cuts):
    """One slice through the source's batch featurizer with PINNED cuts
    (a slice's own ECDF would bin values differently slice-over-slice
    and churn the vocabulary for nothing — serving/events.py's rule)."""
    return get_source(dsource).featurize(
        lines, skip_header=False, precomputed_cuts=cuts
    )


def _derive_cuts(lines, dsource: str, qtiles_path: str = ""):
    """Pin the stream's quantile cuts: from a qtiles file when the
    source supports one (stable word identity across service restarts),
    else from the bootstrap slice's own ECDF."""
    return get_source(dsource).derive_cuts(lines, qtiles_path)


class ContinuousService:
    """The standing train-and-serve loop.  Drive it with
    `run(slices)` (a paced IngestSlice iterable) or slice-by-slice via
    `ingest_slice` + `maybe_refresh` — tests inject drift that way."""

    def __init__(
        self,
        config: PipelineConfig,
        dsource: str,
        *,
        out_dir: str,
        tenant: str = "stream",
        fresh_control: bool = False,
        warmup_refreshes: "int | None" = None,
    ) -> None:
        if dsource not in source_names():
            raise ValueError(
                f"dsource must be one of {'|'.join(source_names())}, "
                f"got {dsource!r}"
            )
        self.config = config
        self.cc = config.continuous
        self.dsource = dsource
        self.out_dir = formats.ensure_dir(out_dir)
        self.tenant = tenant
        self.fresh_control = fresh_control
        if warmup_refreshes is None:
            # "Post-warmup" starts once the window first reaches steady
            # state: while it is still FILLING (the first
            # window_s/refresh_every_s refreshes), each refresh can
            # legitimately meet a novel doc-length bucket and trace it
            # — that is startup, not churn.
            warmup_refreshes = int(
                np.ceil(self.cc.window_s
                        / max(self.cc.refresh_every_s, 1e-9))
            ) + 1
        self.warmup_refreshes = int(warmup_refreshes)

        from ..dataplane import CorpusWindow
        from ..models.drift import DriftDetector
        from ..serving import FleetRegistry, TenantSpec
        from ..telemetry import Journal, Recorder, RunJournal

        tel = config.telemetry
        self.journal = None
        self.recorder = None
        if tel.journal:
            jpath = os.path.join(self.out_dir, "run_journal.jsonl")
            replayed = Journal.replay(jpath)
            self.journal = RunJournal(
                Journal(jpath, fsync_every=tel.journal_fsync_every)
            )
            self.journal.run_start(
                mode="continuous", dsource=dsource, tenant=tenant,
                window_s=self.cc.window_s,
                refresh_every_s=self.cc.refresh_every_s,
                replayed_records=len(replayed),
            )
            self.recorder = Recorder(journal=self.journal.journal)
        else:
            replayed = []
        raw_journal = (
            self.journal.journal if self.journal is not None else None
        )
        self.window = CorpusWindow(
            self.cc.window_s, vocab_floor=self.cc.vocab_floor,
            recorder=self.recorder, journal=raw_journal,
        )
        self.drift = DriftDetector(
            tol_nats=self.cc.drift_tol_nats,
            history=self.cc.drift_history,
            min_history=self.cc.drift_min_history,
            journal=raw_journal, recorder=self.recorder,
        )
        # A restarted service resumes its drift baseline from the
        # journal instead of re-learning it over min_history refreshes.
        self.drift.prime(replayed)
        self._replayed = replayed
        self._qgate = None          # built lazily once cuts are pinned
        self.fleet = FleetRegistry(
            journal=raw_journal, recorder=self.recorder,
            capacity_tiers=True,
        )
        self.fleet.add_tenant(TenantSpec(tenant=tenant, dsource=dsource))
        self.scorer = None          # created at first publish
        self.cuts = None            # pinned at bootstrap
        self.trainer = None         # one per vocab capacity tier
        self.tier_rebuilds = 0
        self._prev_probs = None     # last PUBLISHED [V_real, K]
        self._prev_alpha = None
        self._last_fresh_iters = None
        self._next_refresh_t = None
        self._ledger: list[_SliceLedger] = []
        from ..telemetry.spans import Recorder as _Recorder

        rec = self.recorder or _Recorder()
        # Two freshness ledgers: wall-clock (what THIS replay measured,
        # speed-dependent) and event-time (cadence lag + refresh wall —
        # what a real-time deployment would deliver, speed-invariant).
        self._freshness = rec.histogram("continuous.freshness_s")
        self._freshness_event = rec.histogram(
            "continuous.freshness_event_s"
        )
        self._freshness_count = 0
        # A standing service runs indefinitely: per-refresh detail is
        # bounded (the journal holds the full history); aggregates are
        # running sums.
        from collections import deque as _deque

        self.refresh_records: "_deque[dict]" = _deque(maxlen=1024)
        self.refresh_count = 0
        self._fit_agg = {
            True: {"fits": 0, "wall_s": 0.0, "em_iters": 0},
            False: {"fits": 0, "wall_s": 0.0, "em_iters": 0},
        }
        self.events = 0
        self.slices = 0
        self.events_rejected = 0
        self.flagged = 0
        self.skipped_refreshes = 0
        self.control_record = None
        self._warmup_counts = None
        self._lda_cfg = None
        self._flagged_file = None
        self._last_ll = None

    # -- per-slice ingest ------------------------------------------------

    def ingest_slice(self, sl: IngestSlice) -> None:
        from ..dataplane import word_count_columns

        if sl.arrival_wall == 0.0:
            sl.arrival_wall = time.perf_counter()
        if self.cuts is None:
            self.cuts = _derive_cuts(sl.lines, self.dsource,
                                     self.config.qtiles_path)
        feats = _featurize_slice(sl.lines, self.dsource, self.cuts)
        self.window.ingest(word_count_columns(feats), sl.t0, sl.t1)
        if self._next_refresh_t is None:
            self._next_refresh_t = sl.t1 + self.cc.refresh_every_s
        self._ledger.append(_SliceLedger(
            index=sl.index, arrival_wall=sl.arrival_wall,
            events=sl.events, t1=sl.t1,
        ))
        self.slices += 1
        self.events += sl.events
        if self.scorer is not None:
            # Scored-the-moment-they-arrive: every event rides the
            # serving path under the CURRENT published model.  Flagged
            # (suspicious) events land through the scorer's on_batch
            # sink (_start_scorer); a malformed event is shed and
            # counted, never allowed to kill the standing service
            # (serve mode's contract).
            for ln in sl.lines:
                try:
                    self.scorer.submit(self.tenant, ln)
                except ValueError:
                    self.events_rejected += 1
            self.scorer.flush()

    def maybe_refresh(self, now_sim: float) -> "dict | None":
        """Run one refresh if `now_sim` crossed the cadence boundary."""
        if (self._next_refresh_t is None
                or now_sim < self._next_refresh_t):
            return None
        while (self._next_refresh_t is not None
               and now_sim >= self._next_refresh_t):
            self._next_refresh_t += self.cc.refresh_every_s
        return self.refresh(now_sim)

    # -- the refresh -----------------------------------------------------

    def _lda_config(self):
        if self._lda_cfg is None:
            import dataclasses

            cc = self.cc
            self._lda_cfg = dataclasses.replace(
                self.config.lda,
                batch_size=cc.batch_size,
                min_bucket_len=cc.min_bucket_len,
                fused_em_chunk=cc.fused_em_chunk,
            )
        return self._lda_cfg

    def refresh(self, now_sim: float) -> dict:
        from ..models.lda import WindowTrainer

        idx = self.refresh_count + self.skipped_refreshes + 1
        self.window.advance(now_sim)
        snap = self.window.snapshot()
        corpus = snap.corpus
        if corpus.num_docs < self.cc.min_refresh_docs:
            self.skipped_refreshes += 1
            return {"refresh": idx, "skipped": "window_too_small",
                    "docs": corpus.num_docs}
        cfg = self._lda_config()
        if (self.trainer is None
                or self.trainer.num_terms != corpus.num_terms):
            # One program family per vocabulary capacity tier: churn
            # inside a tier retraces nothing; crossing a boundary
            # mints exactly one new trainer (and family).
            self.trainer = WindowTrainer(cfg, corpus.num_terms)
            self.tier_rebuilds += 1
        mode = self._train_mode()
        seed_probs = self._prev_probs if mode == "warm" else None
        seed_alpha = self._prev_alpha if mode == "warm" else None
        refresh_wall0 = time.perf_counter()
        t0 = time.perf_counter()
        result = self.trainer.fit(
            corpus, topic_probs=seed_probs, alpha=seed_alpha,
        )
        train_wall = time.perf_counter() - t0
        ll, held_docs = self.drift.evaluate(
            result.log_beta, result.alpha, corpus,
            holdout_frac=self.cc.holdout_frac,
            batch_size=cfg.batch_size,
            min_bucket_len=cfg.min_bucket_len,
            var_max_iters=cfg.var_max_iters, var_tol=cfg.var_tol,
        )
        decision = self.drift.check(
            ll, held_docs=held_docs, docs=corpus.num_docs,
            window_t0=round(snap.t0, 3), window_t1=round(snap.t1, 3),
        )
        version = self.fleet.version(self.tenant)
        ok = self.drift.gate(
            decision, version=version, tenant=self.tenant,
            mode=mode, em_iters=result.em_iters,
        )
        publish_wall = None
        quality_info = {}
        if ok:
            model = self._build_model(snap, result)
            qgate = self._quality_gate()
            if qgate is not None:
                qdec = qgate.check(model)
                ok = qgate.gate(
                    qdec, version=version, tenant=self.tenant,
                )
                quality_info = {
                    "quality_recall": round(qdec.recall, 6),
                    "quality_regressed": qdec.regressed,
                }
            if ok:
                self._publish(model, snap)
                publish_wall = time.perf_counter()
                self._prev_probs = np.asarray(
                    model.p[:-1], np.float64
                )  # drop fallback row: the [V_real, K] warm-start seed
                self._prev_alpha = result.alpha
        if mode == "fresh":
            self._last_fresh_iters = result.em_iters
        iters_saved = (
            self._last_fresh_iters - result.em_iters
            if mode == "warm" and self._last_fresh_iters is not None
            else None
        )
        fresh = self._freshness_record(publish_wall, now_sim,
                                       refresh_wall0)
        record = {
            "refresh": idx,
            "mode": mode,
            "warm_start": mode == "warm",
            "em_iters": result.em_iters,
            "iters_saved": iters_saved,
            "train_wall_s": round(train_wall, 4),
            "held_out_ll": round(ll, 6),
            "held_docs": held_docs,
            "drifted": decision.drifted,
            "published": ok,
            "version": self.fleet.version(self.tenant),
            "docs": corpus.num_docs,
            "vocab": snap.real_vocab,
            "vocab_capacity": snap.vocab_capacity,
            "window_chunks": snap.chunks,
            **quality_info,
            **fresh,
        }
        self.refresh_records.append(record)
        self.refresh_count += 1
        agg = self._fit_agg[mode == "warm"]
        agg["fits"] += 1
        agg["wall_s"] += train_wall
        agg["em_iters"] += result.em_iters
        self._last_ll = ll
        if (self.fresh_control and self.control_record is None
                and mode == "warm" and ok
                and idx > self.warmup_refreshes):
            self.control_record = self._run_fresh_control(
                corpus, record, seed_probs, seed_alpha
            )
        if (self._warmup_counts is None
                and idx >= self.warmup_refreshes):
            from ..plans import warmup as plans_warmup

            self._warmup_counts = plans_warmup.compile_counts()
        return record

    def _train_mode(self) -> str:
        cc = self.cc
        if cc.warm_start not in ("auto", "always", "never"):
            raise ValueError(
                f"ContinuousConfig.warm_start={cc.warm_start!r}: "
                "expected 'auto', 'always', or 'never'"
            )
        if self._prev_probs is None or cc.warm_start == "never":
            return "fresh"
        if cc.warm_start == "always":
            return "warm"
        return self.drift.mode        # fresh right after a veto

    def _build_model(self, snap, result):
        from ..scoring import ScoringModel

        fallback = get_source(self.dsource).fallback(self.config.scoring)
        corpus = snap.corpus
        # The published model covers the REAL vocabulary only: the
        # tier's pad words never occur in an event and must not ride
        # into word_index.
        return ScoringModel.from_lda(
            corpus.doc_names,
            result.gamma,
            corpus.vocab[: snap.real_vocab],
            result.log_beta[:, : snap.real_vocab],
            fallback,
        )

    def _publish(self, model, snap) -> None:
        self.fleet.publish(
            self.tenant, model,
            source=f"window@{round(snap.t1, 1)}",
        )
        if self.scorer is None:
            self._start_scorer()

    def _quality_gate(self):
        """The detection-quality publish gate, built lazily: the
        injection suite needs the stream's pinned cuts, which exist
        only after the bootstrap slice.  Off unless
        ContinuousConfig.quality_gate."""
        if not self.cc.quality_gate:
            return None
        if self._qgate is None:
            from ..models.drift import QualityGate
            from ..sources.quality import QualitySuite

            cc = self.cc
            suite = QualitySuite(
                self.dsource, self.cuts,
                n_events=cc.quality_events, seed=cc.quality_seed,
                attack_events=cc.quality_attack_events, k=cc.quality_k,
            )
            raw_journal = (
                self.journal.journal if self.journal is not None
                else None
            )
            if raw_journal is not None:
                # The suite's provenance record: what was injected,
                # under which seed — the ground truth every subsequent
                # quality_gate record is judged against.
                raw_journal.append(suite.manifest)
            self._qgate = QualityGate(
                suite,
                tol=cc.quality_tol,
                history=cc.quality_history,
                min_history=cc.quality_min_history,
                journal=raw_journal, recorder=self.recorder,
            )
            self._qgate.prime(self._replayed)
        return self._qgate

    def _start_scorer(self) -> None:
        from ..serving import FleetScorer

        fz = get_source(self.dsource).event_featurizer(self.cuts)
        # Flagged-event product sink: the scored output IS the
        # pipeline's purpose — suspicious connects stream to
        # flagged_events.jsonl as they score (serve mode's on_batch
        # contract), not just into the freshness ledger.
        self._flagged_file = open(
            os.path.join(self.out_dir, "flagged_events.jsonl"), "a"
        )

        def on_batch(tenant, snapshot, feats, scores):
            threshold = self.scorer.tenant_threshold(tenant)
            for i in np.where(scores < threshold)[0]:
                self.flagged += 1
                self._flagged_file.write(json.dumps({
                    "tenant": tenant,
                    "flagged": feats.featurized_row(int(i)),
                    "score": float(scores[i]),
                    "model_version": snapshot.version,
                }) + "\n")
            self._flagged_file.flush()

        self.scorer = FleetScorer(
            self.fleet, {self.tenant: fz}, self.config.serving,
            on_batch=on_batch, journal=self.journal,
        )

    def _freshness_record(self, publish_wall: "float | None",
                          now_sim: float,
                          refresh_wall0: float) -> dict:
        """Resolve the freshness ledger at a successful publish: every
        not-yet-covered slice's events became servable under a model
        trained on a window containing them.  Wall freshness is what
        THIS replay measured (speed-dependent); event-time freshness
        is the cadence lag plus the refresh's own wall — what a
        real-time deployment would deliver, invariant to the replay
        speed."""
        if publish_wall is None:
            return {"freshness_slices": 0}
        refresh_cost = publish_wall - refresh_wall0
        n = 0
        wall_max = 0.0
        event_max = 0.0
        for entry in self._ledger:
            wall = publish_wall - entry.arrival_wall
            event_s = max(now_sim - entry.t1, 0.0) + refresh_cost
            n += 1
            wall_max = max(wall_max, wall)
            event_max = max(event_max, event_s)
            self._freshness_count += 1
            self._freshness.observe(wall)
            self._freshness_event.observe(event_s)
        # Covered entries can never be re-covered: drop them, so a
        # standing service's ledger holds only the slices since the
        # last successful publish (bounded, and each publish's scan is
        # O(new slices), not O(slices ever)).
        self._ledger.clear()
        if n and self.journal is not None:
            # The freshness-latency lane trace_view plots: per publish,
            # the worst newly-covered slice's arrival→servable gap.
            self.journal.append({
                "kind": "freshness",
                "slices": n,
                "wall_max_s": round(wall_max, 3),
                "event_max_s": round(event_max, 3),
            })
        return {"freshness_slices": n}

    def _run_fresh_control(self, corpus, record, seed_probs,
                           seed_alpha):
        """The apples-to-apples warm-vs-fresh measurement: re-run the
        warm fit AND one fresh fit back-to-back on the exact snapshot
        a warm refresh just trained (neither is published) — same
        data, same shapes, both on already-traced programs, so the
        bench's warm_start_speedup compares pure EM walls at matched
        held-out likelihood, not a compile against a cache hit."""
        cfg = self._lda_config()

        def _eval(result):
            ll, _ = self.drift.evaluate(
                result.log_beta, result.alpha, corpus,
                holdout_frac=self.cc.holdout_frac,
                batch_size=cfg.batch_size,
                min_bucket_len=cfg.min_bucket_len,
                var_max_iters=cfg.var_max_iters, var_tol=cfg.var_tol,
            )
            return ll

        t0 = time.perf_counter()
        warm_res = self.trainer.fit(
            corpus, topic_probs=seed_probs, alpha=seed_alpha
        )
        warm_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        fresh_res = self.trainer.fit(corpus)
        fresh_wall = time.perf_counter() - t0
        warm_ll = _eval(warm_res)
        fresh_ll = _eval(fresh_res)
        self._last_fresh_iters = fresh_res.em_iters
        return {
            "at_refresh": record["refresh"],
            "warm_wall_s": round(warm_wall, 4),
            "fresh_wall_s": round(fresh_wall, 4),
            "warm_em_iters": warm_res.em_iters,
            "fresh_em_iters": fresh_res.em_iters,
            "warm_start_speedup": round(
                fresh_wall / max(warm_wall, 1e-9), 3
            ),
            "held_out_ll_warm": round(warm_ll, 6),
            "held_out_ll_fresh": round(fresh_ll, 6),
            "held_out_ll_delta": round(warm_ll - fresh_ll, 6),
        }

    # -- drive + close ---------------------------------------------------

    def run(self, slices) -> dict:
        """Consume a paced slice stream to exhaustion, then close."""
        try:
            for sl in slices:
                self.ingest_slice(sl)
                self.maybe_refresh(sl.t1)
        finally:
            payload = self.close()
        return payload

    def close(self) -> dict:
        if self.scorer is not None:
            self.scorer.close(timeout=60.0)
            self.scorer = None
        if self._flagged_file is not None:
            self._flagged_file.close()
            self._flagged_file = None
        payload = self.summary()
        if self.journal is not None:
            self.journal.run_end(ok=True, publishes=self.drift.publishes,
                                 vetoes=self.drift.vetoes)
            self.journal.close()
            self.journal = None
        with open(os.path.join(self.out_dir, "continuous_metrics.json"),
                  "w") as f:
            json.dump(payload, f, indent=1)
        return payload

    def summary(self) -> dict:
        def _fit_stats(warm: bool) -> dict:
            agg = self._fit_agg[warm]
            if not agg["fits"]:
                return {"fits": 0}
            return {
                "fits": agg["fits"],
                "mean_wall_s": round(agg["wall_s"] / agg["fits"], 4),
                "mean_em_iters": round(
                    agg["em_iters"] / agg["fits"], 2
                ),
            }

        fresh_q = {}
        if self._freshness_count:
            fresh_q = {
                "freshness_p50_s": round(
                    self._freshness.quantile(0.50), 3
                ),
                "freshness_p99_s": round(
                    self._freshness.quantile(0.99), 3
                ),
                "freshness_event_p50_min": round(
                    self._freshness_event.quantile(0.50) / 60.0, 3
                ),
                "freshness_event_p99_min": round(
                    self._freshness_event.quantile(0.99) / 60.0, 3
                ),
            }
        retraces = None
        if self._warmup_counts is not None:
            from ..plans import warmup as plans_warmup

            delta = plans_warmup.counts_delta(self._warmup_counts)
            retraces = delta.get("traces", 0)
        return {
            "dsource": self.dsource,
            "tenant": self.tenant,
            "slices": self.slices,
            "events": self.events,
            "events_rejected": self.events_rejected,
            "flagged": self.flagged,
            "refreshes": self.refresh_count,
            "skipped_refreshes": self.skipped_refreshes,
            "publishes": self.drift.publishes,
            "vetoes": self.drift.vetoes,
            "quality_checks": (
                self._qgate.checks if self._qgate is not None else 0
            ),
            "quality_vetoes": (
                self._qgate.vetoes if self._qgate is not None else 0
            ),
            "version": (
                self.fleet.version(self.tenant)
                if self.tenant in self.fleet.tenants() else 0
            ),
            **fresh_q,
            "freshness_samples": self._freshness_count,
            "uncovered_slices": len(self._ledger),
            "warm": _fit_stats(True),
            "fresh": _fit_stats(False),
            "fresh_control": self.control_record,
            "held_out_ll": self._last_ll,
            "vocab": self.window.vocab_size,
            "vocab_capacity": self.window.vocab_capacity(),
            "tier_rebuilds": self.tier_rebuilds,
            "evicted_chunks": self.window.evicted_chunks,
            "retraces_after_warmup": retraces,
            # Bounded recent detail (maxlen 1024); the journal holds
            # the full history.
            "refresh_records": list(self.refresh_records),
        }


def run_continuous(
    config: PipelineConfig,
    dsource: str,
    slices,
    *,
    out_dir: str,
    tenant: str = "stream",
    fresh_control: bool = False,
    warmup_refreshes: "int | None" = None,
) -> dict:
    """Convenience wrapper: stand up a ContinuousService, wire the
    persistent compilation cache (the zero-retrace counters count
    nothing without it), and drive the slice stream to exhaustion."""
    from ..plans import warmup as plans_warmup

    if config.plans.compilation_cache:
        plans_warmup.setup_compilation_cache(
            cache_dir=config.plans.compilation_cache_dir
        )
    plans_warmup._ensure_listener()
    service = ContinuousService(
        config, dsource, out_dir=out_dir, tenant=tenant,
        fresh_control=fresh_control, warmup_refreshes=warmup_refreshes,
    )
    return service.run(slices)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ml_ops continuous",
        description="continuous ingestion: windowed streaming corpus, "
        "warm-start EM refreshes, drift-gated fleet publishes — "
        "freshness in minutes, not next-day (tools/day_replay.py "
        "paces a historical day into this mode)",
    )
    p.add_argument("dsource", choices=list(source_names()))
    p.add_argument("--flow-path", default=None,
                   help="raw netflow CSV to replay (FLOW_PATH env)")
    p.add_argument("--dns-path", default=None,
                   help="raw DNS CSV to replay (DNS_PATH env)")
    p.add_argument("--proxy-path", default=None,
                   help="raw proxy/HTTP log CSV to replay (PROXY_PATH "
                   "env)")
    p.add_argument("--quality-gate", action="store_true",
                   help="veto publishes that regress recall@k on the "
                   "labeled-injection suite (sources/inject.py)")
    p.add_argument("--data-dir", default=None,
                   help="output/journal directory (LPATH env)")
    p.add_argument("--qtiles", default=None,
                   help="pinned flow quantile cuts (stable word "
                   "identity across restarts)")
    p.add_argument("--speed", type=float, default=60.0,
                   help="replay speed multiplier over event time "
                   "(60 = an hour of events per wall minute)")
    p.add_argument("--slice-s", type=float, default=300.0,
                   help="ingest slice span in EVENT seconds")
    p.add_argument("--window-s", type=float, default=None,
                   help="override ContinuousConfig.window_s")
    p.add_argument("--refresh-s", type=float, default=None,
                   help="override ContinuousConfig.refresh_every_s")
    p.add_argument("--tenant", default="stream")
    p.add_argument("--fresh-control", action="store_true",
                   help="measure one fresh fit against a warm refresh's "
                   "snapshot (the warm_start_speedup number)")
    p.add_argument("--no-sleep", action="store_true",
                   help="deliver slices as fast as consumed (tests/CI)")
    return p


def main(argv: "list[str] | None" = None) -> int:
    import dataclasses

    args = build_parser().parse_args(argv)
    env = os.environ
    path = (
        getattr(args, f"{args.dsource}_path", None)
        or env.get(f"{args.dsource.upper()}_PATH", "")
    )
    if not path or not os.path.exists(path):
        print(f"continuous: no input file at {path!r}", flush=True)
        return 2
    config = PipelineConfig(
        data_dir=args.data_dir or env.get("LPATH", "."),
        qtiles_path=args.qtiles or "",
    )
    cc = config.continuous
    overrides = {}
    if args.window_s is not None:
        overrides["window_s"] = args.window_s
    if args.refresh_s is not None:
        overrides["refresh_every_s"] = args.refresh_s
    if args.quality_gate:
        overrides["quality_gate"] = True
    if overrides:
        config = config.replace(
            continuous=dataclasses.replace(cc, **overrides)
        )
    with open(path) as f:
        lines = f.readlines()
    slices = slice_events(lines, args.dsource, args.slice_s)
    speed = float("inf") if args.no_sleep else args.speed
    payload = run_continuous(
        config, args.dsource, paced_slices(slices, speed),
        out_dir=os.path.join(config.data_dir, "continuous"),
        tenant=args.tenant, fresh_control=args.fresh_control,
    )
    print(json.dumps(payload), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
