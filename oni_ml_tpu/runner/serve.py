"""`ml_ops serve` — run the streaming scoring service from a completed
day directory (SURVEY §5: the reference's only serving story is
re-running tomorrow's batch).

    python -m oni_ml_tpu.runner.ml_ops serve \
        --day-dir /data/days/20160122 --dsource flow \
        --input - --refresh-every 8

reads raw CSV events (one per line) from --input (file or stdin),
scores them in micro-batches against the registry's active model, emits
one {"stage": "serve", ...} metrics line per batch, prints flagged
events (score < threshold) as JSON lines, and — with --refresh-every —
folds the stream into online-LDA updates that hot-swap refreshed models
in without a restart.

`--dry-run` runs the whole stack (registry -> micro-batches ->
mid-stream hot-swap -> refresh republish) against a small synthetic
in-memory day and verifies the exactly-once contract; it needs no day
directory, no accelerator, and finishes in seconds — the CI smoke
(tools/serve_smoke.py) wraps it.
"""

from __future__ import annotations

import argparse
import json
import pickle
import sys

import numpy as np

from ..config import OnlineLDAConfig, ScoringConfig, ServingConfig
from ..sources import get as get_source
from ..sources import names as source_names
from ..serving import (
    BatchScorer,
    MetricsEmitter,
    ModelRegistry,
    RefreshLoop,
    featurizer_from_features,
)


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ml_ops serve",
        description="streaming scoring service over a completed day's "
        "model (micro-batch serving with online-LDA hot-swap refresh)",
    )
    p.add_argument("--day-dir", default=None,
                   help="completed day directory (doc_results.csv / "
                   "word_results.csv / features.pkl)")
    p.add_argument("--dsource", choices=list(source_names()),
                   default="flow")
    p.add_argument("--input", default="-", metavar="PATH",
                   help="raw event CSV stream; '-' = stdin (default)")
    p.add_argument("--threshold", type=float,
                   default=ScoringConfig.threshold,
                   help="emit events scoring under this as suspicious")
    # None = "not passed": the flag applies to whichever scorer the
    # mode runs (BatchScorer max_batch/max_wait_ms, or the
    # FleetScorer's fleet_max_batch/fleet_max_wait_ms under --fleet),
    # and a None sentinel — unlike comparing against the default value
    # — distinguishes 'unset' from 'explicitly set to the default' for
    # the dry runs' rescaling.
    p.add_argument("--max-batch", type=int, default=None,
                   help="micro-batch flush size (default: config/plan; "
                   "under --fleet this sets the cross-tenant flush "
                   "size)")
    p.add_argument("--max-wait-ms", type=float, default=None,
                   help="micro-batch latency trigger in ms (default: "
                   "config/plan; under --fleet this sets the "
                   "cross-tenant trigger)")
    p.add_argument("--device-score-min", type=int,
                   default=ServingConfig.device_score_min,
                   help="batches at/above this size score on device "
                   "(jit); smaller stay on the host f64 path; 0 = "
                   "pick the break-even from the measured dispatch "
                   "calibration (the default, so the device path can "
                   "never silently lose to host)")
    p.add_argument("--refresh-every", type=int, default=0, metavar="N",
                   help="fold every N scored batches into one online-LDA "
                   "step and hot-swap the refreshed model in (0=off)")
    p.add_argument("--metrics", default="", metavar="PATH",
                   help="also append per-batch metrics JSON lines here")
    p.add_argument("--metrics-port", type=int,
                   default=ServingConfig.metrics_port, metavar="PORT",
                   help="serve an OpenMetrics scrape endpoint (GET "
                   "/metrics) on this port: live counters, fixed-"
                   "boundary latency histograms with p50/p99/p999, and "
                   "roofline utilization gauges (0 = off)")
    p.add_argument("--metrics-host", default=ServingConfig.metrics_host,
                   metavar="ADDR",
                   help="bind address for --metrics-port (default "
                   "loopback; pass 0.0.0.0 to let remote collectors "
                   "scrape)")
    p.add_argument("--openmetrics", default=ServingConfig.openmetrics_path,
                   metavar="PATH",
                   help="write the same OpenMetrics text here at stream "
                   "end — the headless/CI file sink")
    p.add_argument("--journal", default="", metavar="PATH",
                   help="append every serving event to a crash-safe "
                   "telemetry journal (telemetry/journal.py JSONL: "
                   "atomic line writes, fsync cadence) — the serving "
                   "analogue of the pipeline's run_journal.jsonl; "
                   "tools/trace_view.py summarizes it")
    p.add_argument("--top-domains", default=None,
                   help="top-1m.csv whitelist for DNS featurization")
    p.add_argument("--no-plans", action="store_true",
                   help="disable measured-plan lookups "
                   "(oni_ml_tpu/plans): max_batch/max_wait_ms and the "
                   "dispatch calibration fall back to config/defaults")
    p.add_argument("--no-compilation-cache", action="store_true",
                   help="do not wire jax_compilation_cache_dir (by "
                   "default compiled scoring programs persist across "
                   "restarts, and startup AOT-warms the device scorer "
                   "at the plan's shapes before the first event)")
    p.add_argument("--dry-run", action="store_true",
                   help="exercise the full serving stack on a synthetic "
                   "in-memory day (no --day-dir needed) and exit")
    p.add_argument("--fleet", default="", metavar="MANIFEST",
                   help="multi-tenant fleet mode: serve every tenant in "
                   "this JSON manifest (serving/tenants.py) through one "
                   "shared compiled batch family; stream lines are "
                   "'<tenant>\\t<raw csv line>'.  With --dry-run, the "
                   "literal value 'synthetic' (or 'synthetic:N') runs "
                   "the fleet acceptance path on N in-memory tenants "
                   "(default 2) and exits")
    p.add_argument("--hot-tenants", type=int,
                   default=ServingConfig.fleet_hot_tenants, metavar="N",
                   help="tiered residency: at most N tenants per "
                   "K-group stay HBM-hot (stack-resident); the rest "
                   "page host-warm/checkpoint-cold on demand "
                   "(serving/residency.py).  0 = unbounded unless a "
                   "measured plan supplies a capacity")
    p.add_argument("--warm-tenants", type=int,
                   default=ServingConfig.fleet_warm_tenants, metavar="N",
                   help="at most N non-hot tenants keep host-resident "
                   "models; beyond that the coldest spill to "
                   "checkpoint-cold (0 = unbounded)")
    p.add_argument("--residency-policy",
                   choices=["lru", "lfu"],
                   default=ServingConfig.residency_policy,
                   help="eviction victim selection (admission-aware "
                   "LRU or LFU)")
    p.add_argument("--residency-spill",
                   default=ServingConfig.residency_spill_dir,
                   metavar="DIR",
                   help="cold-tier spill dir for tenants without a "
                   "reloadable day_dir (default: per-process temp "
                   "dir; manifest tenants reload from their day_dir "
                   "and never spill)")
    p.add_argument("--stack-precision", choices=["f32", "bf16"],
                   default=ServingConfig.stack_precision,
                   help="stacked-snapshot device storage dtype; bf16 "
                   "doubles HBM-hot tenant residency per byte with "
                   "f32 accumulation (~2^-8 relative score drift, "
                   "documented tolerance)")
    return p


def _serving_config(args) -> ServingConfig:
    mb, mw = args.max_batch, args.max_wait_ms
    return ServingConfig(
        max_batch=mb if mb is not None else ServingConfig.max_batch,
        max_wait_ms=mw if mw is not None else ServingConfig.max_wait_ms,
        fleet_max_batch=(mb if mb is not None
                         else ServingConfig.fleet_max_batch),
        fleet_max_wait_ms=(mw if mw is not None
                           else ServingConfig.fleet_max_wait_ms),
        device_score_min=args.device_score_min,
        refresh_every=args.refresh_every,
        threshold=args.threshold,
        metrics_path=args.metrics,
        metrics_port=getattr(args, "metrics_port", 0),
        metrics_host=getattr(args, "metrics_host",
                             ServingConfig.metrics_host),
        openmetrics_path=getattr(args, "openmetrics", ""),
        fleet_manifest=getattr(args, "fleet", ""),
        fleet_hot_tenants=getattr(args, "hot_tenants",
                                  ServingConfig.fleet_hot_tenants),
        fleet_warm_tenants=getattr(args, "warm_tenants",
                                   ServingConfig.fleet_warm_tenants),
        residency_policy=getattr(args, "residency_policy",
                                 ServingConfig.residency_policy),
        residency_spill_dir=getattr(args, "residency_spill",
                                    ServingConfig.residency_spill_dir),
        stack_precision=getattr(args, "stack_precision",
                                ServingConfig.stack_precision),
    )


def _load_featurizer(day_dir: str, top_domains_path: "str | None"):
    import os

    feats_path = os.path.join(day_dir, "features.pkl")
    if not os.path.exists(feats_path):
        raise FileNotFoundError(
            f"{feats_path} missing — serving pins word identity to the "
            "trained day's quantile cuts, which ride in features.pkl "
            "(run the pre stage, or keep the day dir intact)"
        )
    with open(feats_path, "rb") as f:
        features = pickle.load(f)
    top = frozenset()
    if top_domains_path:
        from ..features import load_top_domains

        top = load_top_domains(top_domains_path)
    return featurizer_from_features(features, top_domains=top)


def _looks_like_header(line: str, dsource: str) -> bool:
    """True when a stream's FIRST line is a column-name header: the
    source spec's always-numeric probe column (flow `hour`, dns
    `unix_tstamp`, proxy `duration`) doesn't parse.  Only consulted
    for the first line, so mid-stream garbage rows keep the batch
    path's NaN-featurize-and-score semantics."""
    parts = line.strip().split(",")
    col = get_source(dsource).header_probe_col
    if len(parts) <= col:
        return False
    try:
        float(parts[col])
        return False
    except ValueError:
        return True


def _make_serve_roofline(metrics, journal):
    """Serve roofline gauge, computed at SCRAPE time (and once at
    shutdown): the warmed micro-batch program's harvested cost over the
    cumulative DEVICE scoring wall (the serve.device_score_ms histogram
    — device-path flushes only; pricing host flushes as device
    dispatches would inflate the gauge arbitrarily) — achieved vs peak
    for the serving phase, utilization null off-TPU.  Shared by the
    single-model and fleet serve paths (the fleet's per-flush aggregate
    record feeds the same histograms)."""
    from ..telemetry import roofline as _roofline

    def _serve_roofline(emit_journal: bool = False):
        rec = metrics.recorder
        kw = {"journal": journal} if emit_journal else {}
        hd = rec.histograms.get("serve.device_score_ms")
        if hd is not None and hd.count:
            dev_events = rec.counters.get("serve.device_events")
            return _roofline.emit(
                "serve.micro_batch", hd.total / 1e3, dispatches=hd.count,
                recorder=rec, path="device",
                events=dev_events.value
                if dev_events is not None else None, **kw,
            )
        # Host-path-only session (every flush under break-even): no
        # device program ran, so there is no cost to join — emit a
        # wall-time-only record over the full scoring wall (the entry
        # name is unharvested by construction), never the device
        # program's cost times host flushes.
        h = rec.histograms.get("serve.score_ms")
        if h is None or not h.count:
            return None
        return _roofline.emit(
            "serve.micro_batch", h.total / 1e3, dispatches=h.count,
            recorder=rec, entry="serve.micro_batch.host", path="host",
            **kw,
        )

    return _serve_roofline


def serve_stream(args) -> int:
    from ..config import ScoringConfig as SC
    from ..plans import warmup as plans_warmup

    if not args.day_dir:
        raise SystemExit("serve needs --day-dir (or --dry-run)")
    # Persistent compilation cache BEFORE the first trace: a restarted
    # service deserializes yesterday's compiled scorers instead of
    # re-tracing them while events queue.  (--no-plans scoping is
    # main()'s job — it binds both this path and --dry-run.)
    cc_rec = plans_warmup.setup_compilation_cache(
        enabled=not args.no_compilation_cache
    )
    cfg = _serving_config(args)
    sc = SC()
    fallback = get_source(args.dsource).fallback(sc)
    registry = ModelRegistry()
    snap = registry.load_day(args.day_dir, fallback)
    featurizer = _load_featurizer(args.day_dir, args.top_domains)
    if featurizer.dsource != args.dsource:
        raise SystemExit(
            f"--dsource {args.dsource} but {args.day_dir} holds "
            f"{featurizer.dsource} features"
        )
    journal = None
    if getattr(args, "journal", ""):
        from ..telemetry import Journal

        journal = Journal(args.journal)
    metrics = MetricsEmitter(path=cfg.metrics_path, journal=journal)
    metrics.emit({
        "stage": "serve", "event": "model_loaded",
        "source": snap.source, "model_version": snap.version,
        "ips": len(snap.model.ip_index),
        "vocab": len(snap.model.word_index),
    })

    _serve_roofline = _make_serve_roofline(metrics, journal)

    mserver = None
    if cfg.metrics_port:
        from ..telemetry import MetricsServer

        mserver = MetricsServer(
            metrics.recorder, port=cfg.metrics_port,
            host=cfg.metrics_host, refresh=_serve_roofline,
        )
        metrics.emit({
            "stage": "serve", "event": "metrics_endpoint",
            "port": mserver.port, "path": "/metrics",
        })

    # Everything below runs under one finally that releases the HTTP
    # endpoint, the metrics file, and the journal: a mid-stream
    # exception must not leave the ThreadingHTTPServer bound (an
    # in-process restart on the same port would EADDRINUSE) or the
    # sinks open.
    try:
        refresh = (
            RefreshLoop(
                registry,
                OnlineLDAConfig(num_topics=snap.model.num_topics),
                every=cfg.refresh_every,
                total_docs=cfg.refresh_total_docs,
            )
            if cfg.refresh_every
            else None
        )

        def on_batch(snapshot, feats, scores):
            for i in np.where(scores < cfg.threshold)[0]:
                print(json.dumps({
                    "flagged": feats.featurized_row(int(i)),
                    "score": float(scores[i]),
                    "model_version": snapshot.version,
                }), flush=True)
            if refresh is not None:
                from ..serving import event_documents

                ips, words = event_documents(feats, featurizer.dsource)
                new = refresh.observe(snapshot, ips, words)
                if new is not None:
                    metrics.emit({
                        "stage": "serve", "event": "model_refresh",
                        "model_version": new.version,
                        "source": new.source,
                    })

        scorer = BatchScorer(
            registry, featurizer, cfg, metrics=metrics, on_batch=on_batch
        )
        # AOT warmup at the PLAN's shapes: the padded micro-batch device
        # programs (break-even .. max_batch, powers of two) compile NOW
        # — into the persistent cache — instead of stalling the first
        # over-break-even flush mid-stream.  The emitted record names
        # every resolved knob's source and the cache-hit vs trace
        # counts, so a restarted service can be ASSERTED warm, not
        # assumed.
        try:
            warm = plans_warmup.warmup_serving(
                snap.model.theta.shape[0], snap.model.p.shape[0],
                snap.model.num_topics, scorer.max_batch,
                cfg.device_score_min,
            )
        except Exception as e:  # warmup must never block serving
            warm = {"error": repr(e)[:200]}
        metrics.emit({
            "stage": "serve", "event": "plans",
            "knobs": scorer.plan,
            "compilation_cache": cc_rec,
            "warmup": warm,
        })
        stream = sys.stdin if args.input == "-" else open(args.input)
        submitted = rejected = header_skipped = 0
        header = None
        first = True
        try:
            for line in stream:
                if not line.strip():
                    continue
                # The batch pre stage drops the CSV header and its
                # duplicates (featurize_flow's removeHeader); serving
                # must match, or a piped raw day file scores one phantom
                # event (header numerics parse NaN, word lands in the
                # max bins).  Mid-stream garbage rows still score —
                # batch parity.
                if first:
                    first = False
                    if _looks_like_header(line, args.dsource):
                        header = line
                        header_skipped += 1
                        continue
                if header is not None and line == header:
                    header_skipped += 1
                    continue
                try:
                    scorer.submit(line)
                    submitted += 1
                except ValueError:
                    rejected += 1
        finally:
            if stream is not sys.stdin:
                stream.close()
            scorer.close()
        metrics.emit({
            "stage": "serve", "event": "stream_end",
            "submitted": submitted, "rejected": rejected,
            "header_skipped": header_skipped,
            "events_scored": scorer.events_scored,
            "batches": scorer.batches_flushed,
            "final_model_version": registry.version,
        })
        # Final roofline record (journaled) + OpenMetrics file sink,
        # then the shutdown aggregate from the shared registry: the
        # counters and latency distributions — now with true
        # p50/p99/p999 — the per-batch lines fed all along.
        _serve_roofline(emit_journal=True)
        if cfg.openmetrics_path:
            from ..telemetry import write_openmetrics

            try:
                write_openmetrics(cfg.openmetrics_path, metrics.recorder)
            except OSError as e:
                print(f"serve: openmetrics sink failed: {e!r}",
                      file=sys.stderr)
        metrics.emit({
            "stage": "serve", "event": "registry_snapshot",
            **metrics.snapshot(),
        })
        return 0 if scorer.events_scored == submitted else 1
    finally:
        if mserver is not None:
            mserver.close()
        metrics.close()
        if journal is not None:
            journal.close()


# ---------------------------------------------------------------------------
# --dry-run: synthetic end-to-end smoke
# ---------------------------------------------------------------------------


def _synthetic_day(n_events: int = 96, n_clients: int = 8, n_doms: int = 6,
                   seed: int = 42):
    """A tiny deterministic DNS day: raw rows + the model trained
    'yesterday' on them (dirichlet-random theta/p over the day's actual
    IP/word populations, like bench.py's scoring benches).  `seed`
    varies the day — fleet harnesses use distinct seeds per tenant so
    cross-tenant demux corruption cannot hide behind identical
    models."""
    from ..features.dns import featurize_dns
    from ..scoring import ScoringModel

    rng = np.random.default_rng(seed)
    rows = [
        [
            "t", str(1454000000 + int(rng.integers(0, 86400))),
            str(int(rng.integers(40, 1500))),
            f"10.0.0.{i % n_clients}",
            f"sub{int(rng.integers(0, 20))}.dom{int(rng.integers(0, n_doms))}.com",
            "1", str(int(rng.integers(1, 17))), str(int(rng.integers(0, 4))),
        ]
        for i in range(n_events)
    ]
    feats = featurize_dns(rows)
    ips = sorted({feats.client_ip(i) for i in range(feats.num_events)})
    vocab = sorted(set(feats.word))
    k = 5
    theta = rng.dirichlet(np.ones(k), size=len(ips))
    p = rng.dirichlet(np.ones(len(vocab)), size=k).T
    model = ScoringModel.from_results(ips, theta, vocab, p, fallback=0.1)
    cuts = (feats.time_cuts, feats.frame_length_cuts,
            feats.subdomain_length_cuts, feats.entropy_cuts,
            feats.numperiods_cuts)
    return rows, model, cuts


def dry_run(args) -> int:
    """Load a synthetic model, score a stream of >= 3 micro-batches,
    hot-swap to a refreshed model mid-stream, and verify zero dropped /
    double-scored events — the acceptance path, runnable anywhere."""
    from ..serving import DnsEventFeaturizer, event_documents

    rows, model, cuts = _synthetic_day()
    registry = ModelRegistry()
    registry.publish(model, source="dry-run-synthetic")
    # Flags carry through; only values the operator did NOT pass
    # rescale to the 96-event synthetic day (max_batch=4096 would make
    # one batch and refresh_every=0 no swap — neither exercises the
    # acceptance path; the max_wait_ms default already fits the dry
    # run).
    cfg = ServingConfig(
        max_batch=(args.max_batch
                   if args.max_batch is not None else 32),
        max_wait_ms=(args.max_wait_ms
                     if args.max_wait_ms is not None
                     else ServingConfig.max_wait_ms),
        refresh_every=args.refresh_every or 2,
        threshold=args.threshold,
        device_score_min=args.device_score_min,
        metrics_path=args.metrics,
    )
    metrics = MetricsEmitter(path=cfg.metrics_path)
    refresh = RefreshLoop(registry, OnlineLDAConfig(
        num_topics=model.num_topics), every=cfg.refresh_every)
    swaps = []

    def on_batch(snapshot, feats, scores):
        ips, words = event_documents(feats, "dns")
        new = refresh.observe(snapshot, ips, words)
        if new is not None:
            swaps.append(new.version)

    featurizer = DnsEventFeaturizer(cuts)
    scorer = BatchScorer(registry, featurizer, cfg, metrics=metrics,
                         on_batch=on_batch)
    futures = [scorer.submit(r) for r in rows]
    # Resolve BEFORE close so the flushes exercise the live triggers
    # (max_batch here; max_wait has its own test), not the close drain.
    results = [f.result(timeout=30.0) for f in futures]
    scorer.close()
    versions = sorted({v for _, v in results})
    triggers: dict[str, int] = {}
    for r in metrics.records:
        if "trigger" in r:
            triggers[r["trigger"]] = triggers.get(r["trigger"], 0) + 1
    ok = (
        len(results) == len(rows)                   # zero dropped
        and all(f.done() for f in futures)          # every future resolved
        and scorer.events_scored == len(rows)       # zero double-scored
        and scorer.batches_flushed >= 3
        and len(swaps) >= 1                         # hot-swap happened
        and len(versions) >= 2                      # ...and served traffic
        and all(np.isfinite(s) for s, _ in results)
    )
    summary = {
        "serve_dry_run": "ok" if ok else "FAILED",
        "events": len(rows),
        "events_scored": scorer.events_scored,
        "batches": scorer.batches_flushed,
        "triggers": triggers,
        "refresh_swaps": len(swaps),
        "model_versions_served": versions,
        "final_model_version": registry.version,
    }
    print(json.dumps(summary), flush=True)
    metrics.close()
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# --fleet: multi-tenant serving
# ---------------------------------------------------------------------------


def serve_fleet_stream(args) -> int:
    """Serve every tenant of a fleet manifest through one FleetScorer:
    shared device residency + one AOT-warmed compiled batch family,
    per-tenant admission/metrics/hot-swap.  Stream lines are
    ``<tenant>\\t<raw csv line>`` (a single-tenant manifest also
    accepts untagged lines)."""
    from ..config import ScoringConfig as SC
    from ..plans import warmup as plans_warmup
    from ..serving import (
        FleetRegistry,
        FleetScorer,
        ResidencyManager,
        load_manifest,
        resolve_hot_capacity,
    )

    cc_rec = plans_warmup.setup_compilation_cache(
        enabled=not args.no_compilation_cache
    )
    cfg = _serving_config(args)
    specs = load_manifest(args.fleet)
    journal = None
    if getattr(args, "journal", ""):
        from ..telemetry import Journal

        journal = Journal(args.journal)
    metrics = MetricsEmitter(path=cfg.metrics_path, journal=journal)
    # Tiered residency: an explicit --hot-tenants (or a measured plan
    # capacity) bounds HBM-hot stack membership; the stack then pads to
    # power-of-two capacity tiers so paging churn never retraces.
    hot_cap, hot_src = resolve_hot_capacity(cfg)
    tiered = hot_cap > 0
    fleet = FleetRegistry(
        journal=journal, recorder=metrics.recorder,
        capacity_tiers=tiered, stack_precision=cfg.stack_precision,
    )
    residency = None
    if tiered:
        residency = ResidencyManager(
            fleet, hot_capacity=hot_cap,
            warm_capacity=cfg.fleet_warm_tenants,
            policy=cfg.residency_policy,
            spill_dir=cfg.residency_spill_dir,
            journal=journal, recorder=metrics.recorder,
            capacity_source=hot_src,
        )
    sc = SC()
    featurizers: dict = {}
    for spec in specs:
        if not spec.day_dir:
            raise SystemExit(
                f"fleet manifest tenant {spec.tenant!r} has no day_dir"
            )
        # Under residency every tenant starts host-warm: a
        # thousand-tenant census pays ZERO startup stack builds; the
        # first admissions fill the hot tier.
        fleet.add_tenant(spec, hot=not tiered)
        fallback = get_source(spec.dsource).fallback(sc)
        snap = fleet.load_day(spec.tenant, spec.day_dir, fallback)
        if residency is not None:
            residency.register(
                spec.tenant, day_source=(spec.day_dir, fallback),
            )
        fz = _load_featurizer(spec.day_dir, args.top_domains)
        if fz.dsource != spec.dsource:
            raise SystemExit(
                f"tenant {spec.tenant!r} declares dsource "
                f"{spec.dsource} but {spec.day_dir} holds "
                f"{fz.dsource} features"
            )
        featurizers[spec.tenant] = fz
        metrics.emit({
            "stage": "serve", "event": "model_loaded",
            "tenant": spec.tenant, "source": snap.source,
            "model_version": snap.version,
            "tier": (residency.tier_of(spec.tenant)
                     if residency is not None else "hot"),
            "ips": len(snap.model.ip_index),
            "vocab": len(snap.model.word_index),
        })
    _serve_roofline = _make_serve_roofline(metrics, journal)
    mserver = None
    if cfg.metrics_port:
        from ..telemetry import MetricsServer

        mserver = MetricsServer(
            metrics.recorder, port=cfg.metrics_port,
            host=cfg.metrics_host, refresh=_serve_roofline,
        )
        metrics.emit({
            "stage": "serve", "event": "metrics_endpoint",
            "port": mserver.port, "path": "/metrics",
        })
    try:
        refreshes: dict = {}
        for spec in specs:
            every = spec.refresh_every or cfg.refresh_every
            if every:
                k = fleet.active(spec.tenant).model.num_topics
                refreshes[spec.tenant] = RefreshLoop(
                    fleet.view(spec.tenant),
                    OnlineLDAConfig(num_topics=k),
                    every=every,
                    total_docs=cfg.refresh_total_docs,
                )

        def on_batch(tenant, snapshot, feats, scores):
            # `scorer` binds at call time (defined just below): the
            # lane's resolved threshold is the one resolution of
            # spec-override-else-config, shared with the flagged
            # counters.
            for i in np.where(
                    scores < scorer.tenant_threshold(tenant))[0]:
                print(json.dumps({
                    "tenant": tenant,
                    "flagged": feats.featurized_row(int(i)),
                    "score": float(scores[i]),
                    "model_version": snapshot.version,
                }), flush=True)
            refresh = refreshes.get(tenant)
            if refresh is not None:
                from ..serving import event_documents

                ips, words = event_documents(
                    feats, featurizers[tenant].dsource
                )
                new = refresh.observe(snapshot, ips, words)
                if new is not None:
                    metrics.emit({
                        "stage": "serve", "event": "model_refresh",
                        "tenant": tenant,
                        "model_version": new.version,
                        "source": new.source,
                    })

        scorer = FleetScorer(
            fleet, featurizers, cfg, metrics=metrics,
            on_batch=on_batch, journal=journal, residency=residency,
        )
        if residency is not None:
            residency.set_pending_probe(
                lambda t: len(scorer._lanes[t].pending) > 0
            )
        # AOT warmup per pack group: the padded compiled batch family
        # is shared across every tenant of a K-group, so warming the
        # STACKED shapes once covers the whole fleet — and because
        # hot-swaps preserve per-tenant row counts (and paging churn
        # preserves the capacity-tier shape), these are the only
        # shapes serving will ever dispatch (zero retraces after
        # warmup, the acceptance criterion the fleet SLO bench pins).
        # Under residency the stack materializes at the FIRST
        # promotions, so warm the hot tier with the head tenants
        # before asking for stacked shapes.
        warm: "list | dict"
        try:
            warm = []
            ks = sorted({fleet.tenant_k(s.tenant) for s in specs})
            if residency is not None:
                by_k: dict = {}
                for s in specs:
                    by_k.setdefault(
                        fleet.tenant_k(s.tenant), []).append(s.tenant)
                for k, group in by_k.items():
                    for t in group[:max(1, hot_cap)]:
                        residency.ensure_hot(t)
            for k in ks:
                stack = fleet.stack(k)
                mult = max(
                    get_source(fleet.spec(t).dsource).pairs_per_event
                    for t in stack.tenants
                )
                warm.append({
                    "k": k, "tenants": len(stack.tenants),
                    "capacity": stack.capacity or None,
                    **plans_warmup.warmup_serving(
                        stack.model.theta.shape[0],
                        stack.model.p.shape[0], k,
                        scorer.max_batch * mult,
                        cfg.device_score_min,
                    ),
                })
        except Exception as e:  # warmup must never block serving
            warm = {"error": repr(e)[:200]}
        metrics.emit({
            "stage": "serve", "event": "plans",
            "knobs": (
                {**scorer.plan, **residency.plan}
                if residency is not None else scorer.plan
            ),
            "compilation_cache": cc_rec,
            "warmup": warm,
        })
        from ..serving import AdmissionRejected

        stream = sys.stdin if args.input == "-" else open(args.input)
        submitted = rejected = header_skipped = 0
        default_tenant = specs[0].tenant if len(specs) == 1 else None
        first_seen: dict = {}
        headers: dict = {}
        try:
            for line in stream:
                if not line.strip():
                    continue
                tenant, sep, payload = line.partition("\t")
                if sep:
                    tenant = tenant.strip()
                elif default_tenant is not None:
                    tenant, payload = default_tenant, line
                else:
                    rejected += 1      # untagged line, ambiguous tenant
                    continue
                if tenant not in featurizers:
                    rejected += 1
                    continue
                # Per-tenant header handling, batch-parity semantics
                # (serve_stream): each tenant's FIRST line may be a CSV
                # header; duplicates of it are dropped too.
                if first_seen.get(tenant) is None:
                    first_seen[tenant] = True
                    if _looks_like_header(
                            payload, featurizers[tenant].dsource):
                        headers[tenant] = payload
                        header_skipped += 1
                        continue
                if headers.get(tenant) is not None \
                        and payload == headers[tenant]:
                    header_skipped += 1
                    continue
                try:
                    scorer.submit(tenant, payload)
                    submitted += 1
                except AdmissionRejected:
                    rejected += 1      # journaled + counted per tenant
                except ValueError:
                    rejected += 1
        finally:
            if stream is not sys.stdin:
                stream.close()
            scorer.close()
        metrics.emit({
            "stage": "serve", "event": "stream_end",
            "submitted": submitted, "rejected": rejected,
            "header_skipped": header_skipped,
            "events_scored": scorer.events_scored,
            "batches": scorer.batches_flushed,
            "tenant_stats": scorer.tenant_stats(),
            "residency": (residency.stats_snapshot()
                          if residency is not None else None),
            "final_versions": {
                s.tenant: fleet.version(s.tenant) for s in specs
            },
        })
        _serve_roofline(emit_journal=True)
        if cfg.openmetrics_path:
            from ..telemetry import write_openmetrics

            try:
                write_openmetrics(cfg.openmetrics_path, metrics.recorder)
            except OSError as e:
                print(f"serve: openmetrics sink failed: {e!r}",
                      file=sys.stderr)
        metrics.emit({
            "stage": "serve", "event": "registry_snapshot",
            **metrics.snapshot(),
        })
        if submitted == 0 and rejected > 0:
            # A whole stream of rejects means the FRAMING is wrong
            # (untagged lines into a multi-tenant fleet, or tenant tags
            # not in the manifest) — rc 0 here would let a CI smoke
            # call "success" on zero scored events.
            print(
                f"serve: all {rejected} stream lines rejected — check "
                "the '<tenant>\\t<line>' framing against the manifest "
                "tenant ids", file=sys.stderr,
            )
            return 1
        return 0 if scorer.events_scored == submitted else 1
    finally:
        if residency is not None:
            residency.close()
        if mserver is not None:
            mserver.close()
        metrics.close()
        if journal is not None:
            journal.close()


def _parse_synthetic_fleet(value: str) -> "int | None":
    """'synthetic' / 'synthetic:N' -> N (default 2); anything else is a
    manifest path -> None."""
    if value == "synthetic":
        return 2
    if value.startswith("synthetic:"):
        try:
            n = int(value.split(":", 1)[1])
        except ValueError:
            raise SystemExit(
                f"--fleet {value!r}: N in 'synthetic:N' must be an "
                "integer"
            ) from None
        if n < 2:
            raise SystemExit("--fleet synthetic:N needs N >= 2 (the "
                             "fleet acceptance path is cross-tenant)")
        return n
    return None


def dry_run_fleet(args) -> int:
    """Fleet acceptance path on synthetic in-memory tenants: distinct
    per-tenant models score a tagged interleaved stream through ONE
    FleetScorer, tenant 0 hot-swaps mid-stream, and the run verifies
    per-tenant exactly-once delivery, cross-tenant packing (flushes
    spanning >= 2 tenants), and swap isolation (the other tenants'
    versions and futures are untouched).  Runnable anywhere, seconds,
    no day directory — the fleet half of tools/serve_smoke.py."""
    from ..serving import (
        DnsEventFeaturizer,
        FleetRegistry,
        FleetScorer,
        TenantSpec,
    )

    n_tenants = _parse_synthetic_fleet(args.fleet)
    if n_tenants is None:
        # A real manifest under --dry-run must not be SILENTLY replaced
        # by the synthetic fleet — an operator smoke-testing their
        # production manifest would get "ok" without it ever being
        # opened.
        raise SystemExit(
            "--dry-run --fleet takes 'synthetic[:N]' (the dry run "
            "builds in-memory tenants); to serve a real manifest, run "
            "without --dry-run"
        )
    tenants = [f"t{i}" for i in range(n_tenants)]
    days = {
        t: _synthetic_day(seed=42 + i)
        for i, t in enumerate(tenants)
    }
    fleet = FleetRegistry()
    featurizers = {}
    for t in tenants:
        rows, model, cuts = days[t]
        fleet.add_tenant(TenantSpec(tenant=t, dsource="dns"))
        fleet.publish(t, model, source=f"dry-run-{t}")
        featurizers[t] = DnsEventFeaturizer(cuts)
    cfg = ServingConfig(
        fleet_max_batch=(args.max_batch
                         if args.max_batch is not None else 32),
        fleet_max_wait_ms=(args.max_wait_ms
                           if args.max_wait_ms is not None
                           else ServingConfig.fleet_max_wait_ms),
        threshold=args.threshold,
        device_score_min=args.device_score_min,
        metrics_path=args.metrics,
    )
    metrics = MetricsEmitter(path=cfg.metrics_path)
    scorer = FleetScorer(fleet, featurizers, cfg, metrics=metrics)
    futures: dict = {t: [] for t in tenants}
    # First half of every tenant's day, interleaved round-robin — the
    # packed flushes must span tenants.
    half = {t: len(days[t][0]) // 2 for t in tenants}
    for i in range(max(half.values())):
        for t in tenants:
            if i < half[t]:
                futures[t].append(scorer.submit(t, days[t][0][i]))
    scorer.flush()
    first_results = {
        t: [f.result(timeout=30.0) for f in futures[t]] for t in tenants
    }
    # Mid-stream hot-swap of tenant 0 ONLY, then the second half.
    swapped = tenants[0]
    fleet.publish(swapped, _perturbed_model(days[swapped][1]),
                  source="dry-run-refresh")
    for t in tenants:
        for row in days[t][0][half[t]:]:
            futures[t].append(scorer.submit(t, row))
    scorer.flush()
    results = {
        t: [f.result(timeout=30.0) for f in futures[t]] for t in tenants
    }
    scorer.close()
    versions = {t: sorted({v for _, v in results[t]}) for t in tenants}
    packed_flushes = sum(
        1 for r in metrics.records
        if "tenants" in r and isinstance(r.get("tenants"), int)
        and r["tenants"] >= 2
    )
    ok = (
        all(len(results[t]) == len(days[t][0]) for t in tenants)
        and scorer.events_scored == sum(
            len(days[t][0]) for t in tenants
        )
        and packed_flushes >= 1                      # cross-tenant packing
        and versions[swapped][-1] >= 2               # swap served traffic
        and all(versions[t] == [1] for t in tenants[1:])  # isolation
        and all(
            np.isfinite(s) for t in tenants for s, _ in results[t]
        )
    )
    summary = {
        "serve_fleet_dry_run": "ok" if ok else "FAILED",
        "tenants": n_tenants,
        "events": sum(len(days[t][0]) for t in tenants),
        "events_scored": scorer.events_scored,
        "batches": scorer.batches_flushed,
        "packed_flushes": packed_flushes,
        "versions_served": versions,
        "first_flush_events": sum(len(v) for v in first_results.values()),
        "tenant_stats": scorer.tenant_stats(),
    }
    print(json.dumps(summary), flush=True)
    metrics.close()
    return 0 if ok else 1


def _perturbed_model(model):
    """A validly-normalized variant of `model` — the dry run's stand-in
    for a refreshed publish (same shapes, different values, so the
    stacked snapshot rebuilds without a retrace)."""
    from ..scoring import ScoringModel

    rng = np.random.default_rng(7)
    theta = model.theta * rng.uniform(0.5, 1.5, model.theta.shape)
    theta[:-1] /= theta[:-1].sum(1, keepdims=True)
    p = model.p * rng.uniform(0.5, 1.5, model.p.shape)
    p[:-1] /= p[:-1].sum(0, keepdims=True)
    return ScoringModel(
        ip_index=model.ip_index, theta=theta,
        word_index=model.word_index, p=p,
    )


def main(argv: "list[str] | None" = None) -> int:
    args = build_serve_parser().parse_args(argv)
    # --no-plans binds BOTH entry paths here, once: a BatchScorer
    # (serve or dry run) would otherwise resolve flush triggers from —
    # and record its dispatch calibration into — the live user cache;
    # a smoke run must not tune production.
    import contextlib

    from ..plans import NullStore, use_store

    ctx = (
        use_store(NullStore()) if args.no_plans
        else contextlib.nullcontext()
    )
    with ctx:
        if args.dry_run:
            if args.fleet:
                return dry_run_fleet(args)
            return dry_run(args)
        if args.fleet:
            if _parse_synthetic_fleet(args.fleet) is not None:
                raise SystemExit(
                    "--fleet synthetic is a --dry-run mode; a live "
                    "serve needs a manifest file"
                )
            return serve_fleet_stream(args)
        return serve_stream(args)


if __name__ == "__main__":
    raise SystemExit(main())
