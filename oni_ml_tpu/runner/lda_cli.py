"""Drop-in CLI for the reference's `lda` binary (oni-lda-c).

The reference orchestrator invokes its MPI LDA engine as

    mpiexec -n 20 -f machinefile ./lda est 2.5 20 settings.txt 20 \
        ../FDATE/model.dat random ../FDATE

(ml_ops.sh:80; argument meanings reconstructed in SURVEY.md §2.8).  This
module accepts the same argument vector so an existing deployment can
swap `mpiexec ... ./lda` for `python -m oni_ml_tpu.runner.lda_cli` and
get the TPU engine with unchanged scripts:

    python -m oni_ml_tpu.runner.lda_cli est 2.5 20 settings.txt 20 \
        ../FDATE/model.dat random ../FDATE

Differences from the reference, by design:
- `<nproc>` is accepted and ignored — device parallelism comes from the
  mesh (all local devices by default; ONI_ML_TPU_MESH="data,model" to
  override), not from a rank count.
- `random` is the only supported init (the reference's only used mode);
  `seeded`/`manual` from stock lda-c are not reproduced.
- per-rank `<i>.beta`/`<i>.gamma` shard files are not written — they
  were an MPI implementation artifact; `final.*` and `likelihood.dat`
  are the real contract (README.md:116-121).

settings.txt uses Blei lda-c's key-value format:

    var max iter 20
    var convergence 1e-6
    em max iter 100
    em convergence 1e-4
    alpha estimate
"""

from __future__ import annotations

import os
import sys

from ..config import LDAConfig


def read_settings(path: str) -> dict:
    """Parse lda-c settings.txt: 'key words value' lines, last token the
    value; `alpha estimate|fixed` is a bare flag."""
    out: dict = {}
    with open(path) as f:
        for raw in f:
            line = raw.strip().lower()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            if parts[:2] == ["alpha", "estimate"]:
                out["estimate_alpha"] = True
            elif parts[:2] == ["alpha", "fixed"]:
                out["estimate_alpha"] = False
            elif parts[:3] == ["var", "max", "iter"] and len(parts) > 3:
                n = int(float(parts[3]))
                # lda-c treats -1 as "iterate until converged"; our loop
                # bound is finite, so map it to a cap no real doc reaches.
                out["var_max_iters"] = 10_000 if n == -1 else n
            elif parts[:2] == ["var", "convergence"] and len(parts) > 2:
                out["var_tol"] = float(parts[2])
            elif parts[:3] == ["em", "max", "iter"] and len(parts) > 3:
                out["em_max_iters"] = int(float(parts[3]))
            elif parts[:2] == ["em", "convergence"] and len(parts) > 2:
                out["em_tol"] = float(parts[2])
            # Unknown keys and truncated lines are ignored, like lda-c's
            # fscanf-based reader.
    return out


def config_from_settings(path: str, alpha: float, k: int) -> LDAConfig:
    # warm_start_gamma pinned off: this CLI is the drop-in for
    # oni-lda-c (ml_ops.sh:80), whose E-step fresh-initializes gamma
    # every EM iteration — warm start reaches the same optimum but
    # shifts mid-run likelihood.dat values in late decimals, and this
    # surface promises the reference's exact semantics.
    # alpha_max_iters pinned to lda-c's MAX_ALPHA_ITER=100 (the
    # production default moved to the unrolled cap of 8 — equivalent
    # training, pinned in tests/test_lda.py — but THIS surface promises
    # the reference's exact alpha-Newton loop).
    return LDAConfig(num_topics=k, alpha_init=alpha,
                     warm_start_gamma=False, alpha_max_iters=100,
                     **read_settings(path))


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    wants_help = bool(argv) and argv[0] in ("-h", "--help")
    if wants_help or len(argv) != 8 or argv[0] != "est":
        print(
            "usage: python -m oni_ml_tpu.runner.lda_cli est <alpha> "
            "<num_topics> <settings.txt> <nproc-ignored> <model.dat> "
            "random <out_dir>",
            file=sys.stdout if wants_help else sys.stderr,
        )
        return 0 if wants_help else 2
    _, alpha_s, k_s, settings_path, _nproc, corpus_path, init, out_dir = argv
    if init != "random":
        print(f"only 'random' init is supported, got {init!r}", file=sys.stderr)
        return 2

    from ..io import Corpus
    from ..models import train_corpus

    cfg = config_from_settings(settings_path, float(alpha_s), int(k_s))
    corpus = Corpus.from_model_dat(corpus_path)

    mesh = None
    vocab_sharded = False
    mesh_env = os.environ.get("ONI_ML_TPU_MESH", "")
    if mesh_env:
        from ..parallel.mesh import mesh_from_spec

        try:
            mesh, vocab_sharded = mesh_from_spec(mesh_env)
        except ValueError as e:
            print(f"ONI_ML_TPU_MESH: {e}", file=sys.stderr)
            return 2

    os.makedirs(out_dir, exist_ok=True)
    result = train_corpus(
        corpus, cfg, out_dir=out_dir, mesh=mesh, vocab_sharded=vocab_sharded
    )
    final_ll = result.likelihoods[-1][0] if result.likelihoods else float("nan")
    print(
        f"em iterations: {result.em_iters}  "
        f"final likelihood: {final_ll:.6f}  "
        f"alpha: {result.alpha:.6f}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
