"""Pipeline runner — replaces ml_ops.sh (SURVEY.md §2.1).

`ml_ops.sh YYYYMMDD {flow|dns} [TOL]` drove five processes across three
runtimes (Spark/YARN, local Python, a 20-rank MPI binary) glued by HDFS
copies, scp fan-outs, and sleep-based barriers.  Here the same run is one
process driving device computations:

    python -m oni_ml_tpu.runner.ml_ops 20160122 flow 1e-20 \
        --flow-path raw.csv --data-dir /data

Stages (each persists its reference-format outputs into the day directory
and can be resumed individually — the per-stage checkpointing the
reference's architecture implies but never implements, SURVEY §5.3-5.4):

    pre     raw events -> FeatureTable (features.pkl) + word_counts.dat
    corpus  word_counts.dat -> words.dat / doc.dat / model.dat
    lda     model.dat -> final.beta/.gamma/.other + likelihood.dat
            -> doc_results.csv / word_results.csv
    score   features + results -> <dsource>_results.csv

Config comes from flags (duxbay.conf's env-var contract is honored as
fallback: FLOW_PATH, DNS_PATH, LPATH, TOL, DUPFACTOR).  Per-stage
wall-clock and row counts stream as JSON lines to stdout and
metrics.json — the structured observability the reference lacked
(its diagnostics were bash `time` + println, SURVEY §5.1, §5.5).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import pickle
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable

from ..config import (
    DataplaneConfig,
    FeedbackConfig,
    LDAConfig,
    OnlineLDAConfig,
    PipelineConfig,
    PlansConfig,
    ScoringConfig,
    TelemetryConfig,
)
from ..io import Corpus, formats
from ..models import train_corpus, train_corpus_online
from ..scoring import ScoringModel


class Stage(str, Enum):
    PRE = "pre"
    CORPUS = "corpus"
    LDA = "lda"
    SCORE = "score"


class MissingArtifactError(RuntimeError):
    """A stage needed an upstream checkpoint that is not on disk — a
    `--stages` invocation against an incomplete (or --no-checkpoints)
    day.  Raised BEFORE the loader so the operator gets the artifact
    name and the flag that regenerates it, not a stack trace from deep
    inside a parser."""


STAGE_ORDER = [Stage.PRE, Stage.CORPUS, Stage.LDA, Stage.SCORE]

# Stage -> files that mark it complete (resume contract).
_STAGE_OUTPUTS = {
    Stage.PRE: ["features.pkl", "word_counts.dat"],
    Stage.CORPUS: ["words.dat", "doc.dat", "model.dat"],
    Stage.LDA: [
        "final.beta", "final.gamma", "final.other", "likelihood.dat",
        "doc_results.csv", "word_results.csv",
    ],
    Stage.SCORE: [],  # results file name depends on dsource
}


@dataclass
class RunContext:
    config: PipelineConfig
    fdate: str
    dsource: str  # "flow" | "dns"
    day_dir: str
    mesh: object = None
    vocab_sharded: bool = False
    online: bool = False
    eval_quality: bool = False
    eval_holdout: float = 0.0
    metrics: list = field(default_factory=list)
    # In-process featurizer→corpus handoff: stage_pre parks the live
    # feature container here so stage_corpus builds the Corpus straight
    # from its interned tables (Corpus.from_features) instead of
    # re-parsing word_counts.dat; stage_corpus clears it once consumed.
    features: object = None
    # Background word_counts.dat writer (stage_pre): the file is the
    # resume/audit contract, not an input to this run, so its write
    # overlaps the LDA stage.  Joined (and errors re-raised) before
    # run_pipeline returns.
    wc_writer: object = None
    wc_writer_err: list = field(default_factory=list)
    # Telemetry flight recorder (oni_ml_tpu/telemetry/): the crash-safe
    # run journal (RunJournal; None on non-coordinator ranks and when
    # disabled), the stages this run may skip because a replayed
    # journal marked them complete, the span recorder, and the optional
    # device heartbeat whose check() gates each stage entry.
    journal: object = None
    journal_done: set = field(default_factory=set)
    recorder: object = None
    heartbeat: object = None
    # Streaming dataplane (oni_ml_tpu/dataplane/): the per-run
    # orchestrator for background checkpoint sinks, overlap tasks, and
    # bounded inter-stage channels (None = the serial file-contract
    # path: --no-dataplane, or any multi-process rank).  The hand-off
    # slots carry live stage outputs downstream so no stage re-reads
    # what the previous one just computed: `features` (pre→corpus AND
    # pre→score — the featurized day is scoring's input too, so with a
    # dataplane it survives until the score stage consumes it),
    # `corpus_handoff` (corpus→lda), `model_handoff` (lda→score, the
    # round-trip-exact ScoringModel), and `score_prep` (the
    # tokenization/index prep task running concurrently with EM).
    plane: object = None
    corpus_handoff: object = None
    model_handoff: object = None
    score_prep: object = None
    # Stages this invocation may run (wanted) — stage fns consult it to
    # decide whether a downstream hand-off is worth producing.
    wanted: list = field(default_factory=list)
    # True when a replayed journal shows a prior --no-checkpoints run
    # of this day: fail-fast messages then name the provenance of the
    # missing file contract.
    prior_no_checkpoints: bool = False
    # Background-write failures collected at dataplane drain (the
    # generalization of wc_writer_err) — the run fails on them after
    # the finally block, without masking the run's own exception.
    background_errs: list = field(default_factory=list)

    def path(self, name: str) -> str:
        return os.path.join(self.day_dir, name)

    def results_name(self) -> str:
        return f"{self.dsource}_results.csv"

    def emit(self, record: dict) -> None:
        record = {"fdate": self.fdate, "dsource": self.dsource, **record}
        print(json.dumps(record), flush=True)
        self.metrics.append(record)


def _stage_done(ctx: RunContext, stage: Stage) -> "str | None":
    """Why this stage can be skipped, or None if it must run.

    The file contract is necessary either way (a journal that says
    "done" about artifacts someone deleted must not win); the journal
    upgrades the evidence — replayed `stage end` records from a prior
    run of this day mean the resume is journal-driven, which the skip
    record names so post-mortems can tell the two apart."""
    names = _STAGE_OUTPUTS[stage] or [ctx.results_name()]
    if not all(os.path.exists(ctx.path(n)) for n in names):
        return None
    if stage.value in ctx.journal_done:
        return "journal: stage completed in a prior run"
    return "outputs exist"


def _require_artifacts(ctx: RunContext, names: list, stage: Stage,
                       regen_stage: Stage) -> None:
    """Fail fast — naming the artifact and the regenerating flag —
    when a stage's file-contract input is missing (the `--stages` /
    resume path; in-process runs hand the live object downstream and
    never get here)."""
    missing = [n for n in names if not os.path.exists(ctx.path(n))]
    if not missing:
        return
    msg = (
        f"stage {stage.value} needs {missing[0]} in {ctx.day_dir} and it "
        f"does not exist; regenerate it with `ml_ops {ctx.fdate} "
        f"{ctx.dsource} --stages {regen_stage.value} --force`"
        + (f" (also missing: {', '.join(missing[1:])})"
           if len(missing) > 1 else "")
    )
    if ctx.prior_no_checkpoints:
        msg += (
            " — note: a prior run of this day used --no-checkpoints, so "
            "no inter-stage files were written; resume is refused by "
            "design, re-run the full day"
        )
    raise MissingArtifactError(msg)


def _score_wanted(ctx: RunContext) -> bool:
    """Whether this invocation may still run the score stage — decides
    if the lda stage should produce the model hand-off and spawn the
    scoring-prep overlap task."""
    return Stage.SCORE in (ctx.wanted or STAGE_ORDER)


def _coord_decision(value: bool) -> bool:
    """Make a per-stage decision on the coordinator and broadcast it, so
    ranks can never desync on filesystem state (a rank skipping a stage
    the others run would starve their suff-stats allreduce).  The
    broadcast doubles as the inter-stage barrier: non-coordinators wait
    here until the coordinator has finished the previous stage's writes.

    Rides the coordination client's KV store (parallel/allreduce.py) —
    NOT an XLA collective, which the CPU runtime cannot execute across
    processes (the old multihost_utils broadcast was exactly that, and
    the root of the suite's XlaRuntimeError)."""
    import jax

    if jax.process_count() == 1:
        return value
    from ..parallel.allreduce import get_collective

    return bool(get_collective().broadcast_obj(
        bool(value), "stage_decision"
    ))


def _all_ranks_ok(ok: bool) -> bool:
    """All-gather per-rank outcome flags; True only if EVERY rank
    succeeded.  Unlike a one-to-all broadcast this also relays
    non-coordinator failures (e.g. a rank whose shared-FS read raised
    before it entered the stage's collectives).  KV-store allgather —
    the wait polls the failure key, so a rank that already posted a
    structured failure surfaces as PeerFailure here rather than a
    barrier timeout."""
    import jax

    if jax.process_count() == 1:
        return ok
    from ..parallel.allreduce import get_collective

    flags = get_collective().allgather_obj(bool(ok), "stage_outcome")
    return all(flags)


def _run_stage(ctx: RunContext, stage: Stage, fn: Callable[[], dict]) -> None:
    from ..telemetry.spans import maybe_span  # jax-free fast import

    if ctx.heartbeat is not None:
        # Fail CLEANLY at the stage boundary once the backend is gone —
        # entering the stage would hang in its first device call.
        ctx.heartbeat.check()
    if ctx.journal is not None:
        ctx.journal.stage_begin(stage.value)
    t0 = time.perf_counter()
    try:
        with maybe_span(f"stage.{stage.value}", fdate=ctx.fdate,
                        dsource=ctx.dsource):
            info = fn()
    except BaseException as e:
        if ctx.journal is not None:
            ctx.journal.stage_end(
                stage.value, ok=False,
                wall_s=round(time.perf_counter() - t0, 3),
                error=repr(e)[:300],
            )
        raise
    wall_s = round(time.perf_counter() - t0, 3)
    ctx.emit({"stage": stage.value, "wall_s": wall_s, **info})
    if ctx.journal is not None:
        # sync=True inside stage_end: the resume contract is durable
        # the moment the stage's outputs are.
        ctx.journal.stage_end(stage.value, ok=True, wall_s=wall_s, **info)


# ---------------------------------------------------------------------------
# Stages
# ---------------------------------------------------------------------------


def stage_pre(ctx: RunContext) -> dict:
    cfg = ctx.config
    from ..features.shards import resolve_pre_workers
    from ..sources import get as get_source

    workers, workers_src = resolve_pre_workers(
        cfg.pre_workers, with_source=True
    )
    timings: dict = {}
    # The whole day rides the source spec's `featurize_day` hook —
    # feedback ingestion, pinned-cut resolution, native/spill-file
    # streaming — so a registered source needs zero edits here.
    features, fb_rows = get_source(ctx.dsource).featurize_day(
        cfg, ctx.path("raw_lines.bin"), workers, timings,
    )
    if ctx.plane is not None:
        return _finish_pre_dataplane(ctx, features, fb_rows, workers,
                                     workers_src, timings)
    t0 = time.perf_counter()
    with open(ctx.path("features.pkl"), "wb") as f:
        pickle.dump(features, f, protocol=pickle.HIGHEST_PROTOCOL)
    timings["pickle_s"] = round(time.perf_counter() - t0, 3)
    # Native containers emit the whole word_counts buffer in C++ from
    # their interned tables + aggregated id arrays; building ~1.5M
    # Python (str,str,int) tuples and writing line-by-line was half the
    # pre stage on a 2M-event day.  Byte-identical to the fallback
    # (pinned by tests/test_scoring.py::test_native_word_counts_emit_*).
    t0 = time.perf_counter()
    n_wc = None
    blob = None
    if hasattr(features, "wc_ip"):
        from ..native_emit import word_counts_emit

        blob = word_counts_emit(features)
    if blob is not None:
        timings["wc_emit_s"] = round(time.perf_counter() - t0, 3)
        n_wc = len(features.wc_ip)
        # word_counts.dat is the resume/audit contract (_stage_done),
        # not an input to THIS run — stage_corpus consumes the live
        # container via Corpus.from_features.  Writing it on a
        # background thread overlaps the file IO with the LDA stage;
        # run_pipeline joins (and surfaces errors) before returning.
        # The write is tmp+rename so the contract name only ever names
        # a COMPLETE file: _stage_done checks bare existence, and the
        # overlap window spans the whole LDA stage — a hard kill
        # mid-write must not leave a truncated word_counts.dat that a
        # resumed run would silently parse.
        wc_path = ctx.path("word_counts.dat")
        # Remove any PRIOR run's contract file before the overlap
        # window opens: tmp+rename protects against truncation, not
        # staleness — a force rerun killed during LDA must leave a day
        # dir whose resume re-runs pre, never one that silently pairs
        # this run's features.pkl with the previous run's
        # word_counts.dat.
        for stale in (wc_path, wc_path + ".tmp"):
            try:
                os.unlink(stale)
            except FileNotFoundError:
                pass

        def _write_wc(blob=blob, path=wc_path):
            try:
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)
            except BaseException as e:  # surfaced at join
                ctx.wc_writer_err.append(e)

        import threading

        ctx.wc_writer = threading.Thread(
            target=_write_wc, name="wc-writer"
        )
        ctx.wc_writer.start()
        timings["wc_write"] = "background"
    else:
        triples = features.word_counts()
        # Same atomic publish as the background path: a crash mid-write
        # must not leave a partial contract file under the real name.
        formats.write_word_counts(ctx.path("word_counts.dat.tmp"), triples)
        os.replace(ctx.path("word_counts.dat.tmp"),
                   ctx.path("word_counts.dat"))
        n_wc = len(triples)
        timings["wc_emit_s"] = round(time.perf_counter() - t0, 3)
        timings["wc_write"] = "inline"
    ctx.features = features  # direct handoff to stage_corpus
    return _pre_record(ctx, features, fb_rows, workers, workers_src,
                       timings, n_wc)


def _pre_record(ctx: RunContext, features, fb_rows, workers, workers_src,
                timings, n_wc) -> dict:
    """The pre stage's metrics record, shared by the serial and
    dataplane tails."""
    merge_wall = timings.pop("merge_s", None)
    out = {
        "events": features.num_events,
        "word_count_rows": n_wc,
        "feedback_rows": len(fb_rows),
        "pre_workers": workers,
        "plans": {
            "pre_workers": {"value": workers, "source": workers_src}
        },
        "wall": timings,
    }
    if merge_wall is not None:
        out["merge_wall_s"] = merge_wall
    return out


def _finish_pre_dataplane(ctx: RunContext, features, fb_rows, workers,
                          workers_src, timings) -> dict:
    """Dataplane tail of the pre stage: the live container is the
    hand-off (to corpus assembly AND, later, to scoring), and both
    file artifacts — features.pkl and word_counts.dat — are demoted to
    background checkpoint sinks whose writes overlap the downstream
    stages.  Stale contract files are cleared synchronously BEFORE the
    overlap window opens (tmp+rename protects against truncation, not
    staleness — see the serial path's word_counts note)."""
    from ..dataplane import atomic_write, atomic_write_bytes, clear_stale

    plane = ctx.plane
    pkl_path = ctx.path("features.pkl")
    wc_path = ctx.path("word_counts.dat")
    clear_stale(pkl_path, wc_path)

    def _write_pkl(path=pkl_path, features=features):
        def _dump(tmp):
            with open(tmp, "wb") as f:
                pickle.dump(features, f, protocol=pickle.HIGHEST_PROTOCOL)
        atomic_write(path, _dump)

    n_wc = None
    if hasattr(features, "wc_ip"):
        n_wc = len(features.wc_ip)

        def _write_wc(path=wc_path, features=features):
            from ..native_emit import word_counts_emit

            blob = word_counts_emit(features)
            if blob is not None:
                atomic_write_bytes(path, blob)
            else:
                atomic_write(path, lambda tmp: formats.write_word_counts(
                    tmp, features.word_counts()))
    else:
        # Fallback containers materialize triples anyway; count them
        # here (the record needs n_wc) and only the write goes async.
        triples = features.word_counts()
        n_wc = len(triples)

        def _write_wc(path=wc_path, triples=triples):
            atomic_write(path, lambda tmp: formats.write_word_counts(
                tmp, triples))

    if plane.checkpoints:
        plane.checkpoint("features_pkl", _write_pkl, stage=Stage.PRE.value)
        plane.checkpoint("word_counts", _write_wc, stage=Stage.PRE.value)
        timings["pickle"] = "background"
        timings["wc_write"] = "background"
    else:
        timings["pickle"] = "skipped"
        timings["wc_write"] = "skipped"
    ctx.features = features  # hand-off: corpus assembly + scoring
    return _pre_record(ctx, features, fb_rows, workers, workers_src,
                       timings, n_wc)


def stage_corpus(ctx: RunContext) -> dict:
    plane = ctx.plane
    stream_info = None
    if ctx.features is not None and plane is not None:
        # Streaming dataplane: the featurizer's columnar word counts
        # flow through a bounded channel into incremental first-seen
        # assembly while the pre stage's demoted checkpoint writes
        # (features.pkl, word_counts.dat) are still in flight — the
        # full-day pre→corpus barrier is gone.  Identical corpus to
        # Corpus.from_features (pinned by tests/test_dataplane.py).
        # The features container stays parked: it is the score stage's
        # input too.
        from ..dataplane import (
            consume_corpus,
            stream_word_counts,
            word_count_columns,
        )

        wc = word_count_columns(ctx.features)
        ch = plane.channel("pre.wc->corpus")
        plane.spawn(
            "wc_stream",
            lambda: stream_word_counts(
                wc, ch, ctx.config.dataplane.chunk_rows
            ),
            stage=Stage.CORPUS.value,
            # The producer's put() backpressure waits are idle, not
            # work: exclude them from the task's work accounting so
            # bench's sum-of-stage-walls can't double-count the
            # consumer's inline wall.
            stall=lambda: ch.stats()["put_stall_s"],
        )
        corpus, builder = consume_corpus(ch, wc.ip_table, wc.word_table)
        handoff = "direct"
        stream_info = {"chunks": builder.chunks, "rows": builder.rows}
    elif ctx.features is not None:
        # In-process serial run: the featurizer's container is still
        # live — build the CSR straight from its interned tables
        # instead of re-parsing the ~word_count_rows text triples
        # stage_pre just held in native arrays (identical output,
        # pinned by tests/test_pre_parallel.py).
        corpus = Corpus.from_features(ctx.features)
        handoff = "direct"
        ctx.features = None  # release featurizer arrays before LDA
    else:
        # Resume path (--stages corpus, or pre skipped as done): the
        # emitted file is the contract.
        _require_artifacts(ctx, ["word_counts.dat"], Stage.CORPUS,
                           Stage.PRE)
        corpus = Corpus.from_word_counts_file(ctx.path("word_counts.dat"))
        handoff = "file"
    if plane is not None:
        # The LDA-C corpus triplet demoted to a background checkpoint
        # overlapping EM; the live corpus hands off in memory, so the
        # lda stage no longer re-parses model.dat it just watched this
        # stage write.
        from ..dataplane import clear_stale

        clear_stale(*(ctx.path(n) for n in _STAGE_OUTPUTS[Stage.CORPUS]))
        plane.checkpoint(
            "corpus_dat", lambda: corpus.save_atomic(ctx.day_dir),
            stage=Stage.CORPUS.value,
        )
        ctx.corpus_handoff = corpus
    else:
        corpus.save(ctx.day_dir)
    out = {
        "docs": corpus.num_docs,
        "vocab": corpus.num_terms,
        "tokens": corpus.num_tokens,
        "handoff": handoff,
    }
    if stream_info is not None:
        out["stream"] = stream_info
    return out


def _em_progress(ctx: RunContext):
    """Progress callback streaming EM likelihood points into the run
    journal — fired at the fused driver's host-sync cadence
    (LDAConfig.host_sync_every), so a killed fit leaves its sub-run
    likelihood trajectory on disk, not just likelihood.dat's possibly
    unflushed tail."""
    if ctx.journal is None:
        return None

    def progress(it: int, ll: float, conv: float) -> None:
        ctx.journal.em_likelihood(it, ll, conv)

    return progress


def stage_lda(ctx: RunContext) -> dict:
    plane = ctx.plane
    if ctx.corpus_handoff is not None:
        # Streamed corpus: EM consumes the CSR the corpus stage just
        # assembled in memory — the serial path's write-model.dat-then
        # -re-parse-it round trip is gone (the file is a background
        # checkpoint, not this stage's input).  Identical training:
        # same id orderings, same CSR values (tests/test_dataplane.py
        # pins final.beta/likelihood.dat bytes against the file path).
        corpus = ctx.corpus_handoff
        ctx.corpus_handoff = None
        corpus_src = "handoff"
    else:
        _require_artifacts(ctx, ["model.dat", "words.dat", "doc.dat"],
                           Stage.LDA, Stage.CORPUS)
        corpus = Corpus.from_model_dat(
            ctx.path("model.dat"), ctx.path("words.dat"),
            ctx.path("doc.dat")
        )
        corpus_src = "file"
    # The streamlined demotion path: plain batch EM only (the online
    # and holdout trainers own their file writes inline; they keep the
    # serial tail).
    streamline = (plane is not None and not ctx.online
                  and not ctx.eval_holdout)
    if (plane is not None and ctx.features is not None
            and not ctx.online and _score_wanted(ctx)):
        # Scoring prep overlaps EM: the event tokenization / model-row
        # index resolution depends only on the corpus orderings and
        # the featurized day — both final here — so it runs on a
        # background task for the whole fit and scoring dispatch
        # starts the moment the model converges.
        from ..dataplane import build_scoring_prep

        feats = ctx.features
        ctx.score_prep = plane.spawn(
            "score_prep",
            lambda: build_scoring_prep(
                feats, corpus.doc_names, corpus.vocab, ctx.dsource
            ),
            stage=Stage.SCORE.value,
        )
    held_metrics = {}
    if ctx.online:
        if ctx.vocab_sharded:
            raise ValueError(
                "--online supports data-parallel meshes only "
                "(vocab sharding is batch-mode)"
            )
        if ctx.eval_holdout:
            raise ValueError("--eval-holdout is batch-mode only")
        online_progress = None
        if ctx.journal is not None:
            def online_progress(info, _ctx=ctx):
                # StreamStepInfo: step/likelihood map onto the same
                # em_ll stream batch EM writes (conv has no online
                # analogue; rho is the useful third column).
                _ctx.journal.append({
                    "kind": "em_ll", "iter": int(info.step),
                    "ll": float(info.likelihood), "rho": float(info.rho),
                })
        result = train_corpus_online(
            corpus, ctx.config.online_lda, out_dir=ctx.day_dir,
            mesh=ctx.mesh, progress=online_progress,
        )
    elif ctx.eval_holdout:
        result, held_metrics = _train_with_holdout(ctx, corpus)
    else:
        # With checkpoints off, out_dir=None turns off likelihood.dat
        # streaming and checkpoint.npz resume too — the run's
        # observability record is the journal's em_ll stream.
        out_dir = ctx.day_dir if (plane is None or plane.checkpoints) \
            else None
        result = train_corpus(
            corpus,
            ctx.config.lda,
            out_dir=out_dir,
            mesh=ctx.mesh,
            vocab_sharded=ctx.vocab_sharded,
            progress=_em_progress(ctx),
            # Streamlined runs demote final.* to checkpoint sinks
            # below; the trainer must not also write them inline.
            save_final=not streamline,
        )
    from ..models.lda import _is_coordinator

    if _is_coordinator():
        if streamline:
            _demote_lda_artifacts(ctx, corpus, result)
        else:
            # result is rank-identical (collective gathers in
            # train_corpus*); the shared day dir has exactly one writer.
            formats.write_doc_results(
                ctx.path("doc_results.csv"), corpus.doc_names, result.gamma
            )
            formats.write_word_results(
                ctx.path("word_results.csv"), corpus.vocab, result.log_beta
            )
    if streamline and _score_wanted(ctx):
        # lda→score hand-off: the ScoringModel assembled in memory with
        # the results CSVs' round-trip arithmetic (ScoringModel.from_lda
        # — identical doubles, so identical scored bytes), parked so
        # scoring starts without reading back the demoted checkpoints.
        from ..sources import get as get_source

        ctx.model_handoff = ScoringModel.from_lda(
            corpus.doc_names, result.gamma, corpus.vocab, result.log_beta,
            get_source(ctx.dsource).fallback(ctx.config.scoring),
        )
    lls = [ll for ll, _ in result.likelihoods]
    out = {
        "em_iters": result.em_iters,
        "final_likelihood": lls[-1] if lls else None,
        "alpha": result.alpha,
        "corpus": corpus_src,
    }
    # Dispatch-knob provenance (plans.resolve via the trainer): which
    # source — config override, measured plan, or shipped default —
    # each tuned constant came from this run.
    plan_rec = getattr(result, "plan", None)
    if plan_rec:
        out["plans"] = plan_rec
    if ctx.eval_quality and _is_coordinator():
        out.update(_completion_score(ctx, result.log_beta, result.alpha,
                                     corpus))
    out.update(held_metrics)
    return out


def _demote_lda_artifacts(ctx: RunContext, corpus, result) -> None:
    """Submit the model artifacts (final.beta/gamma/other,
    doc_results.csv, word_results.csv) as background checkpoint sinks
    overlapping the score stage — same bytes as the serial inline
    writes, published atomically because the write window now spans
    downstream compute."""
    from ..dataplane import atomic_write, clear_stale

    plane = ctx.plane
    clear_stale(*(ctx.path(n) for n in (
        "final.beta", "final.gamma", "final.other",
        "doc_results.csv", "word_results.csv",
    )))
    log_beta, gamma, alpha = result.log_beta, result.gamma, result.alpha
    k = log_beta.shape[0]
    num_terms = corpus.num_terms
    doc_names, vocab = corpus.doc_names, corpus.vocab

    def _write_final():
        atomic_write(ctx.path("final.beta"),
                     lambda tmp: formats.write_beta(tmp, log_beta))
        atomic_write(ctx.path("final.gamma"),
                     lambda tmp: formats.write_gamma(tmp, gamma))
        atomic_write(ctx.path("final.other"),
                     lambda tmp: formats.write_other(tmp, k, num_terms,
                                                     alpha))

    plane.checkpoint("final_model", _write_final, stage=Stage.LDA.value)
    plane.checkpoint(
        "doc_results",
        lambda: atomic_write(
            ctx.path("doc_results.csv"),
            lambda tmp: formats.write_doc_results(tmp, doc_names, gamma),
        ),
        stage=Stage.LDA.value,
    )
    plane.checkpoint(
        "word_results",
        lambda: atomic_write(
            ctx.path("word_results.csv"),
            lambda tmp: formats.write_word_results(tmp, vocab, log_beta),
        ),
        stage=Stage.LDA.value,
    )


def _train_with_holdout(ctx: RunContext, corpus):
    """--eval-holdout FRAC: hash-split documents BEFORE training, train
    beta on the remainder only, and report the true held-out
    per-token log-likelihood of the excluded split (document-completion
    protocol, models/evaluate.py).  Unlike --eval-quality's
    training-set completion score, this number is valid for
    hyperparameter selection — beta never saw the held-out documents.

    The pipeline file contract is preserved: final.gamma /
    doc_results.csv still carry EVERY document (held-out docs get their
    doc-topic posterior inferred post-hoc under the trained beta — the
    scorer needs a theta row per IP), and final.beta/likelihood.dat
    reflect the train-split run."""
    import math

    import numpy as np

    from ..io import make_batches
    from ..models.evaluate import hash_split, held_out_per_token_ll
    from ..models.lda import LDAResult, _is_coordinator
    from ..ops import estep

    cfg = ctx.config.lda
    train_idx, held_idx = hash_split(corpus.doc_names, ctx.eval_holdout)
    if len(held_idx) == 0 or len(train_idx) == 0:
        raise ValueError(
            f"--eval-holdout {ctx.eval_holdout} split to "
            f"{len(train_idx)} train / {len(held_idx)} held-out docs of "
            f"{corpus.num_docs}; need both non-empty (tiny day?)"
        )
    # out_dir stays the day dir so likelihood.dat streams crash-safe and
    # checkpoint_every keeps working; train_corpus's final.* writes
    # cover the train subset only and are overwritten with the
    # full-contract versions below in the same process.
    result = train_corpus(
        corpus.select(train_idx),
        cfg,
        out_dir=ctx.day_dir,
        mesh=ctx.mesh,
        vocab_sharded=ctx.vocab_sharded,
        progress=_em_progress(ctx),
    )

    held_batches = make_batches(
        corpus.select(held_idx), batch_size=cfg.batch_size,
        min_bucket_len=cfg.min_bucket_len,
    )
    score = held_out_per_token_ll(
        result.log_beta, result.alpha, held_batches,
        var_max_iters=cfg.var_max_iters, var_tol=cfg.var_tol,
    )

    # Full-contract gamma: train rows from the fit, held-out rows
    # inferred under the trained beta (full tokens — what the scorer
    # conditions on for p(event)).
    import jax.numpy as jnp

    full_gamma = np.zeros((corpus.num_docs, result.gamma.shape[1]))
    full_gamma[train_idx] = result.gamma
    log_beta_dev = jnp.asarray(result.log_beta, jnp.float32)
    for b in held_batches:
        res = estep.e_step(
            log_beta_dev, jnp.float32(result.alpha),
            jnp.asarray(b.word_idx),
            jnp.asarray(b.counts, jnp.float32),
            jnp.asarray(b.doc_mask, jnp.float32),
            var_max_iters=cfg.var_max_iters, var_tol=cfg.var_tol,
            backend="xla",
        )
        sel = b.doc_mask == 1
        full_gamma[held_idx[b.doc_index[sel]]] = np.asarray(
            res.gamma, np.float64
        )[sel]

    full = LDAResult(
        log_beta=result.log_beta, gamma=full_gamma, alpha=result.alpha,
        likelihoods=result.likelihoods, em_iters=result.em_iters,
    )
    if _is_coordinator():
        # likelihood.dat was already streamed during fit.
        full.save(ctx.day_dir, include_likelihood=False)
    return full, {
        "held_out_frac": ctx.eval_holdout,
        "held_out_docs": int(len(held_idx)),
        "held_out_per_token_ll": score,
        "held_out_perplexity": math.exp(-score),
    }


def _completion_score(ctx: RunContext, log_beta, alpha, corpus=None) -> dict:
    """Document-completion score of the day's model (models/evaluate.py):
    gamma fits on each doc's even token slots, the odd slots score under
    the predictive distribution.  Run over the TRAINING day, this is a
    drift-monitoring number comparable across days — NOT a true held-out
    score (the odd tokens helped fit beta, so it is optimistic; for
    hyperparameter selection use models.evaluate on an excluded corpus
    split)."""
    import math

    from ..io import make_batches
    from ..models.evaluate import held_out_per_token_ll

    if corpus is None:
        corpus = Corpus.from_model_dat(
            ctx.path("model.dat"), ctx.path("words.dat"), ctx.path("doc.dat")
        )
    score = held_out_per_token_ll(
        log_beta, alpha, make_batches(corpus, ctx.config.lda.batch_size)
    )
    return {
        "completion_per_token_ll": score,
        "completion_perplexity": math.exp(-score),
    }


def stage_score(ctx: RunContext) -> dict:
    if ctx.features is not None:
        # Streaming dataplane: the live featurized day IS the scoring
        # input — no features.pkl read-back (that file is a background
        # checkpoint of the same object, so the arrays are identical).
        features = ctx.features
        ctx.features = None
        feat_src = "handoff"
    else:
        _require_artifacts(ctx, ["features.pkl"], Stage.SCORE, Stage.PRE)
        with open(ctx.path("features.pkl"), "rb") as f:
            features = pickle.load(f)
        feat_src = "file"
        _resolve_spill_blobs(ctx, features)
    from ..sources import get as get_source

    fallback = get_source(ctx.dsource).fallback(ctx.config.scoring)
    if ctx.model_handoff is not None:
        model = ctx.model_handoff
        ctx.model_handoff = None
        model_src = "handoff"
    else:
        _require_artifacts(
            ctx, ["doc_results.csv", "word_results.csv"], Stage.SCORE,
            Stage.LDA,
        )
        model = ScoringModel.from_files(
            ctx.path("doc_results.csv"), ctx.path("word_results.csv"),
            fallback,
        )
        model_src = "file"
    prep = None
    if ctx.score_prep is not None:
        # Join the EM-overlapped tokenization/index prep; by the time
        # training has converged this is normally already done, so the
        # span prices (near-)zero wait — a long join here means the
        # overlap failed to hide the prep and shows up in trace_view.
        from ..telemetry.spans import maybe_span

        with maybe_span("dataplane.prep_join"):
            prep = ctx.score_prep.result()
        ctx.score_prep = None
    return _score_day(ctx, features, model, prep,
                      feat_src=feat_src, model_src=model_src)


def _resolve_spill_blobs(ctx: RunContext, features) -> None:
    # Spilled raw rows (stage_pre) are referenced by the path recorded
    # at pre time.  The spill file lives beside features.pkl, so a
    # moved/renamed/published day dir invalidates the recorded path
    # while the file itself is right here — when (and ONLY when) the
    # recorded path is gone, re-resolve against this day dir (round-3
    # advisor finding: the stale path used to surface as a bare
    # FileNotFoundError deep in scoring; a valid recorded path always
    # wins, so a stale same-named spill here can't be silently
    # substituted), failing recoverably, naming the move, when neither
    # location has the file.
    for attr in ("lines_blob", "rows_blob"):
        blob = getattr(features, attr, None)
        if blob is None or not hasattr(blob, "path"):
            continue

        def _check_size(path):
            # Identity check before trusting ANY candidate — recorded
            # or re-resolved: a spill of a DIFFERENT size than the one
            # features.pkl was written against (stale leftover of an
            # earlier run in a copied day dir, or a partial rewrite
            # from an interrupted pre re-run at the recorded path)
            # would be scored against mismatched row offsets — wrong
            # lines, not an error (round-4 advisor finding; round-5
            # review widened it to the recorded path).  Size at spill
            # time rides in the pickle; pre-round-5 pickles lack it
            # and keep the old adopt-by-name behavior.
            want = getattr(blob, "size", None)
            have = os.path.getsize(path)
            if want is not None and have != want:
                raise FileNotFoundError(
                    f"features.pkl references spilled raw rows of "
                    f"{want} bytes (size at pre time); {path} holds "
                    f"{have} bytes — a stale or partial spill from a "
                    "different run, refusing to score against "
                    "mismatched offsets; re-run the pre stage "
                    "(--stages pre --force)"
                )

        if os.path.exists(blob.path):
            _check_size(blob.path)
            continue  # recorded path valid: never silently substitute
        local = ctx.path(os.path.basename(blob.path))
        if os.path.exists(local):
            _check_size(local)
            blob.path = local
        else:
            raise FileNotFoundError(
                f"features.pkl references spilled raw rows at {blob.path}, "
                f"and no {os.path.basename(blob.path)} exists in this day "
                f"directory ({ctx.day_dir}) either — the spill file was "
                "deleted or the day dir moved without it; re-run the pre "
                "stage (--stages pre --force)"
            )

def _score_day(ctx: RunContext, features, model, prep,
               feat_src: str, model_src: str) -> dict:
    sc = ctx.config.scoring
    from ..scoring import DispatchStats
    from ..sources import get as get_source

    score_fn = get_source(ctx.dsource).score_csv
    # engine="device" runs the fused on-chip filter pipeline
    # (scoring/pipeline.py), data-parallel over the run's mesh when one
    # is active — the same mesh the LDA stage trained on.  The default
    # host engine keeps the golden float64 CSV bytes.
    from ..plans import resolve
    from ..scoring.score import _score_engine

    device = _score_engine(sc.engine) == "device"
    chunk = sc.device_chunk
    plans_rec = None
    if device:
        # Resolve only on the engine that USES the knob: a host run's
        # record must not attribute a device chunk it never dispatched.
        chunk, chunk_src = resolve("score_device_chunk", sc.device_chunk)
        chunk = int(chunk)
        plans_rec = {
            "score_device_chunk": {"value": chunk, "source": chunk_src}
        }
    stats = DispatchStats() if device else None
    warm = None
    if device and ctx.mesh is None:
        # AOT-compile the plan's entry points before the chunk loop so
        # the persistent compilation cache holds them (and the first
        # dispatch doesn't stall on a trace); counters distinguish
        # cache hits from fresh traces.  Warm at the EFFECTIVE chunk —
        # the pipeline shrinks it for days smaller than the plan's
        # chunk, and a warmup at the unshrunk shape would compile a
        # program the day never dispatches.
        from ..plans.warmup import warmup_scoring
        from ..scoring.pipeline import _effective_chunk

        try:
            warm = warmup_scoring(
                model.theta.shape[0], model.p.shape[0],
                model.num_topics,
                _effective_chunk(features.num_raw_events, chunk, None),
                dsource=ctx.dsource,
            )
        except Exception as e:  # warmup must never fail the stage
            warm = {"error": repr(e)[:200]}
    blob, scores = score_fn(
        features, model, sc.threshold,
        engine=sc.engine, chunk=chunk, mesh=ctx.mesh,
        stats=stats, prep=prep,
    )
    res_path = ctx.path(ctx.results_name())
    if ctx.plane is not None:
        # The results CSV is a PRODUCT, not a checkpoint: its write is
        # demoted to a background sink (overlapping the run's drain /
        # metrics tail) but never skipped by --no-checkpoints.
        from ..dataplane import atomic_write_bytes, clear_stale

        clear_stale(res_path)
        ctx.plane.output(
            "results_csv",
            lambda: atomic_write_bytes(res_path, blob),
            stage=Stage.SCORE.value,
        )
    else:
        with open(res_path, "wb") as f:
            f.write(blob)
    out = {
        "scored_events": features.num_raw_events,
        "flagged": int(len(scores)),
        "min_score": float(scores[0]) if len(scores) else None,
        "features": feat_src,
        "model": model_src,
        "prep": "overlapped" if prep is not None else "inline",
    }
    if plans_rec is not None:
        out["plans"] = plans_rec
    if warm is not None:
        out["warmup"] = warm
    if stats is not None:
        out["score_dispatch"] = stats.as_record()
        if ctx.journal is not None:
            ctx.journal.dispatch_stats(stats.as_record(), stage="score")
    return out


_STAGE_FNS = {
    Stage.PRE: stage_pre,
    Stage.CORPUS: stage_corpus,
    Stage.LDA: stage_lda,
    Stage.SCORE: stage_score,
}


def publish_day(day_dir: str, dest: str) -> dict:
    """Deliver the completed day directory to the operational-analytics
    consumer — the reference's final `scp -r ${LPATH} ${UINODE}:${RPATH}`
    (ml_ops.sh:118-121).  `dest` is either a local/NFS directory (copied
    with shutil) or an scp-style `host:path` remote."""
    name = os.path.basename(os.path.normpath(day_dir))
    if ":" in dest.split(os.sep, 1)[0]:
        import subprocess

        proc = subprocess.run(
            ["scp", "-r", day_dir, dest], capture_output=True, text=True
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"publish to {dest} failed (rc={proc.returncode}): "
                f"{proc.stderr.strip()[-500:]}"
            )
        return {"published": f"{dest}/{name}", "transport": "scp"}
    import shutil

    target = os.path.join(dest, name)
    shutil.copytree(day_dir, target, dirs_exist_ok=True)
    return {"published": target, "transport": "copy"}


# ---------------------------------------------------------------------------
# Entry
# ---------------------------------------------------------------------------


def run_pipeline(
    config: PipelineConfig,
    fdate: str,
    dsource: str,
    force: bool = False,
    stages: list[Stage] | None = None,
    mesh=None,
    vocab_sharded: bool = False,
    online: bool = False,
    publish: str | None = None,
    eval_quality: bool = False,
    eval_holdout: float = 0.0,
) -> list[dict]:
    """Run (or resume) the pipeline for one day.  Completed stages are
    skipped unless `force`; `stages` restricts to a subset (they still run
    in pipeline order)."""
    from ..sources import names as source_names

    if dsource not in source_names():
        raise ValueError(
            f"dsource must be one of {'|'.join(source_names())}, "
            f"got {dsource!r}"
        )
    if online and eval_holdout:
        raise ValueError("--eval-holdout is batch-mode only")
    if eval_quality and eval_holdout:
        # Combining them would score the FULL corpus under a beta
        # trained on the remainder — a third metric that matches
        # neither flag's documented semantics and silently breaks
        # --eval-quality's day-over-day comparability.
        raise ValueError(
            "--eval-quality and --eval-holdout are mutually exclusive: "
            "use --eval-quality for drift monitoring (full-day training "
            "and scoring) or --eval-holdout for a true held-out score"
        )
    dp = config.dataplane
    if not dp.checkpoints:
        # Checkpoints-off is the pure-streaming mode: nothing but the
        # product artifacts is written, so there is no file contract to
        # resume against.  Restrict it to the configurations where that
        # is coherent — a full in-process batch chain.
        if not dp.enabled:
            raise ValueError(
                "--no-checkpoints requires the streaming dataplane "
                "(drop --no-dataplane)"
            )
        if stages is not None:
            raise ValueError(
                "--no-checkpoints cannot run a --stages subset: without "
                "the file contract there is nothing for a partial run "
                "to read or resume from"
            )
        if online or eval_holdout:
            raise ValueError(
                "--no-checkpoints supports the plain batch pipeline "
                "only (the online/holdout trainers own their file "
                "contracts)"
            )
    day_dir = formats.ensure_dir(config.day_dir(fdate))
    ctx = RunContext(
        config=config,
        fdate=fdate,
        dsource=dsource,
        day_dir=day_dir,
        mesh=mesh,
        vocab_sharded=vocab_sharded,
        online=online,
        eval_quality=eval_quality,
        eval_holdout=eval_holdout,
    )
    import jax

    # Measured-plans layer (oni_ml_tpu/plans): wire the persistent
    # compilation cache BEFORE the first trace so every compiled
    # program serializes to disk (a re-run deserializes instead of
    # re-tracing — the counters below prove it per run), then pin the
    # run's plan store so every consumer resolves tuned knobs against
    # the same cache.
    plc = config.plans
    from ..plans import NullStore, PlanStore, counters_snapshot, use_store
    from ..plans import warmup as _plans_warmup

    cc_rec = _plans_warmup.setup_compilation_cache(
        enabled=plc.compilation_cache,
        cache_dir=plc.compilation_cache_dir,
    )
    if not plc.enabled:
        plan_store: "PlanStore | NullStore | None" = NullStore()
    elif plc.cache_path:
        plan_store = PlanStore(plc.cache_path)
    else:
        plan_store = None        # the default store (seeds + user cache)
    plans_cc0 = _plans_warmup.compile_counts()
    plans_ctr0 = counters_snapshot()
    from ..telemetry import roofline as _rl0

    roofline0 = _rl0.emit_count()   # scope the rollup to THIS run

    # Multi-host contract (--multihost): every rank runs run_pipeline
    # against a SHARED day dir.  Host-only stages (pre/corpus/score) and
    # all file writes execute on the coordinator alone; stage_lda runs
    # on every rank — each trains its document shards HOST-LOCALLY and
    # the sufficient statistics cross processes through the explicit
    # allreduce (parallel/allreduce.py), never a global mesh spanning
    # processes.  Stage skip/run decisions broadcast from the
    # coordinator (KV store) so ranks cannot desync on filesystem
    # state.
    multiproc = jax.process_count() > 1
    is_coord = jax.process_index() == 0
    if multiproc and mesh is not None:
        from ..parallel.mesh import is_local_mesh

        if not is_local_mesh(mesh):
            raise ValueError(
                "multi-process runs take a HOST-LOCAL mesh only "
                "(parallel.local_mesh(); --mesh under --multihost is "
                "interpreted per host): distributed EM shards documents "
                "across processes and allreduces the suff-stats "
                "explicitly instead of building one global SPMD program"
            )
    wanted = stages or STAGE_ORDER
    ctx.wanted = list(wanted)
    if not dp.checkpoints and multiproc:
        # Multi-host ranks coordinate through the shared file contract
        # (the plane is single-process only) — a pure-streaming run is
        # impossible there, and silently writing the full contract
        # would contradict what the operator asked for.
        raise ValueError(
            "--no-checkpoints requires a single-process run: multi-host "
            "ranks coordinate through the inter-stage file contract"
        )

    # Telemetry flight recorder (docs/observability.md).  Coordinator
    # only: the shared day dir has exactly one journal writer, like
    # metrics.json.  The existing journal is replayed FIRST (tolerating
    # a killed run's truncated tail) so `--stages` resume can pick up
    # from it; then this run appends behind a run_start marker.
    tel = config.telemetry
    hb = None
    from ..telemetry.spans import use_recorder

    if tel.journal and is_coord:
        from ..telemetry import (
            HeartbeatMonitor,
            Journal,
            Recorder,
            RunJournal,
        )

        jpath = ctx.path("run_journal.jsonl")
        replayed = Journal.replay(jpath)
        prior_done = RunJournal.completed_stages(replayed)
        # Provenance for fail-fast messages: a prior --no-checkpoints
        # run explains a day dir with a journal but no file contract.
        ctx.prior_no_checkpoints = any(
            r.get("kind") == "run_start"
            and r.get("checkpoints") is False
            for r in replayed
        )
        ctx.journal = RunJournal(
            Journal(jpath, fsync_every=tel.journal_fsync_every)
        )
        ctx.journal_done = set() if force else prior_done
        ctx.journal.run_start(
            force=force, fdate=fdate, dsource=dsource,
            stages=[Stage(s).value for s in wanted],
            replayed_records=len(replayed),
            journal_done=sorted(prior_done),
            checkpoints=dp.checkpoints,
        )
        ctx.recorder = Recorder(journal=ctx.journal.journal)
        if tel.heartbeat_s > 0:
            hb = HeartbeatMonitor(
                interval_s=tel.heartbeat_s,
                timeout_s=tel.heartbeat_timeout_s,
                max_misses=tel.heartbeat_max_misses,
                journal=ctx.journal,
                # Probe round trips feed the run's shared registry
                # (heartbeat.probe_latency_s histogram): degradation is
                # on the metrics plane before BackendLost ever fires.
                recorder=ctx.recorder,
            ).start()
            ctx.heartbeat = hb

    # Streaming dataplane (oni_ml_tpu/dataplane): single-process runs
    # only — multi-host ranks coordinate through the shared file
    # contract, exactly as before.  The plane owns the run's background
    # checkpoint sinks, overlap tasks, and bounded channels; it is
    # drained (joined, errors surfaced) in the finally below, the
    # generalization of the old word_counts writer join.
    plane_record = None
    if dp.enabled and not multiproc:
        from ..dataplane import Dataplane

        ctx.plane = Dataplane(
            dp,
            recorder=ctx.recorder,
            journal=ctx.journal.journal if ctx.journal is not None
            else None,
        )

    run_ok = False
    run_err: "BaseException | None" = None
    try:
        with (use_recorder(ctx.recorder) if ctx.recorder is not None
              else contextlib.nullcontext()), \
             (use_store(plan_store) if plan_store is not None
              else contextlib.nullcontext()):
            _run_stages(ctx, wanted, force, multiproc, is_coord)
        run_ok = True
    except BaseException as e:
        run_err = e
        raise
    finally:
        # The background word_counts.dat writer (stage_pre) must finish
        # before this process hands the day dir to anyone — it is the
        # resume/audit contract.  Joined even on a failing run so a
        # crashed LDA stage can't leave a half-written contract file
        # racing the interpreter exit.
        th = ctx.wc_writer
        if th is not None:
            th.join()
            ctx.wc_writer = None
        if ctx.plane is not None:
            # Drain the dataplane: join every background checkpoint
            # sink and overlap task (demoted writes are part of the
            # run's contract — the day dir must be complete before
            # this process hands it to anyone), collect their errors,
            # and keep the per-task/per-edge accounting for the
            # metrics record below.
            plane_record = ctx.plane.drain()
            ctx.background_errs.extend(ctx.plane.errors)
        if ctx.wc_writer_err:
            ctx.background_errs.extend(
                ("word_counts", e) for e in ctx.wc_writer_err
            )
        if hb is not None:
            hb.stop()
        if ctx.journal is not None:
            # A failed background checkpoint write fails the RUN (the
            # RuntimeError below) — the journal's run_end must not
            # record ok=True for an invocation whose caller saw an
            # exception and whose contract file is missing.
            err = run_err if run_err is not None else (
                ctx.background_errs[0][1] if ctx.background_errs else None
            )
            ctx.journal.run_end(
                ok=run_ok and not ctx.background_errs,
                **({} if err is None else {"error": repr(err)[:300]}),
            )
            ctx.journal.close()
        if plc.cache_path and plan_store is not None:
            # Run-scoped store (--plan-cache): close its journal fd on
            # every exit path; the process-wide default store stays
            # open.
            plan_store.close()
    if ctx.background_errs:
        name, first = ctx.background_errs[0]
        raise RuntimeError(
            f"dataplane background write/task {name!r} failed"
        ) from first
    if is_coord:
        # The run's plans/compile accounting: how many XLA compile
        # requests the persistent cache served (a fully warmed re-run
        # shows traces == 0) and how many autotune sweeps actually ran
        # (a tuned backend shows 0) — the acceptance counters, in
        # metrics.json where tests can assert them.
        cc_end = dict(cc_rec)
        if cc_rec.get("enabled"):
            cc_end["entries_end"] = _plans_warmup.cache_entries(
                cc_rec["dir"]
            )
        ctr = counters_snapshot()
        ctx.emit({
            "stage": "plans",
            "enabled": plc.enabled,
            "store": getattr(
                plan_store, "path", None
            ) if plan_store is not None else "default",
            "compilation_cache": cc_end,
            **_plans_warmup.counts_delta(plans_cc0),
            **{k: ctr[k] - plans_ctr0.get(k, 0) for k in ctr},
        })
        # Roofline rollup (telemetry/roofline.py): every per-phase
        # record the stages emitted into the journal, surfaced in
        # metrics.json too, so "how far from the hardware was this
        # run, per phase?" is greppable without replaying the journal.
        from ..telemetry import roofline as _roofline

        rl_records = _roofline.emitted_records(since=roofline0)
        if rl_records:
            ctx.emit({"stage": "roofline", "records": rl_records})
        if plane_record is not None and (
            plane_record["tasks"] or plane_record["edges"]
        ):
            # Dataplane accounting: per-task walls with stage
            # attribution (the work the overlap hid) and per-edge
            # queue/stall totals — what bench.py's pipeline_e2e
            # critical-path breakdown and trace_view's stall table
            # consume.
            ctx.emit({"stage": "dataplane", **plane_record})

    def _dump_metrics() -> None:
        with open(ctx.path("metrics.json"), "w") as f:
            json.dump(ctx.metrics, f, indent=1)

    # metrics.json lands BEFORE publish so the delivered day dir carries
    # the run's metrics — and so a failed delivery cannot lose them.
    if is_coord:
        _dump_metrics()
    if publish and is_coord:
        t0 = time.perf_counter()
        info = publish_day(day_dir, publish)
        ctx.emit(
            {"stage": "publish",
             "wall_s": round(time.perf_counter() - t0, 3), **info}
        )
        _dump_metrics()  # refresh the local copy with the publish record
    return ctx.metrics


def _release_handoffs(ctx: RunContext, stage: Stage) -> None:
    """Drop hand-offs whose consumer (this stage) will not run.  The
    featurizer container has TWO consumers on the dataplane — corpus
    assembly and scoring — so it survives a skipped corpus stage when
    the score stage is still coming; a serial run keeps the legacy
    release-before-LDA's-peak behavior (scoring re-reads
    features.pkl)."""
    if stage is Stage.CORPUS:
        if ctx.plane is None or not _score_wanted(ctx):
            ctx.features = None
    elif stage is Stage.LDA:
        ctx.corpus_handoff = None
    elif stage is Stage.SCORE:
        ctx.features = None
        ctx.model_handoff = None


def _run_stages(ctx: RunContext, wanted, force: bool, multiproc: bool,
                is_coord: bool) -> None:
    for stage in STAGE_ORDER:
        if stage not in wanted:
            _release_handoffs(ctx, stage)
            continue
        done = (
            _stage_done(ctx, stage) if (is_coord or not multiproc) else None
        )
        skip = bool(done) and not force
        if multiproc:
            skip = _coord_decision(skip)
        if skip:
            _release_handoffs(ctx, stage)
            if is_coord:
                record = {"stage": stage.value, "skipped": done}
                if ctx.journal is not None:
                    ctx.journal.stage_skipped(stage.value, done)
                if stage is Stage.LDA and ctx.eval_quality:
                    # The eval only needs the saved model; a resumed run
                    # still gets its day-quality number.
                    other = formats.read_other(ctx.path("final.other"))
                    log_beta = formats.read_beta(ctx.path("final.beta"))
                    record.update(
                        _completion_score(ctx, log_beta, other["alpha"])
                    )
                ctx.emit(record)
            continue
        err: Exception | None = None
        if is_coord or stage is Stage.LDA:
            try:
                _run_stage(ctx, stage, lambda s=stage: _STAGE_FNS[s](ctx))
            except Exception as e:  # relayed to the other ranks below
                err = e
        if multiproc:
            if err is not None:
                # Structured failure relay (parallel/allreduce.py): the
                # failure key unblocks peers stuck INSIDE the stage's
                # suff-stats allreduce (their waits poll it between
                # slices) as well as peers already at the outcome
                # barrier below — they raise PeerFailure ("failed on
                # another rank"), a BackendLost subclass, so ml_ops
                # exits rc=3 with the structured payload instead of a
                # raw traceback.
                from ..parallel.allreduce import get_collective

                get_collective().fail(f"stage {stage.value}: {err!r}")
            # Outcome barrier: a stage failure on ANY rank must fail
            # every rank — otherwise the survivors block forever in the
            # next decision broadcast.  A rank that dies WITHOUT posting
            # (SIGKILL) surfaces on its peers as a bounded PeerFailure
            # timeout in the collective wait (covered by
            # tests/test_multihost.py's failure-injection tests).
            try:
                ok = _all_ranks_ok(err is None)
            except Exception as barrier_err:
                # The barrier collective itself can fail when another
                # rank is inside a different collective or already died;
                # the local stage error (if any) is the root cause and
                # must not be masked by it.
                if err is not None:
                    raise err from barrier_err
                raise
            if not ok and err is None:
                from ..parallel.allreduce import PeerFailure

                raise PeerFailure(
                    f"stage {stage.value} failed on another rank; "
                    "aborting this rank"
                )
        if err is not None:
            raise err


def _build_config(args: argparse.Namespace) -> PipelineConfig:
    env = os.environ
    return PipelineConfig(
        data_dir=args.data_dir or env.get("LPATH", "."),
        flow_path=args.flow_path or env.get("FLOW_PATH", ""),
        dns_path=args.dns_path or env.get("DNS_PATH", ""),
        proxy_path=args.proxy_path or env.get("PROXY_PATH", ""),
        top_domains_path=args.top_domains or "",
        qtiles_path=args.qtiles or "",
        pre_workers=args.pre_workers,
        lda=LDAConfig(
            num_topics=args.topics,
            alpha_init=args.alpha,
            em_max_iters=args.em_max_iters,
            batch_size=args.batch_size,
            seed=args.seed,
            checkpoint_every=args.checkpoint_every,
            warm_start_gamma=args.warm_start,
            dense_precision=args.dense_precision,
            em_shards=args.em_shards,
        ),
        online_lda=OnlineLDAConfig(
            num_topics=args.topics,
            alpha=args.alpha,
            eta=args.eta,
            tau0=args.tau0,
            kappa=args.kappa,
            batch_size=args.batch_size,
            seed=args.seed,
            checkpoint_every=args.checkpoint_every,
        ),
        feedback=FeedbackConfig(
            dup_factor=(
                args.dup_factor
                if args.dup_factor is not None
                else int(env.get("DUPFACTOR", 1000))
            )
        ),
        scoring=ScoringConfig(threshold=args.tol),
        telemetry=TelemetryConfig(
            journal=not args.no_journal,
            heartbeat_s=args.heartbeat,
        ),
        plans=PlansConfig(
            enabled=not args.no_plans,
            cache_path=args.plan_cache or "",
            compilation_cache=not args.no_compilation_cache,
        ),
        dataplane=DataplaneConfig(
            enabled=not args.no_dataplane,
            checkpoints=not args.no_checkpoints,
        ),
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ml_ops",
        description="oni_ml_tpu suspicious-connects pipeline "
        "(replaces ml_ops.sh YYYYMMDD {flow|dns} [TOL]); "
        "`ml_ops serve --help` for the streaming scoring service, "
        "`ml_ops continuous --help` for windowed streaming ingestion "
        "with warm-start EM and drift-gated publishes "
        "(--stream/--replicated composes the multi-tenant standing "
        "service over the replica fleet)",
    )
    from ..sources import names as source_names

    p.add_argument("fdate", help="day to analyze, YYYYMMDD")
    p.add_argument("dsource", choices=list(source_names()))
    p.add_argument(
        "tol", nargs="?", type=float,
        default=float(os.environ.get("TOL", 1.1)),
        help="suspicion threshold (ml_ops.sh:17-18 defaults TOL=1.1)",
    )
    p.add_argument("--data-dir", default=None, help="working dir (LPATH)")
    p.add_argument(
        "--flow-path", default=None,
        help="netflow CSV input: file, directory, glob, or "
        "comma-separated list — multiple files ingest as one corpus "
        "with joint quantile cuts (the reference's HDFS FLOW_PATH "
        "location; config 3's 30-day corpus)",
    )
    p.add_argument(
        "--dns-path", default=None,
        help="DNS input: CSV/parquet file, directory, glob, or "
        "comma-separated list (the reference's comma-separated Hive "
        "parquet paths, dns_pre_lda.scala:142)",
    )
    p.add_argument(
        "--proxy-path", default=None,
        help="proxy/HTTP log CSV input: file, directory, glob, or "
        "comma-separated list (sources/generic.ProxySource columns)",
    )
    p.add_argument("--top-domains", default=None, help="top-1m.csv path")
    p.add_argument(
        "--qtiles", default=None,
        help="precomputed flow quantile cuts file (flow_qtiles format); "
        "skips the in-run ECDF pass and pins word identity across days",
    )
    p.add_argument(
        "--pre-workers", type=int, default=0, metavar="N",
        help="pre-stage shard workers: day files split into line-aligned "
        "byte ranges featurized concurrently, with a deterministic "
        "first-seen merge keeping every output byte-identical to the "
        "sequential pass (0 = auto from host cores, 1 = legacy "
        "single-pass)",
    )
    p.add_argument("--topics", type=int, default=20)
    p.add_argument("--alpha", type=float, default=2.5)
    p.add_argument("--em-max-iters", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=1024)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--checkpoint-every", type=int, default=0,
        help="persist (beta, alpha, iter) every N EM iterations; an "
        "interrupted lda stage resumes from the checkpoint (0=off)",
    )
    p.add_argument(
        "--dup-factor", type=int, default=None,
        help="feedback duplication (default: DUPFACTOR env or 1000)",
    )
    p.add_argument(
        "--eval-quality", action="store_true",
        help="score the day's model by document completion "
        "(per-token log-likelihood / perplexity on each doc's "
        "odd token slots; models/evaluate.py) and record it in the "
        "lda stage metrics — a drift-monitoring number comparable "
        "across days, optimistic vs a true held-out split",
    )
    p.add_argument(
        "--eval-holdout", type=float, default=0.0, metavar="FRAC",
        help="hash-split FRAC of documents out BEFORE training, train "
        "beta on the remainder, and record the true held-out per-token "
        "log-likelihood of the excluded split in the lda stage metrics "
        "— valid for hyperparameter selection, unlike --eval-quality's "
        "training-set completion score.  doc_results.csv still covers "
        "every document (held-out docs get their theta inferred under "
        "the trained beta).  Batch mode only; mutually exclusive with "
        "--eval-quality",
    )
    p.add_argument(
        "--warm-start", action=argparse.BooleanOptionalAction, default=True,
        help="seed each EM iteration's variational fixed point from the "
        "previous gamma (same optimum, fewer inner iterations; default "
        "on — use --no-warm-start for the reference's fresh-start "
        "likelihood.dat semantics, whose mid-run values differ in late "
        "decimals)",
    )
    p.add_argument(
        "--dense-precision", choices=["f32", "bf16"], default="f32",
        help="dense E-step matmul operand storage; bf16 is bit-identical "
        "under XLA's DEFAULT matmul precision on current TPUs (measured "
        "on v5e; that default already truncates MXU inputs — refused if "
        "a jax.default_matmul_precision override is active) and ~10%% "
        "faster",
    )
    p.add_argument(
        "--online", action="store_true",
        help="streaming (stochastic variational) LDA instead of batch EM",
    )
    p.add_argument("--eta", type=float, default=0.01,
                   help="online: topic-word Dirichlet prior")
    p.add_argument("--tau0", type=float, default=64.0,
                   help="online: learning-rate delay")
    p.add_argument("--kappa", type=float, default=0.7,
                   help="online: learning-rate decay exponent")
    p.add_argument("--force", action="store_true", help="re-run all stages")
    p.add_argument(
        "--stages", default=None,
        help="comma-separated subset of pre,corpus,lda,score",
    )
    p.add_argument(
        "--mesh", default=None, metavar="DATA,MODEL",
        help="device mesh shape; MODEL>1 shards the vocabulary",
    )
    p.add_argument(
        "--publish", default=None, metavar="DEST",
        help="after all stages complete, deliver the day directory to "
        "DEST: a local/NFS path (copied) or an scp-style host:path — "
        "the reference's final scp to the UI node (ml_ops.sh:118-121)",
    )
    p.add_argument(
        "--multihost", action="store_true",
        help="initialize jax.distributed (one controller process per host; "
        "coordinator/process env via JAX_COORDINATOR_ADDRESS etc.) for "
        "pod-scale distributed EM — the reference's mpiexec -f "
        "machinefile fan-out (ml_ops.sh:80), minus MPI: each rank trains "
        "a deterministic contiguous document shard on ITS OWN devices "
        "(--mesh is per host: parallel.local_mesh) and the beta/alpha "
        "sufficient statistics cross processes through an explicit "
        "allreduce (psum over ICI on real pods, a coordination-service "
        "KV ring on CPU clusters).  Requires --data-dir on a filesystem "
        "shared by all hosts: the coordinator is the only writer; other "
        "ranks read the shared stage outputs and join the reduce",
    )
    p.add_argument(
        "--em-shards", type=int, default=0, metavar="N",
        help="distributed-EM document shard count (0 = auto: 8, grown "
        "to cover the process count).  The shard plan — and the "
        "suff-stats reduction tree — derives from the corpus and N, "
        "not the rank count, so runs at different rank counts with the "
        "same N produce byte-identical coordinator artifacts "
        "(ONI_ML_TPU_EM_SHARDS overrides)",
    )
    p.add_argument(
        "--no-journal", action="store_true",
        help="disable the crash-safe run journal "
        "(run_journal.jsonl in the day dir: stage spans, EM likelihood "
        "points, scoring dispatch stats — the resume/post-mortem "
        "contract; docs/observability.md)",
    )
    p.add_argument(
        "--heartbeat", type=float, default=0.0, metavar="SECS",
        help="probe device liveness every SECS seconds on a background "
        "thread (tiny jitted add + transfer, journaled); a backend that "
        "stops answering becomes a clean BackendLost failure at the "
        "next stage boundary instead of a silent hang (0 = off)",
    )
    p.add_argument(
        "--no-plans", action="store_true",
        help="disable measured-plan lookups (oni_ml_tpu/plans): every "
        "tuned knob falls back to config/default exactly as before the "
        "plan cache existed; nothing is read from or written to the "
        "cache",
    )
    p.add_argument(
        "--plan-cache", default=None, metavar="PATH",
        help="plan-cache JSONL file for this run (default: "
        "ONI_ML_TPU_PLAN_CACHE env, else ~/.cache/oni_ml_tpu/"
        "plans.jsonl; checked-in seed plans always load underneath)",
    )
    p.add_argument(
        "--no-compilation-cache", action="store_true",
        help="do not wire jax_compilation_cache_dir (by default every "
        "compiled program persists to ~/.cache/oni_ml_tpu/jax_cache — "
        "or JAX_COMPILATION_CACHE_DIR — so a re-run re-traces nothing; "
        "the run's metrics record compile requests vs cache hits)",
    )
    p.add_argument(
        "--no-dataplane", action="store_true",
        help="disable the streaming dataplane (oni_ml_tpu/dataplane): "
        "run the serial file-contract pipeline — every stage writes "
        "its artifacts inline and the next stage reads them back from "
        "disk.  Artifacts are byte-identical either way; the dataplane "
        "only changes when files land and what stages read",
    )
    p.add_argument(
        "--no-checkpoints", action="store_true",
        help="skip the demoted inter-stage checkpoint files entirely "
        "(features.pkl, word_counts.dat, words/doc/model.dat, final.*, "
        "likelihood.dat, doc/word_results.csv): the run streams "
        "everything in memory and writes only its products (results "
        "CSV, metrics.json, run_journal.jsonl).  A later --stages "
        "resume against such a day is refused — there is no file "
        "contract to resume from.  Full-chain batch runs only",
    )
    p.add_argument(
        "--profile", default=None, metavar="DIR",
        help="capture a jax.profiler trace of the whole run into DIR "
        "(view with TensorBoard); replaces the reference's bash `time` "
        "stage timing (SURVEY §5.1)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    import sys

    if argv is None:
        argv = sys.argv[1:]
    # `ml_ops serve ...` is the streaming scoring service (runner/serve.py)
    # — a long-running process over a COMPLETED day's artifacts, not a
    # fifth batch stage, so it routes before the YYYYMMDD parser.
    if argv and argv[0] == "serve":
        from . import serve

        return serve.main(argv[1:])
    # `ml_ops continuous ...` is the windowed streaming-ingestion mode
    # (runner/continuous.py): a standing train-and-serve loop — ring-
    # buffered corpus window, warm-start EM refreshes, drift-gated
    # fleet publishes — rather than a per-day batch run, so it routes
    # before the YYYYMMDD parser like serve.  With `--stream ...
    # --replicated N` it is the COMPOSED standing service: N tenants
    # share one train/serve co-scheduler (preemptible refresh chunks)
    # and publish through the replicated router fleet.
    if argv and argv[0] == "continuous":
        from . import continuous

        return continuous.main(argv[1:])
    # `ml_ops replica ...` / `ml_ops route ...` are the replicated
    # elastic serving fleet (runner/route.py): N serve replica
    # processes behind an async router with consistent-hash tenant
    # placement and shadow-promotion failover — long-running services,
    # so they route before the YYYYMMDD parser like serve.
    if argv and argv[0] == "replica":
        from .route import replica_main

        return replica_main(argv[1:])
    if argv and argv[0] == "route":
        from .route import route_main

        return route_main(argv[1:])
    # `ml_ops lint ...` is the static-analysis gate (oni_ml_tpu/analysis)
    # — same engine as tools/graftlint.py and the oni-graftlint console
    # script; routes before the YYYYMMDD parser like serve.
    if argv and argv[0] == "lint":
        from ..analysis.cli import main as lint_main

        return lint_main(argv[1:])
    p = build_parser()
    args = p.parse_args(argv)
    if len(args.fdate) != 8 or not args.fdate.isdigit():
        p.error("fdate must be YYYYMMDD (ml_ops.sh:8-20)")

    if args.multihost:
        from ..parallel import initialize_distributed

        # TPU pods / SLURM auto-detect through jax's cluster plugins;
        # plain CPU clusters (this jax version has no env-var cluster
        # plugin) bootstrap from the documented explicit env vars.
        env = os.environ
        initialize_distributed(
            env.get("JAX_COORDINATOR_ADDRESS") or None,
            int(env["JAX_NUM_PROCESSES"])
            if env.get("JAX_NUM_PROCESSES") else None,
            int(env["JAX_PROCESS_ID"])
            if env.get("JAX_PROCESS_ID") else None,
        )

    mesh = None
    vocab_sharded = False
    if args.mesh:
        from ..parallel.mesh import local_mesh, mesh_from_spec

        try:
            if args.multihost:
                # Per-host mesh: distributed EM is host-local; the
                # cross-process reduce is the explicit allreduce, so
                # the spec applies to THIS process's devices.
                parts = args.mesh.split(",")
                if len(parts) != 2:
                    raise ValueError(
                        f"mesh spec must be 'DATA,MODEL', got "
                        f"{args.mesh!r}"
                    )
                mesh = local_mesh(int(parts[0]), int(parts[1]))
                vocab_sharded = int(parts[1]) > 1
            else:
                mesh, vocab_sharded = mesh_from_spec(args.mesh)
        except ValueError as e:
            p.error(str(e))
    stages = (
        [Stage(s) for s in args.stages.split(",")] if args.stages else None
    )

    import contextlib

    profile_ctx = contextlib.nullcontext()
    if args.profile:
        import jax

        profile_ctx = jax.profiler.trace(
            args.profile, create_perfetto_trace=True
        )
    from ..telemetry import BackendLost

    try:
        with profile_ctx:
            run_pipeline(
                _build_config(args),
                args.fdate,
                args.dsource,
                force=args.force,
                stages=stages,
                mesh=mesh,
                vocab_sharded=vocab_sharded,
                online=args.online,
                publish=args.publish,
                eval_quality=args.eval_quality,
                eval_holdout=args.eval_holdout,
            )
    except BackendLost as e:
        # The heartbeat's whole point: a dead backend exits as a
        # structured, journaled failure, not a hang or a bare
        # traceback.  The journal already carries the backend_lost
        # record and every completed stage.
        print(
            json.dumps({
                "fdate": args.fdate, "dsource": args.dsource,
                "error": "backend_lost", "detail": str(e),
            }),
            flush=True,
        )
        return 3
    except MissingArtifactError as e:
        # A --stages resume against a missing upstream checkpoint:
        # structured fail-fast naming the artifact and the regenerating
        # flag, not a loader stack trace.
        print(
            json.dumps({
                "fdate": args.fdate, "dsource": args.dsource,
                "error": "missing_artifact", "detail": str(e),
            }),
            flush=True,
        )
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
