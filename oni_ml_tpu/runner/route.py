"""`ml_ops replica` / `ml_ops route` — the replicated-serving CLI.

``ml_ops replica --id r0`` runs ONE serve replica process
(serving/replica.py): the full FleetRegistry/FleetScorer stack behind
the framed socket protocol, heartbeating into the shared file-KV
membership directory.  ``ml_ops route`` runs the router in front
(serving/router.py): it spawns (``--replicas N``) or attaches to
(``--connect``) the replicas, places every manifest tenant on a
primary + shadow via the consistent-hash ring, and then speaks the
fleet serve-stream protocol on stdin/stdout — ``<tenant>\\t<csv line>``
in, flagged events out — exactly like ``ml_ops serve --fleet``, except
the scoring happens N processes away and a dead replica costs a
shadow promotion instead of the fleet.

Zero-downtime redeploy from the CLI: ``--redeploy-after N`` performs a
rolling drain-one-join-one cycle over every replica after N events —
the acceptance path for ROADMAP item 5's "drain-one-replica-at-a-time
behind the router".

``--dry-run synthetic:TxR`` is the self-contained acceptance run
(in-process replicas, synthetic tenant days): packed scoring parity,
a mid-stream replica KILL with zero dropped events, and a rolling
redeploy, reported as one JSON summary with rc 0/1.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque

import numpy as np


def build_replica_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ml_ops replica",
        description="Run one serve replica of the replicated fleet.",
    )
    p.add_argument("--id", required=True, help="replica id (becomes "
                   "the membership/journal key)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="listen port (0 = ephemeral)")
    p.add_argument("--kv-dir", default="",
                   help="shared file-KV membership directory "
                   "(parallel/membership.FileKVClient); empty = no "
                   "membership/heartbeats")
    p.add_argument("--kv-connect", default="", metavar="HOST:PORT",
                   help="TCP KV server to register membership with "
                   "(parallel/membership.TcpKVClient) — the cross-host "
                   "alternative to --kv-dir")
    p.add_argument("--port-file", default="",
                   help="write 'host port' here once listening (the "
                   "spawn handshake)")
    p.add_argument("--fleet-max-batch", type=int, default=None)
    p.add_argument("--fleet-max-wait-ms", type=float, default=None)
    p.add_argument("--device-score-min", default=None,
                   help="int threshold, 'none' to pin host scoring, "
                   "or unset for the measured auto calibration")
    return p


def _make_kv(kv_dir: str, kv_connect: str):
    """Membership transport off the CLI flags: a TCP KV client
    (cross-host), the shared file-KV directory (same-host), or None
    (no membership)."""
    if kv_connect:
        from ..parallel.membership import TcpKVClient

        host, _, port = kv_connect.partition(":")
        return TcpKVClient(host or "127.0.0.1", int(port))
    if kv_dir:
        from ..parallel.membership import FileKVClient

        return FileKVClient(kv_dir)
    return None


def _parse_device_score_min(v):
    if v is None:
        return 0
    if isinstance(v, str) and v.lower() in ("none", "host"):
        return None
    return int(v)


def replica_main(argv: "list[str] | None" = None) -> int:
    import dataclasses

    from ..config import ServingConfig
    from ..serving import ReplicaServer

    args = build_replica_parser().parse_args(argv)
    cfg = ServingConfig(
        device_score_min=_parse_device_score_min(args.device_score_min),
    )
    if args.fleet_max_batch is not None:
        cfg = dataclasses.replace(
            cfg, fleet_max_batch=args.fleet_max_batch)
    if args.fleet_max_wait_ms is not None:
        cfg = dataclasses.replace(
            cfg, fleet_max_wait_ms=args.fleet_max_wait_ms)
    kv = _make_kv(args.kv_dir, args.kv_connect)
    # Persistent compilation cache + compile counters BEFORE the first
    # trace: replicas share the cache, so a respawned replica (rolling
    # redeploy) warm-starts its compiled family from disk — the
    # zero-retrace recovery contract — and the stats op's counter
    # deltas are the proof.
    from ..plans import warmup as plans_warmup

    plans_warmup.setup_compilation_cache()
    plans_warmup._ensure_listener()
    server = ReplicaServer(
        args.id, cfg, host=args.host, port=args.port, kv=kv,
    )
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{server.host} {server.port}\n")
        os.replace(tmp, args.port_file)
    print(f"REPLICA_READY {args.id} {server.host} {server.port}",
          flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    # Exit on SIGTERM/SIGINT or a shutdown op over the wire.
    while not stop.is_set() and not server.stopped.wait(0.2):
        pass
    server.stop()
    return 0


# ---------------------------------------------------------------------------
# router CLI
# ---------------------------------------------------------------------------


def build_route_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ml_ops route",
        description="Async fleet router over N serve replicas: "
        "consistent-hash tenant placement, shadow-promotion failover, "
        "rolling redeploy.",
    )
    p.add_argument("--fleet", default="",
                   help="fleet manifest (serving/tenants.py) naming "
                   "the tenants and their day_dirs")
    p.add_argument("--replicas", type=int, default=0, metavar="N",
                   help="spawn N replica subprocesses (ml_ops "
                   "replica) on this host")
    p.add_argument("--connect", default="", metavar="ID=HOST:PORT,...",
                   help="attach to already-running replicas instead "
                   "of spawning")
    p.add_argument("--kv-dir", default="",
                   help="membership directory shared with the "
                   "replicas (default: a temp dir when spawning)")
    p.add_argument("--kv-listen", default="", metavar="[HOST][:PORT]",
                   help="run the TCP KV membership server "
                   "(parallel/membership.KVServer) here and point "
                   "spawned replicas at it — the cross-host control "
                   "plane (empty PORT = ephemeral)")
    p.add_argument("--kv-connect", default="", metavar="HOST:PORT",
                   help="join an existing TCP KV membership server "
                   "(another router's --kv-listen)")
    p.add_argument("--autoscale", action="store_true",
                   help="run the Little's-law autoscaler: spawn/drain "
                   "replicas between autoscale_min_replicas and "
                   "autoscale_max_replicas to hold admission-window "
                   "occupancy inside the hysteresis band")
    p.add_argument("--threshold", type=float, default=None,
                   help="suspicion threshold for flagged output "
                   "(default: ServingConfig)")
    p.add_argument("--top-domains", default=None)
    p.add_argument("--redeploy-after", type=int, default=0,
                   metavar="N",
                   help="after N routed events, rolling-redeploy "
                   "every spawned replica (drain one, respawn, join, "
                   "next)")
    p.add_argument("--dry-run", default="", metavar="synthetic[:TxR]",
                   help="self-contained acceptance run: T synthetic "
                   "tenants over R in-process replicas (default 6x3) "
                   "with a mid-stream kill and a rolling redeploy")
    return p


def _spawn_replica(rid: str, kv_flags: "str | list[str]", workdir: str,
                   extra: "list[str] | None" = None,
                   timeout_s: float = 120.0):
    """One `ml_ops replica` subprocess; returns (proc, host, port)
    after the port-file handshake.  `kv_flags` is either the shared
    file-KV directory (the historical signature) or a ready-made flag
    list (["--kv-connect", "host:port"] for the TCP control plane)."""
    if isinstance(kv_flags, str):
        kv_flags = ["--kv-dir", kv_flags]
    port_file = os.path.join(workdir, f"{rid}.port")
    try:
        os.remove(port_file)
    except FileNotFoundError:
        pass
    cmd = [
        sys.executable, "-m", "oni_ml_tpu.runner.ml_ops", "replica",
        "--id", rid, "--port-file", port_file,
    ] + kv_flags + (extra or [])
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # The child must import THIS checkout's package wherever the
    # router was launched from (the repo is run in place, not
    # installed).
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    # The child's stdout must not interleave with the router's (a
    # bench phase's stdout is a JSON contract); the port file is the
    # readiness handshake, so the log file is purely diagnostic.
    log = open(os.path.join(workdir, f"{rid}.log"), "ab")
    try:
        proc = subprocess.Popen(cmd, env=env, stdout=log,
                                stderr=subprocess.STDOUT)
    finally:
        log.close()
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica {rid} exited rc={proc.returncode} before "
                "listening"
            )
        try:
            with open(port_file) as f:
                host, port = f.read().split()
            return proc, host, int(port)
        except (FileNotFoundError, ValueError):
            time.sleep(0.05)
    proc.kill()
    raise RuntimeError(f"replica {rid} never wrote {port_file}")


class _FlagCollector:
    """FIFO future resolver for the stream front: resolves routed
    futures in submit order and writes flagged events (score under the
    tenant threshold) to stdout in the fleet framing."""

    def __init__(self, thresholds: dict, out) -> None:
        self._thresholds = thresholds
        self._out = out
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._stopped = False
        self.resolved = 0
        self.errors = 0
        self.flagged = 0
        self._thread = threading.Thread(
            target=self._run, name="oni-route-flags", daemon=True)
        self._thread.start()

    def add(self, tenant: str, line: str, future) -> None:
        with self._cond:
            self._queue.append((tenant, line, future))
            self._cond.notify_all()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._cond.wait()
                if not self._queue:
                    return
                tenant, line, fut = self._queue.popleft()
            try:
                score, _ = fut.result(timeout=300.0)
            except Exception:
                with self._cond:
                    self.errors += 1
                continue
            with self._cond:
                self.resolved += 1
                flag = score < self._thresholds.get(tenant, 0.0)
                if flag:
                    self.flagged += 1
            if flag:
                self._out.write(f"{tenant}\t{score:.6e}\t{line}\n")
                self._out.flush()

    def close(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=300.0)


def _rolling_redeploy(router, procs: dict, kv_flags, workdir: str,
                      extra: "list[str]") -> "list[dict]":
    """Drain-one-respawn-one over every spawned replica: the fleet
    keeps serving throughout (the router promotes each drained
    replica's tenants to their warm shadows, then the placement pulls
    them back when the replacement joins under the same id slot)."""
    out = []
    for rid in sorted(procs):
        drained = router.drain_replica(rid)
        proc = procs.pop(rid)
        proc.terminate()
        proc.wait(timeout=60.0)
        new_id = f"{rid}v2"
        proc2, host, port = _spawn_replica(
            new_id, kv_flags, workdir, extra)
        procs[new_id] = proc2
        joined = router.join_replica(new_id, host, port)
        out.append({"drained": drained, "joined": joined})
    return out


def route_stream(args) -> int:
    from ..config import ServingConfig
    from ..serving import FleetRouter, ModelRegistry, load_manifest
    from ..serving.router import ReplicaLink  # noqa: F401  (re-export)
    from .serve import _load_featurizer

    if not args.fleet:
        print("route: --fleet MANIFEST is required for stream mode",
              file=sys.stderr)
        return 2
    specs = load_manifest(args.fleet)
    cfg = ServingConfig()
    workdir = tempfile.mkdtemp(prefix="oni_route_")
    kv_server = None
    if args.kv_listen:
        from ..parallel.membership import KVServer, TcpKVClient

        lhost, _, lport = args.kv_listen.partition(":")
        kv_server = KVServer(lhost or "127.0.0.1",
                             int(lport) if lport else 0)
        print(f"KV_LISTEN {kv_server.host} {kv_server.port}",
              file=sys.stderr, flush=True)
        kv = TcpKVClient(kv_server.host, kv_server.port)
        kv_flags = ["--kv-connect",
                    f"{kv_server.host}:{kv_server.port}"]
    elif args.kv_connect:
        from ..parallel.membership import TcpKVClient

        chost, _, cport = args.kv_connect.partition(":")
        kv = TcpKVClient(chost or "127.0.0.1", int(cport))
        kv_flags = ["--kv-connect", args.kv_connect]
    else:
        from ..parallel.membership import FileKVClient

        kv_dir = args.kv_dir or os.path.join(workdir, "kv")
        kv = FileKVClient(kv_dir)
        kv_flags = ["--kv-dir", kv_dir]
    procs: dict = {}
    extra: "list[str]" = []
    router = FleetRouter(cfg, kv=kv)
    scaler = None
    try:
        if args.replicas:
            for i in range(args.replicas):
                rid = f"r{i}"
                proc, host, port = _spawn_replica(
                    rid, kv_flags, workdir, extra)
                procs[rid] = proc
                router.connect_replica(rid, host, port)
        elif args.connect:
            for part in args.connect.split(","):
                rid, _, addr = part.strip().partition("=")
                host, _, port = addr.partition(":")
                router.connect_replica(rid, host, int(port))
        else:
            print("route: need --replicas N or --connect",
                  file=sys.stderr)
            return 2
        thresholds: dict = {}
        sc_threshold = (args.threshold if args.threshold is not None
                        else cfg.threshold)
        from ..config import ScoringConfig as SC

        for spec in specs:
            if not spec.day_dir:
                raise SystemExit(
                    f"tenant {spec.tenant!r} has no day_dir")
            from ..sources import get as get_source

            fallback = get_source(spec.dsource).fallback(SC())
            snap = ModelRegistry().load_day(spec.day_dir, fallback)
            fz = _load_featurizer(spec.day_dir, args.top_domains)
            router.add_tenant(spec, (), snap.model, featurizer=fz)
            thresholds[spec.tenant] = (
                spec.threshold if spec.threshold is not None
                else sc_threshold)
        router.start()
        if args.autoscale:
            from ..serving.autoscale import AutoScaler

            spawn_seq = [len(procs)]

            def _as_spawn():
                rid = f"as{spawn_seq[0]}"
                spawn_seq[0] += 1
                proc, host, port = _spawn_replica(
                    rid, kv_flags, workdir, extra)
                procs[rid] = proc
                return rid, host, port

            def _as_stop(rid):
                proc = procs.pop(rid, None)
                if proc is not None:
                    proc.terminate()

            scaler = AutoScaler(router, spawn=_as_spawn,
                                stop=_as_stop, config=cfg)
            scaler.start()
        collector = _FlagCollector(thresholds, sys.stdout)
        routed = skipped = 0
        redeploys: "list[dict]" = []
        for line in sys.stdin:
            line = line.rstrip("\n")
            if not line:
                continue
            tenant, sep, payload = line.partition("\t")
            if not sep:
                skipped += 1
                continue
            try:
                fut = router.submit(tenant, payload.split(","))
            except (KeyError, ValueError, RuntimeError):
                skipped += 1
                continue
            collector.add(tenant, payload, fut)
            routed += 1
            if (args.redeploy_after and procs
                    and routed == args.redeploy_after):
                redeploys = _rolling_redeploy(
                    router, procs, kv_flags, workdir, extra)
        router.flush()
        collector.close()
        summary = {
            "route": "ok",
            "routed": routed,
            "skipped": skipped,
            "resolved": collector.resolved,
            "errors": collector.errors,
            "flagged": collector.flagged,
            "redeploys": len(redeploys),
            "stats": router.stats(),
        }
        if scaler is not None:
            summary["autoscale"] = [
                d for d in scaler.decisions if d["action"] != "hold"]
        print(json.dumps(summary), file=sys.stderr, flush=True)
        return 0 if collector.errors == 0 else 1
    finally:
        if scaler is not None:
            scaler.close()
        router.close()
        if kv_server is not None:
            kv_server.close()
        for proc in procs.values():
            proc.terminate()
        for proc in procs.values():
            try:
                proc.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                proc.kill()
        import shutil

        # The workdir (port files, replica logs, the default kv dir)
        # is ours; a long-running service front must not leave one
        # oni_route_* directory per restart in the tempdir.
        shutil.rmtree(workdir, ignore_errors=True)


def _parse_dry_run(spec: str) -> "tuple[int, int]":
    """``synthetic`` or ``synthetic:TxR`` -> (tenants, replicas)."""
    if not spec.startswith("synthetic"):
        raise SystemExit(
            f"--dry-run wants synthetic[:TxR], got {spec!r}")
    _, _, dims = spec.partition(":")
    if not dims:
        return 6, 3
    t, _, r = dims.partition("x")
    return max(2, int(t)), max(2, int(r))


def dry_run(args) -> int:
    """The acceptance path, runnable anywhere: T synthetic tenants
    placed over R in-process replicas; scores must match the
    single-process oracle bit-for-bit, a mid-stream replica kill must
    drop zero events (shadow promotion + admission-journal replay),
    and a rolling drain+join must keep every surviving future
    resolvable."""
    from ..config import ServingConfig
    from ..serving import (
        DnsEventFeaturizer,
        FleetRouter,
        ReplicaServer,
        TenantSpec,
        score_features,
    )
    from .serve import _synthetic_day

    n_tenants, n_replicas = _parse_dry_run(args.dry_run)
    cfg = ServingConfig(fleet_max_batch=32, fleet_max_wait_ms=5.0,
                        device_score_min=None)
    replicas = {
        f"r{i}": ReplicaServer(f"r{i}", cfg) for i in range(n_replicas)
    }
    router = FleetRouter(cfg)
    days = {}
    try:
        for rid, rep in replicas.items():
            router.connect_replica(rid, rep.host, rep.port)
        for i in range(n_tenants):
            t = f"t{i}"
            days[t] = _synthetic_day(n_events=48, seed=100 + i)
            rows, model, cuts = days[t]
            router.add_tenant(
                TenantSpec(tenant=t, dsource="dns"), cuts, model)
        router.start()
        placement = router.placement()

        def replay(rows_per_tenant: int):
            futs = {
                t: [router.submit(t, r)
                    for r in days[t][0][:rows_per_tenant]]
                for t in days
            }
            router.flush()
            ok, dropped = True, 0
            for t, fs in futs.items():
                rows, model, cuts = days[t]
                feats = DnsEventFeaturizer(cuts)(
                    rows[:rows_per_tenant])
                oracle = score_features(model, feats, "dns")
                try:
                    got = np.array(
                        [f.result(timeout=60.0)[0] for f in fs])
                except Exception:
                    dropped += 1
                    ok = False
                    continue
                if not np.array_equal(got, oracle):
                    ok = False
            return ok, dropped

        parity_ok, dropped0 = replay(24)
        # Chaos: kill the replica that primaries t0 with events in
        # flight; every future must still resolve (shadow promotion +
        # admission-journal replay), and survivors stay bit-identical.
        victim = placement["t0"].primary
        futs = {t: [router.submit(t, r) for r in days[t][0][24:44]]
                for t in days}
        replicas[victim].kill()
        router.flush()
        chaos_dropped = 0
        for t, fs in futs.items():
            for f in fs:
                try:
                    f.result(timeout=60.0)
                except Exception:
                    chaos_dropped += 1
        post_ok, dropped1 = replay(16)
        failovers = router.stats()["failovers"]
        # Rolling redeploy over the survivors: join a fresh replica,
        # then drain one — the fleet serves throughout.
        spare = ReplicaServer("rx", cfg)
        replicas["rx"] = spare
        router.join_replica("rx", spare.host, spare.port)
        drain_target = next(
            r for r in sorted(replicas) if r != victim and r != "rx"
            and replicas[r] is not None
        )
        drained = router.drain_replica(drain_target)
        redeploy_ok, dropped2 = replay(12)
        ok = (
            parity_ok and post_ok and redeploy_ok
            and chaos_dropped == 0
            and dropped0 == dropped1 == dropped2 == 0
            and len(failovers) >= 1
            and drained["drained"]
        )
        summary = {
            "route_dry_run": "ok" if ok else "FAILED",
            "tenants": n_tenants,
            "replicas": n_replicas,
            "parity": parity_ok,
            "killed": victim,
            "chaos_dropped": chaos_dropped,
            "post_failover_parity": post_ok,
            "failovers": failovers,
            "redeploy": {"drained": drained,
                         "parity": redeploy_ok},
        }
        print(json.dumps(summary), flush=True)
        return 0 if ok else 1
    finally:
        router.close()
        for rep in replicas.values():
            rep.stop()


def route_main(argv: "list[str] | None" = None) -> int:
    args = build_route_parser().parse_args(argv)
    if args.dry_run:
        return dry_run(args)
    return route_stream(args)
