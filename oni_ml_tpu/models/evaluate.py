"""Model-quality evaluation: held-out per-token log-likelihood.

The reference's only quality signal is the training-set convergence log
(likelihood.dat, README.md:119) — it never measures generalization.
This module adds the standard document-completion protocol from the
online-LDA literature (Hoffman, Blei, Bach, NIPS 2010 — see PAPERS.md):
for each held-out document, condition on half its tokens (even slots),
infer the doc-topic posterior gamma from that half only, then score the
unseen half's tokens under the predictive distribution

    p(w | w_obs) = sum_k  E[theta_k | gamma(w_obs)] * E[beta_kw]

and report  sum(count * log p) / sum(count)  over the held-out halves —
a per-token score comparable across corpus sizes, batch vs online
trainers, and hyperparameters (higher is better; exp(-score) is the
perplexity).

Works on any point-estimate topics in the final.beta contract (log
p(w|topic) rows, LOG_ZERO floor): the batch trainer's log_beta, the
online trainer's log E_q[beta], or a final.beta file read back via
io.formats.  Evaluation is cheap relative to training, so it runs
unsharded on the default device.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..io import Batch
from ..ops import estep


@partial(jax.jit, static_argnames=("var_max_iters",))
def _batch_held_out(log_beta, alpha, word_idx, counts, doc_mask,
                    var_max_iters, var_tol):
    """One padded batch -> (sum log p over held-out tokens, token count).

    Token slots split deterministically by position parity: even slots
    are observed, odd slots held out.  Bucketed batches store one unique
    word per slot, so the split is over a doc's distinct words; padding
    slots carry count 0 and drop out of both halves.
    """
    pos = jnp.arange(word_idx.shape[1])
    obs = counts * (pos % 2 == 0)
    ho = counts * (pos % 2 == 1)
    res = estep.e_step(
        log_beta, alpha, word_idx, obs, doc_mask,
        var_max_iters=var_max_iters, var_tol=var_tol, backend="xla",
    )
    theta = res.gamma / res.gamma.sum(-1, keepdims=True)
    beta_bt = estep.gather_beta(log_beta, word_idx)  # [B, L, K] probabilities
    p = jnp.einsum("bk,blk->bl", theta, beta_bt)
    # Floor must be representable in float32: on a TRUE held-out split a
    # word can be absent from training entirely (every topic at the
    # LOG_ZERO floor -> p underflows to exactly 0f), and a subnormal
    # floor like 1e-300 flushes to 0, yielding log(0)*0 = NaN for
    # observed-half slots.  1e-30 charges unseen words ~-69 nats.
    ll = (ho * jnp.log(jnp.maximum(p, 1e-30))).sum(-1) * doc_mask
    return ll.sum(), (ho.sum(-1) * doc_mask).sum()


def hash_split(doc_names: Sequence[str], frac: float,
               salt: str = "holdout") -> tuple[np.ndarray, np.ndarray]:
    """Deterministic (train_idx, held_idx) split by document-name hash.

    Hashing the NAME (the IP in this pipeline) rather than the index
    keeps a document's membership stable across days and corpus
    orderings — the property that makes held-out scores comparable
    day-over-day.  crc32 is stable across processes and platforms
    (unlike Python's salted hash())."""
    import zlib

    if not 0.0 < frac < 1.0:
        raise ValueError(f"holdout fraction must be in (0, 1); got {frac}")
    cut = int(frac * 2**32)
    held = np.fromiter(
        (
            zlib.crc32(f"{salt}:{name}".encode("utf-8", "surrogateescape"))
            < cut
            for name in doc_names
        ),
        dtype=bool,
        count=len(doc_names),
    )
    idx = np.arange(len(doc_names))
    return idx[~held], idx[held]


def held_out_per_token_ll(
    log_beta: np.ndarray,
    alpha: float,
    batches: Sequence[Batch],
    var_max_iters: int = 20,
    var_tol: float = 1e-6,
) -> float:
    """Held-out per-token log-likelihood of `batches` under the topics.

    `batches` must be documents the model was NOT trained on (or the
    score is optimistic); make them with io.make_batches over a held-out
    corpus split.
    """
    log_beta = jnp.asarray(log_beta, jnp.float32)
    alpha_dev = jnp.asarray(alpha, log_beta.dtype)
    total_ll = 0.0
    total_tok = 0.0
    for b in batches:
        ll, tok = _batch_held_out(
            log_beta, alpha_dev,
            jnp.asarray(b.word_idx),
            jnp.asarray(b.counts, log_beta.dtype),
            jnp.asarray(b.doc_mask, log_beta.dtype),
            var_max_iters, var_tol,
        )
        total_ll += float(ll)
        total_tok += float(tok)
    return total_ll / max(total_tok, 1.0)
