from .drift import DriftDecision, DriftDetector
from .lda import (
    LDAResult,
    LDATrainer,
    WindowTrainer,
    train_corpus,
    warm_start_log_beta,
)
from .online_lda import OnlineLDATrainer, train_corpus_online

__all__ = [
    "DriftDecision",
    "DriftDetector",
    "LDAResult",
    "LDATrainer",
    "OnlineLDATrainer",
    "WindowTrainer",
    "train_corpus",
    "train_corpus_online",
    "warm_start_log_beta",
]
