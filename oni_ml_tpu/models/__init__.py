from .lda import LDAResult, LDATrainer, train_corpus

__all__ = ["LDAResult", "LDATrainer", "train_corpus"]
