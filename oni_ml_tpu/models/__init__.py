from .lda import LDAResult, LDATrainer, train_corpus
from .online_lda import OnlineLDATrainer, train_corpus_online

__all__ = [
    "LDAResult",
    "LDATrainer",
    "OnlineLDATrainer",
    "train_corpus",
    "train_corpus_online",
]
