"""Variational-EM LDA trainer — the in-tree replacement for the
reference's MPI `oni-lda-c` engine (SURVEY.md §2.8, ml_ops.sh:80).

Reference contract reproduced here:
- input: LDA-C corpus (`model.dat`), K topics, initial symmetric alpha,
  `random` topic initialization;
- outputs: `final.beta` (K x V log p(w|z)), `final.gamma` (D x K
  unnormalized doc-topic Dirichlets), `final.other`, `likelihood.dat`
  (one "<likelihood>\\t<convergence>" line per EM iteration, README.md:119);
- EM loop: per-doc variational fixed point (E) -> MLE beta + Newton alpha
  (M) until |Δℓ/ℓ| < em_tol.

TPU-native design: documents ride padded length-bucketed batches
(io/corpus.py); each (B, L) shape compiles once and the EM loop replays
compiled programs.  Sufficient statistics accumulate on device in [V, K];
the distributed variant (oni_ml_tpu/parallel) shards batches across the
mesh's `data` axis and `psum`s the suff stats over ICI where the reference
did an `MPI_Reduce` across 20 ranks.
"""

from __future__ import annotations

import os
from contextlib import nullcontext
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import digamma, polygamma

from ..config import LDAConfig
from ..io import Batch, Corpus, formats, make_batches
from ..ops import estep
from ..telemetry.spans import current_recorder, maybe_span, now_ns
from . import fused


# ---------------------------------------------------------------------------
# Newton update for the symmetric Dirichlet alpha (lda-c opt_alpha)
# ---------------------------------------------------------------------------


def _alpha_objective_grads(log_a: jnp.ndarray, ss: jnp.ndarray, d: int, k: int):
    a = jnp.exp(log_a)
    df = d * k * (digamma(k * a) - digamma(a)) + ss
    d2f = d * k * k * polygamma(1, k * a) - d * k * polygamma(1, a)
    return a, df, d2f


# static_argnames spelled explicitly for max_iters: every caller passes
# it by KEYWORD, and argnums-only treatment of a keyword arg leans on
# JAX's signature inference (argnum -> name resolution), which is
# version-dependent behavior — a JAX where it no longer applies would
# trace max_iters as dynamic and fail on the Python `if max_iters <= 16`
# below.  d/k stay positional at every call site, so argnums covers them.
@partial(jax.jit, static_argnums=(2, 3), static_argnames=("max_iters",))
def update_alpha(alpha_ss: jnp.ndarray, alpha_init: jnp.ndarray, d: int, k: int,
                 max_iters: int = 100):
    """Maximize L(a) = D(lgam(Ka) - K lgam(a)) + a * ss over the symmetric
    Dirichlet parameter with Newton iterations in log space.

    This is the standard lda-c `opt_alpha` scheme: iterate
    log a <- log a - df / (d2f * a + df) from the current alpha, which is
    Newton's method on the reparameterized objective and keeps a > 0.

    `max_iters` (lda-c's MAX_ALPHA_ITER=100 by default) bounds the
    scalar Newton while_loop — the worst shape for a TPU (sequenced
    scalar digamma/trigamma per trip).  Mid-EM the warm start from the
    previous alpha converges in a handful of trips, so a small cap
    (LDAConfig.alpha_max_iters; tools/tpu_probes.py's alpha_ab probe
    measures the cost) trades nothing measurable in practice; the
    default preserves lda-c semantics exactly.

    When max_iters <= 16 the loop is UNROLLED with a convergence mask
    instead of lowered as lax.while_loop: the r05 alpha_ab probe put
    the estimate at ~0.5 ms of the ~0.94 ms device floor per EM
    iteration, and a dynamic-trip scalar while_loop pays per-trip
    loop machinery that an unrolled scalar chain (one fused kernel)
    does not.  The mask replicates the while_loop exit exactly —
    trips after |df| <= 1e-5 leave the state untouched — so the two
    lowerings compute the same value (pinned in tests/test_lda.py)."""
    ss = alpha_ss

    def body(state):
        log_a, _, it = state
        a, df, d2f = _alpha_objective_grads(log_a, ss, d, k)
        log_a_new = log_a - df / (d2f * a + df)
        return log_a_new, jnp.abs(df), it + 1

    def cond(state):
        log_a, df_abs, it = state
        return jnp.logical_and(it < max_iters, df_abs > 1e-5)

    log_a0 = jnp.log(alpha_init)
    if max_iters <= 16:
        log_a = log_a0
        df_abs = jnp.asarray(jnp.inf, log_a0.dtype)
        for _ in range(max_iters):
            a_it, df, d2f = _alpha_objective_grads(log_a, ss, d, k)
            step = log_a - df / (d2f * a_it + df)
            active = df_abs > 1e-5
            log_a = jnp.where(active, step, log_a)
            df_abs = jnp.where(active, jnp.abs(df), df_abs)
    else:
        log_a, _, _ = jax.lax.while_loop(
            cond, body,
            (log_a0, jnp.asarray(jnp.inf, log_a0.dtype),
             jnp.asarray(0, jnp.int32)),
        )
    a = jnp.exp(log_a)
    # Guard divergence (lda-c restarts with alpha*10; we fall back to the
    # previous value, which keeps EM monotone-safe).
    bad = jnp.logical_or(jnp.isnan(a), jnp.logical_or(a <= 0, jnp.isinf(a)))
    return jnp.where(bad, alpha_init, a)


# ---------------------------------------------------------------------------
# Trainer
# ---------------------------------------------------------------------------


@dataclass
class LDAResult:
    log_beta: np.ndarray       # [K, V]
    gamma: np.ndarray          # [D, K]
    alpha: float
    likelihoods: list = field(default_factory=list)  # [(ll, conv)] per EM iter
    em_iters: int = 0
    # Dispatch-knob resolution this fit ran under (plans.resolve):
    # {knob: {"value", "source": "config"|"plan"|"default"}} — surfaced
    # in the runner's lda stage record.
    plan: dict = field(default_factory=dict)

    def save(
        self,
        directory: str,
        num_terms: int | None = None,
        include_likelihood: bool = True,
    ) -> None:
        """Write final.beta / final.gamma / final.other (and, unless the
        trainer already streamed it, likelihood.dat) with the reference
        formats (README.md:116-119)."""
        k, v = self.log_beta.shape
        formats.write_beta(os.path.join(directory, "final.beta"), self.log_beta)
        formats.write_gamma(os.path.join(directory, "final.gamma"), self.gamma)
        formats.write_other(
            os.path.join(directory, "final.other"), k, num_terms or v, self.alpha
        )
        if include_likelihood:
            with open(os.path.join(directory, "likelihood.dat"), "w") as f:
                for ll, conv in self.likelihoods:
                    formats.append_likelihood(f, ll, conv)


def to_host(x, mesh=None) -> np.ndarray:
    """Device->host as float64.  Arrays sharded over a multi-host mesh are
    not fully addressable from any one process, so gather first."""
    if mesh is not None and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        x = multihost_utils.process_allgather(x, tiled=True)
    return np.asarray(x, dtype=np.float64)


def _is_coordinator() -> bool:
    """True on the single process that owns shared-filesystem writes."""
    return jax.process_index() == 0


def save_checkpoint(
    path: str,
    log_beta: np.ndarray,
    alpha: float,
    em_iter: int,
    likelihoods: list[tuple[float, float]],
) -> None:
    """Atomic in-training checkpoint: (beta, alpha, EM iteration, likelihood
    history).  The reference has no in-training resume at all — a crashed
    20-rank MPI run restarts from scratch (SURVEY §5.3-5.4).

    Call only from the coordinator process in multi-host runs (the
    trainers gate on it); day_dir is a shared filesystem there."""
    tmp = path + ".tmp.npz"  # savez appends nothing to an .npz name
    np.savez(
        tmp,
        log_beta=np.asarray(log_beta),
        alpha=np.float64(alpha),
        em_iter=np.int64(em_iter),
        likelihoods=np.asarray(likelihoods, np.float64).reshape(-1, 2),
    )
    os.replace(tmp, path)


def load_checkpoint(path: str) -> dict:
    """Load a batch EM checkpoint, rejecting streaming-LDA checkpoints
    that can share the same out_dir/checkpoint.npz filename: new-format
    ones carry `lam`, and legacy ones smuggled a strictly positive
    lambda through `log_beta` where real log-probabilities are <= 0
    (the mirror of online_lda.load_stream_checkpoint's guard)."""
    with np.load(path) as z:
        if "lam" in z.files:
            raise ValueError(
                f"{path} is a streaming-LDA checkpoint; resume it with "
                "the online trainer or remove it"
            )
        log_beta = z["log_beta"]
        if log_beta.size and (log_beta > 0).all():
            raise ValueError(
                f"{path} holds strictly positive values — a legacy "
                "streaming-LDA checkpoint (lambda), not batch log_beta; "
                "resume it with the online trainer or remove it"
            )
        return {
            "log_beta": log_beta,
            "alpha": float(z["alpha"]),
            "em_iter": int(z["em_iter"]),
            "likelihoods": [tuple(row) for row in z["likelihoods"]],
        }


def init_log_beta(key: jax.Array, k: int, v: int, dtype=jnp.float32) -> jnp.ndarray:
    """`random` initialization per the reference CLI (ml_ops.sh:80):
    uniform noise + 1/V, log-normalized per topic (lda-c random_initialize_ss)."""
    noise = jax.random.uniform(key, (k, v), dtype=dtype) + 1.0 / v
    return jnp.log(noise / noise.sum(-1, keepdims=True))


class LDATrainer:
    """Single-process EM driver over bucketed batches.

    The `e_step_fn` hook lets the distributed layer substitute a wrapped
    E-step (shard_map over the mesh's data axis, psum on the outputs)
    without changing the math; see oni_ml_tpu/parallel.
    """

    def __init__(
        self,
        config: LDAConfig,
        num_terms: int,
        e_step_fn: Callable | None = None,
        m_step_fn: Callable | None = None,
        mesh=None,
        vocab_sharded: bool = False,
        collective=None,
        shard_plan=None,
        shard_batches=None,
        yield_hook: Callable | None = None,
    ):
        """When `mesh` is set, batches are device_put ONCE with the
        data-axis layout (and beta with the vocab-sharded layout if
        requested).  Since the distributed-EM restructure the mesh is
        HOST-LOCAL only (parallel.local_mesh): cross-process training
        runs the E-step locally per document shard and reduces the
        sufficient statistics through `collective`
        (parallel/allreduce.py) — `shard_plan`/`shard_batches` (shard
        index -> that shard's batches, doc_index GLOBAL) switch fit()
        onto the distributed driver (`_distributed_loop`).

        `yield_hook` (a context-manager factory; see
        serving/coscheduler.py) makes the fit PREEMPTIBLE at its
        natural dispatch grain: the fused driver enters one slot per
        chunk dispatch, the stepwise driver one per EM iteration, the
        distributed driver one per local E-step round — a co-resident
        serving plane wins the next dispatch slot at every boundary."""
        self.config = config
        self.num_terms = num_terms
        self.mesh = mesh
        self.vocab_sharded = vocab_sharded
        self.collective = collective
        self.shard_plan = shard_plan
        self._shard_batches = shard_batches
        self.yield_hook = yield_hook
        self._partial_runner = None  # distributed-loop jit, fit-reused
        base = e_step_fn or estep.e_step
        self._e_base = base
        self._m_base = m_step_fn or estep.m_step
        self._e_step = jax.jit(
            partial(
                base,
                var_max_iters=config.var_max_iters,
                var_tol=config.var_tol,
            )
        )
        # Warm-start variant for the stepwise loop (separate jit: the
        # fresh path must not pay for unused gamma_prev plumbing).
        self._e_step_warm = None   # stays None for non-capable e_fns
        if getattr(base, "_oni_warm_capable", False):
            self._e_step_warm = jax.jit(
                lambda lb, a, w, c, m, g, wm: base(
                    lb, a, w, c, m,
                    var_max_iters=config.var_max_iters,
                    var_tol=config.var_tol,
                    gamma_prev=g, warm=wm,
                )
            )
        self._m_step = jax.jit(self._m_base)

    def fit(
        self,
        batches: Sequence[Batch],
        num_docs: int,
        likelihood_file: str | None = None,
        progress: Callable[[int, float, float], None] | None = None,
        initial_log_beta: np.ndarray | None = None,
        initial_alpha: float | None = None,
        checkpoint_path: str | None = None,
    ) -> LDAResult:
        """Run EM to convergence.  `initial_log_beta`/`initial_alpha` warm-
        start the model (checkpoint resume, tests pinning the init); by
        default beta gets the reference's `random` initialization.

        With `checkpoint_path`, training state (beta, alpha, iteration,
        likelihood history) is persisted every `config.checkpoint_every`
        EM iterations and, if the file already exists, training resumes
        from it instead of reinitializing."""
        cfg = self.config
        k, v = cfg.num_topics, self.num_terms
        dtype = jnp.dtype(cfg.compute_dtype)

        restored: list[tuple[float, float]] = []
        start_it = 0
        if checkpoint_path and os.path.exists(checkpoint_path):
            ckpt = load_checkpoint(checkpoint_path)
            if ckpt["log_beta"].shape != (k, v):
                raise ValueError(
                    f"checkpoint beta shape {ckpt['log_beta'].shape} does "
                    f"not match config ({k}, {v})"
                )
            initial_log_beta = ckpt["log_beta"]
            initial_alpha = ckpt["alpha"]
            restored = ckpt["likelihoods"]
            # Resuming a run checkpointed at (or past) the last iteration
            # re-runs one iteration: gamma comes from the final E-step.
            start_it = min(ckpt["em_iter"], cfg.em_max_iters - 1)

        if initial_log_beta is not None:
            log_beta = jnp.asarray(initial_log_beta, dtype)
        else:
            log_beta = init_log_beta(jax.random.PRNGKey(cfg.seed), k, v, dtype)
        alpha = jnp.asarray(
            cfg.alpha_init if initial_alpha is None else initial_alpha, dtype
        )
        if self.mesh is not None:
            from ..parallel.mesh import (
                DATA_AXIS,
                batch_sharding,
                beta_sharding,
                replicated,
            )

            data_size = self.mesh.shape[DATA_AXIS]
            for b in batches:
                if b.word_idx.shape[0] % data_size:
                    raise ValueError(
                        f"batch of {b.word_idx.shape[0]} docs not divisible "
                        f"by data axis {data_size}"
                    )
            log_beta = jax.device_put(
                log_beta,
                beta_sharding(self.mesh)
                if self.vocab_sharded
                else replicated(self.mesh),
            )

            def put(x):
                return jax.device_put(jnp.asarray(x), batch_sharding(self.mesh))

        else:

            def put(x):
                return jnp.asarray(x)

        gamma_out = np.zeros((num_docs, k), dtype=np.float64)
        likelihoods: list[tuple[float, float]] = list(restored[:start_it])
        # Only the coordinator streams likelihood.dat: in multi-host runs
        # every process executes fit() against a shared day dir, and two
        # appenders on one file would interleave.
        ll_file = (
            open(likelihood_file, "w")
            if likelihood_file and _is_coordinator()
            else None
        )
        if ll_file:
            for ll_r, conv_r in likelihoods:
                formats.append_likelihood(ll_file, ll_r, conv_r)
        ll_prev = likelihoods[-1][0] if likelihoods else None
        if self._shard_batches is not None:
            # Distributed EM: one explicit reduce per EM iteration, so
            # the chunk/host-sync knobs don't apply — the reduce IS the
            # host sync.
            self.plan_record = {}
            loop = self._distributed_loop
        else:
            self._em_chunk, self._em_sync = self._resolve_em_plan(batches)
            loop = (
                self._fused_loop if self._em_chunk > 1
                else self._stepwise_loop
            )
        try:
            log_beta, alpha, it = loop(
                batches, put, log_beta, alpha, ll_prev, start_it, num_docs,
                likelihoods, ll_file, progress, checkpoint_path, gamma_out,
            )
        finally:
            if ll_file:
                ll_file.close()
        if (
            checkpoint_path
            and _is_coordinator()
            and os.path.exists(checkpoint_path)
        ):
            os.remove(checkpoint_path)  # run completed; day dir stays clean

        return LDAResult(
            log_beta=to_host(log_beta, self.mesh),
            gamma=gamma_out,
            alpha=float(alpha),
            likelihoods=likelihoods,
            em_iters=it,
            plan=getattr(self, "plan_record", {}),
        )

    def _resolve_em_plan(self, batches) -> tuple[int, int]:
        """Resolve the fused driver's dispatch knobs through the plan
        layer (oni_ml_tpu/plans): an explicitly-set config value always
        wins, else a measured plan entry for this backend+shape, else
        the shipped default.  The resolution rides `plan_record` (and
        LDAResult.plan) so stage records can name the source each run
        actually trained under."""
        cfg = self.config
        if cfg.host_sync_every < 0:
            # min(chunk, negative) would request negative steps every
            # dispatch — a silent zero-iteration "fit" writing out the
            # random init as if trained.
            raise ValueError(
                f"host_sync_every must be >= 0, got {cfg.host_sync_every}"
            )
        from ..plans import em_shape, resolve

        # Multi-host runs resolve from config/defaults only: every rank
        # must build the SAME chunk program, and per-host plan caches
        # (each host's ~/.cache) could legally hold different measured
        # winners — a rank-divergent while_loop bound would desync the
        # training collectives.
        kw = {"store": None} if jax.process_count() > 1 else {}
        sig = em_shape(cfg.num_topics, self.num_terms, batches)
        chunk, chunk_src = resolve(
            "fused_em_chunk", cfg.fused_em_chunk, shape=sig, **kw
        )
        sync, sync_src = resolve(
            "host_sync_every", cfg.host_sync_every, shape=sig, **kw
        )
        chunk, sync = int(chunk), max(0, int(sync))
        self.plan_record = {
            "fused_em_chunk": {"value": chunk, "source": chunk_src},
            "host_sync_every": {"value": sync, "source": sync_src},
        }
        return chunk, sync

    # -- EM drivers ---------------------------------------------------------
    #
    # Both share the fit() contract: advance (log_beta, alpha) from
    # `start_it` until convergence or em_max_iters, appending to
    # `likelihoods`, streaming `ll_file`/`progress`/checkpoints, and
    # scattering the final E-step's gammas into `gamma_out`.

    def _log_iteration(
        self, it, ll, ll_prev, likelihoods, ll_file, progress
    ) -> float:
        """Record one EM iteration host-side; returns its convergence."""
        conv = abs((ll_prev - ll) / ll_prev) if ll_prev is not None else 1.0
        likelihoods.append((ll, conv))
        if ll_file:
            formats.append_likelihood(ll_file, ll, conv)
            ll_file.flush()
        if progress:
            progress(it, ll, conv)
        return conv

    def _maybe_checkpoint(self, checkpoint_path, log_beta, alpha, it,
                          likelihoods) -> None:
        cfg = self.config
        if (
            checkpoint_path
            and cfg.checkpoint_every
            and it % cfg.checkpoint_every == 0
        ):
            # to_host is collective on multi-host meshes (process_allgather)
            # — every process must reach it; only the coordinator writes.
            beta_host = to_host(log_beta, self.mesh)
            if _is_coordinator():
                save_checkpoint(
                    checkpoint_path, beta_host, float(alpha), it, likelihoods,
                )

    def _stepwise_loop(
        self, batches, put, log_beta, alpha, ll_prev, start_it, num_docs,
        likelihoods, ll_file, progress, checkpoint_path, gamma_out,
    ):
        """One device dispatch per batch per EM iteration; the likelihood
        syncs to the host every iteration (convergence decided in float64).
        Kept for fused_em_chunk <= 1 and as the numerical cross-check for
        the fused driver."""
        cfg = self.config
        k, v = cfg.num_topics, self.num_terms
        dtype = jnp.dtype(cfg.compute_dtype)
        dev_batches = [
            (
                put(b.word_idx),
                put(b.counts.astype(dtype)),
                put(b.doc_mask.astype(dtype)),
            )
            for b in batches
        ]
        # Warm start mirrors the fused driver's semantics (same gammas
        # seed the next iteration's fixed point) so the stepwise loop
        # stays its numerical cross-check under the default config.
        use_warm = cfg.warm_start_gamma and getattr(
            self._e_base, "_oni_warm_capable", False
        )
        # Roofline accounting (telemetry/roofline.py) is recorder-gated;
        # the harvest itself happens AFTER the loop so the programs are
        # already traced (the AOT cost read is then a compilation-cache
        # hit, never a cold compile ahead of first results).
        rl = None
        if current_recorder() is not None and dev_batches:
            from ..telemetry import roofline as rl
        t_loop0 = now_ns()
        n_e_disp = n_a_disp = n_warm_disp = 0
        gammas = []
        it = start_it
        for it in range(start_it + 1, cfg.em_max_iters + 1):
            # One EM iteration is the stepwise driver's preemption
            # grain (fused_em_chunk=1 means the iteration IS the
            # chunk): the whole dispatch burst — E-steps, M-step,
            # alpha Newton — runs inside one yield-hook slot, and a
            # co-resident scoring flush wins the slot between
            # iterations.
            slot = (self.yield_hook() if self.yield_hook is not None
                    else nullcontext())
            with slot:
                total_ss = jnp.zeros((v, k), dtype)
                total_ll = jnp.zeros((), dtype)
                total_ass = jnp.zeros((), dtype)
                prev_gammas = gammas if use_warm else []
                gammas = []
                for bi, (widx, cnts, mask) in enumerate(dev_batches):
                    if prev_gammas:
                        res = self._e_step_warm(
                            log_beta, alpha, widx, cnts, mask,
                            prev_gammas[bi], jnp.asarray(1, jnp.int32),
                        )
                        n_warm_disp += 1
                    else:
                        res = self._e_step(
                            log_beta, alpha, widx, cnts, mask
                        )
                    total_ss = total_ss + res.suff_stats
                    total_ll = total_ll + res.likelihood
                    total_ass = total_ass + res.alpha_ss
                    gammas.append(res.gamma)
                    n_e_disp += 1

                log_beta = self._m_step(total_ss)
                if cfg.estimate_alpha:
                    alpha = update_alpha(total_ass, alpha, num_docs, k,
                                         max_iters=cfg.alpha_max_iters)
                    n_a_disp += 1

            # The per-iteration convergence read is the stepwise
            # driver's one deliberate device sync; span it like the
            # fused driver's em.host_sync so the flight recorder
            # prices the stall instead of it hiding in iteration wall.
            with maybe_span("em.host_sync", it=it):
                ll = float(total_ll)
            conv = self._log_iteration(
                it, ll, ll_prev, likelihoods, ll_file, progress
            )
            self._maybe_checkpoint(
                checkpoint_path, log_beta, alpha, it, likelihoods
            )
            if ll_prev is not None and conv < cfg.em_tol:
                break
            ll_prev = ll

        if rl is not None:
            # Harvest the stepwise driver's jitted entry points — the
            # per-batch E-step and the alpha Newton are the "E-step" and
            # "alpha update" roofline phases (the fused driver inlines
            # both into em.run_chunk).  Done post-loop: the programs are
            # already traced (cache-hit lowering), and with warm starts
            # the warm variant dominated dispatches (all but the first
            # iteration), so price against the variant that actually
            # ran the majority — a mixed run is an approximation the
            # record's shape suffix names.
            b0 = batches[0].word_idx.shape[0]
            widx0, cnts0, mask0 = dev_batches[0]
            if n_warm_disp * 2 >= n_e_disp and gammas:
                rl.ensure_harvested(
                    "em.e_step", self._e_step_warm, log_beta, alpha,
                    widx0, cnts0, mask0, gammas[0],
                    jnp.asarray(1, jnp.int32), shape=f"b{b0}.warm",
                )
            else:
                rl.ensure_harvested(
                    "em.e_step", self._e_step, log_beta, alpha, widx0,
                    cnts0, mask0, shape=f"b{b0}",
                )
            if n_a_disp:
                rl.ensure_harvested(
                    "em.update_alpha", update_alpha,
                    jnp.zeros((), dtype), alpha, num_docs, k,
                    max_iters=cfg.alpha_max_iters,
                )
            # One roofline record per stepwise phase, joined with the
            # loop wall (the E-step dominates it; the alpha Newton's
            # record shares the wall and self-describes via
            # `wall_shared`) — journaled as {"kind": "roofline"} and
            # published as roofline.* gauges.
            wall_s = (now_ns() - t_loop0) / 1e9
            rl.emit("em.e_step", wall_s, dispatches=n_e_disp,
                    em_iters=it - start_it)
            if n_a_disp:
                rl.emit("em.update_alpha", wall_s, dispatches=n_a_disp,
                        wall_shared="em.e_step")

        for g, b in zip(gammas, batches):
            g = to_host(g, self.mesh)
            sel = b.doc_mask == 1
            gamma_out[b.doc_index[sel]] = g[sel]
        return log_beta, alpha, it

    def _distributed_loop(
        self, batches, put, log_beta, alpha, ll_prev, start_it, num_docs,
        likelihoods, ll_file, progress, checkpoint_path, gamma_out,
    ):
        """Pod-scale EM: host-local E-step per document shard, explicit
        sufficient-statistics allreduce, identical M-step everywhere.

        Each owned shard's stacked groups run through ONE jitted
        partial-stats program (fused.make_partial_runner — the full
        E-step, including the sparse Pallas engine over the shard's
        bucketed layout with its per-bucket segment-sum already folded
        into the [V, K] factor).  The per-shard partials cross
        processes through parallel/allreduce.reduce_partials — whose
        fixed pairwise tree over the corpus-derived shard plan makes
        the reduced bytes identical on every rank AND invariant to the
        rank count — and then every rank runs the same M-step, alpha
        Newton, and float64 convergence check from the reduced stats.
        Rank parity of the final model is ASSERTED (digest allgather),
        not assumed."""
        import hashlib

        from ..parallel.allreduce import reduce_partials

        cfg = self.config
        k = cfg.num_topics
        dtype = jnp.dtype(cfg.compute_dtype)
        coll, plan = self.collective, self.shard_plan
        owned = sorted(self._shard_batches)

        put_stacked = put
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import DATA_AXIS

            stacked_sh = NamedSharding(self.mesh, P(None, DATA_AXIS))

            def put_stacked(x):
                return jax.device_put(jnp.asarray(x), stacked_sh)

        compiler_options = None
        if (
            getattr(self._e_base, "_oni_sparse_engine", False)
            and jax.default_backend() == "tpu"
        ):
            from ..ops import sparse_estep

            # Same scoped-VMEM forwarding the fused driver needs: XLA
            # drops a fusion-wrapped pallas_call's own CompilerParams
            # limit inside the jitted program.
            kibs = [
                sparse_estep.scoped_vmem_kib(
                    b.word_idx.shape[0], b.word_idx.shape[1], k,
                    getattr(self._e_base, "precision", "f32"),
                )
                for bs in self._shard_batches.values() for b in bs
            ]
            if any(kibs):
                compiler_options = {
                    "xla_tpu_scoped_vmem_limit_kib": str(
                        max(filter(None, kibs))
                    )
                }
        # The jitted partial-stats program is FIT-REUSED: a standing
        # service (WindowTrainer with a collective) calls fit() every
        # refresh with fresh shard batches but identical group shapes,
        # and rebuilding the jit wrapper each fit would re-trace a
        # program the compilation cache already holds.  Keyed by the
        # compiler options in case the scoped-VMEM forwarding changes
        # with the shard census.
        co_key = (tuple(sorted(compiler_options.items()))
                  if compiler_options else None)
        if (self._partial_runner is None
                or self._partial_runner[0] != co_key):
            self._partial_runner = (co_key, fused.make_partial_runner(
                num_topics=k, num_terms=self.num_terms,
                var_max_iters=cfg.var_max_iters, var_tol=cfg.var_tol,
                e_step_fn=self._e_base, warm_start=cfg.warm_start_gamma,
                compiler_options=compiler_options,
            ))
        runner = self._partial_runner[1]
        shard_groups = [
            fused.stack_batches(
                self._shard_batches[s], np.dtype(cfg.compute_dtype),
                put_stacked,
            )
            for s in owned
        ]
        gammas_prev = [
            tuple(
                put_stacked(g)
                for g in fused.initial_gammas(sg.arrays, k, dtype)
            )
            for sg in shard_groups
        ]
        have_prev = False
        # env > config, matching every other distributed knob.  Applies
        # to the bulk suff-stats reduce ONLY — the f64 gamma merge
        # below pins f32 (= uncompressed) so posteriors stay exact.
        ar_precision = (
            os.environ.get("ONI_ML_TPU_ALLREDUCE_PRECISION", "")
            or cfg.allreduce_precision
        )
        ar0 = dict(coll.stats)
        t_loop0 = now_ns()
        n_reduce = 0
        it = start_it
        for it in range(start_it + 1, cfg.em_max_iters + 1):
            warm = jnp.asarray(
                1 if (have_prev and cfg.warm_start_gamma) else 0, jnp.int32
            )
            shard_stats = {}
            new_gammas = []
            # The local E-step round is the distributed driver's
            # preemption grain (the reduce that follows is host-side
            # comms, never held under the slot — a slow peer must not
            # block a co-resident scoring flush).
            slot = (self.yield_hook() if self.yield_hook is not None
                    else nullcontext())
            with slot:
                for si, sg, gp in zip(owned, shard_groups, gammas_prev):
                    ss, ll, ass, gammas, _ = runner(
                        log_beta, alpha, sg.arrays, gp, warm
                    )
                    new_gammas.append(gammas)
                    # The partial transfer is THE deliberate device
                    # sync of the distributed driver (one per shard per
                    # iteration); span it so the flight recorder prices
                    # it next to the allreduce wait instead of it
                    # hiding in iteration wall.
                    with maybe_span("em.host_sync", it=it, shard=si):
                        shard_stats[si] = dict(zip(
                            estep.PARTIAL_STAT_FIELDS,
                            (np.asarray(ss), np.asarray(ll),
                             np.asarray(ass)),
                        ))
            gammas_prev, have_prev = new_gammas, True
            reduced = reduce_partials(coll, plan, shard_stats,
                                      f"em{it}", precision=ar_precision)
            n_reduce += 1
            log_beta = self._m_step(jnp.asarray(reduced["suff_stats"]))
            if cfg.estimate_alpha:
                alpha = update_alpha(
                    jnp.asarray(reduced["alpha_ss"], dtype), alpha,
                    num_docs, k, max_iters=cfg.alpha_max_iters,
                )
            # reduced[...] is a HOST array (the allreduce output); the
            # span prices the implicit alpha/beta dependency drain.
            with maybe_span("em.host_sync", it=it):
                ll = float(reduced["likelihood"])
            conv = self._log_iteration(
                it, ll, ll_prev, likelihoods, ll_file, progress
            )
            self._maybe_checkpoint(
                checkpoint_path, log_beta, alpha, it, likelihoods
            )
            if ll_prev is not None and conv < cfg.em_tol:
                break
            ll_prev = ll

        if current_recorder() is not None and n_reduce:
            # The comms side of the roofline: measured allreduce bytes
            # and wall over the whole fit ({"kind": "roofline"},
            # cost_source "measured_comms" — interconnect traffic, so
            # no HBM utilization fraction is claimed).
            from ..telemetry import roofline

            d = coll.stats
            roofline.emit(
                "em.allreduce", (now_ns() - t_loop0) / 1e9,
                dispatches=n_reduce,
                measured_bytes=float(
                    d["bytes_out"] - ar0["bytes_out"]
                    + d["bytes_in"] - ar0["bytes_in"]
                ),
                transport=coll.transport, nprocs=coll.num_processes,
                allreduce_wall_s=round(d["wall_s"] - ar0["wall_s"], 6),
            )

        # Scatter owned shards' final posteriors (global doc ids), then
        # merge across ranks: unowned rows are exact zeros, so the sum
        # is a disjoint union whatever the combine order.
        for si, sg, gms in zip(owned, shard_groups, gammas_prev):
            bs = self._shard_batches[si]
            for g_arr, slots in zip(gms, sg.batch_slots):
                g_group = to_host(g_arr, self.mesh)
                for j, bi in enumerate(slots):
                    b = bs[bi]
                    sel = b.doc_mask == 1
                    gamma_out[b.doc_index[sel]] = g_group[j][sel]
        if coll.num_processes > 1:
            # Ship only the OWNED contiguous row blocks (a rank owns
            # 1/P of the documents; gathering the full mostly-zero
            # [D, K] from every rank would move P× the bytes) and place
            # them by shard bounds — pure placement into disjoint
            # ranges, no arithmetic, so the merged gamma is exact and
            # rank-identical.
            payload = {
                s: gamma_out[plan.bounds[s][0]:plan.bounds[s][1]]
                for s in owned
            }
            # precision pinned: the gamma merge ships f64 posteriors
            # whose exactness the artifact byte-identity contract
            # depends on — never bf16-compress it.
            for g in coll.allgather_arrays(payload, "em_gamma",
                                           precision="f32"):
                for s, rows in g.items():
                    st, en = plan.bounds[s]
                    gamma_out[st:en] = rows

        # Rank parity: every rank derived its model from the same
        # reduced stats; divergence (mixed configs, a nondeterministic
        # kernel) must fail loudly here, not ship mismatched artifacts.
        beta_host = to_host(log_beta, self.mesh)
        digest = hashlib.sha256(beta_host.tobytes()).hexdigest()
        digests = coll.allgather_obj(
            (digest, float(alpha), it), "em_parity"
        )
        if any(d != digests[0] for d in digests):
            raise RuntimeError(
                f"distributed EM rank parity violated: {digests}"
            )
        return log_beta, alpha, it

    def _local_batch(self, batch) -> int:
        """Documents each data shard's kernel sees for one batch."""
        if self.mesh is None:
            return batch.word_idx.shape[0]
        from ..parallel.mesh import DATA_AXIS

        return batch.word_idx.shape[0] // self.mesh.shape[DATA_AXIS]

    def _use_dense(self, batches) -> bool:
        """Decide whether the fused loop runs the dense-corpus E-step
        (ops/dense_estep.py).  Auto mode requires: a TPU backend, the
        stock E-step or this package's own sharded wrappers (a user's
        custom e_step_fn must not be silently bypassed), VMEM-feasible
        doc blocks for every PER-SHARD batch, and the densified corpus
        under the HBM budget.  With a data mesh the Pallas kernel runs
        under shard_map (parallel.make_data_parallel_dense_e_step),
        suff-stats psum'd over ICI; with a vocab-sharded trainer the
        XLA-level make_vocab_sharded_dense_e_step plan applies instead
        (_use_dense_vocab_sharded)."""
        from ..ops import dense_estep

        env = os.environ.get("ONI_ML_TPU_ESTEP", "")
        # "compact" forces the compact-vocab dense variant: full-V dense
        # off here, then _plan_compact treats the same env as forced-on.
        # "sparse" forces the fused sparse bucketed engine — the whole
        # dense family stands down.
        mode = {"dense": "on", "compact": "off", "xla": "off",
                "pallas": "off", "sparse": "off"}.get(
                    env, self.config.dense_em)
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"LDAConfig.dense_em={mode!r}: expected 'auto', 'on', or "
                "'off'"
            )
        if mode == "off":
            return False
        own_parallel = getattr(self._e_base, "_oni_data_parallel", False)
        if self.vocab_sharded:
            return self._use_dense_vocab_sharded(batches, mode)
        incompatible = (
            "a custom e_step_fn is installed"
            if self._e_base is not estep.e_step and not own_parallel
            else None
        )
        if incompatible:
            if mode == "on":
                raise ValueError(f"dense E-step forced but {incompatible}")
            return False
        k, v = self.config.num_topics, self.num_terms
        # Feasibility is per data shard: each device's kernel sees its
        # local slice of the batch.
        feasible = all(
            dense_estep.pick_block(self._local_batch(b), v, k,
                                   self.config.dense_precision)
            is not None
            for b in batches
        )
        if mode == "on":
            if not feasible:
                # Forced dense with an infeasible full-V shape: the
                # compact-vocab variant is still the dense family —
                # rescue through it when it can serve (single-process,
                # per-batch widths blockable), else keep the hard error.
                if self.mesh is None:
                    self._compact_rescue = fused.plan_compact(
                        batches, k, self.config.dense_precision,
                        wmajor=self.config.dense_wmajor,
                    )
                    if self._compact_rescue is not None:
                        return False
                raise ValueError(
                    "dense E-step forced but a batch shape has no "
                    f"VMEM-feasible doc block (V={v}, K={k}) and the "
                    "compact-vocab fallback is not feasible either"
                )
            return True
        # Peak device memory during densify_groups holds BOTH the sparse
        # stacked arrays (scatter inputs) and the dense output, so budget
        # the sum, not just the dense corpus.  The budget is per DEVICE:
        # a data mesh shards the doc axis, dividing both terms.
        if self.mesh is None:
            shards = 1
        else:
            from ..parallel.mesh import DATA_AXIS

            shards = self.mesh.shape[DATA_AXIS]
        sparse_bytes = sum(
            b.word_idx.size * 8 for b in batches  # int32 idx + f32 counts
        ) // shards
        return (
            feasible
            and jax.default_backend() == "tpu"
            and fused.dense_groups_bytes(batches, v) // shards + sparse_bytes
            <= self.config.dense_hbm_budget
        )

    def _use_dense_vocab_sharded(self, batches, mode) -> bool:
        """Gate for the vocab-sharded dense plan
        (parallel.make_vocab_sharded_dense_e_step): an XLA-level matmul
        fixed point with C and beta sharded over `model` — config 4's
        MXU path.  No Pallas/VMEM feasibility applies (XLA tiles any
        shape); the auto-mode gate is device memory: each data shard
        materializes its [B/d, W] densify transient before the model
        axis splits it, and the run keeps a resident [docs/d, W/m]
        corpus slice per device."""
        from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

        d = self.mesh.shape[DATA_AXIS]
        m = self.mesh.shape[MODEL_AXIS]
        own_vocab = getattr(self._e_base, "_oni_vocab_sharded", False)
        incompatible = (
            "the vocabulary is sharded and the installed e_step_fn is "
            "not this package's vocab-sharded plan"
            if not own_vocab
            else f"padded vocab {self.num_terms} not divisible by "
            f"model axis {m}"
            if self.num_terms % m
            else None
        )
        if incompatible:
            if mode == "on":
                raise ValueError(f"dense E-step forced but {incompatible}")
            return False
        if mode == "on":
            return True
        if jax.default_backend() != "tpu":
            return False
        total_docs = sum(b.word_idx.shape[0] for b in batches)
        sparse_bytes = sum(b.word_idx.size * 8 for b in batches) // d
        transient = (
            max(b.word_idx.shape[0] for b in batches) // d
            * self.num_terms * 4
        )
        resident = total_docs // d * (self.num_terms // m) * 4
        return (
            transient + resident + sparse_bytes
            <= self.config.dense_hbm_budget
        )

    def _plan_compact(self, batches):
        """Compact-vocab dense fallback decision (fused.plan_compact).

        When the FULL vocabulary is too wide to densify — config-4
        scale, the combinatorial DNS word space of
        dns_pre_lda.scala:320-326 — each batch still only touches the
        words its documents contain, so remapping every batch onto its
        own compacted vocabulary (width Wc << V) recovers the
        gather/scatter-free MXU kernel at the cost of one beta-column
        gather and one suff-stats row-scatter per batch per EM
        iteration.  Gates mirror _use_dense: auto needs the TPU
        backend, the stock E-step, and the compacted corpus under the
        HBM budget; ONI_ML_TPU_ESTEP=compact forces it (tests /
        interpret runs).  Single-process only — the multi-chip huge-V
        story is the vocab-sharded dense plan (parallel/sharded.py)."""
        from ..ops import dense_estep

        env = os.environ.get("ONI_ML_TPU_ESTEP", "")
        rescue = getattr(self, "_compact_rescue", None)
        self._compact_rescue = None
        if env == "dense":
            # Forced dense that _use_dense could not serve at full V:
            # the rescue plan (when one was feasible) IS the
            # dense-family fallback; no separate compact gating.
            return rescue
        if env and env != "compact":
            return None
        mode = "on" if env == "compact" else self.config.dense_em
        blocked = (
            "a mesh is active (the multi-chip huge-V story is the "
            "vocab-sharded dense plan)"
            if self.mesh is not None or self.vocab_sharded
            else "a custom e_step_fn is installed"
            if self._e_base is not estep.e_step
            else None
        )
        if mode == "off" or blocked:
            if env == "compact" and blocked:
                raise ValueError(
                    f"compact dense E-step forced but {blocked}"
                )
            return None
        if rescue is not None:  # dense_em="on" rescue from _use_dense
            return rescue
        if mode != "on" and jax.default_backend() != "tpu":
            return None
        cfg = self.config
        cell_max = max(
            dense_estep.max_dense_cell(b.word_idx, b.counts)
            for b in batches
        )
        # Cache for _fused_loop's corpus_store derivation: this is a
        # full O(tokens) host pass the compact path must not pay twice.
        self._compact_cell_max = cell_max
        itemsize = jnp.dtype(
            dense_estep.corpus_dtype(cell_max, cfg.dense_precision)
        ).itemsize
        plan = fused.plan_compact(
            batches, cfg.num_topics, cfg.dense_precision,
            wmajor=cfg.dense_wmajor, itemsize=itemsize,
        )
        if plan is None:
            if mode == "on":
                raise ValueError(
                    "compact dense E-step forced but a batch's compact "
                    "width admits no VMEM-feasible doc block"
                )
            return None
        if mode == "on":
            return plan
        # Peak device memory: the whole compacted corpus plus the
        # largest single group's sparse stacks (compact_stack_batches
        # uploads sparse arrays one group at a time, unlike
        # densify_groups which holds them all).
        groups: dict[tuple, int] = {}
        for b in batches:
            groups[b.word_idx.shape] = (
                groups.get(b.word_idx.shape, 0) + b.word_idx.size * 8
            )
        if plan.corpus_bytes + max(groups.values()) > cfg.dense_hbm_budget:
            return None
        return plan

    def _fused_loop(
        self, batches, put, log_beta, alpha, ll_prev, start_it, num_docs,
        likelihoods, ll_file, progress, checkpoint_path, gamma_out,
    ):
        """Device-resident EM (models/fused.py): up to fused_em_chunk
        iterations per compiled call.  The device checks convergence in
        compute dtype to stop mid-chunk; the host re-derives conv in
        float64 at chunk boundaries (_log_iteration) and that value is
        authoritative — a device stop that float64 disagrees with (the
        ~1-ulp |Δll/ll| boundary) resumes, so the stop decision always
        matches the conv written to likelihood.dat and the stepwise
        driver's float64 semantics."""
        cfg = self.config
        k = cfg.num_topics
        dtype = jnp.dtype(cfg.compute_dtype)

        put_stacked = put
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import DATA_AXIS

            stacked_sh = NamedSharding(self.mesh, P(None, DATA_AXIS))

            def put_stacked(x):
                return jax.device_put(jnp.asarray(x), stacked_sh)

        compiler_options = None
        use_dense = self._use_dense(batches)
        self._compact_cell_max = None  # set by _plan_compact's scan
        compact = None if use_dense else self._plan_compact(batches)
        use_wmajor = False
        dense_e_fn = None
        corpus_store = None
        if use_dense or compact is not None:
            from ..ops import dense_estep as _de

            # bf16 corpus storage when exact and the run is already in
            # bf16 operand mode — halves the corpus' HBM streaming with
            # bit-identical results.  The gate bounds the DENSIFIED
            # cells (duplicate (doc, word) tokens sum — the DUPFACTOR
            # feedback path makes ~1000-count cells out of count-1
            # tokens), not the raw counts.
            cell_max = self._compact_cell_max
            if cell_max is None:
                cell_max = max(
                    _de.max_dense_cell(b.word_idx, b.counts)
                    for b in batches
                )
            corpus_store = _de.corpus_dtype(cell_max, cfg.dense_precision)
        if compact is not None:
            from ..ops import dense_estep

            # Compact-vocab dense groups are built straight from the
            # host batches (no sparse stacked upload to discard).  The
            # chunk runner dispatches on the group layout itself
            # (fused._compact_dense gathers beta columns and scatters
            # suff-stats rows per batch).
            use_wmajor = compact.wmajor
            groups = fused.compact_stack_batches(
                batches, np.dtype(cfg.compute_dtype), put, compact,
                corpus_store=corpus_store,
            )
            shapes = sorted({b.word_idx.shape for b in batches})
            kibs = [
                dense_estep.scoped_vmem_kib(
                    shape[0], wc, k, wmajor=use_wmajor,
                    precision=cfg.dense_precision,
                )
                for shape, wc in zip(shapes, compact.widths)
            ]
            if any(kibs) and jax.default_backend() == "tpu":
                compiler_options = {
                    "xla_tpu_scoped_vmem_limit_kib": str(
                        max(filter(None, kibs))
                    )
                }
        else:
            groups = fused.stack_batches(
                batches, np.dtype(cfg.compute_dtype), put_stacked
            )
        if use_dense and self.vocab_sharded:
            from functools import partial as _partial

            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel import sharded
            from ..parallel.mesh import DATA_AXIS as _DA, MODEL_AXIS as _MA

            # XLA-level vocab-sharded dense plan: stacked dense groups
            # [NB, B, W] shard docs over `data` and vocab columns over
            # `model`; width == the (model-divisible) padded vocab, so
            # suff-stats land exactly in the sparse plan's shard layout
            # and the vocab-sharded m_step consumes them unchanged.
            dense_sh = NamedSharding(self.mesh, P(None, _DA, _MA))
            dense_e_fn = _partial(
                sharded.make_vocab_sharded_dense_e_step(
                    self.mesh, precision=cfg.dense_precision
                ),
                var_max_iters=cfg.var_max_iters,
                var_tol=cfg.var_tol,
            )
            groups = fused.densify_groups(
                groups, self.num_terms, wmajor=False,
                put=lambda x: jax.device_put(x, dense_sh),
                width=self.num_terms, dtype=corpus_store,
            )
        elif use_dense:
            from functools import partial as _partial

            from ..ops import dense_estep

            # Feasibility checks run against the PER-SHARD batch (each
            # data shard's kernel sees its local slice).  W-major needs
            # the doc axis on the 128-lane dimension; fall back to
            # row-major when any batch shape can't block that way.
            use_wmajor = cfg.dense_wmajor and all(
                dense_estep.pick_block_w(self._local_batch(b),
                                         self.num_terms, k,
                                         cfg.dense_precision)
                for b in batches
            )
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                from ..parallel import sharded
                from ..parallel.mesh import DATA_AXIS as _DA

                dense_sh = NamedSharding(
                    self.mesh,
                    P(None, None, _DA) if use_wmajor else P(None, _DA),
                )
                dense_put = lambda x: jax.device_put(x, dense_sh)  # noqa: E731
                dense_e_fn = _partial(
                    sharded.make_data_parallel_dense_e_step(
                        self.mesh, wmajor=use_wmajor,
                        precision=cfg.dense_precision,
                    ),
                    var_max_iters=cfg.var_max_iters,
                    var_tol=cfg.var_tol,
                    interpret=jax.default_backend() != "tpu",
                )
            else:
                dense_put = None
            groups = fused.densify_groups(
                groups, self.num_terms, wmajor=use_wmajor, put=dense_put,
                dtype=corpus_store,
            )
            # XLA drops the pallas kernel's own scoped-VMEM limit when the
            # call is fusion-wrapped inside a stacked-group scan; forward
            # the limit as a program-level compiler option instead.  The
            # option only exists on the TPU compiler (CPU interpret runs
            # have no VMEM to limit).
            kibs = [
                dense_estep.scoped_vmem_kib(self._local_batch(b),
                                            self.num_terms, k,
                                            wmajor=use_wmajor,
                                            precision=cfg.dense_precision)
                for b in batches
            ]
            if any(kibs) and jax.default_backend() == "tpu":
                compiler_options = {
                    "xla_tpu_scoped_vmem_limit_kib": str(max(filter(None, kibs)))
                }
        if (
            not use_dense
            and compact is None
            and getattr(self._e_base, "_oni_sparse_engine", False)
            and jax.default_backend() == "tpu"
        ):
            from ..ops import sparse_estep

            # Same scoped-VMEM forwarding the dense kernels need: XLA
            # drops a fusion-wrapped pallas_call's own CompilerParams
            # limit inside the chunk program.
            kibs = [
                sparse_estep.scoped_vmem_kib(
                    b.word_idx.shape[0], b.word_idx.shape[1], k,
                    getattr(self._e_base, "precision", "f32"),
                )
                for b in batches
            ]
            if any(kibs):
                compiler_options = {
                    "xla_tpu_scoped_vmem_limit_kib": str(
                        max(filter(None, kibs))
                    )
                }
        run_chunk = fused.make_chunk_runner(
            num_docs=num_docs,
            num_topics=k,
            num_terms=self.num_terms,
            chunk=self._em_chunk,
            var_max_iters=cfg.var_max_iters,
            var_tol=cfg.var_tol,
            em_tol=cfg.em_tol,
            estimate_alpha=cfg.estimate_alpha,
            e_step_fn=self._e_base,
            m_step_fn=self._m_base,
            compiler_options=compiler_options,
            dense_wmajor=use_wmajor,
            warm_start=cfg.warm_start_gamma,
            dense_e_step_fn=dense_e_fn,
            dense_precision=cfg.dense_precision,
            alpha_max_iters=cfg.alpha_max_iters,
            yield_hook=self.yield_hook,
        )

        ll_prev_dev = jnp.asarray(
            np.nan if ll_prev is None else ll_prev, dtype
        )
        it = start_it
        res = None
        # Same data-axis commitment as every other device input: on a
        # multi-host mesh an uncommitted buffer spanning non-addressable
        # devices fails outright, and even single-host meshes would pay
        # a reshard on the first chunk (gamma buffers are [NB, B, K]
        # with B on the data axis, like the stacked batches).
        gammas_prev = tuple(
            put_stacked(g)
            for g in fused.initial_gammas(
                groups.arrays, k, dtype, dense_wmajor=use_wmajor
            )
        )
        have_prev = jnp.asarray(False)
        # Host-sync cadence: host_sync_every bounds the iterations per
        # dispatch independently of the compiled chunk size, so
        # likelihood.dat streams (and progress fires) at least that
        # often — with chunk=128 and checkpointing off a whole fit is
        # otherwise ONE dispatch and a crash loses every likelihood
        # line.  The chunk program takes its step count dynamically
        # (like the checkpoint cap below), so no recompile.  Both knobs
        # arrive plan-resolved (_resolve_em_plan; negative
        # host_sync_every already rejected there).
        sync_chunk = self._em_chunk
        if self._em_sync:
            sync_chunk = min(sync_chunk, self._em_sync)
        t_loop0 = now_ns()
        n_disp = 0
        while it < cfg.em_max_iters:
            stop = min(it + sync_chunk, cfg.em_max_iters)
            if checkpoint_path and cfg.checkpoint_every:
                next_ckpt = (
                    it // cfg.checkpoint_every + 1
                ) * cfg.checkpoint_every
                stop = min(stop, next_ckpt)
            res = run_chunk(
                log_beta, alpha, ll_prev_dev, groups.arrays, stop - it,
                gammas_prev, have_prev,
            )
            n_disp += 1
            # Carry the chunk's final posteriors so warm start survives
            # the host sync at chunk boundaries.
            gammas_prev, have_prev = res.gammas, res.steps_done > 0
            log_beta, alpha, ll_prev_dev = res.log_beta, res.alpha, res.ll_prev
            # The host sync: int()/np.asarray block on the device here,
            # then likelihood.dat lines stream, progress fires (the
            # runner's journal em_ll points ride it), and checkpoints
            # land — the flight-recorder span that, with fused.py's
            # em.run_chunk dispatch span, decomposes an EM wall into
            # enqueue glue vs blocking sync (telemetry/spans.py).
            with maybe_span("em.host_sync", it=it) as sp:
                steps = int(res.steps_done)
                if sp is not None and hasattr(sp, "annotate"):
                    sp.annotate(steps=steps)
                host_conv = None
                for ll in np.asarray(res.lls[:steps], np.float64):
                    it += 1
                    ll = float(ll)
                    host_conv = self._log_iteration(
                        it, ll, ll_prev, likelihoods, ll_file, progress
                    )
                    ll_prev = ll
                self._maybe_checkpoint(
                    checkpoint_path, log_beta, alpha, it, likelihoods
                )
            if steps == 0:
                break
            # float64 conv (what likelihood.dat records) decides the stop;
            # res.converged only ends a chunk early.  Near em_tol the
            # compute-dtype device check can disagree by ~1 ulp — if it
            # stopped but float64 says not converged, keep iterating.
            if host_conv is not None and host_conv < cfg.em_tol:
                break

        if current_recorder() is not None and n_disp:
            # The EM roofline record: the chunk program's harvested
            # per-dispatch cost (fused.py's runner wrapper registers it
            # at first instrumented dispatch) joined with the loop's
            # monotonic wall — enqueue glue AND blocking host syncs, the
            # whole EM phase.  Journaled as {"kind": "roofline"}; on
            # backends with registered peaks the record carries
            # mxu_pct/hbm_pct, elsewhere `utilization: null`.
            from ..telemetry import roofline

            roofline.emit(
                "em.run_chunk", (now_ns() - t_loop0) / 1e9,
                dispatches=n_disp, em_iters=it - start_it,
                chunk=self._em_chunk,
            )

        if res is not None and int(res.steps_done) > 0:
            for g_arr, slots in zip(res.gammas, groups.batch_slots):
                g_group = to_host(g_arr, self.mesh)  # one transfer per group
                for j, bi in enumerate(slots):
                    b = batches[bi]
                    sel = b.doc_mask == 1
                    gamma_out[b.doc_index[sel]] = g_group[j][sel]
        return log_beta, alpha, it


def warm_start_log_beta(
    topic_probs: np.ndarray, num_terms: int
) -> np.ndarray:
    """[V0, K] p(word|topic) from a previous fit -> a [K, num_terms]
    log-beta EM init padded for vocabulary growth.

    Day N's window contains words day N−1 never saw; its beta needs a
    row for each.  New words get one symmetric-prior quantum of mass
    (1/num_terms — what a uniform Dirichlet prior would put there) and
    every topic renormalizes, so the previous topics carry over almost
    unchanged while unseen words start at small-but-trainable mass
    rather than the LOG_ZERO floor (a floored word could never grow
    back under the multiplicative fixed point).  Shrinking the
    vocabulary is refused: global word ids are first-seen-stable, so a
    smaller V means the caller mixed id spaces."""
    p = np.asarray(topic_probs, np.float64)
    if p.ndim != 2:
        raise ValueError(f"topic_probs must be [V, K], got {p.shape}")
    v0, k = p.shape
    if num_terms < v0:
        raise ValueError(
            f"vocabulary cannot shrink: previous topics cover {v0} "
            f"words, new corpus has {num_terms} — window word ids are "
            "first-seen-stable, so a smaller V means mixed id spaces"
        )
    if not np.isfinite(p).all() or (p < 0).any():
        raise ValueError("topic_probs must be finite and nonnegative")
    prior = 1.0 / max(num_terms, 1)
    full = np.concatenate(
        [p, np.full((num_terms - v0, k), prior, np.float64)], axis=0
    )
    full = full / np.maximum(full.sum(axis=0, keepdims=True), 1e-300)
    beta = full.T  # [K, num_terms]
    return np.where(
        beta > 0, np.log(np.maximum(beta, 1e-300)), estep.LOG_ZERO
    )


class WindowTrainer:
    """Shape-stable, warm-startable EM driver for continuous window
    refreshes (runner/continuous.py; ROADMAP item 3).

    One instance lives for the window's whole vocabulary capacity tier
    and is reused refresh-over-refresh: the jitted E/M programs hang
    off the inner LDATrainer, so window N+1 re-dispatches the programs
    window N traced — with the window's pow2 vocab padding and the
    full-batch-size bucket padding below, a drifting doc census never
    changes a compiled shape.  Batches always pad to the FULL batch
    size (make_batches' default padding, not the pipeline's
    multiple-of-8 tail padding) for exactly that reason.

    `fit()` seeds EM from the previous refresh's topics
    (warm_start_log_beta pads for vocabulary growth) when given them;
    the existing float64 convergence check then early-exits after the
    few iterations the stream actually moved — the warm-start-vs-fresh
    trade the streaming_freshness bench measures.

    With a `collective` (parallel/allreduce.py) the refresh trains
    DISTRIBUTED: the warm-start seed broadcasts from the coordinator
    (rank-identical topics even when only rank 0 holds the publish
    history), documents shard by the PR 11 plan, the local E-steps
    reduce through the collective, and — because a standing service
    refits the SAME trainer forever — the per-shard batch census pads
    to power-of-two counts (`pad_batch_census_pow2`) so the stacked
    [NB, B, L] group shapes stay compiled-stable while the window's
    doc count wobbles.  `yield_hook` threads through to the EM driver
    (see LDATrainer) so refresh fits are preemptible by a co-resident
    serving plane."""

    def __init__(self, config: LDAConfig, num_terms: int, *,
                 collective=None, yield_hook=None) -> None:
        self.config = config
        self.num_terms = num_terms
        self.collective = collective
        self._trainer = LDATrainer(
            config, num_terms=num_terms, collective=collective,
            yield_hook=yield_hook,
        )
        self.fits = 0

    def fit(
        self,
        corpus: Corpus,
        *,
        topic_probs: "np.ndarray | None" = None,
        alpha: "float | None" = None,
        progress: "Callable | None" = None,
    ) -> LDAResult:
        """One window refresh: corpus -> LDAResult.  With
        `topic_probs` (the previous published [V_prev, K] matrix), EM
        warm-starts from them (rows padded for vocab growth) and
        `alpha` seeds the Newton; without, the reference's random
        init.  `result.plan["warm_start"]` records which path ran."""
        cfg = self.config
        if corpus.num_terms != self.num_terms:
            raise ValueError(
                f"window corpus has V={corpus.num_terms} but this "
                f"trainer's capacity tier is {self.num_terms} — "
                "rebuild the trainer at the new tier (one program "
                "family per tier, by design)"
            )
        if self.collective is not None:
            # Rank-identical warm start: the coordinator's seed is THE
            # seed (only it holds the drift-gated publish history);
            # every rank trains from the broadcast copy.  The tag keys
            # on the fit count, which advances in lockstep.
            topic_probs, alpha = self.collective.broadcast_obj(
                (topic_probs, alpha) if self.collective.rank == 0
                else None,
                f"window_seed{self.fits}",
            )
        warm = topic_probs is not None
        init_lb = (
            warm_start_log_beta(topic_probs, self.num_terms)
            if warm else None
        )
        if self.collective is not None:
            batches, num_docs = self._shard_window(corpus)
        else:
            batches = make_batches(
                corpus, batch_size=cfg.batch_size,
                min_bucket_len=cfg.min_bucket_len,
            )
            num_docs = corpus.num_docs
        result = self._trainer.fit(
            batches,
            num_docs,
            progress=progress,
            initial_log_beta=init_lb,
            initial_alpha=alpha if warm else None,
        )
        self.fits += 1
        result.plan["warm_start"] = {
            "value": bool(warm), "source": "window"
        }
        if self.collective is not None:
            result.plan["em_shards"] = {
                "value": self._trainer.shard_plan.num_shards,
                "source": "window",
            }
            result.plan["allreduce"] = {
                "transport": self.collective.transport,
                "nprocs": self.collective.num_processes,
            }
        return result

    def _shard_window(self, corpus: Corpus):
        """Per-refresh shard plan + batches for the distributed driver.
        The plan re-derives from the window's live doc count every
        refresh (documents churn), but the trainer — and its jitted
        partial-stats program — is REUSED: shard batches are plain
        attributes on LDATrainer, and the census padding below keeps
        the stacked group shapes the cached program was traced at."""
        from ..parallel.shard_plan import plan_shards, resolve_em_shards

        cfg = self.config
        coll = self.collective
        plan = plan_shards(
            corpus.num_docs, coll.num_processes,
            resolve_em_shards(cfg.em_shards, coll.num_processes),
        )
        shard_batches = {
            s: pad_batch_census_pow2([
                Batch(b.word_idx, b.counts,
                      b.doc_index + plan.bounds[s][0], b.doc_mask)
                for b in make_batches(
                    corpus.shard(*plan.bounds[s]),
                    batch_size=cfg.batch_size,
                    min_bucket_len=cfg.min_bucket_len,
                )
            ])
            for s in plan.owned(coll.rank)
        }
        self._trainer.shard_plan = plan
        self._trainer._shard_batches = shard_batches
        return (
            [b for s in sorted(shard_batches)
             for b in shard_batches[s]],
            corpus.num_docs,
        )


def pad_batch_census_pow2(batches: "list[Batch]") -> "list[Batch]":
    """Pad each (B, L)-shaped batch group's COUNT to a power of two
    with fully-masked empty batches.

    The window's vocab pads to pow2 capacity tiers and its batches pad
    to the full batch size, but the distributed driver stacks same-
    shaped batches into [NB, B, L] groups — and NB is the one shape
    left keyed on the raw doc census, so a window gaining one batch
    would retrace the partial-stats program.  Census tiers close the
    gap: NB pads to pow2 exactly like the vocabulary does.  A pad
    batch is inert by the same mechanism as in-batch pad rows —
    doc_mask 0 zeroes its suff-stats/likelihood contributions, and the
    gamma scatter selects no rows (doc_index 0 is never read)."""
    groups: "dict[tuple, list[Batch]]" = {}
    order: list = []
    for b in batches:
        key = b.word_idx.shape
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(b)
    out: "list[Batch]" = []
    for key in order:
        grp = groups[key]
        target = 1
        while target < len(grp):
            target *= 2
        bb, ll = key
        for _ in range(target - len(grp)):
            grp.append(Batch(
                np.zeros((bb, ll), np.int32),
                np.zeros((bb, ll), np.float32),
                np.zeros((bb,), np.int32),
                np.zeros((bb,), np.float32),
            ))
        out.extend(grp)
    return out


def resolve_estep_engine(
    corpus: Corpus, config: LDAConfig, mesh=None, vocab_sharded: bool = False,
    distributed: bool = False, shard_plan=None,
) -> "tuple[str, str]":
    """Resolve the E-step engine FAMILY for a batch training run:
    ("sparse" | "dense", source).

    "sparse" is the fused bucketed Pallas engine (ops/sparse_estep.py:
    corpus packed by Corpus.bucketed_layout, K×L work per doc);
    "dense" is everything that exists today — the dense/compact/XLA/
    Pallas family whose internal gates (_use_dense, _plan_compact,
    estep.e_step auto) are unchanged.  Precedence mirrors the rest of
    the plan layer: ONI_ML_TPU_ESTEP env ("env") > an explicit
    LDAConfig.estep_engine ("config") > the MEASURED dense-vs-sparse
    crossover from the plan cache (sparse_estep.engine_crossover —
    source "plan" when a persisted entry serves, "measured" when this
    run sweeps it once) on TPU, else the dense family ("default").

    The sparse engine is single-process PER RANK — a mesh whose data
    axis would shard its layout still takes the dense family, and
    forcing sparse there is an error, not a silent fallback.  But
    `distributed=True` (host-local E-step shards + explicit allreduce,
    parallel/allreduce.py) IS a set of single-process programs: with no
    local mesh the sparse engine is fully allowed, feasibility is
    checked over every shard's bucket shapes (`shard_plan`), and the
    crossover is consulted at the dominant LOCAL shard shape — the
    shapes the kernel will actually see, which per-shard batching makes
    smaller than the whole-corpus shapes."""
    env = os.environ.get("ONI_ML_TPU_ESTEP", "")
    choice = config.estep_engine
    if choice not in ("auto", "dense", "sparse"):
        raise ValueError(
            f"LDAConfig.estep_engine={choice!r}: expected 'auto', "
            "'dense', or 'sparse'"
        )
    forced_sparse = env == "sparse" or (not env and choice == "sparse")
    if mesh is not None or vocab_sharded:
        if forced_sparse:
            raise ValueError(
                "the sparse bucketed E-step engine is single-process; "
                "meshes keep the sharded dense/sparse plans "
                "(unset ONI_ML_TPU_ESTEP=sparse / estep_engine='sparse'"
                + (" or drop the local mesh — distributed EM runs the "
                   "sparse engine host-locally without one)"
                   if distributed else ")")
            )
        return "dense", "default"
    if forced_sparse and config.dense_em == "on":
        raise ValueError(
            "estep_engine='sparse' conflicts with dense_em='on' — pin "
            "one engine family, not both"
        )
    if env:
        return ("sparse", "env") if env == "sparse" else ("dense", "env")
    if choice != "auto":
        return choice, "config"
    if jax.default_backend() != "tpu" or config.dense_em == "on":
        # CPU/interpret runs keep today's paths (the dense family's
        # auto already resolves to XLA there); dense_em="on" is an
        # explicit family pin.
        return "dense", "default"
    from ..ops import sparse_estep

    l_len, _ = sparse_estep.resolve_layout_len(
        config.sparse_min_bucket_len, use_plans=not distributed
    )
    # Shapes only — the O(tokens) packing pass is deferred to
    # train_corpus's sparse branch, so a dense-winning crossover never
    # pays for (or keeps cached) padded tiles it won't train on.
    # Distributed runs derive them per SHARD: each shard buckets
    # independently, so the engine must be feasible for every shard's
    # shapes and the crossover keys on the shapes a rank actually
    # dispatches.
    pieces = (
        [corpus.shard(st, en) for st, en in shard_plan.bounds]
        if distributed and shard_plan is not None
        else [corpus]
    )
    shapes = [
        s
        for piece in pieces
        for s in piece.bucket_shapes(
            min_len=l_len, batch_cap=config.batch_size,
            pad_multiple=sparse_estep.pad_multiple_for(
                config.dense_precision
            ),
        )
    ]
    if not shapes:
        return "dense", "default"
    # EVERY bucket shape must admit a block — the VMEM-worst bucket is
    # typically a small-B huge-L one, not the largest batch.
    if any(
        sparse_estep.pick_block(
            bb, ll, config.num_topics, config.dense_precision
        ) is None
        for bb, ll, _ in shapes
    ):
        return "dense", "default"
    b_dom, l_dom, _ = max(shapes, key=lambda s: s[2])
    cross = sparse_estep.engine_crossover(
        config.num_topics, corpus.num_terms, b_dom, l_dom,
        precision=config.dense_precision,
    )
    return cross["engine"], cross["source"]


def train_corpus(
    corpus: Corpus,
    config: LDAConfig,
    out_dir: str | None = None,
    progress: Callable[[int, float, float], None] | None = None,
    mesh=None,
    vocab_sharded: bool = False,
    save_final: bool = True,
    distributed: "bool | None" = None,
    collective=None,
) -> LDAResult:
    """Convenience: corpus -> batches -> fit -> (optionally) reference
    output files in `out_dir`.

    With `mesh`, documents shard over the mesh's `data` axis (suff-stats
    psum — the reference's MPI_Reduce, SURVEY §2.8); with
    `vocab_sharded` additionally, beta/suff-stats shard their vocabulary
    axis over `model` (BASELINE.json config 4).  Since the distributed
    restructure the mesh must be HOST-LOCAL (parallel.local_mesh): one
    global SPMD program spanning processes is not a thing this trainer
    builds any more (the CPU runtime cannot execute it, and it forced
    the sparse engine dense).

    `distributed` (default: auto — `jax.process_count() > 1`) switches
    to pod-scale EM: every rank receives the SAME full corpus, trains
    only its document shards host-locally (parallel/shard_plan.py),
    and the sufficient statistics cross processes through the explicit
    allreduce (parallel/allreduce.py).  Also runnable single-process
    (the byte-identity baseline, bench distributed_em, the MULTICHIP
    dryrun topology plans).

    `save_final=False` keeps likelihood.dat streaming and checkpoint
    resume (both keyed off `out_dir`) but skips the final.* writes —
    the streaming dataplane demotes those to background checkpoint
    sinks that overlap scoring, so the trainer must not also write
    them inline on the critical path.
    """
    if distributed is None:
        distributed = jax.process_count() > 1
    if distributed:
        return _train_corpus_distributed(
            corpus, config, out_dir=out_dir, progress=progress,
            mesh=mesh, vocab_sharded=vocab_sharded,
            save_final=save_final, collective=collective,
        )
    e_fn = m_fn = None
    num_terms = corpus.num_terms
    initial_log_beta = None
    if vocab_sharded and mesh is None:
        raise ValueError("vocab_sharded=True requires a mesh")
    engine, engine_src = resolve_estep_engine(
        corpus, config, mesh=mesh, vocab_sharded=vocab_sharded
    )
    sparse_layout = None
    sparse_l_record = None
    if engine == "sparse":
        from ..ops import sparse_estep

        sparse_l, sparse_l_src = sparse_estep.resolve_layout_len(
            config.sparse_min_bucket_len
        )
        sparse_l_record = {"value": sparse_l, "source": sparse_l_src}
        # The batch axis pads to the engine precision's sublane tile
        # (16 for bf16) so every bucket's padded doc count admits a
        # kernel block; a forced-sparse run whose shapes still cannot
        # block fails HERE with the shapes named, not mid-training
        # inside the chunk program.
        pad = sparse_estep.pad_multiple_for(config.dense_precision)
        bad = [
            (bb, ll)
            for bb, ll, _ in corpus.bucket_shapes(
                min_len=sparse_l, batch_cap=config.batch_size,
                pad_multiple=pad,
            )
            if sparse_estep.pick_block(
                bb, ll, config.num_topics, config.dense_precision
            ) is None
        ]
        if bad:
            raise ValueError(
                f"sparse E-step engine selected but bucket shapes {bad} "
                "admit no VMEM-feasible doc block at precision "
                f"{config.dense_precision!r} (K={config.num_topics}); "
                "use the dense family for this corpus"
            )
        sparse_layout = corpus.bucketed_layout(
            min_len=sparse_l, batch_cap=config.batch_size,
            pad_multiple=pad,
        )
        e_fn = sparse_estep.make_e_step_fn(precision=config.dense_precision)
    data_size = 1
    if mesh is not None:
        e_fn, m_fn, num_terms, initial_log_beta, data_size = (
            _mesh_trainer_setup(corpus, config, mesh, vocab_sharded)
        )

    if sparse_layout is not None:
        # The sparse engine trains over the bucketed layout's packed
        # tiles; Batch.doc_index carries the permutation, so fit()'s
        # gamma scatter restores document order bit-exactly
        # (layout.inv_perm is the same map, pinned by tests).
        batches = list(sparse_layout.batches)
    else:
        batches = make_batches(
            corpus, batch_size=config.batch_size,
            min_bucket_len=config.min_bucket_len,
            pad_multiple=data_size if mesh is not None else 8,
        )
    trainer = LDATrainer(
        config,
        num_terms=num_terms,
        e_step_fn=e_fn,
        m_step_fn=m_fn,
        mesh=mesh,
        vocab_sharded=vocab_sharded,
    )
    ll_path = os.path.join(out_dir, "likelihood.dat") if out_dir else None
    ckpt_path = (
        os.path.join(out_dir, "checkpoint.npz")
        if out_dir and config.checkpoint_every
        else None
    )
    result = trainer.fit(
        batches,
        corpus.num_docs,
        likelihood_file=ll_path,
        progress=progress,
        initial_log_beta=initial_log_beta,
        checkpoint_path=ckpt_path,
    )
    # Engine attribution rides the same plan record every other
    # resolved knob does (stage records surface it per run).
    result.plan["estep_engine"] = {"value": engine, "source": engine_src}
    if sparse_l_record is not None:
        result.plan["sparse_estep_l"] = sparse_l_record
    if num_terms != corpus.num_terms:
        result.log_beta = result.log_beta[:, : corpus.num_terms]
    if out_dir and save_final and _is_coordinator():
        # likelihood.dat was already streamed (crash-safe) during fit;
        # multi-host: the result is identical on every process (to_host
        # gathers collectively) but only the coordinator owns the files.
        result.save(out_dir, num_terms=corpus.num_terms, include_likelihood=False)
    return result


def _mesh_trainer_setup(corpus: Corpus, config: LDAConfig, mesh,
                        vocab_sharded: bool):
    """Shared mesh-path trainer setup for train_corpus AND the
    distributed variant (one copy of the divisibility check, the
    idle-model-axis warning, and the e_fn/m_fn selection):
    (e_fn, m_fn, num_terms, initial_log_beta, data_size)."""
    from ..parallel import sharded
    from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

    if config.batch_size % mesh.shape[DATA_AXIS]:
        # fit() re-checks per batch; failing here gives the clearer
        # message before any batching work happens.
        raise ValueError(
            f"batch_size {config.batch_size} not divisible by data axis "
            f"{mesh.shape[DATA_AXIS]}"
        )
    if not vocab_sharded and mesh.shape[MODEL_AXIS] > 1:
        import warnings

        warnings.warn(
            f"mesh has model axis {mesh.shape[MODEL_AXIS]} but "
            "vocab_sharded=False: those devices will replicate work",
            stacklevel=3,
        )
    if vocab_sharded:
        e_fn, m_fn, num_terms, initial_log_beta = _vocab_sharded_setup(
            corpus, config, mesh
        )
    else:
        e_fn = sharded.make_data_parallel_e_step(mesh)
        m_fn = None
        num_terms = corpus.num_terms
        initial_log_beta = None
    return e_fn, m_fn, num_terms, initial_log_beta, mesh.shape[DATA_AXIS]


def _vocab_sharded_setup(corpus: Corpus, config: LDAConfig, mesh):
    """(e_fn, m_fn, padded num_terms, initial_log_beta) for a
    vocab-sharded trainer: the shard_map'd E/M pair with the vocabulary
    padded to the mesh's model axis, the init padded with LOG_ZERO
    columns so padded words carry ~no mass and single- vs multi-device
    runs agree numerically."""
    from ..parallel import sharded
    from ..parallel.mesh import MODEL_AXIS

    e_fn, m_fn = sharded.make_vocab_sharded_fns(mesh)
    num_terms = sharded.pad_vocab(corpus.num_terms, mesh.shape[MODEL_AXIS])
    initial_log_beta = None
    if num_terms != corpus.num_terms:
        base = init_log_beta(
            jax.random.PRNGKey(config.seed),
            config.num_topics,
            corpus.num_terms,
            jnp.dtype(config.compute_dtype),
        )
        initial_log_beta = jnp.pad(
            base,
            ((0, 0), (0, num_terms - corpus.num_terms)),
            constant_values=estep.LOG_ZERO,
        )
    return e_fn, m_fn, num_terms, initial_log_beta


def _train_corpus_distributed(
    corpus: Corpus,
    config: LDAConfig,
    out_dir: str | None = None,
    progress: Callable[[int, float, float], None] | None = None,
    mesh=None,
    vocab_sharded: bool = False,
    save_final: bool = True,
    collective=None,
) -> LDAResult:
    """Pod-scale distributed EM (ROADMAP item 1): host-local E-step
    shards + explicit sufficient-statistics allreduce.

    Every rank holds the SAME full corpus (stage_corpus's shared
    model.dat, or the in-memory corpus single-process) and the same
    deterministic shard plan; each trains only its owned contiguous
    document shards on its own devices — including the PR 9 sparse
    Pallas engine over a per-shard bucketed layout — and the [V, K]
    beta factor, alpha suff-stats, and ELBO scalar cross processes
    through parallel/allreduce.  The M-step, alpha Newton, convergence
    check, and likelihood journal then run identically on every rank
    from the reduced stats (parity asserted), so the LDAResult is
    rank-identical and the coordinator alone writes the shared files.

    The engine decision is made ONCE on the coordinator (crossover
    consulted at the local shard shapes, plan lookups per-host) and
    broadcast, so ranks can never train under different engines."""
    from ..parallel.allreduce import PeerFailure, get_collective
    from ..parallel.mesh import is_local_mesh
    from ..parallel.shard_plan import plan_shards, resolve_em_shards

    coll = collective if collective is not None else get_collective()
    if mesh is not None and not is_local_mesh(mesh):
        raise ValueError(
            "distributed EM is host-local: the mesh may span this "
            "process's devices only (parallel.local_mesh()); the "
            "cross-process reduction is the explicit suff-stats "
            "allreduce, not a global mesh spanning processes"
        )
    if vocab_sharded and mesh is None:
        raise ValueError("vocab_sharded=True requires a mesh")
    nshards = resolve_em_shards(config.em_shards, coll.num_processes)
    plan = plan_shards(corpus.num_docs, coll.num_processes, nshards)
    # One engine for the whole process group: the coordinator resolves
    # (its plan cache, its crossover measurement at the local shard
    # shapes) and broadcasts — per-host plan caches may legally
    # disagree, and rank-divergent engines would silently break the
    # cross-rank-count byte-identity contract.
    if coll.rank == 0:
        try:
            decision = resolve_estep_engine(
                corpus, config, mesh=mesh, vocab_sharded=vocab_sharded,
                distributed=True, shard_plan=plan,
            )
        except BaseException as e:
            # Library-level relay (the runner's stage barrier is not in
            # play for direct train_corpus callers): without this, a
            # coordinator-only config error leaves every peer blocked
            # in the broadcast for the full collective timeout with a
            # misleading "peer stalled or died" message.
            coll.fail(f"estep engine resolution: {e!r}")
            raise
    else:
        decision = None
    engine, engine_src = coll.broadcast_obj(decision, "estep_engine")

    e_fn = m_fn = None
    num_terms = corpus.num_terms
    initial_log_beta = None
    sparse_l_record = None
    owned = plan.owned(coll.rank)
    shard_corpora = {s: corpus.shard(*plan.bounds[s]) for s in owned}
    data_size = 1
    if mesh is not None:
        e_fn, m_fn, num_terms, initial_log_beta, data_size = (
            _mesh_trainer_setup(corpus, config, mesh, vocab_sharded)
        )

    if engine == "sparse":
        from ..ops import sparse_estep

        # ALWAYS plans-off in distributed mode (matching the engine
        # resolution's use_plans=not distributed): a measured
        # sparse_estep_l serving only at some rank counts would give
        # the 1-rank and N-rank runs different bucketed layouts —
        # breaking the byte-identical-artifacts contract — and train at
        # a different L than the coordinator's feasibility/crossover
        # checks keyed on.
        sparse_l, sparse_l_src = sparse_estep.resolve_layout_len(
            config.sparse_min_bucket_len, use_plans=False,
        )
        sparse_l_record = {"value": sparse_l, "source": sparse_l_src}
        pad = sparse_estep.pad_multiple_for(config.dense_precision)
        # Feasibility over EVERY shard of the GLOBAL plan (not just the
        # owned ones): the engine decision must be a function of the
        # plan alone so every rank count trains the same shards the
        # same way; a forced-sparse corpus whose shard shapes cannot
        # block fails HERE with the shapes named.
        bad = [
            (bb, ll)
            for st, en in plan.bounds
            for bb, ll, _ in corpus.shard(st, en).bucket_shapes(
                min_len=sparse_l, batch_cap=config.batch_size,
                pad_multiple=pad,
            )
            if sparse_estep.pick_block(
                bb, ll, config.num_topics, config.dense_precision
            ) is None
        ]
        if bad:
            raise ValueError(
                f"sparse E-step engine selected but shard bucket shapes "
                f"{bad} admit no VMEM-feasible doc block at precision "
                f"{config.dense_precision!r} (K={config.num_topics}); "
                "use the dense family for this corpus"
            )
        e_fn = sparse_estep.make_e_step_fn(precision=config.dense_precision)
        shard_batches = {
            s: [
                Batch(b.word_idx, b.counts,
                      b.doc_index + plan.bounds[s][0], b.doc_mask)
                for b in sc.bucketed_layout(
                    min_len=sparse_l, batch_cap=config.batch_size,
                    pad_multiple=pad,
                ).batches
            ]
            for s, sc in shard_corpora.items()
        }
    else:
        shard_batches = {
            s: [
                Batch(b.word_idx, b.counts,
                      b.doc_index + plan.bounds[s][0], b.doc_mask)
                for b in make_batches(
                    sc, batch_size=config.batch_size,
                    min_bucket_len=config.min_bucket_len,
                    pad_multiple=data_size if mesh is not None else 8,
                )
            ]
            for s, sc in shard_corpora.items()
        }

    trainer = LDATrainer(
        config,
        num_terms=num_terms,
        e_step_fn=e_fn,
        m_step_fn=m_fn,
        mesh=mesh,
        vocab_sharded=vocab_sharded,
        collective=coll,
        shard_plan=plan,
        shard_batches=shard_batches,
    )
    ll_path = os.path.join(out_dir, "likelihood.dat") if out_dir else None
    ckpt_path = (
        os.path.join(out_dir, "checkpoint.npz")
        if out_dir and config.checkpoint_every
        else None
    )
    rec = current_recorder()
    if rec is not None:
        # The journaled shard plan: enough to reconstruct the exact
        # split this run trained under ({"kind": "shard_plan"}).
        rec.journal_record(plan.record(coll.rank))
    ar0 = dict(coll.stats)
    flat = [b for s in sorted(shard_batches) for b in shard_batches[s]]
    try:
        result = trainer.fit(
            flat,
            corpus.num_docs,
            likelihood_file=ll_path,
            progress=progress,
            initial_log_beta=initial_log_beta,
            checkpoint_path=ckpt_path,
        )
    except PeerFailure:
        raise          # already relayed by whoever actually failed
    except BaseException as e:
        # Same library-level relay for mid-fit failures (a rank's OOM
        # or IO error): peers stuck in the next iteration's allreduce
        # see the key within one poll slice instead of the timeout.
        coll.fail(f"distributed fit rank {coll.rank}: {e!r}")
        raise
    result.plan["estep_engine"] = {"value": engine, "source": engine_src}
    if sparse_l_record is not None:
        result.plan["sparse_estep_l"] = sparse_l_record
    # Provenance mirrors resolve_em_shards' precedence: env beats
    # config beats the auto default.
    result.plan["em_shards"] = {
        "value": plan.num_shards,
        "source": (
            "env" if os.environ.get("ONI_ML_TPU_EM_SHARDS")
            else "config" if config.em_shards else "default"
        ),
    }
    d = coll.stats
    result.plan["allreduce"] = {
        "transport": coll.transport,
        # APPLIED precision — the Collective's own rule, so this
        # provenance can never disagree with what the data-plane ops
        # journaled (psum/local/1-process runs never compress).
        "precision": coll.applied_precision(
            os.environ.get("ONI_ML_TPU_ALLREDUCE_PRECISION", "")
            or config.allreduce_precision
        ),
        "nprocs": coll.num_processes,
        "ops": d["ops"] - ar0["ops"],
        "bytes_out": d["bytes_out"] - ar0["bytes_out"],
        "bytes_in": d["bytes_in"] - ar0["bytes_in"],
        "wall_s": round(d["wall_s"] - ar0["wall_s"], 6),
    }
    if num_terms != corpus.num_terms:
        result.log_beta = result.log_beta[:, : corpus.num_terms]
    if out_dir and save_final and _is_coordinator():
        result.save(out_dir, num_terms=corpus.num_terms,
                    include_likelihood=False)
    return result
