"""Online (streaming) variational LDA — BASELINE.json config 5.

The reference engine is strictly batch: one day of netflow becomes one
corpus, EM runs to convergence, done (ml_ops.sh:80; SURVEY.md §2.8).  For
hourly micro-batches that design re-trains from scratch every hour.  This
module adds the streaming alternative: stochastic variational inference
(Hoffman, Blei, Bach, "Online Learning for Latent Dirichlet Allocation",
NIPS 2010 — see PAPERS.md), where each micro-batch performs one
natural-gradient step on a variational Dirichlet posterior lambda [K, V]
over the topics:

    rho_t   = (tau0 + t)^(-kappa)
    lambda <- (1 - rho_t) lambda + rho_t (eta + D/|S_t| * suff_stats_t)

The per-document local step is *identical math* to the batch E-step
(ops/estep.py): Hoffman's update uses exp(E_q[log beta]) everywhere the
batch algorithm uses beta, so we simply feed ``E_q[log beta]`` (digamma
form) to ``e_step`` — no duplicated inner loop, and the same Pallas/
sharded substitutions apply.

TPU notes: the whole update (E-step fixed point + scatter + blend) is one
jitted program per (B, L) shape; lambda lives on device across the stream
so each micro-batch moves only its own tokens over PCIe/ICI.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from typing import Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import OnlineLDAConfig
from ..io import Batch
from ..ops import estep
from ..ops.estep import e_log_dirichlet as expected_log_beta
from .lda import LDAResult


def save_stream_checkpoint(
    path: str,
    lam: np.ndarray,
    alpha: float,
    step: int,
    history: list[tuple[float, float]],
) -> None:
    """Atomic streaming checkpoint with SVI-native field names: `lam`
    (the variational Dirichlet posterior over topics — NOT a log beta),
    `step` (micro-batch count), `history` rows of (likelihood, rho).
    Early revisions smuggled these through the batch checkpoint's
    log_beta/em_iter/likelihoods fields; load_stream_checkpoint still
    reads that layout."""
    tmp = path + ".tmp.npz"  # savez appends nothing to an .npz name
    np.savez(
        tmp,
        lam=np.asarray(lam),
        alpha=np.float64(alpha),
        step=np.int64(step),
        history=np.asarray(history, np.float64).reshape(-1, 2),
    )
    os.replace(tmp, path)


def load_stream_checkpoint(path: str) -> dict:
    with np.load(path) as z:
        if "lam" in z.files:
            return {
                "lam": z["lam"],
                "alpha": float(z["alpha"]),
                "step": int(z["step"]),
                "history": [tuple(row) for row in z["history"]],
            }
        # Legacy layout (batch-checkpoint field names smuggling lambda).
        # A real batch EM checkpoint shares these field names AND the
        # (K, V) shape but holds log-probabilities (all <= 0), while a
        # variational lambda is strictly positive Dirichlet parameters —
        # reject it instead of streaming NaN topics out of digamma.
        lam = z["log_beta"]
        if not (lam > 0).all():
            raise ValueError(
                f"{path} is a batch EM checkpoint (log_beta has "
                "non-positive entries), not a streaming-LDA checkpoint; "
                "resume it with the batch trainer or remove it"
            )
        return {
            "lam": lam,
            "alpha": float(z["alpha"]),
            "step": int(z["em_iter"]),
            "history": [tuple(row) for row in z["likelihoods"]],
        }


@dataclass
class StreamStepInfo:
    step: int
    rho: float
    batch_docs: int
    # ELBO local term over the micro-batch.  Kept as a DEVICE scalar so the
    # streaming hot path never blocks on a host sync between micro-batches;
    # float(info.likelihood) materializes it on demand.
    likelihood: "jnp.ndarray"
    tokens: int

    @property
    def per_token_ll(self) -> float:
        return float(self.likelihood) / max(self.tokens, 1)


class OnlineLDATrainer:
    """Streaming natural-gradient LDA over padded micro-batches.

    ``total_docs`` is the population size D the stream is drawn from (for
    the reference pipelines: the expected number of active IPs in the
    window being modeled).  It scales each micro-batch's sufficient
    statistics to a full-corpus estimate; a too-small D under-weights new
    evidence but never destabilizes the update.

    With a ``mesh``, micro-batches shard over its `data` axis and the
    suff-stats psum over ICI (the shard_map'd E-step from
    oni_ml_tpu/parallel); lambda replicates.  Vocab sharding is a batch-
    only feature for now — the natural-gradient blend wants the full
    lambda row normalizer every step.  The ``e_step_fn`` hook still
    allows arbitrary substitution, exactly as in the batch trainer.
    """

    def __init__(
        self,
        config: OnlineLDAConfig,
        num_terms: int,
        total_docs: int,
        e_step_fn: Callable | None = None,
        mesh=None,
        checkpoint_path: str | None = None,
        collective=None,
        distributed: "bool | None" = None,
    ):
        self.config = config
        self.num_terms = num_terms
        self.total_docs = total_docs
        self.mesh = mesh
        self.checkpoint_path = checkpoint_path
        self.step_count = 0
        self.history: list[StreamStepInfo] = []
        dtype = jnp.dtype(config.compute_dtype)

        # Distributed streaming (parallel/allreduce.py): each rank runs
        # the local E-step on its contiguous row slice of EVERY
        # micro-batch and the suff-stats allreduce feeds the identical
        # natural-gradient blend on every rank — the host-local
        # restructure of the old global-mesh data sharding, which the
        # CPU runtime could not execute at all.  lambda stays
        # rank-identical (asserted by the multihost suite).
        if distributed is None:
            distributed = jax.process_count() > 1
        self._coll = None
        if distributed:
            from ..parallel.allreduce import get_collective
            from ..parallel.mesh import is_local_mesh

            if mesh is not None and not is_local_mesh(mesh):
                raise ValueError(
                    "distributed streaming LDA is host-local: the mesh "
                    "may span this process's devices only "
                    "(parallel.local_mesh())"
                )
            self._coll = (
                collective if collective is not None else get_collective()
            )

        if mesh is not None and e_step_fn is None:
            from ..parallel.mesh import MODEL_AXIS
            from ..parallel.sharded import make_data_parallel_e_step

            if mesh.shape[MODEL_AXIS] > 1:
                raise ValueError(
                    "online LDA supports data-parallel meshes only; "
                    f"got model axis {mesh.shape[MODEL_AXIS]}"
                )
            e_step_fn = make_data_parallel_e_step(mesh)

        # Hoffman's init: lambda ~ Gamma(100, 1/100) per entry.
        key = jax.random.PRNGKey(config.seed)
        self._lam = jax.random.gamma(
            key, 100.0, (config.num_topics, num_terms), dtype
        ) / 100.0
        self._alpha = jnp.asarray(config.alpha, dtype)
        if checkpoint_path is not None and os.path.exists(checkpoint_path):
            ckpt = load_stream_checkpoint(checkpoint_path)
            if ckpt["lam"].shape != self._lam.shape:
                raise ValueError(
                    f"checkpoint lambda shape {ckpt['lam'].shape} does "
                    f"not match ({config.num_topics}, {num_terms})"
                )
            self._lam = jnp.asarray(ckpt["lam"], dtype)
            self.step_count = ckpt["step"]
            self.history = [
                StreamStepInfo(step=i + 1, rho=rho, batch_docs=0,
                               likelihood=jnp.asarray(ll, dtype), tokens=0)
                for i, (ll, rho) in enumerate(ckpt["history"])
            ]
        if mesh is not None:
            from ..parallel.mesh import replicated

            self._lam = jax.device_put(self._lam, replicated(mesh))

        if config.dense_em not in ("auto", "on", "off"):
            raise ValueError(
                f"OnlineLDAConfig.dense_em={config.dense_em!r}: expected "
                "'auto', 'on', or 'off'"
            )
        if config.dense_em == "on" and (e_step_fn is not None
                                        or mesh is not None):
            # Fail at construction, not at the first step() call: a
            # misconfigured streaming job should die before startup.
            raise ValueError(
                "dense_em='on' needs the default single-process "
                "E-step (no mesh, no custom e_step_fn)"
            )
        self._custom_e_fn = e_step_fn is not None
        base = e_step_fn or estep.e_step
        self._e_fn = partial(
            base, var_max_iters=config.var_max_iters, var_tol=config.var_tol
        )
        # One jitted update per micro-batch shape: the dense-vs-sparse
        # choice and the scoped-VMEM compiler option both depend on B,
        # which is only known when the first batch of a shape arrives.
        # LRU-bounded (see _get_update): callers should bucket/pad
        # micro-batch shapes (io.make_batches does) — naturally ragged
        # streams would otherwise accumulate one compiled program per
        # distinct (B, L) without limit.
        self._updates: dict = {}

    # Max distinct (B, L) compiled updates kept resident.  io.make_batches
    # produces one B and a handful of power-of-two L buckets, so a real
    # deployment never evicts; the bound only protects long-running jobs
    # fed un-bucketed ragged micro-batches from unbounded compile-cache
    # growth (evicting the least-recently-used program costs a recompile
    # if that shape ever returns).
    _UPDATE_CACHE_MAX = 32

    def _use_dense(self, b: int) -> bool:
        from ..ops import dense_estep

        cfg = self.config
        # dense_em='on' with a mesh/custom e_fn is rejected in __init__.
        if cfg.dense_em == "off" or self._custom_e_fn or self.mesh is not None:
            return False
        feasible = dense_estep.pick_block(b, self.num_terms,
                                          cfg.num_topics) is not None
        if cfg.dense_em == "on":
            if not feasible:
                raise ValueError(
                    f"dense_em forced but B={b}, V={self.num_terms}, "
                    f"K={cfg.num_topics} has no VMEM-feasible doc block"
                )
            return True
        return feasible and jax.default_backend() == "tpu"

    def _make_e_fn(self, b: int):
        """Per-batch-shape E-step choice: the dense MXU path when
        feasible (ops/dense_estep.py — one densify scatter per
        micro-batch instead of a beta-slab gather per fixed-point
        iteration), else the configured sparse/sharded e_fn.  Returns
        (e_fn, compiler_options)."""
        from ..ops import dense_estep

        cfg = self.config
        if not self._use_dense(b):
            return self._e_fn, None
        v, k = self.num_terms, cfg.num_topics
        _, wmajor, compiler_options = dense_estep.plan(b, v, k)

        def e_fn(elog_beta, alpha, word_idx, counts, doc_mask):
            dense = dense_estep.densify(word_idx, counts, v)
            if wmajor:
                dense = dense.T
            return dense_estep.e_step_dense(
                elog_beta, alpha, dense, doc_mask,
                cfg.var_max_iters, cfg.var_tol,
                interpret=jax.default_backend() != "tpu",
                wmajor=wmajor,
            )

        return e_fn, compiler_options

    def _cache_get(self, key):
        got = self._updates.pop(key, None)
        if got is not None:
            self._updates[key] = got      # re-insert: most recently used
        return got

    def _cache_update(self, key, jitted):
        while len(self._updates) >= self._UPDATE_CACHE_MAX:
            self._updates.pop(next(iter(self._updates)))
        self._updates[key] = jitted
        return jitted

    def _get_update(self, b: int, l: int):
        key = (b, l)
        got = self._cache_get(key)
        if got is not None:
            return got
        cfg = self.config
        total_docs = self.total_docs
        e_fn, compiler_options = self._make_e_fn(b)

        def update(lam, rho, word_idx, counts, doc_mask):
            res = e_fn(expected_log_beta(lam), self._alpha, word_idx,
                       counts, doc_mask)
            batch_docs = jnp.maximum(doc_mask.sum(), 1.0)
            lam_hat = cfg.eta + (total_docs / batch_docs) * res.suff_stats.T
            new_lam = (1.0 - rho) * lam + rho * lam_hat
            return new_lam, res.likelihood, res.gamma

        return self._cache_update(
            key, jax.jit(update, donate_argnums=(0,),
                         compiler_options=compiler_options)
        )

    def _get_update_many(self, n: int, b: int, l: int):
        """The chunked streaming program: `n` same-shape micro-batches
        as ONE jitted `lax.scan` — lambda never leaves the device
        between the scanned natural-gradient steps, and the rho
        schedule advances in-scan from the traced start step.  This is
        models/fused.py's chunking applied to SVI: through a
        remote-relay PJRT backend the per-step dispatch round-trip
        otherwise dominates streaming wall-clock."""
        key = ("many", n, b, l)
        got = self._cache_get(key)
        if got is not None:
            return got
        cfg = self.config
        total_docs = self.total_docs
        e_fn, compiler_options = self._make_e_fn(b)
        tau0, kappa, eta = cfg.tau0, cfg.kappa, cfg.eta

        def update_many(lam, t0, word_idx, counts, doc_mask):
            def body(carry, xs):
                lam, t = carry
                w, c, m = xs
                # step()'s host-side rho, evaluated on device (f32 pow
                # instead of float64 — the schedules agree to ~1e-7
                # relative).  t stays f32 bookkeeping whatever the
                # batch compute_dtype: in bf16 t + 1.0 rounds back to
                # t past 256 and the schedule would freeze.
                rho = ((tau0 + t) ** (-kappa)).astype(lam.dtype)
                res = e_fn(expected_log_beta(lam), self._alpha, w, c, m)
                batch_docs = jnp.maximum(m.sum(), 1.0)
                lam_hat = (
                    eta + (total_docs / batch_docs) * res.suff_stats.T
                )
                lam = (1.0 - rho) * lam + rho * lam_hat
                return (lam, t + 1.0), res.likelihood

            (lam, _), lls = jax.lax.scan(
                body, (lam, t0), (word_idx, counts, doc_mask)
            )
            return lam, lls

        return self._cache_update(
            key, jax.jit(update_many, donate_argnums=(0,),
                         compiler_options=compiler_options)
        )

    @classmethod
    def from_topic_probs(
        cls,
        config: OnlineLDAConfig,
        topic_probs: np.ndarray,
        total_docs: int,
        pseudo_tokens: float = 1e4,
        num_terms: "int | None" = None,
        **kwargs,
    ) -> "OnlineLDATrainer":
        """Seed the stream from an EXISTING model instead of Hoffman's
        random init: `topic_probs` is the [V, K] p(word|topic) matrix
        the batch pipeline publishes (word_results.csv columns, each
        topic summing to 1 over words).  lambda[k, v] = eta +
        pseudo_tokens * p[v, k], so E_q[beta] ≈ p for pseudo_tokens >>
        eta*V and the first natural-gradient steps REFINE the batch
        topics rather than washing them out (rho at t=0 is already
        < tau0^-kappa).  This is the serving refresh loop's entry point
        (oni_ml_tpu/serving/refresh.py): day artifacts -> streaming
        updates without a retrain.

        `num_terms` > V seeds a GROWN vocabulary (continuous
        ingestion: day N's window holds words day N−1 never saw —
        first-seen word ids are stable, so the new words are exactly
        rows V..num_terms-1): the new lambda rows start at the
        symmetric prior eta alone (p's contribution is zero — the old
        model had no opinion about them), so E_q[beta] for new words
        begins at the prior and the stream's evidence grows them.
        Shrinking (num_terms < V) is refused: stable first-seen ids
        mean a smaller vocabulary is a mixed id space, not growth."""
        p = np.asarray(topic_probs, np.float64)
        if p.ndim != 2 or p.shape[1] != config.num_topics:
            raise ValueError(
                f"topic_probs must be [V, {config.num_topics}], got "
                f"{p.shape}"
            )
        if not np.isfinite(p).all() or (p < 0).any():
            raise ValueError("topic_probs must be finite and nonnegative")
        if num_terms is None:
            num_terms = p.shape[0]
        if num_terms < p.shape[0]:
            raise ValueError(
                f"num_terms={num_terms} would SHRINK the vocabulary "
                f"(topic_probs covers {p.shape[0]} words): window word "
                "ids are first-seen-stable, so pass the grown vocab "
                "size or slice topic_probs explicitly"
            )
        if num_terms > p.shape[0]:
            p = np.concatenate(
                [p, np.zeros((num_terms - p.shape[0],
                              config.num_topics), np.float64)],
                axis=0,
            )
        trainer = cls(config, num_terms=num_terms,
                      total_docs=total_docs, **kwargs)
        if trainer.step_count > 0:
            # A checkpoint_path kwarg restored an in-progress stream:
            # the RESUME wins — overwriting lambda with the seed while
            # keeping the checkpoint's step_count would put the rho
            # schedule at step N over reset topics, a silently
            # inconsistent state.
            return trainer
        dtype = jnp.dtype(config.compute_dtype)
        lam = jnp.asarray(config.eta + pseudo_tokens * p.T, dtype)
        if trainer.mesh is not None:
            from ..parallel.mesh import replicated

            lam = jax.device_put(lam, replicated(trainer.mesh))
        trainer._lam = lam
        return trainer

    @property
    def lam(self) -> jnp.ndarray:
        return self._lam

    def _check_data_divisible(self, ndocs: int) -> None:
        from ..parallel.mesh import DATA_AXIS

        data_size = self.mesh.shape[DATA_AXIS]
        if ndocs % data_size:
            raise ValueError(
                f"micro-batch of {ndocs} docs not divisible by data "
                f"axis {data_size}"
            )

    def _put_batch(self, batch: Batch):
        """Device placement for one micro-batch (data-axis sharded when a
        mesh is active, plain transfer otherwise)."""
        dtype = jnp.dtype(self.config.compute_dtype)
        arrays = (
            jnp.asarray(batch.word_idx),
            jnp.asarray(batch.counts, dtype),
            jnp.asarray(batch.doc_mask, dtype),
        )
        if self.mesh is None:
            return arrays
        from ..parallel.mesh import batch_sharding

        self._check_data_divisible(batch.word_idx.shape[0])
        sh = batch_sharding(self.mesh)
        return tuple(jax.device_put(a, sh) for a in arrays)

    def _get_update_dist(self, b: int, l: int):
        """The distributed split of `_get_update`: a jitted local
        partial program (this rank's row slice -> suff-stats + ELBO)
        and a jitted blend program consuming the REDUCED stats — the
        explicit allreduce runs on the host between them, so the
        natural-gradient update is computed identically on every rank
        from identical inputs."""
        key = ("dist", b, l)
        got = self._cache_get(key)
        if got is not None:
            return got
        cfg = self.config
        total_docs = self.total_docs
        e_fn, compiler_options = self._make_e_fn(b)

        def local_part(lam, word_idx, counts, doc_mask):
            res = e_fn(expected_log_beta(lam), self._alpha, word_idx,
                       counts, doc_mask)
            return res.suff_stats, res.likelihood

        def blend(lam, rho, ss, batch_docs):
            lam_hat = cfg.eta + (total_docs / batch_docs) * ss.T
            return (1.0 - rho) * lam + rho * lam_hat

        pair = (
            jax.jit(local_part, compiler_options=compiler_options),
            jax.jit(blend, donate_argnums=(0,)),
        )
        return self._cache_update(key, pair)

    def _step_distributed(self, batch: Batch) -> StreamStepInfo:
        """One update with the micro-batch row-split across ranks and
        the suff-stats crossing processes through the collective.
        `batch_docs` stays the GLOBAL real-doc count (each rank sees
        the full batch host-side; only the device work splits), so the
        update equals the single-process step up to reduction order."""
        from ..parallel.allreduce import tree_combine

        cfg = self.config
        coll = self._coll
        p, r = coll.num_processes, coll.rank
        b, l = batch.word_idx.shape
        if b % p:
            raise ValueError(
                f"micro-batch of {b} docs not divisible by {p} "
                "processes (make_batches pad_multiple must cover the "
                "process count)"
            )
        t = self.step_count
        rho = float((cfg.tau0 + t) ** (-cfg.kappa))
        dtype = jnp.dtype(cfg.compute_dtype)
        lo, hi = r * b // p, (r + 1) * b // p
        if self.mesh is not None:
            # The PER-RANK slice is what the host-local mesh shards.
            self._check_data_divisible(hi - lo)
        part_prog, blend_prog = self._get_update_dist(hi - lo, l)
        ss, ll = part_prog(
            self._lam,
            jnp.asarray(batch.word_idx[lo:hi]),
            jnp.asarray(batch.counts[lo:hi], dtype),
            jnp.asarray(batch.doc_mask[lo:hi], dtype),
        )
        # precision pinned: the streaming lambda-blend parity contract
        # (rank-count-invariant lambda BYTES) would not survive a
        # bf16-compressed wire; the env knob targets the batch
        # suff-stats reduce, not this path.
        reduced = tree_combine(coll.allgather_arrays(
            {"suff_stats": np.asarray(ss), "likelihood": np.asarray(ll)},
            f"svi{t}", precision="f32",
        ))
        self._lam = blend_prog(
            self._lam,
            jnp.asarray(rho, dtype),
            jnp.asarray(reduced["suff_stats"], dtype),
            jnp.asarray(max(float(batch.doc_mask.sum()), 1.0), dtype),
        )
        self.step_count += 1
        info = StreamStepInfo(
            step=self.step_count,
            rho=rho,
            batch_docs=int(batch.doc_mask.sum()),
            likelihood=jnp.asarray(reduced["likelihood"], dtype),
            tokens=int(batch.counts.sum()),
        )
        self.history.append(info)
        self._maybe_stream_checkpoint(prev_count=self.step_count - 1)
        return info

    def step(self, batch: Batch) -> StreamStepInfo:
        """One natural-gradient update from one micro-batch."""
        if self._coll is not None and self._coll.num_processes > 1:
            return self._step_distributed(batch)
        cfg = self.config
        t = self.step_count
        rho = float((cfg.tau0 + t) ** (-cfg.kappa))
        dtype = jnp.dtype(cfg.compute_dtype)
        widx, cnts, mask = self._put_batch(batch)
        update = self._get_update(widx.shape[0], widx.shape[1])
        from ..telemetry.spans import current_recorder

        if current_recorder() is not None:
            # Roofline harvest of the refresh-loop's natural-gradient
            # program, once per process, BEFORE the dispatch below
            # donates self._lam (lowering only reads shapes).
            from ..telemetry import roofline

            roofline.ensure_harvested(
                "serve.refresh_step", update, self._lam,
                jnp.asarray(rho, dtype), widx, cnts, mask,
                shape=f"b{widx.shape[0]}.l{widx.shape[1]}",
            )
        self._lam, ll, _ = update(
            self._lam, jnp.asarray(rho, dtype), widx, cnts, mask
        )
        self.step_count += 1
        info = StreamStepInfo(
            step=self.step_count,
            rho=rho,
            batch_docs=int(batch.doc_mask.sum()),
            likelihood=ll,  # device scalar; no sync on the hot path
            tokens=int(batch.counts.sum()),
        )
        self.history.append(info)
        self._maybe_stream_checkpoint(prev_count=self.step_count - 1)
        return info

    def _maybe_stream_checkpoint(self, prev_count: int) -> None:
        """Checkpoint when a checkpoint_every boundary was crossed since
        `prev_count` (chunked steps cross it mid-chunk; only the
        end-of-chunk lambda is materialized, so the checkpoint lands on
        the first step call after the boundary)."""
        cfg = self.config
        every = cfg.checkpoint_every
        if not (self.checkpoint_path and every):
            return
        if (self.step_count // every) <= (prev_count // every):
            return
        from .lda import _is_coordinator

        # _to_host is collective on multi-host meshes
        # (process_allgather) — every process must reach it; only
        # the coordinator writes.
        lam_host = self._to_host(self._lam)
        if _is_coordinator():
            save_stream_checkpoint(
                self.checkpoint_path,
                lam_host,
                float(self._alpha),
                self.step_count,
                [(float(h.likelihood), h.rho) for h in self.history],
            )

    def _put_stack(self, run: Sequence[Batch]):
        """Device placement for a stacked [N, B, ...] run of same-shape
        micro-batches (docs axis 1 sharded over `data` on a mesh)."""
        dtype = jnp.dtype(self.config.compute_dtype)
        w = np.stack([b.word_idx for b in run])
        c = np.stack([b.counts for b in run]).astype(dtype)
        m = np.stack([b.doc_mask for b in run]).astype(dtype)
        if self.mesh is None:
            return jnp.asarray(w), jnp.asarray(c), jnp.asarray(m)
        from ..parallel.mesh import stacked_batch_sharding

        self._check_data_divisible(w.shape[1])
        sh = stacked_batch_sharding(self.mesh)
        return tuple(jax.device_put(a, sh) for a in (w, c, m))

    def _run_chunk(self, run: Sequence[Batch]) -> list[StreamStepInfo]:
        """Execute a same-shape run of micro-batches as one scan chunk."""
        cfg = self.config
        w, c, m = self._put_stack(run)
        update = self._get_update_many(len(run), w.shape[1], w.shape[2])
        prev = self.step_count
        t0 = jnp.asarray(float(prev), jnp.float32)  # f32 bookkeeping
        self._lam, lls = update(self._lam, t0, w, c, m)
        infos = []
        for i, b in enumerate(run):
            rho = float((cfg.tau0 + self.step_count) ** (-cfg.kappa))
            self.step_count += 1
            info = StreamStepInfo(
                step=self.step_count,
                rho=rho,
                batch_docs=int(b.doc_mask.sum()),
                likelihood=lls[i],  # device scalar; no sync here
                tokens=int(b.counts.sum()),
            )
            self.history.append(info)
            infos.append(info)
        self._maybe_stream_checkpoint(prev_count=prev)
        return infos

    def step_many(
        self, batches: Sequence[Batch], chunk: int = 16
    ) -> list[StreamStepInfo]:
        """Natural-gradient updates over `batches` IN ORDER, executing
        each contiguous same-shape run as device-resident scans (one
        dispatch per scan — see _get_update_many).  Runs split into
        power-of-two scan lengths capped at `chunk` (a 7-batch run =
        scan4 + scan2 + step): any run of >= 2 amortizes dispatches,
        while the number of compiled scan programs stays bounded at
        log2(chunk) per micro-batch shape — a 7-batch epoch reuses the
        same two programs every epoch.  Numerically it is step()
        applied to each micro-batch in sequence (modulo the rho
        schedule's f32 evaluation); only the dispatch granularity and
        checkpoint timing coarsen."""
        if self._coll is not None and self._coll.num_processes > 1:
            # Chunked device-resident scans cannot host-reduce between
            # steps; distributed streams take the per-step path (the
            # allreduce IS the per-step host boundary).
            return [self.step(b) for b in batches]
        if chunk < 2:
            return [self.step(b) for b in batches]
        infos: list[StreamStepInfo] = []
        i, n = 0, len(batches)
        while i < n:
            shape = batches[i].word_idx.shape
            j = i
            while j < n and batches[j].word_idx.shape == shape:
                j += 1
            while i < j:
                c = min(j - i, chunk)
                c = 1 << (c.bit_length() - 1)   # largest power of two <= c
                if c >= 2:
                    infos.extend(self._run_chunk(batches[i:i + c]))
                else:
                    infos.append(self.step(batches[i]))
                i += c
        return infos

    def fit_stream(
        self,
        batches: Iterable[Batch],
        progress: Callable[[StreamStepInfo], None] | None = None,
        chunk: int = 16,
    ) -> "OnlineLDATrainer":
        """Consume a micro-batch stream, buffering contiguous same-shape
        runs into step_many chunks (progress fires per micro-batch, but
        only after its chunk completes)."""
        buf: list[Batch] = []

        def flush():
            infos = self.step_many(buf, chunk=chunk)
            buf.clear()
            if progress:
                for info in infos:
                    progress(info)

        for b in batches:
            if buf and (
                b.word_idx.shape != buf[0].word_idx.shape
                or len(buf) >= chunk
            ):
                flush()
            buf.append(b)
        flush()
        return self

    # -- model extraction ---------------------------------------------------

    def _to_host(self, x) -> np.ndarray:
        from .lda import to_host

        return to_host(x, self.mesh)

    def log_beta(self) -> np.ndarray:
        """Point-estimate topics: log E_q[beta] = log(lambda / sum lambda),
        with the batch engine's LOG_ZERO floor so downstream file contracts
        (final.beta, word_results.csv) behave identically."""
        lam = self._to_host(self._lam)
        beta = lam / lam.sum(-1, keepdims=True)
        return np.where(beta > 0, np.log(np.maximum(beta, 1e-300)),
                        estep.LOG_ZERO)

    def held_out_per_token_ll(self, batches: Sequence[Batch]) -> float:
        """Held-out per-token log-likelihood (document completion,
        models/evaluate.py) of unseen docs under the current topics —
        the quality number for streaming runs, where training ELBO per
        micro-batch (history) is too noisy to compare configurations."""
        from .evaluate import held_out_per_token_ll

        return held_out_per_token_ll(
            self.log_beta(), float(self._alpha), batches,
            var_max_iters=self.config.var_max_iters,
            var_tol=self.config.var_tol,
        )

    def infer_gamma(self, batches: Sequence[Batch], num_docs: int) -> np.ndarray:
        """Final inference pass: doc-topic posteriors for ``num_docs`` docs
        under the current (frozen) topics — produces final.gamma for the
        scoring stage just like the batch trainer's last E-step.  Runs
        through the same (possibly shard_map'd) E-step as training."""
        cfg = self.config
        # One jitted wrapper for the trainer's lifetime: the serving
        # refresh loop calls infer_gamma every few batches, and a fresh
        # jax.jit per call would pay wrapper-cache misses on the scoring
        # worker thread instead of hitting the (B, L)-shape cache.
        e_fn = getattr(self, "_infer_e_fn", None)
        if e_fn is None:
            e_fn = self._infer_e_fn = jax.jit(self._e_fn)
        log_b = expected_log_beta(self._lam)
        gamma_out = np.zeros((num_docs, cfg.num_topics), np.float64)
        for b in batches:
            widx, cnts, mask = self._put_batch(b)
            res = e_fn(log_b, self._alpha, widx, cnts, mask)
            g = self._to_host(res.gamma)
            sel = b.doc_mask == 1
            gamma_out[b.doc_index[sel]] = g[sel]
        return gamma_out

    def result(
        self, batches: Sequence[Batch] | None = None, num_docs: int = 0
    ) -> LDAResult:
        gamma = (
            self.infer_gamma(batches, num_docs)
            if batches is not None
            else np.zeros((0, self.config.num_topics))
        )
        # likelihood.dat contract: column 2 is the relative change between
        # consecutive entries (README.md:119), here between micro-batch
        # ELBOs — NOT the learning rate, which lives in history[i].rho.
        raw = [float(h.likelihood) for h in self.history]
        lls = [
            (ll, abs((raw[i - 1] - ll) / raw[i - 1]) if i else 1.0)
            for i, ll in enumerate(raw)
        ]
        return LDAResult(
            log_beta=self.log_beta(),
            gamma=gamma,
            alpha=float(self._alpha),
            likelihoods=lls,
            em_iters=self.step_count,
        )


def train_corpus_online(
    corpus,
    config: OnlineLDAConfig,
    out_dir: str | None = None,
    epochs: int = 1,
    progress: Callable[[StreamStepInfo], None] | None = None,
    mesh=None,
) -> LDAResult:
    """Stream an in-memory corpus through the online trainer, micro-batch
    by micro-batch, then write the reference-format outputs.

    This is the drop-in path for `ml_ops --online`: the day's corpus is
    consumed as a stream (each bucketed batch = one micro-batch), which on
    hourly data extends naturally to feeding each hour's batches as they
    arrive without retraining from scratch.
    """
    from ..io import make_batches

    # Distributed streams row-split every micro-batch across ranks, so
    # the batch axis must divide by the process count AND each rank's
    # row slice must still divide by the (host-local) mesh's data axis
    # — i.e. pad to a multiple of base_pad * nproc, not merely their
    # rounding (ceil(base/nproc)*nproc would hand shard_map an uneven
    # per-rank slice on tail batches).
    nproc = jax.process_count()
    base_pad = mesh.shape["data"] if mesh is not None else 8
    pad = base_pad if nproc <= 1 else base_pad * nproc
    batches = make_batches(
        corpus, batch_size=config.batch_size,
        min_bucket_len=config.min_bucket_len,
        pad_multiple=pad,
    )
    ckpt_path = (
        os.path.join(out_dir, "checkpoint.npz")
        if out_dir and config.checkpoint_every
        else None
    )
    trainer = OnlineLDATrainer(
        config,
        num_terms=corpus.num_terms,
        total_docs=corpus.num_docs,
        mesh=mesh,
        checkpoint_path=ckpt_path,
    )
    # The epoch-shuffled stream order is deterministic in the seed, so a
    # resumed run fast-forwards past the first `step_count` micro-batches.
    done = trainer.step_count
    rng = np.random.default_rng(config.seed)
    for _ in range(epochs):
        # Stable-group the epoch's shuffled order by micro-batch shape
        # (still deterministic in the seed, still a valid SVI sampling
        # order): same-shape runs then stream through fit_stream's
        # chunked device-resident scans instead of per-step dispatches.
        order = sorted(
            rng.permutation(len(batches)),
            key=lambda i: batches[i].word_idx.shape,
        )
        skip, done = min(done, len(order)), max(done - len(order), 0)
        trainer.fit_stream(
            (batches[i] for i in order[skip:]), progress=progress
        )
    result = trainer.result(batches, corpus.num_docs)
    from .lda import _is_coordinator

    if ckpt_path and os.path.exists(ckpt_path) and _is_coordinator():
        os.remove(ckpt_path)
    if out_dir and _is_coordinator():
        # Multi-host: result is identical on every rank (collective
        # gathers), but the shared day dir has exactly one writer.
        result.save(out_dir, num_terms=corpus.num_terms)
    return result
