"""Held-out-likelihood drift detection and the fleet publish gate.

The batch world's implicit quality gate was a human looking at
tomorrow's results; a continuous pipeline that hot-swaps a fresh model
every half hour has no human in that loop, so it needs a mechanical
one.  This module supplies it:

* `DriftDetector.evaluate` scores each window refresh's model by
  held-out per-token log-likelihood (models/evaluate.py document
  completion over a deterministic hash split of the window's
  documents) — the one quality number this package already uses
  everywhere models are compared.
* `check()` compares that number against a rolling-median baseline of
  the detector's own history (replayable from the journal's
  `drift_check` records, so a restarted service resumes its baseline
  instead of re-learning it) and declares drift when the likelihood
  regresses by more than `tol_nats`.  Drifted refreshes do NOT enter
  the baseline — a corrupted window must not drag the baseline down to
  meet it.
* `gate()` turns the decision into the publish gate: a drifted model
  is VETOED — journaled as `{"kind": "publish_gate", "action":
  "vetoed"}` — and never reaches `FleetRegistry.publish`, so serving
  keeps scoring bit-identically on the prior version (pinned by
  tests/test_streaming.py).  A recovered window publishes normally.

Drift also steers the NEXT refresh's training mode: warm-starting from
topics that just failed the quality bar would launder the drift into
the next model, so the refresh after a veto trains fresh
(`mode_next == "fresh"`).

`QualityGate` is the drift gate's detection-side twin: where the drift
detector asks "does the model still describe the stream?", the quality
gate asks "does it still RANK attacks low?" — every publish candidate
is scored against a pinned labeled-injection suite
(sources/quality.QualitySuite) and a recall@k drop of more than
`tol` below the rolling-median baseline of accepted candidates vetoes
the publish, journaled as `{"kind": "quality_gate", "action":
"vetoed"}`.  Same rolling-baseline/veto mechanics, same
no-regressed-entry rule, same journal replay contract.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class DriftDecision:
    """One refresh's drift verdict."""

    drifted: bool
    ll: float
    baseline_ll: "float | None"   # rolling-median baseline (None: warming up)
    delta: "float | None"         # ll - baseline (negative = worse)
    history: int                  # baseline depth at decision time
    mode_next: str                # "warm" | "fresh" for the NEXT refresh


class DriftDetector:
    """Rolling held-out-likelihood regression detector over the
    journal's refresh history."""

    def __init__(
        self,
        *,
        tol_nats: float = 0.5,
        history: int = 8,
        min_history: int = 2,
        journal=None,
        recorder=None,
    ) -> None:
        if tol_nats <= 0:
            raise ValueError(f"tol_nats must be > 0, got {tol_nats}")
        if min_history < 1:
            raise ValueError(
                f"min_history must be >= 1, got {min_history}"
            )
        self.tol_nats = float(tol_nats)
        self.min_history = int(min_history)
        self._history: deque = deque(maxlen=max(int(history), 1))
        self._journal = journal
        self._recorder = recorder
        self.checks = 0
        self.drifts = 0
        self.publishes = 0
        self.vetoes = 0
        self._last_drifted = False

    # -- baseline persistence -------------------------------------------

    def prime(self, records) -> int:
        """Rebuild the baseline from replayed journal records (the
        `drift_check` vocabulary): non-drifted checks re-enter the
        rolling history in order.  Returns how many were adopted."""
        n = 0
        for rec in records:
            if rec.get("kind") != "drift_check":
                continue
            ll = rec.get("ll")
            if rec.get("drifted") or not isinstance(ll, (int, float)):
                continue
            self._history.append(float(ll))
            n += 1
        return n

    # -- evaluation ------------------------------------------------------

    def evaluate(
        self,
        log_beta: np.ndarray,
        alpha: float,
        corpus,
        *,
        holdout_frac: float = 0.1,
        batch_size: int = 1024,
        min_bucket_len: int = 16,
        var_max_iters: int = 20,
        var_tol: float = 1e-6,
    ) -> "tuple[float, int]":
        """(held-out per-token LL, held-out doc count) for one refresh:
        document-completion score over a deterministic hash split of
        the window's documents (same salt every refresh, so an IP's
        membership is stable and the series is comparable
        refresh-over-refresh)."""
        from ..io import make_batches
        from .evaluate import hash_split, held_out_per_token_ll

        _, held_idx = hash_split(corpus.doc_names, holdout_frac)
        if len(held_idx) == 0:
            # Degenerate tiny window: score every doc rather than none
            # (completion splits tokens per doc, so this stays a
            # meaningful, if optimistic, number).
            held_idx = np.arange(corpus.num_docs)
        held = corpus.select(held_idx)
        batches = make_batches(
            held, batch_size=batch_size, min_bucket_len=min_bucket_len
        )
        ll = held_out_per_token_ll(
            log_beta, alpha, batches,
            var_max_iters=var_max_iters, var_tol=var_tol,
        )
        return float(ll), int(len(held_idx))

    # -- decision --------------------------------------------------------

    @property
    def baseline(self) -> "float | None":
        if len(self._history) < self.min_history:
            return None
        return float(np.median(np.asarray(self._history, np.float64)))

    def check(self, ll: float, **info) -> DriftDecision:
        """Drift verdict for one refresh's held-out LL; journals the
        `{"kind": "drift_check"}` record.  Extra `info` keys ride the
        record (window span, doc counts)."""
        baseline = self.baseline
        delta = None if baseline is None else float(ll) - baseline
        drifted = delta is not None and delta < -self.tol_nats
        self.checks += 1
        if drifted:
            self.drifts += 1
        else:
            self._history.append(float(ll))
        decision = DriftDecision(
            drifted=drifted,
            ll=float(ll),
            baseline_ll=baseline,
            delta=delta,
            history=len(self._history),
            mode_next="fresh" if drifted else "warm",
        )
        self._last_drifted = drifted
        record = {
            "kind": "drift_check",
            "ll": round(float(ll), 6),
            "baseline_ll": (
                None if baseline is None else round(baseline, 6)
            ),
            "delta": None if delta is None else round(delta, 6),
            "tol_nats": self.tol_nats,
            "drifted": drifted,
            "history": len(self._history),
            **info,
        }
        if self._journal is not None:
            self._journal.append(record)
        rec = self._recorder
        if rec is not None:
            rec.gauge("drift.held_out_ll", float(ll))
            if drifted:
                rec.counter("drift.drifts").add(1)
        return decision

    @property
    def mode(self) -> str:
        """Training mode for the NEXT refresh under the "auto" policy:
        fresh right after a veto (warm-starting from rejected topics
        would launder the drift forward), warm otherwise."""
        return "fresh" if self._last_drifted else "warm"

    # -- the publish gate ------------------------------------------------

    def gate(self, decision: DriftDecision, *, version: int,
             **info) -> bool:
        """True = publish may proceed; False = vetoed.  Either way the
        verdict is journaled as `{"kind": "publish_gate"}` — the
        record a post-mortem greps to answer "why is serving still on
        Tuesday's model"."""
        ok = not decision.drifted
        if ok:
            self.publishes += 1
        else:
            self.vetoes += 1
        record = {
            "kind": "publish_gate",
            "action": "published" if ok else "vetoed",
            "version": version,
            "ll": round(decision.ll, 6),
            "delta": (
                None if decision.delta is None
                else round(decision.delta, 6)
            ),
            **info,
        }
        if self._journal is not None:
            self._journal.append(record)
        rec = self._recorder
        if rec is not None:
            rec.counter(
                "publish_gate.published" if ok else "publish_gate.vetoed"
            ).add(1)
        return ok


@dataclass(frozen=True)
class QualityDecision:
    """One publish candidate's detection-quality verdict."""

    regressed: bool
    recall: float
    precision: float
    separation: float
    baseline_recall: "float | None"  # rolling median (None: warming up)
    delta: "float | None"            # recall - baseline (negative = worse)
    history: int
    per_scenario: dict


class QualityGate:
    """Rolling recall@k regression gate over a pinned injection suite.

    The suite is any object with an `evaluate(model) -> metrics` hook
    (sources/quality.QualitySuite in production; tests script it).
    Metrics must carry `recall_at_k` / `precision_at_k` /
    `score_separation` and optionally `per_scenario`.  A candidate
    whose recall sits more than `tol` below the rolling-median baseline
    of ACCEPTED candidates is vetoed; vetoed candidates never enter the
    baseline — a weak model must not drag the bar down to meet it."""

    def __init__(
        self,
        suite,
        *,
        tol: float = 0.25,
        history: int = 8,
        min_history: int = 2,
        journal=None,
        recorder=None,
    ) -> None:
        if tol <= 0:
            raise ValueError(f"tol must be > 0, got {tol}")
        if min_history < 1:
            raise ValueError(
                f"min_history must be >= 1, got {min_history}"
            )
        self.suite = suite
        self.tol = float(tol)
        self.min_history = int(min_history)
        self._history: deque = deque(maxlen=max(int(history), 1))
        self._journal = journal
        self._recorder = recorder
        self.checks = 0
        self.publishes = 0
        self.vetoes = 0

    def prime(self, records) -> int:
        """Rebuild the baseline from replayed `quality_gate` journal
        records: published (non-regressed) checks re-enter the rolling
        history in order.  Returns how many were adopted."""
        n = 0
        for rec in records:
            if rec.get("kind") != "quality_gate":
                continue
            recall = rec.get("recall_at_k")
            if (rec.get("action") != "published"
                    or not isinstance(recall, (int, float))):
                continue
            self._history.append(float(recall))
            n += 1
        return n

    @property
    def baseline(self) -> "float | None":
        if len(self._history) < self.min_history:
            return None
        return float(np.median(np.asarray(self._history, np.float64)))

    def check(self, model) -> QualityDecision:
        """Evaluate one publish candidate against the suite and render
        the regression verdict (no journal write — `gate()` owns the
        record so the verdict and the action always land together)."""
        metrics = self.suite.evaluate(model)
        recall = float(metrics.get("recall_at_k", 0.0))
        baseline = self.baseline
        delta = None if baseline is None else recall - baseline
        regressed = delta is not None and delta < -self.tol
        self.checks += 1
        if not regressed:
            self._history.append(recall)
        return QualityDecision(
            regressed=regressed,
            recall=recall,
            precision=float(metrics.get("precision_at_k", 0.0)),
            separation=float(metrics.get("score_separation", 0.0)),
            baseline_recall=baseline,
            delta=delta,
            history=len(self._history),
            per_scenario=metrics.get("per_scenario", {}),
        )

    def gate(self, decision: QualityDecision, *, version: int,
             **info) -> bool:
        """True = publish may proceed; False = vetoed.  Journals the
        `{"kind": "quality_gate"}` record either way — the detection
        twin of `publish_gate`."""
        ok = not decision.regressed
        if ok:
            self.publishes += 1
        else:
            self.vetoes += 1
        record = {
            "kind": "quality_gate",
            "action": "published" if ok else "vetoed",
            "version": version,
            "recall_at_k": round(decision.recall, 6),
            "precision_at_k": round(decision.precision, 6),
            "score_separation": round(decision.separation, 6),
            "baseline_recall": (
                None if decision.baseline_recall is None
                else round(decision.baseline_recall, 6)
            ),
            "delta": (
                None if decision.delta is None
                else round(decision.delta, 6)
            ),
            "tol": self.tol,
            "history": decision.history,
            "per_scenario": {
                name: round(float(m.get("recall_at_k", 0.0)), 6)
                for name, m in decision.per_scenario.items()
            },
            **info,
        }
        if self._journal is not None:
            self._journal.append(record)
        rec = self._recorder
        if rec is not None:
            rec.gauge("quality.recall_at_k", decision.recall)
            rec.counter(
                "quality_gate.published" if ok else "quality_gate.vetoed"
            ).add(1)
        return ok
