"""Device-resident chunked EM: N iterations per jit call.

The baseline trainer (lda.py) dispatches one E-step per batch per EM
iteration and syncs the likelihood to the host every iteration to decide
convergence.  That host round-trip is pure dead time on the device — and
under remote-relay PJRT backends it dominates wall-clock (measured ~95 ms
per EM iteration of which ~28 ms is compute, on the v5e bench config).

Here the whole EM loop body — scan over batches, suff-stats accumulate,
M-step, Newton alpha, convergence check — runs inside ONE compiled
program as a `lax.while_loop`, executing up to `chunk` EM iterations
before returning control.  The host only syncs at chunk boundaries to
stream `likelihood.dat`, fire progress callbacks, and checkpoint; the
convergence decision is made on device so a run that converges mid-chunk
stops immediately (the reference's `|Δℓ/ℓ| < em_tol` semantics, SURVEY.md
§2.8, evaluated in compute dtype); at each chunk boundary the driver
(lda.py _fused_loop) re-derives conv in float64 and that value is
authoritative, so the final stop always agrees with likelihood.dat.

Batches are grouped by (B, L) shape and stacked [NB, B, L] so each group
is one `lax.scan`; bucketed batching (io/corpus.py) produces few distinct
shapes, so the stacking adds no padding.  The E/M-step hooks are the same
ones the distributed layer substitutes (shard_map over the (data, model)
mesh, psum'd suff-stats) — the fused loop composes with both the
data-parallel and vocab-sharded plans unchanged.
"""

from __future__ import annotations

from contextlib import nullcontext
from typing import Callable, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..io import Batch
from ..ops import estep
from ..telemetry.spans import current_recorder


# Which chunk impl the most recent run_chunk TRACE selected ("fast" |
# "generic"; None before any trace).  Observability only — the two
# impls are equivalence-pinned, so without this marker a regression
# that silently stopped the fast path from ENGAGING (an eligibility
# check drifting) would pass every correctness test while costing the
# headline its glue win.  tests/test_fused.py pins engagement.
LAST_CHUNK_PLAN = None


class StackedGroups(NamedTuple):
    """Shape-grouped batches, stacked for `lax.scan`.

    arrays[g] = (word_idx [NB,B,L], counts [NB,B,L], doc_mask [NB,B]);
    batch_slots[g] is the list of original batch indices, so slot j of
    group g holds batches[batch_slots[g][j]].
    """

    arrays: tuple
    batch_slots: tuple


def stack_batches(
    batches: Sequence[Batch],
    dtype,
    put: Callable[[np.ndarray], jax.Array],
) -> StackedGroups:
    """Group batches by (B, L) and stack each group along a new leading
    axis.  `put` commits the stacked [NB, ...] arrays to device (on a
    mesh: shard the batch axis, axis 1)."""
    groups: dict[tuple, list[int]] = {}
    for i, b in enumerate(batches):
        groups.setdefault(b.word_idx.shape, []).append(i)
    arrays = []
    slots = []
    for shape in sorted(groups):
        idxs = groups[shape]
        arrays.append(
            (
                put(np.stack([batches[i].word_idx for i in idxs])),
                put(
                    np.stack([batches[i].counts for i in idxs]).astype(dtype)
                ),
                put(
                    np.stack([batches[i].doc_mask for i in idxs]).astype(dtype)
                ),
            )
        )
        slots.append(tuple(idxs))
    return StackedGroups(tuple(arrays), tuple(slots))


def densify_groups(
    groups: StackedGroups, num_terms: int, wmajor: bool = False,
    put: Callable | None = None, width: int | None = None,
    dtype=None,
) -> StackedGroups:
    """Convert stacked sparse groups to dense-counts groups for the
    gather/scatter-free E-step (ops/dense_estep.py).

    Each group (word_idx [NB,B,L], counts [NB,B,L], mask [NB,B]) becomes
    (dense_counts [NB,B,V], mask [NB,B]) — or [NB,V,B] with `wmajor`,
    the transposed layout the W-major kernel consumes.  The scatter runs
    ONCE here and is amortized over every EM iteration of the run — that
    amortization is the whole point (a per-iteration scatter is what the
    dense path exists to avoid).  `width` overrides the dense width (the
    vocab-sharded XLA path matches it to the sharded beta width);
    `dtype` is the storage dtype (dense_estep.corpus_dtype — bf16 when
    exact, halving the corpus' HBM footprint and streaming)."""
    from ..ops import dense_estep

    def one(w, c):
        d = dense_estep.densify(w, c, num_terms, width=width, dtype=dtype)
        return d.T if wmajor else d

    arrays = []
    for widx, cnts, mask in groups.arrays:
        dense = jax.jit(jax.vmap(one))(widx, cnts)
        if put is not None:  # e.g. shard the doc axis over a mesh
            dense = put(dense)
        arrays.append((dense, mask))
    return StackedGroups(tuple(arrays), groups.batch_slots)


def dense_groups_bytes(batches: Sequence[Batch], num_terms: int,
                       itemsize: int = 4) -> int:
    """Device bytes the densified corpus would occupy."""
    from ..ops import dense_estep

    width = dense_estep.padded_width(num_terms)
    return sum(b.word_idx.shape[0] for b in batches) * width * itemsize


class CompactPlan(NamedTuple):
    """Host-side plan for the compact-vocab dense E-step (config 4's
    single-chip MXU path — SURVEY.md §5.7's V scaling axis, the
    combinatorial word space of dns_pre_lda.scala:320-326).

    When the FULL vocabulary is too wide to densify ([B, padded V]
    blows the VMEM/HBM budget), each batch still touches only the
    words its documents contain — power-law distributed in real
    traffic, so a 4096x128-token batch of a 500k-word day typically
    holds a few tens of thousands of distinct words.  Remapping each
    batch onto its own compacted vocabulary turns the huge-V E-step
    back into the gather/scatter-free dense kernel at width Wc << V,
    at the cost of ONE [K, Wc] beta-column gather and one [Wc, K]
    suff-stats row-scatter per batch per EM iteration (vs the sparse
    path's per-token gathers in every fixed-point iteration).

    uniques[g][j]: sorted distinct word ids of group g's j-th stacked
    batch; widths[g]: the group's shared compact width (max unique
    count, padded to the 128-lane tile).
    """

    uniques: tuple          # per group: tuple of np.ndarray word ids
    widths: tuple           # per group: int compact width Wc
    wmajor: bool
    corpus_bytes: int       # device bytes of the compacted corpus


def plan_compact(
    batches: Sequence[Batch],
    num_topics: int,
    precision: str = "f32",
    wmajor: bool = True,
    itemsize: int = 4,
    local_div: int = 1,
) -> CompactPlan | None:
    """Build a CompactPlan, or None when some group's compact width
    admits no VMEM-feasible doc block (then the sparse path is the
    only option).  Pure host-side: np.unique over each batch's token
    ids (the corpus is static, so the per-batch vocabulary is fixed
    for the whole run).  `local_div` divides the per-kernel doc count
    (data-mesh shard factor); callers gate mesh support themselves."""
    from ..ops import dense_estep

    groups: dict[tuple, list[int]] = {}
    for i, b in enumerate(batches):
        groups.setdefault(b.word_idx.shape, []).append(i)
    uniques, widths = [], []
    total = 0
    use_wmajor = wmajor
    for shape in sorted(groups):
        idxs = groups[shape]
        us = tuple(np.unique(batches[i].word_idx) for i in idxs)
        wc = max(len(u) for u in us)
        wc = -(-wc // 128) * 128  # lane tile, like padded_width()
        b_local = shape[0] // local_div
        if dense_estep.pick_block(b_local, wc, num_topics,
                                  precision) is None:
            return None
        use_wmajor = use_wmajor and (
            dense_estep.pick_block_w(b_local, wc, num_topics, precision)
            is not None
        )
        uniques.append(us)
        widths.append(wc)
        total += len(idxs) * shape[0] * wc * itemsize
    return CompactPlan(tuple(uniques), tuple(widths), use_wmajor, total)


def compact_stack_batches(
    batches: Sequence[Batch],
    dtype,
    put: Callable[[np.ndarray], jax.Array],
    plan: CompactPlan,
    corpus_store=None,
) -> StackedGroups:
    """Stack batches into compact-dense groups:

    arrays[g] = (dense_local [NB, B, Wc] (or [NB, Wc, B] W-major),
                 doc_mask [NB, B], vocab_map [NB, Wc] int32)

    vocab_map[j, u] is the GLOBAL word id of local column u; columns
    past the batch's unique count repeat id 0 as a sentinel — inert,
    because their local counts are zero, so the kernel produces zero
    suff-stats there and the scatter-back adds zeros to word 0.
    Token ids remap via searchsorted into the batch's sorted unique
    set (exact: every token id is a member)."""
    from ..ops import dense_estep

    groups: dict[tuple, list[int]] = {}
    for i, b in enumerate(batches):
        groups.setdefault(b.word_idx.shape, []).append(i)
    arrays = []
    slots = []
    for g, shape in enumerate(sorted(groups)):
        idxs = groups[shape]
        wc = plan.widths[g]

        local_idx, cnts, masks, vmaps = [], [], [], []
        for j, i in enumerate(idxs):
            u = plan.uniques[g][j]
            local_idx.append(
                np.searchsorted(u, batches[i].word_idx).astype(np.int32)
            )
            cnts.append(batches[i].counts.astype(dtype))
            masks.append(batches[i].doc_mask.astype(dtype))
            vm = np.zeros(wc, np.int32)
            vm[: len(u)] = u
            vmaps.append(vm)

        def one(w, c):
            d = dense_estep.densify(w, c, wc, width=wc, dtype=corpus_store)
            return d.T if plan.wmajor else d

        dense = jax.jit(jax.vmap(one))(
            jnp.asarray(np.stack(local_idx)), jnp.asarray(np.stack(cnts))
        )
        arrays.append(
            (put(dense), put(np.stack(masks)), put(np.stack(vmaps)))
        )
        slots.append(tuple(idxs))
    return StackedGroups(tuple(arrays), tuple(slots))


def initial_gammas(groups_arrays, k: int, dtype, dense_wmajor=False):
    """Zero gamma buffers matching ChunkResult.gammas' structure — what
    drivers pass as the first chunk's `gammas_in` (with have_prev=False)
    so that later chunks can feed `res.gammas` back WITHOUT a retrace
    (same pytree structure/shapes every call)."""
    def batch_dim(g):
        # Dense [NB,B,W] / compact-dense [NB,B,Wc] groups put docs on
        # axis 1 like sparse [NB,B,L]; the W-major layouts transpose
        # docs onto the last axis.  Compact groups are len 3 like
        # sparse but lead with the floating dense corpus (sparse leads
        # with integer word_idx) — same rule run_batch dispatches on.
        is_dense = len(g) == 2 or jnp.issubdtype(g[0].dtype, jnp.floating)
        return g[0].shape[2] if is_dense and dense_wmajor else g[0].shape[1]

    return tuple(
        jnp.zeros((g[0].shape[0], batch_dim(g), k), dtype)
        for g in groups_arrays
    )


def make_em_accumulator(
    *,
    num_topics: int,
    num_terms: int,
    var_max_iters: int,
    var_tol: float,
    e_step_fn: Callable | None = None,
    dense_e_step_fn: Callable | None = None,
    dense_wmajor: bool = False,
    dense_precision: str = "f32",
    warm_start: bool = False,
):
    """Build `accumulate(log_beta, alpha, groups, gammas_prev, warm) ->
    (suff_stats [V, K], likelihood, alpha_ss, gammas, vi_max)` — one EM
    iteration's E-step over stacked groups WITHOUT the M-step tail.

    This is the partial-sufficient-statistics return path: the chunk
    runner composes it with the M-step/alpha update inside one compiled
    program (single-process EM), while the distributed driver
    (models/lda.py `_distributed_loop`) jits it alone per document
    shard (`make_partial_runner`), reduces the partials across
    processes through parallel/allreduce, and only then runs the
    identical M-step on every rank from the reduced stats."""
    e_fn = e_step_fn or estep.e_step
    # Sparse groups warm-start only through callables that declare the
    # gamma_prev/warm kwargs (this package's e_step and its sharded
    # wrappers); a user-supplied custom e_step_fn stays fresh-start
    # rather than breaking on unexpected kwargs.
    e_warm = warm_start and getattr(e_fn, "_oni_warm_capable", False)
    k, v = num_topics, num_terms

    def _default_dense(log_beta, alpha, dense, m, g_in, warm):
        from ..ops import dense_estep

        return dense_estep.e_step_dense(
            log_beta, alpha, dense, m,
            var_max_iters=var_max_iters, var_tol=var_tol,
            interpret=jax.default_backend() != "tpu",
            wmajor=dense_wmajor,
            gamma_prev=g_in, warm=warm, precision=dense_precision,
        )

    dense_fn = dense_e_step_fn or _default_dense

    def _compact_dense(log_beta, alpha, dense_local, m, vocab_map, g_in,
                       warm):
        """Compact-vocab dense E-step (plan_compact): run the dense
        kernel over the batch's own Wc-wide vocabulary slice, then
        scatter the suff-stats rows back to the full [V, K] layout the
        M-step consumes.  Sentinel columns (vocab_map padding repeats
        word 0) carry zero local counts, so their suff-stats are
        exactly zero and the duplicate-index .add() is a no-op."""
        from ..ops import dense_estep

        beta_local = jnp.take(log_beta, vocab_map, axis=1)
        res = dense_estep.e_step_dense(
            beta_local, alpha, dense_local, m,
            var_max_iters=var_max_iters, var_tol=var_tol,
            interpret=jax.default_backend() != "tpu",
            wmajor=dense_wmajor,
            gamma_prev=g_in, warm=warm, precision=dense_precision,
        )
        ss = jnp.zeros((v, k), log_beta.dtype).at[vocab_map].add(
            res.suff_stats
        )
        return res._replace(suff_stats=ss)

    def accumulate(log_beta, alpha, groups, gammas_prev, warm):
        dtype = log_beta.dtype
        total_ss = jnp.zeros((v, k), dtype)
        total_ll = jnp.zeros((), dtype)
        total_ass = jnp.zeros((), dtype)
        vi_max = jnp.zeros((), jnp.int32)
        gammas = []

        def run_batch(batch, g_in):
            if len(batch) == 2:                # dense group: (C [B,V], mask)
                return dense_fn(log_beta, alpha, *batch, g_in, warm)
            if jnp.issubdtype(batch[0].dtype, jnp.floating):
                # compact-dense group: (C_local, mask, vocab_map) —
                # disjoint from sparse, whose leading word_idx is
                # integer (dtype is static at trace time).
                return _compact_dense(log_beta, alpha, *batch, g_in, warm)
            w, c, m = batch                    # sparse group: (w, c, mask)
            if e_warm:
                return e_fn(
                    log_beta, alpha, w, c, m,
                    var_max_iters=var_max_iters, var_tol=var_tol,
                    gamma_prev=g_in, warm=warm,
                )
            return e_fn(
                log_beta, alpha, w, c, m,
                var_max_iters=var_max_iters, var_tol=var_tol,
            )

        for group, g_prev in zip(groups, gammas_prev):
            if group[0].shape[0] == 1:
                # Single-batch group (the common case after bucketing):
                # call the E-step directly instead of a length-1
                # lax.scan, whose slice-in/stack-out machinery adds
                # fixed per-EM-iteration ops inside the chunk loop.
                res = run_batch(
                    tuple(a[0] for a in group), g_prev[0]
                )
                total_ss = total_ss + res.suff_stats
                total_ll = total_ll + res.likelihood
                total_ass = total_ass + res.alpha_ss
                vi_max = jnp.maximum(
                    vi_max, jnp.asarray(res.vi_iters, jnp.int32)
                )
                gammas.append(res.gamma[None])
                continue

            def scan_body(carry, batch_and_gamma):
                ss, ll, ass, vi = carry
                batch, g_in = batch_and_gamma
                res = run_batch(batch, g_in)
                return (
                    (ss + res.suff_stats, ll + res.likelihood,
                     ass + res.alpha_ss,
                     jnp.maximum(vi, jnp.asarray(res.vi_iters, jnp.int32))),
                    res.gamma,
                )

            (total_ss, total_ll, total_ass, vi_max), g = jax.lax.scan(
                scan_body, (total_ss, total_ll, total_ass, vi_max),
                (group, g_prev)
            )
            gammas.append(g)
        return total_ss, total_ll, total_ass, tuple(gammas), vi_max

    return accumulate


def make_partial_runner(*, compiler_options: dict | None = None, **kw):
    """The distributed driver's per-shard E-step program: one jitted
    call of the accumulator above, emitting the partial suff-stats /
    ELBO / alpha-ss for ONE document shard so the explicit allreduce
    (parallel/allreduce.py) can combine them across processes between
    the E and M steps.  `warm` is a traced scalar, so warm-start
    toggling never retraces."""
    acc = make_em_accumulator(**kw)
    return jax.jit(acc, compiler_options=compiler_options)


class ChunkResult(NamedTuple):
    log_beta: jax.Array
    alpha: jax.Array
    ll_prev: jax.Array          # scalar; nan before the first EM iteration
    lls: jax.Array              # [chunk] likelihood per executed step
    steps_done: jax.Array       # int32 scalar in [0, n_steps]
    converged: jax.Array        # bool scalar
    gammas: tuple               # per group: [NB, B, K] from the final E-step
    vi_iters: jax.Array         # [chunk] max inner fixed-point iterations
                                # per executed EM step (observability:
                                # shows the var_tol early exit + warm
                                # start collapsing the inner loop)


def make_chunk_runner(
    *,
    num_docs: int,
    num_topics: int,
    num_terms: int,
    chunk: int,
    var_max_iters: int,
    var_tol: float,
    em_tol: float,
    estimate_alpha: bool,
    e_step_fn: Callable | None = None,
    m_step_fn: Callable | None = None,
    compiler_options: dict | None = None,
    dense_wmajor: bool = False,
    warm_start: bool = False,
    dense_e_step_fn: Callable | None = None,
    dense_precision: str = "f32",
    alpha_max_iters: int = 100,
    yield_hook: Callable | None = None,
):
    """Build the jitted `run_chunk(log_beta, alpha, ll_prev, groups,
    n_steps)` executing up to min(chunk, n_steps) EM iterations on device.

    `n_steps` is a traced scalar, so checkpoint boundaries and the final
    partial chunk reuse the single compiled program.

    `yield_hook` (a context-manager factory, e.g.
    `serving.CoScheduler.train_chunk`) makes each chunk dispatch
    PREEMPTIBLE: the runner enters one hook slot per dispatch, so a
    co-resident serving plane wins the next dispatch slot at every
    chunk boundary — the fused chunk is the natural preemption grain.
    """
    from .lda import update_alpha  # local import: lda.py imports this module

    m_fn = m_step_fn or estep.m_step
    k, v = num_topics, num_terms
    # The E-step callable itself now lives inside the accumulator (the
    # shared partial-stats path the distributed driver also jits).
    accumulate = make_em_accumulator(
        num_topics=num_topics, num_terms=num_terms,
        var_max_iters=var_max_iters, var_tol=var_tol,
        e_step_fn=e_step_fn, dense_e_step_fn=dense_e_step_fn,
        dense_wmajor=dense_wmajor, dense_precision=dense_precision,
        warm_start=warm_start,
    )

    def em_iteration(log_beta, alpha, groups, gammas_prev, warm):
        total_ss, total_ll, total_ass, gammas, vi_max = accumulate(
            log_beta, alpha, groups, gammas_prev, warm
        )
        new_beta = m_fn(total_ss)
        new_alpha = (
            update_alpha(total_ass, alpha, num_docs, k,
                         max_iters=alpha_max_iters)
            if estimate_alpha
            else alpha
        )
        return new_beta, new_alpha, total_ll, tuple(gammas), vi_max

    def _resolve_gammas(groups, gammas_in, have_prev, dtype):
        """Gamma buffers must exist in the carry before the first
        iteration writes them.  `gammas_in`/`have_prev` carry the
        PREVIOUS chunk's posteriors across the host boundary so warm
        start survives chunk boundaries (without them iteration
        chunk*i+1 restarted fresh); when absent, zeros are never read
        back (warm gates on step>0)."""
        if gammas_in is None:
            return (
                initial_gammas(groups, k, dtype,
                               dense_wmajor=dense_wmajor),
                jnp.asarray(False),
            )
        return gammas_in, jnp.asarray(have_prev)

    def _chunk_loop(model0, alpha, ll_prev, gammas0, n_steps, have_prev,
                    iterate, dtype):
        """Shared chunk while-loop skeleton — warm gating, the device
        convergence rule, and step/ll/vi bookkeeping live HERE once,
        for both the generic impl and the dense fast path (a change to
        the stop rule or the warm gate must not be able to land in one
        and not the other).  `iterate(model, alpha, gammas, warm) ->
        (model', alpha', ll, gammas', vi)` supplies the EM iteration
        body; `model` is whatever beta representation the path carries
        (log-space [K, V], or padded exp-space [K, W])."""
        lls0 = jnp.zeros((chunk,), dtype)
        vi0 = jnp.zeros((chunk,), jnp.int32)

        def cond(state):
            _, _, _, step, _, _, converged, _ = state
            return (step < jnp.minimum(n_steps, chunk)) & ~converged

        def body(state):
            model, alpha, ll_prev, step, lls, vis, _, gammas_prev = state
            # Warm start once ANY gamma exists: produced this chunk
            # (step>0) or carried in from the previous one (have_prev).
            warm = (
                (step > 0) | have_prev
                if warm_start
                else jnp.asarray(False)
            )
            model, new_alpha, ll, gammas, vi = iterate(
                model, alpha, gammas_prev, warm
            )
            # The first-ever iteration (ll_prev = nan) never stops — the
            # reference's "no previous likelihood" case.  The host
            # recomputes logged convergence values in float64 from the
            # returned lls.
            conv = jnp.abs((ll_prev - ll) / ll_prev)
            converged = ~jnp.isnan(ll_prev) & (conv < em_tol)
            return (
                model,
                new_alpha,
                ll,
                step + 1,
                lls.at[step].set(ll),
                vis.at[step].set(jnp.asarray(vi, jnp.int32)),
                converged,
                gammas,
            )

        state = (
            model0, alpha, ll_prev, jnp.asarray(0, jnp.int32),
            lls0, vi0, jnp.asarray(False), gammas0,
        )
        return jax.lax.while_loop(cond, body, state)

    def run_chunk_impl(log_beta, alpha, ll_prev, groups, n_steps,
                       gammas_in=None, have_prev=None) -> ChunkResult:
        dtype = log_beta.dtype
        gamma0, have_prev = _resolve_gammas(groups, gammas_in, have_prev,
                                            dtype)

        def iterate(log_beta, alpha, gammas_prev, warm):
            return em_iteration(log_beta, alpha, groups, gammas_prev, warm)

        log_beta, alpha, ll_prev, step, lls, vis, converged, gammas = (
            _chunk_loop(log_beta, alpha, ll_prev, gamma0, n_steps,
                        have_prev, iterate, dtype)
        )
        return ChunkResult(
            log_beta, alpha, ll_prev, lls, step, converged, gammas, vis
        )

    # -- single-dense-group fast path ------------------------------------
    # The production/bench common case (one full-V dense group, stock
    # M-step, no mesh override) carries exp(beta) in the kernel's padded
    # [K, W] layout across EM iterations instead of log-space [K, V]:
    # each iteration is kernel -> elementwise exp-space M-step
    # (ss / total), eliminating the per-iteration exp(log_beta) pass,
    # the log() in m_step, the [V, K] transposes, and the EStepResult
    # assembly.  (The r05 on-chip A/B measured this a WASH at the
    # headline shape — the "~0.9 ms glue" the round-4 decomposition
    # charged here turned out to be per-DISPATCH tunnel round-trip,
    # amortized by the chunk length instead; see docs/performance.md
    # round-5 section.  The path is kept: it is equivalence-pinned,
    # never slower, and XLA fuses either form.)  Log-space beta is
    # reconstructed
    # ONCE at the chunk boundary; log(ss / total) differs from m_step's
    # log(ss) - log(total) by at most 1 ulp for quotients down to
    # exp(-100); BELOW that window (ss/total < ~3.8e-44, where m_step
    # would emit log values in about (-103, -100]) the reconstruction
    # clamps to LOG_ZERO — a deliberate floor on probabilities ~1e-44,
    # covered by the 1e-5-rtol equivalence pins (tests/test_fused.py).
    # Entries with exactly zero mass pin to LOG_ZERO in both paths.
    dense_fast_ok = m_fn is estep.m_step and dense_e_step_fn is None

    def _is_single_dense(groups) -> bool:
        return (
            dense_fast_ok
            and len(groups) == 1
            and len(groups[0]) == 2          # (C, mask): full-V dense
            and groups[0][0].shape[0] == 1   # one stacked batch
        )

    def run_chunk_impl_fast(log_beta, alpha, ll_prev, groups, n_steps,
                            gammas_in=None, have_prev=None) -> ChunkResult:
        from jax.scipy.special import gammaln

        from ..ops import dense_estep

        C, mask = (a[0] for a in groups[0])
        dtype = log_beta.dtype
        w = C.shape[0] if dense_wmajor else C.shape[1]
        exp_beta0 = jnp.exp(log_beta)
        if w != v:
            exp_beta0 = jnp.pad(exp_beta0, ((0, 0), (0, w - v)))
        fp = (
            dense_estep.dense_fixed_point_w
            if dense_wmajor
            else dense_estep.dense_fixed_point
        )
        interp = jax.default_backend() != "tpu"
        gamma0, have_prev = _resolve_gammas(groups, gammas_in, have_prev,
                                            dtype)
        # exp(LOG_ZERO) — the exact value exp(m_step's floor) produces,
        # so zero-mass entries round-trip to LOG_ZERO bit-exactly.
        exp_zero = jnp.asarray(np.exp(np.float64(estep.LOG_ZERO)), dtype)

        def iterate(exp_beta, alpha, g_prev, warm):
            gamma, t, docll, ass, iters = fp(
                exp_beta, alpha, C, mask, var_max_iters, var_tol,
                interpret=interp, gamma_prev=g_prev,
                warm=jnp.asarray(warm, jnp.int32),
                precision=dense_precision,
            )
            alpha_const = gammaln(k * alpha) - k * gammaln(alpha)
            ll = docll.sum() + mask.sum() * alpha_const
            new_alpha = (
                update_alpha(ass.sum(), alpha, num_docs, k,
                             max_iters=alpha_max_iters)
                if estimate_alpha
                else alpha
            )
            suff = exp_beta * t                       # [K, W]
            total = suff.sum(-1, keepdims=True)       # pad cols are 0
            new_exp = jnp.where(suff > 0, suff / total, exp_zero)
            return new_exp, new_alpha, ll, gamma, iters

        exp_beta, alpha, ll_prev, step, lls, vis, converged, gamma = (
            _chunk_loop(exp_beta0, alpha, ll_prev, gamma0[0][0], n_steps,
                        have_prev, iterate, dtype)
        )
        # Reconstruct log-space beta once.  A zero-step chunk must
        # return the INPUT log_beta (log(exp(x)) drifts an ulp).
        eb = exp_beta[:, :v]
        new_log = jnp.where(
            eb > exp_zero, jnp.log(jnp.maximum(eb, 1e-300)),
            estep.LOG_ZERO
        )
        log_out = jnp.where(step > 0, new_log, log_beta)
        return ChunkResult(
            log_out, alpha, ll_prev, lls, step, converged,
            (gamma[None],), vis,
        )

    def run_chunk_dispatch(log_beta, alpha, ll_prev, groups, n_steps,
                           gammas_in=None, have_prev=None) -> ChunkResult:
        global LAST_CHUNK_PLAN
        if _is_single_dense(groups):
            LAST_CHUNK_PLAN = "fast"
            return run_chunk_impl_fast(
                log_beta, alpha, ll_prev, groups, n_steps,
                gammas_in=gammas_in, have_prev=have_prev,
            )
        LAST_CHUNK_PLAN = "generic"
        return run_chunk_impl(
            log_beta, alpha, ll_prev, groups, n_steps,
            gammas_in=gammas_in, have_prev=have_prev,
        )

    jitted = jax.jit(run_chunk_dispatch, compiler_options=compiler_options)

    def runner(log_beta, alpha, ll_prev, groups, n_steps, *args, **kw):
        """Host-side dispatch wrapper: when a telemetry Recorder is
        active (telemetry/spans.py), each chunk dispatch records an
        `em.run_chunk` span and counter.  JAX dispatch is asynchronous,
        so the span measures ENQUEUE (trace/lower on first call, then
        the per-dispatch glue the r05 sweep priced at ~65 ms under the
        tunneled backend) — the quantity the chunked driver exists to
        amortize — not device compute; the driver's host-sync span
        covers the blocking side.  No recorder -> straight through."""
        slot = yield_hook() if yield_hook is not None else nullcontext()
        rec = current_recorder()
        if rec is None:
            with slot:
                return jitted(log_beta, alpha, ll_prev, groups, n_steps,
                              *args, **kw)
        with slot, rec.span("em.run_chunk", chunk=chunk,
                            n_steps=int(n_steps)
                            if isinstance(n_steps, int) else None):
            out = jitted(log_beta, alpha, ll_prev, groups, n_steps,
                         *args, **kw)
        rec.counter("em.chunk_dispatches").add(1)
        # Roofline harvest, once per shape, only under an active
        # recorder — AFTER the live dispatch, so the program is already
        # traced and in the persistent compilation cache: the AOT
        # lower+compile that reads XLA's per-dispatch FLOPs/bytes is a
        # cache hit, never a cold compile delaying first results.
        # (Safe post-dispatch: this jit donates nothing, so the
        # operands' shapes are still readable.)  Uninstrumented runs
        # never pay the extra trace.
        from ..telemetry import roofline

        roofline.ensure_harvested(
            "em.run_chunk", jitted, log_beta, alpha, ll_prev, groups,
            n_steps, *args, shape=f"chunk{chunk}", **kw,
        )
        return out

    # The EFFECTIVE dispatch settings ride on the runner so callers that
    # report them (bench.py's phase records) read what this runner was
    # actually built with — a monkeypatched maker (tools/tpu_probes.py
    # alpha_ab overrides alpha_max_iters inside its wrapper) would
    # otherwise desync the payload from the measurement.
    runner.alpha_max_iters = alpha_max_iters
    runner.chunk = chunk
    runner.jitted = jitted  # AOT access (tools/config4_hbm_probe.lower)
    return runner
